"""Pallas fused softmax-cross-entropy (the second hand-written kernel,
VERDICT r3 missing #4 — picked by the bench profile: the [tokens, 30k]
logits tensor is the single biggest HBM tensor in the BERT pretrain step,
and XLA's log_softmax+gather makes 2-3 full passes over it plus writes
the [tokens, V] softmax back for the backward).

Design (flash-attention's online-softmax pattern turned sideways):
  * grid = (token_blocks, vocab_blocks) with the vocab dimension
    innermost and "arbitrary" — running max / sumexp / picked-logit live
    in VMEM scratch that persists across the vocab sweep, so the kernel
    reads each logit exactly ONCE and never materializes softmax.
  * loss_t = (m + log s) - logit[label_t]; lse is saved for the backward.
  * backward is plain XLA: dlogits = (exp(logits - lse) - onehot) * dy is
    a single fused elementwise pass — no kernel needed there.

Wired into `softmax_with_cross_entropy` behind the `fused_xent` flag
(core/flags) — OFF by default until measured on chip, the r3 lesson:
never ship a hand kernel as the default on an unmeasured heuristic.
`tools/tune_fused_xent.py` does the on-chip A/B.

Reference being replaced: softmax_with_cross_entropy_op.cu's fused
kernels (/root/reference/paddle/fluid/operators/softmax_with_cross_entropy_op.cu:1)
— same fusion goal, CUDA warp reductions there, online vocab streaming
here.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _fit(n, want, mult):
    for b in range(min(want, n), mult - 1, -1):
        if n % b == 0 and b % mult == 0:
            return b
    return None


def _fused_xent_kernel(logits_ref, label_ref, loss_ref, lse_ref,
                       m_ref, s_ref, p_ref, *, V, bv, n_vb, ignore_index):
    from jax.experimental import pallas as pl

    vb = pl.program_id(1)

    @pl.when(vb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        s_ref[...] = jnp.zeros_like(s_ref)
        p_ref[...] = jnp.zeros_like(p_ref)

    chunk = logits_ref[...].astype(jnp.float32)        # [bt, bv]
    bt = chunk.shape[0]
    cols = vb * bv + jax.lax.broadcasted_iota(jnp.int32, (bt, bv), 1)
    valid = cols < V
    chunk = jnp.where(valid, chunk, -jnp.inf)

    m = m_ref[...]                                     # [bt, 1]
    s = s_ref[...]
    m_new = jnp.maximum(m, jnp.max(chunk, axis=-1, keepdims=True))
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.where(valid, jnp.exp(chunk - safe_m), 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    s_new = alpha * s + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    s_ref[...] = s_new

    lbl = label_ref[...]                               # [bt, 1] int32
    hit = cols == lbl
    p_ref[...] += jnp.sum(jnp.where(hit, chunk, 0.0), axis=-1,
                          keepdims=True)

    @pl.when(vb == n_vb - 1)
    def _finish():
        m_f = m_ref[...]
        s_f = s_ref[...]
        lse = jnp.where(jnp.isfinite(m_f),
                        m_f + jnp.log(jnp.maximum(s_f, 1e-30)), -jnp.inf)
        loss = lse - p_ref[...]
        # reference semantics: label == ignore_index rows contribute 0,
        # REGARDLESS of the index's sign (paddle default is -100)
        loss = jnp.where(label_ref[...] == ignore_index, 0.0, loss)
        loss_ref[...] = loss
        lse_ref[...] = lse


def _fused_xent_fwd(logits, label, ignore_index, block_t, block_v,
                    interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, V = logits.shape
    bt = _fit(T, block_t, 8)
    # the vocab sweep masks the ragged tail, so bv only needs the lane
    # multiple, not divisibility of V
    bv = max(128, min(block_v, ((V + 127) // 128) * 128))
    n_vb = (V + bv - 1) // bv
    kernel = functools.partial(_fused_xent_kernel, V=V, bv=bv, n_vb=n_vb,
                               ignore_index=ignore_index)
    grid = (T // bt, n_vb)
    loss, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bt, bv), lambda ti, vi: (ti, vi)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
            pl.BlockSpec((bt, 1), lambda ti, vi: (ti, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
            jax.ShapeDtypeStruct((T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bt, 1), jnp.float32),
            pltpu.VMEM((bt, 1), jnp.float32),
            pltpu.VMEM((bt, 1), jnp.float32),
        ],
        interpret=interpret,
    )(logits, label.reshape(T, 1).astype(jnp.int32))
    return loss, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def fused_softmax_xent(logits, label, ignore_index=-100, block_t=256,
                       block_v=2048, interpret=False):
    """loss [T, 1] fp32 for hard labels [T] over logits [T, V]; softmax
    is never materialized in the forward."""
    loss, _ = _fused_xent_fwd(logits, label, ignore_index, block_t,
                              block_v, interpret)
    return loss


def _fwd(logits, label, ignore_index, block_t, block_v, interpret):
    loss, lse = _fused_xent_fwd(logits, label, ignore_index, block_t,
                                block_v, interpret)
    return loss, (logits, label, lse)


def _bwd(ignore_index, block_t, block_v, interpret, res, dy):
    logits, label, lse = res
    T, V = logits.shape
    lbl = label.reshape(-1).astype(jnp.int32)
    # (softmax - onehot) * dy — one fused elementwise pass, XLA territory
    sm = jnp.exp(logits.astype(jnp.float32) - lse)
    dyf = dy.reshape(T, 1).astype(jnp.float32)
    dyf = jnp.where(lbl.reshape(T, 1) == ignore_index, 0.0, dyf)
    d = sm * dyf
    d = d.at[jnp.arange(T), jnp.clip(lbl, 0, V - 1)].add(-dyf[:, 0])
    return d.astype(logits.dtype), None


fused_softmax_xent.defvjp(_fwd, _bwd)


def fused_xent_enabled() -> bool:
    from ..core.flags import flag
    return bool(flag("fused_xent"))


def enable_fused_xent(on: bool = True):
    from ..core.flags import set_flags
    set_flags({"fused_xent": bool(on)})


def maybe_fused_xent(logits, label, axis, soft_label, ignore_index):
    """Dispatch hook for the softmax_with_cross_entropy kernel: returns
    (loss, lse) when the fused Pallas path applies, else None.
    Conditions: flag on, hard labels, last-axis, the flattened token
    count tiles into sublane blocks, and the call is TRACED (under jit):
    in eager op-by-op execution the Softmax placeholder would really
    allocate, so the base path is kept there."""
    if not fused_xent_enabled() or soft_label:
        return None
    if axis != logits.ndim - 1:
        return None
    if not isinstance(logits, jax.core.Tracer):
        return None
    lead = int(np.prod(logits.shape[:-1]))
    if lead % 8 != 0:
        return None
    interpret = jax.default_backend() != "tpu"
    flat = logits.reshape(lead, logits.shape[-1])
    lbl = label
    if lbl.ndim == logits.ndim and lbl.shape[-1] == 1:
        lbl = lbl[..., 0]
    flat_lbl = lbl.reshape(lead)
    loss = fused_softmax_xent(flat, flat_lbl,
                              ignore_index if ignore_index is not None
                              else -100,
                              256, 2048, interpret)
    return loss.reshape(*logits.shape[:-1], 1)

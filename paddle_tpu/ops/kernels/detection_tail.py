"""Detection op tail — static-shape TPU redesigns of the remaining
/root/reference/paddle/fluid/operators/detection/ ops (matrix_nms_op.cc,
locality_aware_nms_op.cc, retinanet_detection_output_op.cc,
rpn_target_assign_op.cc, target_assign_op.h, mine_hard_examples_op.cc,
collect_fpn_proposals_op.cc, distribute_fpn_proposals_op.cc,
box_decoder_and_assign_op.h, polygon_box_transform_op.cc,
generate_proposal_labels_op.cc, generate_mask_labels_op.cc) plus
psroi_pool_op.h, prroi_pool_op.h, roi_perspective_transform_op.cc and
detection_map_op.cc from operators/.

Same contract as detection.py: the reference emits LoD outputs with
data-dependent row counts; here every op returns FIXED-size outputs padded
with sentinel rows (-1 index / -1 label / zero box) plus an explicit count
tensor, so the whole graph stays one XLA computation.  Selection loops are
`lax.fori_loop`/`top_k` with fixed trip counts; the pooling ops are phrased
as einsums over per-bin weight matrices so they land on the MXU instead of
gather-heavy scalar code.  Only the two inherently host-side ops
(polygon-mask rasterisation, stateful mAP accumulation) go through
jax.pure_callback, mirroring the reference's CPU-only kernels.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op
from .detection import _iou, _nms_fixed


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _pairwise_iou(a, b, normalized=True):
    """IoU matrix of [M,4] x [G,4] boxes. normalized=False adds +1 to
    widths/heights (pixel-box convention, bbox_util.h JaccardOverlap)."""
    off = 0.0 if normalized else 1.0
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def _box_to_delta(ex, gt, weights=None, normalized=False):
    """Encode gt boxes against example boxes (bbox_util.h:54 BoxToDelta)."""
    off = 0.0 if normalized else 1.0
    ew = ex[..., 2] - ex[..., 0] + off
    eh = ex[..., 3] - ex[..., 1] + off
    ecx = ex[..., 0] + 0.5 * ew
    ecy = ex[..., 1] + 0.5 * eh
    gw = gt[..., 2] - gt[..., 0] + off
    gh = gt[..., 3] - gt[..., 1] + off
    gcx = gt[..., 0] + 0.5 * gw
    gcy = gt[..., 1] + 0.5 * gh
    d = jnp.stack([(gcx - ecx) / jnp.maximum(ew, 1e-10),
                   (gcy - ecy) / jnp.maximum(eh, 1e-10),
                   jnp.log(jnp.maximum(gw, 1e-10) / jnp.maximum(ew, 1e-10)),
                   jnp.log(jnp.maximum(gh, 1e-10) / jnp.maximum(eh, 1e-10))],
                  axis=-1)
    if weights is not None:
        d = d / jnp.asarray(weights, d.dtype)
    return d


def _random_topk_mask(key, eligible, k):
    """Pick up to k True positions of `eligible` uniformly at random (the
    XLA analog of the reference's ReservoirSampling): random priority keys
    on the eligible set, prefix of the sorted order.  With key=None picks
    the lowest indices (the deterministic use_random=False path, matching
    the reference's unshuffled resize).  k may be a traced scalar.
    Returns a bool mask."""
    n = eligible.shape[0]
    if key is None:
        pri = jnp.where(eligible,
                        -jnp.arange(n, dtype=jnp.float32), -jnp.inf)
    else:
        pri = jnp.where(eligible,
                        jax.random.uniform(key, (n,)), -jnp.inf)
    k_arr = jnp.minimum(jnp.asarray(k, jnp.int32),
                        jnp.sum(eligible).astype(jnp.int32))
    _, idx = jax.lax.top_k(pri, n)
    sel = jnp.zeros((n,), bool).at[idx].set(jnp.arange(n) < k_arr)
    return sel & eligible


# ---------------------------------------------------------------------------
# matrix_nms — parallel soft-NMS (matrix_nms_op.cc)
# ---------------------------------------------------------------------------

@register_op("matrix_nms", inputs=["BBoxes", "Scores"],
             outputs=["Out", "Index?", "RoisNum?"], grad=None)
def matrix_nms(ins, attrs, ctx):
    """matrix_nms_op.cc — NMSMatrix: per class, sort top nms_top_k by
    score, decay each score by min_j decay(iou_ij, max_iou_j) (gaussian or
    linear), keep decayed > post_threshold; cross-class top keep_top_k.
    Unlike greedy NMS the decay is a closed-form matrix computation — it
    maps to dense [K,K] math on the MXU with no sequential loop at all.
    BBoxes [N,M,4], Scores [N,C,M] -> Out [N,keep,6], Index [N,keep,1],
    RoisNum [N]."""
    boxes = jnp.asarray(ins["BBoxes"])
    scores = jnp.asarray(ins["Scores"])
    score_thr = attrs.get("score_threshold", 0.0)
    post_thr = attrs.get("post_threshold", 0.0)
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    bg = attrs.get("background_label", 0)
    use_gaussian = bool(attrs.get("use_gaussian", False))
    sigma = attrs.get("gaussian_sigma", 2.0)
    normalized = bool(attrs.get("normalized", True))
    N, C, M = scores.shape
    K = min(nms_top_k if nms_top_k > 0 else M, M)
    if keep_top_k < 0:
        keep_top_k = C * K

    def one_class(bx, sc):
        # top-K by score; dead entries (score <= threshold) get -inf keys
        live = jnp.where(sc > score_thr, sc, -jnp.inf)
        top_s, top_i = jax.lax.top_k(live, K)
        valid = jnp.isfinite(top_s)
        b = bx[top_i]
        iou = _pairwise_iou(b, b, normalized)          # [K, K] sorted order
        tril = jnp.tril(jnp.ones((K, K), bool), k=-1)  # j < i
        iou_l = jnp.where(tril, iou, 0.0)
        # iou_max[j] = max IoU of box j vs higher-scored boxes (j'<j)
        iou_max = jnp.max(iou_l, axis=1)
        if use_gaussian:
            decay = jnp.exp((iou_max[None, :] ** 2 - iou_l ** 2) * sigma)
        else:
            decay = (1.0 - iou_l) / jnp.maximum(1.0 - iou_max[None, :],
                                                1e-10)
        decay = jnp.where(tril, decay, 1.0)
        min_decay = jnp.min(decay, axis=1)
        ds = min_decay * jnp.where(valid, top_s, 0.0)
        keep = valid & (ds > post_thr)
        return jnp.where(keep, ds, -1.0), top_i, keep

    def one_image(bx, sc):
        if bg >= 0:
            sc = sc.at[bg].set(-jnp.inf)
        ds, idx, keep = jax.vmap(lambda s: one_class(bx, s))(sc)  # [C,K]
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, K))
        flat_s = jnp.where(keep, ds, -1.0).reshape(-1)
        flat_i = idx.reshape(-1)
        flat_l = labels.reshape(-1)
        k = min(keep_top_k, flat_s.shape[0])
        top_s, sel = jax.lax.top_k(flat_s, k)
        live = top_s >= 0
        out = jnp.concatenate(
            [jnp.where(live, flat_l[sel], -1).astype(bx.dtype)[:, None],
             top_s[:, None],
             jnp.where(live[:, None], bx[flat_i[sel]], 0.0)], axis=1)
        index = jnp.where(live, flat_i[sel], -1).astype(jnp.int32)
        return out, index, jnp.sum(live).astype(jnp.int32)

    out, index, num = jax.vmap(one_image)(boxes, scores)
    return {"Out": out, "Index": index[..., None], "RoisNum": num}


# ---------------------------------------------------------------------------
# locality_aware_nms (locality_aware_nms_op.cc — EAST text detection)
# ---------------------------------------------------------------------------

@register_op("locality_aware_nms", inputs=["BBoxes", "Scores"],
             outputs=["Out", "RoisNum?"], grad=None)
def locality_aware_nms(ins, attrs, ctx):
    """locality_aware_nms_op.cc — a sequential scan first merges runs of
    consecutive overlapping boxes (score-weighted average, scores summed),
    then standard greedy NMS + cross-class keep_top_k.  The merge pass is
    order-dependent by definition, so it is a lax.scan over the M boxes
    (M is a compile-time constant); axis-aligned 4-coord boxes only (the
    reference's polygon path rides the descoped gpc/poly_util)."""
    boxes = jnp.asarray(ins["BBoxes"])
    scores = jnp.asarray(ins["Scores"])
    score_thr = attrs.get("score_threshold", 0.0)
    nms_thr = attrs.get("nms_threshold", 0.3)
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    bg = attrs.get("background_label", -1)
    normalized = bool(attrs.get("normalized", True))
    N, C, M = scores.shape
    per_cls = min(nms_top_k if nms_top_k > 0 else M, M)
    if keep_top_k < 0:
        keep_top_k = C * per_cls

    def merge_pass(bx, sc):
        """Scan boxes in input order; merge box i into the running box when
        IoU > nms_thr, else emit the running box.  Emitted rows are written
        back at the running box's index; swallowed rows get score 0."""
        off = 0.0 if normalized else 1.0

        def iou1(a, b):
            lt = jnp.maximum(a[:2], b[:2])
            rb = jnp.minimum(a[2:], b[2:])
            wh = jnp.maximum(rb - lt + off, 0)
            inter = wh[0] * wh[1]
            aa = jnp.maximum(a[2] - a[0] + off, 0) * \
                jnp.maximum(a[3] - a[1] + off, 0)
            ab = jnp.maximum(b[2] - b[0] + off, 0) * \
                jnp.maximum(b[3] - b[1] + off, 0)
            return jnp.where(aa + ab - inter > 0,
                             inter / jnp.maximum(aa + ab - inter, 1e-10), 0.0)

        def step(carry, i):
            cur_box, cur_s, wp, out_b, out_s = carry
            b, s = bx[i], sc[i]
            ov = iou1(b, cur_box)
            merge = (cur_s > 0) & (ov > nms_thr)
            # weighted merge (PolyWeightedMerge): new = (b*s + cur*cur_s)/(s+cur_s)
            m_box = (b * s + cur_box * cur_s) / jnp.maximum(s + cur_s, 1e-10)
            m_s = cur_s + s
            # on no-merge: flush the finished run at the write cursor
            # (wp <= i always, merges only shrink the emitted count)
            flush = (~merge) & (cur_s > 0)
            out_b = jnp.where(flush, out_b.at[wp].set(cur_box), out_b)
            out_s = jnp.where(flush, out_s.at[wp].set(cur_s), out_s)
            wp = wp + flush.astype(jnp.int32)
            cur_box = jnp.where(merge, m_box, b)
            cur_s = jnp.where(merge, m_s, s)
            return (cur_box, cur_s, wp, out_b, out_s), None

        init = (jnp.zeros((4,), bx.dtype), jnp.zeros((), sc.dtype),
                jnp.zeros((), jnp.int32), jnp.zeros_like(bx),
                jnp.zeros_like(sc))
        (cur_box, cur_s, wp, out_b, out_s), _ = jax.lax.scan(
            step, init, jnp.arange(M))
        # flush the trailing run
        out_b = jnp.where(cur_s > 0, out_b.at[wp].set(cur_box), out_b)
        out_s = jnp.where(cur_s > 0, out_s.at[wp].set(cur_s), out_s)
        return out_b, out_s

    def one_class(bx, sc):
        mb, ms = merge_pass(bx, sc)
        idx, kept = _nms_fixed(mb, jnp.where(ms > score_thr, ms, -1e30),
                               nms_thr, per_cls, score_thr)
        sel = jnp.where(idx[:, None] >= 0, mb[jnp.maximum(idx, 0)], 0.0)
        return kept, sel

    def one_image(bx, sc):
        if bg >= 0:
            sc = sc.at[bg].set(0.0)
        kept, sel = jax.vmap(lambda s: one_class(bx, s))(sc)
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, per_cls))
        flat_s = kept.reshape(-1)
        flat_b = sel.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        k = min(keep_top_k, flat_s.shape[0])
        top_s, sel_i = jax.lax.top_k(flat_s, k)
        live = top_s >= 0
        out = jnp.concatenate(
            [jnp.where(live, flat_l[sel_i], -1).astype(bx.dtype)[:, None],
             jnp.maximum(top_s, -1.0)[:, None], flat_b[sel_i]], axis=1)
        return out, jnp.sum(live).astype(jnp.int32)

    out, num = jax.vmap(one_image)(boxes, scores)
    return {"Out": out, "RoisNum": num}


# ---------------------------------------------------------------------------
# retinanet_detection_output (retinanet_detection_output_op.cc)
# ---------------------------------------------------------------------------

@register_op("retinanet_detection_output",
             inputs=["BBoxes*", "Scores*", "Anchors*", "ImInfo"],
             outputs=["Out", "RoisNum?"], grad=None)
def retinanet_detection_output(ins, attrs, ctx):
    """retinanet_detection_output_op.cc — per FPN level: flatten [A,C]
    sigmoid scores, take top nms_top_k above score_threshold, decode those
    anchors (variance-free, +1 pixel widths, /im_scale, clip to the
    un-scaled image); concat levels, per-class greedy NMS, cross-class top
    keep_top_k.  BBoxes/Scores/Anchors are per-level lists:
    BBoxes[l] [N,A_l,4], Scores[l] [N,A_l,C] -> Out [N,keep,6]."""
    bboxes = [jnp.asarray(b) for b in ins["BBoxes"]]
    scores = [jnp.asarray(s) for s in ins["Scores"]]
    anchors = [jnp.asarray(a) for a in ins["Anchors"]]
    im_info = jnp.asarray(ins["ImInfo"])
    score_thr = attrs.get("score_threshold", 0.05)
    nms_top_k = int(attrs.get("nms_top_k", 1000))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_thr = attrs.get("nms_threshold", 0.3)
    C = scores[0].shape[-1]

    def decode_level(deltas, anc, info):
        """RetinanetDetectionOutput DeltaScoreToPrediction: +1 widths,
        no variances, /im_scale, clip to round(im/scale)-1."""
        ih = jnp.round(info[0] / info[2])
        iw = jnp.round(info[1] / info[2])
        aw = anc[:, 2] - anc[:, 0] + 1
        ah = anc[:, 3] - anc[:, 1] + 1
        acx = anc[:, 0] + aw / 2
        acy = anc[:, 1] + ah / 2
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(deltas[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(deltas[:, 3], 10.0)) * ah
        box = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], axis=1) / info[2]
        hi = jnp.stack([iw - 1, ih - 1, iw - 1, ih - 1])
        return jnp.clip(box, 0.0, hi)

    # loop over batch (N is small for detection inference); per level take
    # top nms_top_k candidates over the flattened [A*C] score grid
    N = scores[0].shape[0]
    outs, nums = [], []
    for n in range(N):
        bx_l = []
        sc_l = []
        for l in range(len(scores)):
            A = scores[l].shape[1]
            flat = jnp.where(scores[l][n] > score_thr, scores[l][n],
                             -jnp.inf).reshape(-1)
            k = min(nms_top_k, A * C)
            top_s, top_i = jax.lax.top_k(flat, k)
            a_idx = top_i // C
            c_idx = top_i % C
            boxes = decode_level(bboxes[l][n][a_idx], anchors[l][a_idx],
                                 im_info[n])
            bx_l.append((boxes,
                         jnp.where(jnp.isfinite(top_s), top_s, -1.0),
                         c_idx))
        b = jnp.concatenate([t[0] for t in bx_l])
        s = jnp.concatenate([t[1] for t in bx_l])
        c = jnp.concatenate([t[2] for t in bx_l])
        per_cls = min(keep_top_k, b.shape[0])

        def one_class(cls, b=b, s=s, c=c, per_cls=per_cls):
            cs = jnp.where((c == cls) & (s > 0), s, -1e30)
            idx, kept = _nms_fixed(b, cs, nms_thr, per_cls, 0.0)
            sel = jnp.where(idx[:, None] >= 0, b[jnp.maximum(idx, 0)], 0.0)
            return kept, sel

        kept, sel = jax.vmap(one_class)(jnp.arange(C))
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, per_cls))
        flat_s = kept.reshape(-1)
        flat_b = sel.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        k = min(keep_top_k, flat_s.shape[0])
        top_s, sel_i = jax.lax.top_k(flat_s, k)
        live = top_s >= 0
        outs.append(jnp.concatenate(
            [jnp.where(live, flat_l[sel_i], -1).astype(b.dtype)[:, None],
             jnp.maximum(top_s, -1.0)[:, None], flat_b[sel_i]], axis=1))
        nums.append(jnp.sum(live).astype(jnp.int32))
    return {"Out": jnp.stack(outs), "RoisNum": jnp.stack(nums)}


# ---------------------------------------------------------------------------
# target_assign (target_assign_op.h)
# ---------------------------------------------------------------------------

@register_op("target_assign",
             inputs=["X", "MatchIndices!", "NegIndices?!"],
             outputs=["Out", "OutWeight"], grad=None)
def target_assign(ins, attrs, ctx):
    """target_assign_op.h — scatter per-image gt rows onto prior slots by
    MatchIndices.  The reference's X is a LoD tensor [sum_gt, P, K]; the
    padded redesign takes X [N, B, K] (per-image gt rows, zero-padded).
    Out[n, m] = X[n, MatchIndices[n, m]] where matched (weight 1), else
    mismatch_value (weight 0).  NegIndices [N, M'] (-1 padded) zeroes the
    listed prior slots to mismatch_value with weight 1."""
    x = jnp.asarray(ins["X"])                      # [N, B, K] or [N, B] -> K=1
    mi = jnp.asarray(ins["MatchIndices"])          # [N, M] int32, -1 = unmatched
    squeeze = x.ndim == 2
    if squeeze:
        x = x[..., None]
    mismatch = attrs.get("mismatch_value", 0)
    matched = mi >= 0
    gathered = jnp.take_along_axis(
        x, jnp.maximum(mi, 0)[..., None], axis=1)
    out = jnp.where(matched[..., None], gathered,
                    jnp.asarray(mismatch, x.dtype))
    wt = matched.astype(jnp.float32)
    neg = ins.get("NegIndices")
    if neg is not None:
        neg = jnp.asarray(neg)
    if neg is not None:
        # rows listed in NegIndices: out = mismatch_value, weight = 1
        M = mi.shape[1]
        neg_mask = jnp.zeros(mi.shape, bool)
        valid = neg >= 0
        n_idx = jnp.broadcast_to(
            jnp.arange(mi.shape[0])[:, None], neg.shape)
        neg_mask = neg_mask.at[n_idx, jnp.clip(neg, 0, M - 1)].max(valid)
        out = jnp.where(neg_mask[..., None],
                        jnp.asarray(mismatch, x.dtype), out)
        wt = jnp.where(neg_mask, 1.0, wt)
    if squeeze:
        out = out[..., 0]
    return {"Out": out, "OutWeight": wt[..., None]}


# ---------------------------------------------------------------------------
# mine_hard_examples (mine_hard_examples_op.cc)
# ---------------------------------------------------------------------------

@register_op("mine_hard_examples",
             inputs=["ClsLoss!", "LocLoss?!", "MatchIndices!", "MatchDist!"],
             outputs=["NegIndices", "UpdatedMatchIndices", "NegNum?"],
             grad=None)
def mine_hard_examples(ins, attrs, ctx):
    """mine_hard_examples_op.cc — OHEM for SSD.  max_negative: among
    unmatched priors with match_dist < neg_dist_threshold, take the
    neg_pos_ratio * #positives highest-cls-loss ones as negatives.
    hard_example: rank ALL priors by cls+loc loss, keep top sample_size;
    positives outside the kept set get match index -1.  NegIndices is
    [N, M] -1-padded (reference: LoD rows) + NegNum counts; indices are
    emitted in ascending prior order (the reference sorts the selected
    set)."""
    cls_loss = jnp.asarray(ins["ClsLoss"])
    loc_loss = ins.get("LocLoss")
    if loc_loss is not None:
        loc_loss = jnp.asarray(loc_loss)
    mi = jnp.asarray(ins["MatchIndices"])
    dist = jnp.asarray(ins["MatchDist"])
    ratio = attrs.get("neg_pos_ratio", 3.0)
    neg_dist_thr = attrs.get("neg_dist_threshold", 0.5)
    sample_size = int(attrs.get("sample_size", 0))
    mining = attrs.get("mining_type", "max_negative")
    N, M = mi.shape

    loss = cls_loss
    if mining == "hard_example" and loc_loss is not None:
        loss = cls_loss + loc_loss

    def one(loss_r, mi_r, dist_r):
        if mining == "max_negative":
            eligible = (mi_r == -1) & (dist_r < neg_dist_thr)
            num_pos = jnp.sum(mi_r != -1)
            neg_sel = jnp.minimum((num_pos * ratio).astype(jnp.int32),
                                  jnp.sum(eligible).astype(jnp.int32))
        else:  # hard_example
            eligible = jnp.ones((M,), bool)
            neg_sel = jnp.minimum(sample_size if sample_size > 0 else M,
                                  M)
            neg_sel = jnp.asarray(neg_sel, jnp.int32)
        key = jnp.where(eligible, loss_r, -jnp.inf)
        _, order = jax.lax.top_k(key, M)
        sel_mask = jnp.zeros((M,), bool).at[order].set(
            (jnp.arange(M) < neg_sel) & jnp.isfinite(key[order]))
        if mining == "hard_example":
            upd = jnp.where((mi_r > -1) & ~sel_mask, -1, mi_r)
            neg_mask = sel_mask & (mi_r == -1)
        else:
            upd = mi_r
            neg_mask = sel_mask
        # ascending prior order, -1 padded
        pos = jnp.where(neg_mask, jnp.arange(M), M)
        srt = jnp.sort(pos)
        neg_idx = jnp.where(srt < M, srt, -1).astype(jnp.int32)
        return neg_idx, upd, jnp.sum(neg_mask).astype(jnp.int32)

    neg_idx, upd, nn = jax.vmap(one)(loss, mi, dist)
    return {"NegIndices": neg_idx, "UpdatedMatchIndices": upd,
            "NegNum": nn}


# ---------------------------------------------------------------------------
# collect_fpn_proposals / distribute_fpn_proposals
# ---------------------------------------------------------------------------

@register_op("collect_fpn_proposals",
             inputs=["MultiLevelRois*", "MultiLevelScores*",
                     "MultiLevelRoIsNum*?!"],
             outputs=["FpnRois", "RoisNum?"], grad=None)
def collect_fpn_proposals(ins, attrs, ctx):
    """collect_fpn_proposals_op.cc — concat per-level RoIs+scores, keep the
    global top post_nms_topN by score.  Padded redesign: each level is
    [N, R_l, 4] with scores [N, R_l] (dead rows score -1); output
    [N, post_nms_topN, 4] + live count."""
    rois_l = [jnp.asarray(r) for r in ins["MultiLevelRois"]]
    scores_l = [jnp.asarray(s) for s in ins["MultiLevelScores"]]
    post_n = int(attrs.get("post_nms_topN", 100))
    rois = jnp.concatenate(rois_l, axis=1)          # [N, R, 4]
    scores = jnp.concatenate(
        [s.reshape(s.shape[0], -1) for s in scores_l], axis=1)

    def one(r, s):
        k = min(post_n, s.shape[0])
        top_s, top_i = jax.lax.top_k(s, k)
        live = top_s > -0.5
        out = jnp.where(live[:, None], r[top_i], 0.0)
        return out, jnp.sum(live).astype(jnp.int32)

    out, num = jax.vmap(one)(rois, scores)
    return {"FpnRois": out, "RoisNum": num}


@register_op("distribute_fpn_proposals",
             inputs=["FpnRois", "RoisNum?!"],
             outputs=["MultiFpnRois*", "RestoreIndex",
                      "MultiLevelRoIsNum*?"], grad=None)
def distribute_fpn_proposals(ins, attrs, ctx):
    """distribute_fpn_proposals_op.cc — route each RoI to FPN level
    floor(refer_level + log2(sqrt(area)/refer_scale)), clamped to
    [min_level, max_level].  Per-level outputs are fixed [N, R, 4] padded
    (a RoI keeps its batch row; rows not on the level are zero), plus
    RestoreIndex mapping the concatenated per-level order back to input
    order.  RoisNum [N] marks live rows of FpnRois [N, R, 4] (area<=0 rows
    are dead padding)."""
    rois = jnp.asarray(ins["FpnRois"])            # [N, R, 4]
    if rois.ndim == 2:
        rois = rois[None]
    min_l = int(attrs.get("min_level", 2))
    max_l = int(attrs.get("max_level", 5))
    refer_l = int(attrs.get("refer_level", 4))
    refer_s = int(attrs.get("refer_scale", 224))
    n_levels = max_l - min_l + 1
    N, R, _ = rois.shape
    num = ins.get("RoisNum")
    if num is not None:
        num = jnp.asarray(num)

    w = rois[..., 2] - rois[..., 0]
    h = rois[..., 3] - rois[..., 1]
    live = (w > 0) & (h > 0)
    if num is not None:
        live = live & (jnp.arange(R)[None, :] < num[:, None])
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-10))
    lvl = jnp.floor(refer_l + jnp.log2(scale / refer_s + 1e-8))
    lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
    lvl = jnp.where(live, lvl, -1)

    multi = []
    nums = []
    for li in range(min_l, max_l + 1):
        on = lvl == li
        multi.append(jnp.where(on[..., None], rois, 0.0))
        nums.append(jnp.sum(on, axis=1).astype(jnp.int32))
    # RestoreIndex: position of each input RoI in the concatenated
    # per-level live-row ordering (reference: argsort of the gather order).
    # Our padded layout keeps rows in place, so restore is the stable
    # argsort by (level, row) over live rows.
    def one(lv):
        order_key = jnp.where(lv >= 0, lv * (R + 1), n_levels * (R + 1)) \
            + jnp.arange(R)
        order = jnp.argsort(order_key)           # concat order -> input row
        restore = jnp.argsort(order)             # input row -> concat pos
        return order.astype(jnp.int32), restore.astype(jnp.int32)

    order, restore = jax.vmap(one)(lvl)
    return {"MultiFpnRois": multi, "RestoreIndex": restore[..., None],
            "MultiLevelRoIsNum": nums}


# ---------------------------------------------------------------------------
# box_decoder_and_assign (box_decoder_and_assign_op.h)
# ---------------------------------------------------------------------------

@register_op("box_decoder_and_assign",
             inputs=["PriorBox!", "PriorBoxVar!", "TargetBox", "BoxScore"],
             outputs=["DecodeBox", "OutputAssignBox"], grad=None)
def box_decoder_and_assign(ins, attrs, ctx):
    """box_decoder_and_assign_op.h — decode per-class deltas against prior
    boxes (+1 pixel widths, var-scaled, exp clipped at box_clip), then
    assign each RoI the decoded box of its argmax non-background class
    (falls back to the prior box when no positive class wins)."""
    prior = jnp.asarray(ins["PriorBox"])    # [R, 4]
    var = jnp.asarray(ins["PriorBoxVar"])   # [4]
    target = jnp.asarray(ins["TargetBox"])  # [R, C*4]
    score = jnp.asarray(ins["BoxScore"])    # [R, C]
    clip = attrs.get("box_clip", 2.302585)  # ln(10)
    R, C = score.shape
    d = target.reshape(R, C, 4)
    pw = prior[:, 2] - prior[:, 0] + 1
    ph = prior[:, 3] - prior[:, 1] + 1
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    dw = jnp.minimum(var[2] * d[..., 2], clip)
    dh = jnp.minimum(var[3] * d[..., 3], clip)
    cx = var[0] * d[..., 0] * pw[:, None] + pcx[:, None]
    cy = var[1] * d[..., 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], axis=-1)
    # argmax over classes j>0 (background 0 excluded)
    sc = score.at[:, 0].set(-jnp.inf)
    best = jnp.argmax(sc, axis=1)
    assign = jnp.where((best > 0)[:, None],
                       jnp.take_along_axis(
                           decoded, best[:, None, None].repeat(4, 2),
                           axis=1)[:, 0], prior)
    return {"DecodeBox": decoded.reshape(R, C * 4),
            "OutputAssignBox": assign}


# ---------------------------------------------------------------------------
# polygon_box_transform (polygon_box_transform_op.cc — EAST geometry head)
# ---------------------------------------------------------------------------

@register_op("polygon_box_transform", inputs=["Input"],
             outputs=["Output"], grad=None)
def polygon_box_transform(ins, attrs, ctx):
    """polygon_box_transform_op.cc — convert EAST per-pixel offsets to
    absolute quad coords: even channels (x offsets) -> 4*w - v, odd
    channels (y offsets) -> 4*h - v."""
    x = jnp.asarray(ins["Input"])                 # [N, G, H, W], G even
    N, G, H, W = x.shape
    ww = jnp.arange(W, dtype=x.dtype) * 4
    hh = jnp.arange(H, dtype=x.dtype)[:, None] * 4
    even = jnp.arange(G) % 2 == 0
    out = jnp.where(even[None, :, None, None], ww - x, hh - x)
    return {"Output": out}


# ---------------------------------------------------------------------------
# psroi_pool (psroi_pool_op.h) — position-sensitive RoI average pooling
# ---------------------------------------------------------------------------

def _bin_weights(start, end, size):
    """[P] bin [start_p, end_p) -> 0/1 overlap weights over `size` integer
    cells: w[p, i] = 1 if floor-start <= i < ceil-end (after clipping)."""
    i = jnp.arange(size, dtype=jnp.float32)
    lo = jnp.clip(jnp.floor(start), 0, size)
    hi = jnp.clip(jnp.ceil(end), 0, size)
    return ((i[None, :] >= lo[:, None]) &
            (i[None, :] < hi[:, None])).astype(jnp.float32)


@register_op("psroi_pool", inputs=["X", "ROIs!", "RoisNum?!"],
             outputs=["Out"])
def psroi_pool(ins, attrs, ctx):
    """psroi_pool_op.h — R-FCN position-sensitive average pooling: output
    channel c at bin (ph,pw) averages input channel (c*PH+ph)*PW+pw over
    the bin's cells.  Phrased as two einsum contractions over per-bin 0/1
    weight vectors so it's dense MXU math instead of per-cell gathers;
    empty bins produce 0 (reference: is_empty -> 0).  ROIs are [R, 5]
    (batch_idx, x1, y1, x2, y2) — the LoD batch mapping carried as an
    explicit leading column in the padded redesign."""
    x = jnp.asarray(ins["X"])        # [N, C_in, H, W]
    rois = jnp.asarray(ins["ROIs"])  # [R, 5]
    ph_n = int(attrs.get("pooled_height", 7))
    pw_n = int(attrs.get("pooled_width", 7))
    scale = attrs.get("spatial_scale", 1.0)
    out_c = int(attrs.get("output_channels"))
    N, C_in, H, W = x.shape
    assert C_in == out_c * ph_n * pw_n, \
        f"psroi_pool: channels {C_in} != {out_c}*{ph_n}*{pw_n}"

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale
        y1 = jnp.round(roi[2]) * scale
        x2 = (jnp.round(roi[3]) + 1.0) * scale
        y2 = (jnp.round(roi[4]) + 1.0) * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bh, bw = rh / ph_n, rw / pw_n
        hs = jnp.arange(ph_n) * bh + y1
        he = (jnp.arange(ph_n) + 1) * bh + y1
        ws = jnp.arange(pw_n) * bw + x1
        we = (jnp.arange(pw_n) + 1) * bw + x1
        wy = _bin_weights(hs, he, H)             # [PH, H]
        wx = _bin_weights(ws, we, W)             # [PW, W]
        cnt = jnp.einsum("ph,qw->pq", wy, wx)    # cells per bin
        feat = x[b].reshape(out_c, ph_n, pw_n, H, W)
        # each output bin reads ITS OWN input channel slice
        s = jnp.einsum("cpqhw,ph,qw->cpq", feat, wy, wx)
        return s / jnp.maximum(cnt, 1.0) * (cnt > 0)

    out = jax.vmap(one)(rois)
    return {"Out": out}


# ---------------------------------------------------------------------------
# prroi_pool (prroi_pool_op.h) — precise RoI pooling (integral of bilinear)
# ---------------------------------------------------------------------------

def _hat_integral(a, b, size):
    """[P] windows [a_p, b_p] -> integral of the unit hat function centred
    at each integer cell i over the window: w[p, i] = ∫_{a_p}^{b_p}
    max(0, 1-|x-i|) dx, in closed form via the hat antiderivative.  The 2-D
    integral of a bilinear interpolant over a box separates into a product
    of these 1-D terms (prroi_pool_op.h PrRoIPoolingMatCalculation computes
    the same quantity cell-by-cell)."""
    i = jnp.arange(size, dtype=jnp.float32)

    def G(t):
        # antiderivative of hat_i evaluated at t: 0 below i-1, quadratics
        # on [i-1,i] and [i,i+1], 1 above
        u = jnp.clip(t[:, None] - (i[None, :] - 1.0), 0.0, 1.0)
        v = jnp.clip(t[:, None] - i[None, :], 0.0, 1.0)
        return 0.5 * u * u + v - 0.5 * v * v

    return G(b) - G(a)


@register_op("prroi_pool", inputs=["X", "ROIs!", "BatchRoINums?!"],
             outputs=["Out"])
def prroi_pool(ins, attrs, ctx):
    """prroi_pool_op.h — Precise RoI Pooling (PrRoI): each output bin is
    the exact integral of the bilinearly-interpolated feature over the bin
    divided by the bin area.  The bilinear interpolant is a sum of
    separable hat functions, so the 2-D integral collapses to
    out[c,p,q] = Σ_h Σ_w f[c,h,w]·Iy[p,h]·Ix[q,w] / area — two dense
    contractions on the MXU.  Differentiable (auto-vjp gives the exact
    continuous gradient, matching the paper's key property).  ROIs [R, 5]
    with leading batch index."""
    x = jnp.asarray(ins["X"])
    rois = jnp.asarray(ins["ROIs"])
    ph_n = int(attrs.get("pooled_height", 7))
    pw_n = int(attrs.get("pooled_width", 7))
    scale = attrs.get("spatial_scale", 1.0)
    N, C, H, W = x.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1 = roi[1] * scale, roi[2] * scale
        x2, y2 = roi[3] * scale, roi[4] * scale
        rw = jnp.maximum(x2 - x1, 0.0)
        rh = jnp.maximum(y2 - y1, 0.0)
        bw, bh = rw / pw_n, rh / ph_n
        ws = jnp.arange(pw_n) * bw + x1
        we = ws + bw
        hs = jnp.arange(ph_n) * bh + y1
        he = hs + bh
        Ix = _hat_integral(ws, we, W)            # [PW, W]
        Iy = _hat_integral(hs, he, H)            # [PH, H]
        area = jnp.maximum(bw * bh, 1e-10)
        return jnp.einsum("chw,ph,qw->cpq", x[b], Iy, Ix) / area

    return {"Out": jax.vmap(one)(rois)}


# ---------------------------------------------------------------------------
# roi_perspective_transform (roi_perspective_transform_op.cc)
# ---------------------------------------------------------------------------

@register_op("roi_perspective_transform", inputs=["X", "ROIs!"],
             outputs=["Out", "Mask?", "TransformMatrix?",
                      "Out2InIdx?", "Out2InWeights?"])
def roi_perspective_transform(ins, attrs, ctx):
    """roi_perspective_transform_op.cc — warp a quad RoI (8 coords:
    x0..y3 clockwise from top-left) to a [transformed_h, transformed_w]
    rectangle by the estimated perspective matrix, bilinear sampling, 0
    outside the image.  Mask marks output cells inside the normalized quad
    extent.  ROIs [R, 9]: (batch_idx, x0, y0, ..., x3, y3)."""
    x = jnp.asarray(ins["X"])        # [N, C, H, W]
    rois = jnp.asarray(ins["ROIs"])  # [R, 9]
    th = int(attrs.get("transformed_height"))
    tw = int(attrs.get("transformed_width"))
    scale = attrs.get("spatial_scale", 1.0)
    N, C, H, W = x.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        rx = roi[1::2] * scale       # [4]
        ry = roi[2::2] * scale
        x0, x1, x2, x3 = rx[0], rx[1], rx[2], rx[3]
        y0, y1, y2, y3 = ry[0], ry[1], ry[2], ry[3]
        len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
        len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
        len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
        len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
        est_h = (len2 + len4) / 2.0
        est_w = (len1 + len3) / 2.0
        norm_h = max(2, th)
        norm_w_f = jnp.round(est_w * (norm_h - 1) /
                             jnp.maximum(est_h, 1e-5)) + 1
        norm_w = jnp.clip(norm_w_f, 2, tw)
        dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
        dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
        den = dx1 * dy2 - dx2 * dy1 + 1e-5
        m6 = (dx3 * dy2 - dx2 * dy3) / den / (norm_w - 1)
        m7 = (dx1 * dy3 - dx3 * dy1) / den / (norm_h - 1)
        m8 = jnp.asarray(1.0, x.dtype)
        m3 = (y1 - y0 + m6 * (norm_w - 1) * y1) / (norm_w - 1)
        m4 = (y3 - y0 + m7 * (norm_h - 1) * y3) / (norm_h - 1)
        m5 = y0
        m0 = (x1 - x0 + m6 * (norm_w - 1) * x1) / (norm_w - 1)
        m1 = (x3 - x0 + m7 * (norm_h - 1) * x3) / (norm_h - 1)
        m2 = x0
        matrix = jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])
        # output grid -> input coords
        oy = jnp.arange(th, dtype=x.dtype)
        ox = jnp.arange(tw, dtype=x.dtype)
        OX, OY = jnp.meshgrid(ox, oy)            # [th, tw]
        wdn = m6 * OX + m7 * OY + m8
        ix = (m0 * OX + m1 * OY + m2) / wdn
        iy = (m3 * OX + m4 * OY + m5) / wdn
        in_quad = (OX <= norm_w - 1) & (OY <= norm_h - 1)
        inside = (ix > -0.5) & (ix < W - 0.5) & \
            (iy > -0.5) & (iy < H - 0.5) & in_quad
        # bilinear sample (0 padding outside)
        x_f = jnp.floor(ix)
        y_f = jnp.floor(iy)
        ax_ = ix - x_f
        ay = iy - y_f

        def tap(yy, xx):
            ok = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            v = x[b][:, jnp.clip(yy, 0, H - 1).astype(jnp.int32),
                     jnp.clip(xx, 0, W - 1).astype(jnp.int32)]
            return jnp.where(ok, v, 0.0)

        v = (tap(y_f, x_f) * (1 - ax_) * (1 - ay) +
             tap(y_f, x_f + 1) * ax_ * (1 - ay) +
             tap(y_f + 1, x_f) * (1 - ax_) * ay +
             tap(y_f + 1, x_f + 1) * ax_ * ay)
        out = jnp.where(inside[None], v, 0.0)
        return out, inside.astype(jnp.int32), matrix

    out, mask, mat = jax.vmap(one)(rois)
    return {"Out": out, "Mask": mask[:, None], "TransformMatrix": mat}


# ---------------------------------------------------------------------------
# rpn_target_assign / retinanet_target_assign (rpn_target_assign_op.cc)
# ---------------------------------------------------------------------------

def _rpn_assign_core(anchors, gt, is_crowd, info, key,
                     straddle_thresh, pos_overlap, neg_overlap,
                     batch_per_im, fg_frac, use_random):
    """Shared anchor->gt matching (rpn_target_assign_op.cc ScoreAssign,
    Detectron convention): fg = (anchor holds some gt's max IoU) or
    (max IoU >= pos_overlap); bg = max IoU < neg_overlap; sample
    fg_frac*batch fg and batch-fg bg.  Returns per-anchor label (-1 ignore
    / 0 bg / 1 fg), matched gt index, and the fg/bg masks."""
    A = anchors.shape[0]
    inside = jnp.ones((A,), bool)
    if straddle_thresh >= 0:
        inside = ((anchors[:, 0] >= -straddle_thresh) &
                  (anchors[:, 1] >= -straddle_thresh) &
                  (anchors[:, 2] < info[1] + straddle_thresh) &
                  (anchors[:, 3] < info[0] + straddle_thresh))
    gt_valid = (~(is_crowd > 0)) & \
        ((gt[:, 2] > gt[:, 0]) | (gt[:, 3] > gt[:, 1]))
    iou = _pairwise_iou(anchors, gt, normalized=True)
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    iou = jnp.where(inside[:, None], iou, -1.0)
    a2g_max = jnp.max(iou, axis=1)
    a2g_arg = jnp.argmax(iou, axis=1)
    g2a_max = jnp.max(iou, axis=0)
    # anchor carries some gt's best overlap (within epsilon)
    eps = 1e-5
    is_best = jnp.any(
        (jnp.abs(iou - g2a_max[None, :]) < eps) & gt_valid[None, :] &
        (g2a_max[None, :] > 0), axis=1)
    fg_cand = inside & (is_best | (a2g_max >= pos_overlap))
    bg_cand = inside & (a2g_max < neg_overlap) & (a2g_max >= 0)
    if batch_per_im > 0 and fg_frac > 0:
        fg_k = int(fg_frac * batch_per_im)
        kf = None
        kb = None
        if use_random and key is not None:
            kf, kb = jax.random.split(key)
        fg_mask = _random_topk_mask(kf if use_random else None, fg_cand,
                                    fg_k)
        n_fg = jnp.sum(fg_mask)
        bg_k = batch_per_im
        bg_mask = _random_topk_mask(kb if use_random else None, bg_cand,
                                    jnp.asarray(batch_per_im) - n_fg)
    else:
        fg_mask = fg_cand
        bg_mask = bg_cand
    # bg overwrites fg on conflict (the reference's two-pass label write)
    fg_mask = fg_mask & ~bg_mask
    return fg_mask, bg_mask, a2g_arg, a2g_max


@register_op("rpn_target_assign",
             inputs=["Anchor!", "GtBoxes!", "IsCrowd!", "ImInfo!"],
             outputs=["LocationIndex", "ScoreIndex", "TargetBBox",
                      "TargetLabel", "BBoxInsideWeight", "LocCount?",
                      "ScoreCount?"], grad=None)
def rpn_target_assign(ins, attrs, ctx):
    """rpn_target_assign_op.cc — sample fg/bg anchors per image and emit
    flattened index/target arrays for the RPN losses.  Fixed-shape
    redesign: LocationIndex/ScoreIndex are [N*rpn_batch_size_per_im]
    padded with -1 (+ LocCount/ScoreCount live counts); indices are global
    (i * A + anchor) like the reference's offset convention.  GtBoxes
    [N, B, 4] zero-padded, IsCrowd [N, B] (pad rows flagged crowd)."""
    anchors = jnp.asarray(ins["Anchor"])          # [A, 4]
    gt = jnp.asarray(ins["GtBoxes"])              # [N, B, 4]
    crowd = jnp.asarray(ins["IsCrowd"])           # [N, B]
    info = jnp.asarray(ins["ImInfo"])             # [N, 3]
    batch_per_im = int(attrs.get("rpn_batch_size_per_im", 256))
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    pos_ov = attrs.get("rpn_positive_overlap", 0.7)
    neg_ov = attrs.get("rpn_negative_overlap", 0.3)
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)
    use_random = bool(attrs.get("use_random", True))
    N = gt.shape[0]
    A = anchors.shape[0]
    cap = batch_per_im if batch_per_im > 0 else A

    base_key = ctx.key(attrs) if use_random else None

    def one(i, gt_i, crowd_i, info_i):
        key = None
        if base_key is not None:
            key = jax.random.fold_in(base_key, i)
        fg, bg, a2g_arg, _ = _rpn_assign_core(
            anchors, gt_i, crowd_i, info_i, key, straddle, pos_ov, neg_ov,
            batch_per_im, fg_frac, use_random)
        # fixed-size index lists: fg first, then bg (ScoreIndex order)
        fg_pos = jnp.where(fg, jnp.arange(A), A)
        fg_srt = jnp.sort(fg_pos)[:cap]
        n_fg = jnp.sum(fg).astype(jnp.int32)
        loc_idx = jnp.where(fg_srt < A, i * A + fg_srt, -1)
        bg_pos = jnp.where(bg, jnp.arange(A), A)
        bg_srt = jnp.sort(bg_pos)[:cap]
        n_bg = jnp.sum(bg).astype(jnp.int32)
        # score index = fg then bg, padded to cap
        slots = jnp.arange(cap)
        fg_part = jnp.where(slots < jnp.minimum(n_fg, cap), fg_srt, A)
        bg_slot = slots - n_fg
        bg_part = jnp.where((bg_slot >= 0) & (bg_slot < n_bg),
                            bg_srt[jnp.clip(bg_slot, 0, cap - 1)], A)
        sc_local = jnp.where(fg_part < A, fg_part, bg_part)
        score_idx = jnp.where(sc_local < A, i * A + sc_local, -1)
        label = jnp.where(slots < n_fg, 1,
                          jnp.where(sc_local < A, 0, -1)).astype(jnp.int32)
        # bbox targets for the fg slots
        m_gt = gt_i[a2g_arg[jnp.clip(fg_srt, 0, A - 1)]]
        m_anc = anchors[jnp.clip(fg_srt, 0, A - 1)]
        tgt = _box_to_delta(m_anc, m_gt, normalized=False)
        live_loc = (fg_srt < A)[:, None]
        tgt = jnp.where(live_loc, tgt, 0.0)
        inw = jnp.where(live_loc, 1.0, 0.0) * jnp.ones((1, 4))
        n_score = jnp.minimum(n_fg + n_bg, cap).astype(jnp.int32)
        return (loc_idx.astype(jnp.int32), score_idx.astype(jnp.int32),
                tgt, label, inw, jnp.minimum(n_fg, cap).astype(jnp.int32),
                n_score)

    loc, sc, tgt, lbl, inw, nloc, nsc = jax.vmap(one)(
        jnp.arange(N), gt, crowd, info)
    return {"LocationIndex": loc.reshape(-1),
            "ScoreIndex": sc.reshape(-1),
            "TargetBBox": tgt.reshape(-1, 4),
            "TargetLabel": lbl.reshape(-1, 1),
            "BBoxInsideWeight": inw.reshape(-1, 4),
            "LocCount": nloc, "ScoreCount": nsc}


@register_op("retinanet_target_assign",
             inputs=["Anchor!", "GtBoxes!", "GtLabels!", "IsCrowd!",
                     "ImInfo!"],
             outputs=["LocationIndex", "ScoreIndex", "TargetBBox",
                      "TargetLabel", "BBoxInsideWeight",
                      "ForegroundNumber"], grad=None)
def retinanet_target_assign(ins, attrs, ctx):
    """rpn_target_assign_op.cc RetinanetTargetAssignKernel — like RPN
    assign but NO sampling (every fg/bg anchor contributes), labels carry
    the matched gt class (bg = 0), and ForegroundNumber feeds the focal
    loss normalizer.  Outputs fixed [N*A] with -1 padding."""
    anchors = jnp.asarray(ins["Anchor"])
    gt = jnp.asarray(ins["GtBoxes"])
    gt_lbl = jnp.asarray(ins["GtLabels"])         # [N, B] int32 (1..C)
    crowd = jnp.asarray(ins["IsCrowd"])
    info = jnp.asarray(ins["ImInfo"])
    pos_ov = attrs.get("positive_overlap", 0.5)
    neg_ov = attrs.get("negative_overlap", 0.4)
    N = gt.shape[0]
    A = anchors.shape[0]

    def one(i, gt_i, lbl_i, crowd_i, info_i):
        fg, bg, a2g_arg, _ = _rpn_assign_core(
            anchors, gt_i, crowd_i, info_i, None, -1.0, pos_ov, neg_ov,
            0, 0.0, False)
        fg_pos = jnp.where(fg, jnp.arange(A), A)
        fg_srt = jnp.sort(fg_pos)
        n_fg = jnp.sum(fg).astype(jnp.int32)
        # global indices (i * A + local) like rpn_target_assign — the
        # layer wrapper gathers from batch-flattened predictions
        loc_idx = jnp.where(fg_srt < A, i * A + fg_srt, -1)
        slots = jnp.arange(A)
        bg_pos = jnp.where(bg, jnp.arange(A), A)
        bg_srt = jnp.sort(bg_pos)
        n_bg = jnp.sum(bg).astype(jnp.int32)
        bg_slot = slots - n_fg
        bg_part = jnp.where((bg_slot >= 0) & (bg_slot < n_bg),
                            bg_srt[jnp.clip(bg_slot, 0, A - 1)], A)
        sc_local = jnp.where(slots < n_fg, fg_srt, bg_part)
        score_idx = jnp.where(sc_local < A, i * A + sc_local, -1)
        safe = jnp.clip(fg_srt, 0, A - 1)
        safe_sc = jnp.clip(sc_local, 0, A - 1)
        label = jnp.where(slots < n_fg,
                          lbl_i[a2g_arg[safe_sc]].astype(jnp.int32),
                          jnp.where(sc_local < A, 0, -1))
        tgt = _box_to_delta(anchors[safe], gt_i[a2g_arg[safe]],
                            normalized=False)
        live = (fg_srt < A)[:, None]
        return (loc_idx.astype(jnp.int32), score_idx.astype(jnp.int32),
                jnp.where(live, tgt, 0.0), label.astype(jnp.int32),
                jnp.where(live, 1.0, 0.0) * jnp.ones((1, 4)),
                n_fg)

    loc, sc, tgt, lbl, inw, nfg = jax.vmap(one)(
        jnp.arange(N), gt, gt_lbl, crowd, info)
    return {"LocationIndex": loc.reshape(-1),
            "ScoreIndex": sc.reshape(-1),
            "TargetBBox": tgt.reshape(-1, 4),
            "TargetLabel": lbl.reshape(-1, 1),
            "BBoxInsideWeight": inw.reshape(-1, 4),
            "ForegroundNumber": nfg[:, None]}


# ---------------------------------------------------------------------------
# generate_proposal_labels (generate_proposal_labels_op.cc)
# ---------------------------------------------------------------------------

@register_op("generate_proposal_labels",
             inputs=["RpnRois!", "GtClasses!", "IsCrowd!", "GtBoxes!",
                     "ImInfo!"],
             outputs=["Rois", "LabelsInt32", "BboxTargets",
                      "BboxInsideWeights", "BboxOutsideWeights",
                      "RoisNum?"], grad=None)
def generate_proposal_labels(ins, attrs, ctx):
    """generate_proposal_labels_op.cc — second-stage RoI sampling: append
    gts to proposals, match by IoU, sample fg (>= fg_thresh) up to
    fg_fraction*batch and bg (bg_thresh_lo <= iou < bg_thresh_hi) for the
    rest, emit class labels + per-class expanded box targets.  Fixed-shape
    redesign: everything is [N, batch_size_per_im, ...] with RoisNum
    counts; rows beyond the count are zero/label -1.  RpnRois [N, R, 4]
    (image-local coords), GtBoxes [N, B, 4] zero-padded."""
    rois_in = jnp.asarray(ins["RpnRois"])         # [N, R, 4]
    gt_cls = jnp.asarray(ins["GtClasses"])        # [N, B]
    crowd = jnp.asarray(ins["IsCrowd"])           # [N, B]
    gt = jnp.asarray(ins["GtBoxes"])              # [N, B, 4]
    info = jnp.asarray(ins["ImInfo"])             # [N, 3]
    batch = int(attrs.get("batch_size_per_im", 256))
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thr = attrs.get("fg_thresh", 0.5)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    weights = attrs.get("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2])
    class_nums = int(attrs.get("class_nums", 81))
    use_random = bool(attrs.get("use_random", True))
    is_cascade = bool(attrs.get("is_cascade_rcnn", False))
    is_agnostic = bool(attrs.get("is_cls_agnostic", False))
    N, R, _ = rois_in.shape
    B = gt.shape[1]
    base_key = ctx.key(attrs) if use_random else None

    def one(i, rois_i, gt_i, cls_i, crowd_i, info_i):
        # boxes arrive in scaled coords; gts are image coords * im_scale
        # in the reference pipeline — the caller is responsible for a
        # consistent frame, we match them as given.
        if not is_cascade:
            cand = jnp.concatenate([rois_i, gt_i], axis=0)   # [R+B, 4]
        else:
            cand = rois_i
        M = cand.shape[0]
        live_cand = (cand[:, 2] > cand[:, 0]) | (cand[:, 3] > cand[:, 1])
        gt_valid = (~(crowd_i > 0)) & \
            ((gt_i[:, 2] > gt_i[:, 0]) | (gt_i[:, 3] > gt_i[:, 1]))
        iou = _pairwise_iou(cand, gt_i, normalized=True)
        iou = jnp.where(gt_valid[None, :] & live_cand[:, None], iou, -1.0)
        max_ov = jnp.max(iou, axis=1)
        argmax = jnp.argmax(iou, axis=1)
        fg_cand = max_ov >= fg_thr
        bg_cand = (max_ov >= bg_lo) & (max_ov < bg_hi) & live_cand
        fg_k = int(fg_frac * batch)
        key = jax.random.fold_in(base_key, i) if base_key is not None \
            else None
        kf = kb = None
        if key is not None:
            kf, kb = jax.random.split(key)
        fg_mask = _random_topk_mask(kf, fg_cand, fg_k)
        n_fg = jnp.sum(fg_mask).astype(jnp.int32)
        bg_mask = _random_topk_mask(kb, bg_cand,
                                    jnp.asarray(batch) - n_fg)
        n_bg = jnp.sum(bg_mask).astype(jnp.int32)
        # pack fg rows then bg rows into the fixed [batch] output
        fg_pos = jnp.sort(jnp.where(fg_mask, jnp.arange(M), M))[:batch]
        bg_pos = jnp.sort(jnp.where(bg_mask, jnp.arange(M), M))[:batch]
        slots = jnp.arange(batch)
        bg_slot = slots - n_fg
        row = jnp.where(slots < n_fg,
                        fg_pos[jnp.clip(slots, 0, batch - 1)],
                        jnp.where((bg_slot >= 0) & (bg_slot < n_bg),
                                  bg_pos[jnp.clip(bg_slot, 0, batch - 1)],
                                  M))
        live = row < M
        safe = jnp.clip(row, 0, M - 1)
        out_rois = jnp.where(live[:, None], cand[safe], 0.0)
        is_fg = slots < n_fg
        label = jnp.where(is_fg,
                          cls_i[argmax[safe]].astype(jnp.int32),
                          jnp.where(live, 0, -1)).astype(jnp.int32)
        # per-class expanded targets
        tgt = _box_to_delta(cand[safe], gt_i[argmax[safe]],
                            weights=weights, normalized=False)
        tgt = jnp.where(is_fg[:, None], tgt, 0.0)
        slot_cls = jnp.where(is_agnostic, jnp.minimum(label, 1), label)
        onehot = jax.nn.one_hot(jnp.clip(slot_cls, 0, class_nums - 1),
                                class_nums, dtype=tgt.dtype)
        onehot = onehot * is_fg[:, None]
        expanded = (onehot[:, :, None] * tgt[:, None, :]).reshape(
            batch, class_nums * 4)
        inw = (onehot[:, :, None] * jnp.ones((1, 1, 4))).reshape(
            batch, class_nums * 4)
        cnt = jnp.minimum(n_fg + n_bg, batch).astype(jnp.int32)
        return out_rois, label, expanded, inw, inw, cnt

    rois, lbl, tgt, inw, outw, cnt = jax.vmap(one)(
        jnp.arange(N), rois_in, gt, gt_cls, crowd, info)
    return {"Rois": rois, "LabelsInt32": lbl[..., None],
            "BboxTargets": tgt, "BboxInsideWeights": inw,
            "BboxOutsideWeights": outw, "RoisNum": cnt}


# ---------------------------------------------------------------------------
# generate_mask_labels (generate_mask_labels_op.cc) — host rasterisation
# ---------------------------------------------------------------------------

def _poly_to_mask_np(polys, box, M):
    """Rasterise polygons (image coords) cropped to `box` onto an MxM grid
    — numpy reimplementation of mask_util.cc Poly2MaskWrapper's
    crop-and-rescale + even-odd fill."""
    x1, y1, x2, y2 = box
    w = max(x2 - x1, 1e-5)
    h = max(y2 - y1, 1e-5)
    yy, xx = np.mgrid[0:M, 0:M]
    # grid cell centers in image coords
    gx = x1 + (xx + 0.5) * w / M
    gy = y1 + (yy + 0.5) * h / M
    mask = np.zeros((M, M), bool)
    for poly in polys:
        if len(poly) < 6:
            continue
        px = np.asarray(poly[0::2], np.float64)
        py = np.asarray(poly[1::2], np.float64)
        # even-odd rule point-in-polygon, vectorised over the grid
        inside = np.zeros((M, M), bool)
        j = len(px) - 1
        for i in range(len(px)):
            cond = ((py[i] > gy) != (py[j] > gy))
            xint = (px[j] - px[i]) * (gy - py[i]) / \
                (py[j] - py[i] + 1e-12) + px[i]
            inside ^= cond & (gx < xint)
            j = i
        mask |= inside
    return mask.astype(np.int32)


@register_op("generate_mask_labels",
             inputs=["ImInfo!", "GtClasses!", "IsCrowd!", "GtSegms!",
                     "Rois!", "LabelsInt32!", "RoisNum?!"],
             outputs=["MaskRois", "RoiHasMaskInt32", "MaskInt32",
                      "MaskRoisNum?"], grad=None)
def generate_mask_labels(ins, attrs, ctx):
    """generate_mask_labels_op.cc — for each fg RoI pick the gt whose box
    best overlaps, rasterise that gt's polygons cropped to the RoI onto a
    resolution x resolution grid, and expand into the class slot
    (MaskInt32 [P, num_classes*M*M], -1 on non-slot cells like the
    reference's mask expansion).  Polygon rasterisation is host-side
    numpy via pure_callback (the reference kernel is CPU-only,
    mask_util.cc) — it feeds the mask head's labels, not the hot path.
    GtSegms is the padded redesign of the LoD polygon nest: [B, V, 2]
    vertex lists with NaN padding, one polygon per gt row."""
    info = jnp.asarray(ins["ImInfo"])             # [N, 3] (unused scale path: coords
    gt_cls = jnp.asarray(ins["GtClasses"])        # [N, B]              already image)
    crowd = jnp.asarray(ins["IsCrowd"])           # [N, B]
    segms = jnp.asarray(ins["GtSegms"])           # [N, B, V, 2] NaN-padded
    rois = jnp.asarray(ins["Rois"])               # [N, P, 4]
    labels = jnp.asarray(ins["LabelsInt32"])      # [N, P, 1] or [N, P]
    num_cls = int(attrs.get("num_classes", 81))
    M = int(attrs.get("resolution", 14))
    N, P = rois.shape[0], rois.shape[1]
    B = segms.shape[1]
    if labels.ndim == 3:
        labels = labels[..., 0]

    def host(info_h, cls_h, crowd_h, segms_h, rois_h, labels_h):
        info_h = np.asarray(info_h)
        out_rois = np.zeros((N, P, 4), np.float32)
        has = np.zeros((N, P), np.int32)
        masks = np.full((N, P, num_cls * M * M), -1, np.int32)
        nums = np.zeros((N,), np.int32)
        for n in range(N):
            k = 0
            for p in range(P):
                lbl = int(labels_h[n, p])
                if lbl <= 0:
                    continue
                roi = rois_h[n, p]
                if roi[2] <= roi[0] and roi[3] <= roi[1]:
                    continue
                # best-overlap gt of the same class
                best, best_ov = -1, -1.0
                for b in range(B):
                    if crowd_h[n, b] > 0 or int(cls_h[n, b]) != lbl:
                        continue
                    poly = segms_h[n, b]
                    pts = poly[~np.isnan(poly[:, 0])]
                    if pts.shape[0] < 3:
                        continue
                    gx1, gy1 = pts.min(0)
                    gx2, gy2 = pts.max(0)
                    ix = max(0, min(roi[2], gx2) - max(roi[0], gx1))
                    iy = max(0, min(roi[3], gy2) - max(roi[1], gy1))
                    inter = ix * iy
                    area = max((gx2 - gx1) * (gy2 - gy1) +
                               (roi[2] - roi[0]) * (roi[3] - roi[1]) -
                               inter, 1e-10)
                    ov = inter / area
                    if ov > best_ov:
                        best_ov, best = ov, b
                if best < 0:
                    continue
                poly = segms_h[n, best]
                pts = poly[~np.isnan(poly[:, 0])]
                m = _poly_to_mask_np([pts.reshape(-1)], roi, M)
                out_rois[n, k] = roi
                has[n, k] = 1
                row = np.full((num_cls, M * M), -1, np.int32)
                row[lbl] = m.reshape(-1)
                masks[n, k] = row.reshape(-1)
                k += 1
            nums[n] = k
        return out_rois, has, masks, nums

    shapes = (jax.ShapeDtypeStruct((N, P, 4), jnp.float32),
              jax.ShapeDtypeStruct((N, P), jnp.int32),
              jax.ShapeDtypeStruct((N, P, num_cls * M * M), jnp.int32),
              jax.ShapeDtypeStruct((N,), jnp.int32))
    out_rois, has, masks, nums = jax.pure_callback(
        host, shapes, info, gt_cls, crowd, segms, rois, labels)
    return {"MaskRois": out_rois, "RoiHasMaskInt32": has[..., None],
            "MaskInt32": masks, "MaskRoisNum": nums}


# ---------------------------------------------------------------------------
# detection_map (operators/detection_map_op.cc) — stateful mAP metric
# ---------------------------------------------------------------------------

@register_op("detection_map",
             inputs=["DetectRes!", "Label!", "HasState?!", "PosCount?!",
                     "TruePos?!", "FalsePos?!"],
             outputs=["AccumPosCount", "AccumTruePos", "AccumFalsePos",
                      "MAP"], grad=None, side_effect=True)
def detection_map(ins, attrs, ctx):
    """detection_map_op.cc — VOC mAP ('integral' or '11point') with
    accumulation state.  Padded redesign of the LoD contract: DetectRes
    [N, D, 6] (label, score, box; label<0 pad), Label [N, G, 6 or 5]
    (label, [difficult], box; label<0 pad).  State tensors are fixed-size:
    PosCount [C,1], TruePos/FalsePos [C, S, 2] (score, tp/fp flag;
    score<0 pad).  Sequential match logic runs host-side via
    pure_callback, like the reference's CPU-only kernel."""
    det = jnp.asarray(ins["DetectRes"])
    label = jnp.asarray(ins["Label"])
    class_num = int(attrs.get("class_num"))
    overlap_t = attrs.get("overlap_threshold", 0.5)
    ap_type = attrs.get("ap_type", "integral")
    eval_difficult = bool(attrs.get("evaluate_difficult", True))
    bg = attrs.get("background_label", 0)
    S = int(attrs.get("state_capacity", 1024))
    has_state = ins.get("HasState")
    pos_in = ins.get("PosCount")
    tp_in = ins.get("TruePos")
    fp_in = ins.get("FalsePos")
    N = det.shape[0]

    def host(det_h, lbl_h, st, pc, tp, fp):
        det_h = np.asarray(det_h)
        lbl_h = np.asarray(lbl_h)
        pos = np.zeros((class_num,), np.int64)
        tps = [[] for _ in range(class_num)]
        fps = [[] for _ in range(class_num)]
        if st is not None and int(np.asarray(st).reshape(-1)[0]) != 0:
            pos += np.asarray(pc).reshape(-1)[:class_num].astype(np.int64)
            for c in range(class_num):
                for s, f in np.asarray(tp)[c]:
                    if s >= 0:
                        tps[c].append((float(s), int(f)))
                for s, f in np.asarray(fp)[c]:
                    if s >= 0:
                        fps[c].append((float(s), int(f)))
        lbl_w = lbl_h.shape[-1]
        for n in range(N):
            gts = lbl_h[n]
            gts = gts[gts[:, 0] >= 0]
            if lbl_w == 6:
                g_lbl = gts[:, 0].astype(int)
                g_dif = gts[:, 1].astype(int)
                g_box = gts[:, 2:6]
            else:
                g_lbl = gts[:, 0].astype(int)
                g_dif = np.zeros_like(g_lbl)
                g_box = gts[:, 1:5]
            for c, dif in zip(g_lbl, g_dif):
                if eval_difficult or not dif:
                    pos[c] += 1
            dets = det_h[n]
            dets = dets[dets[:, 0] >= 0]
            visited = np.zeros(len(g_lbl), bool)
            # per class, score-descending
            for c in range(class_num):
                if c == bg:
                    continue
                rows = dets[dets[:, 0].astype(int) == c]
                rows = rows[np.argsort(-rows[:, 1], kind="stable")]
                g_idx = np.where(g_lbl == c)[0]
                for r in rows:
                    score, box = float(r[1]), r[2:6]
                    best_ov, best_g = -1.0, -1
                    for gi in g_idx:
                        gb = g_box[gi]
                        ix = max(0, min(box[2], gb[2]) -
                                 max(box[0], gb[0]))
                        iy = max(0, min(box[3], gb[3]) -
                                 max(box[1], gb[1]))
                        inter = ix * iy
                        union = max((box[2] - box[0]) * (box[3] - box[1]) +
                                    (gb[2] - gb[0]) * (gb[3] - gb[1]) -
                                    inter, 1e-10)
                        ov = inter / union
                        if ov > best_ov:
                            best_ov, best_g = ov, gi
                    if best_ov > overlap_t:
                        if eval_difficult or not g_dif[best_g]:
                            if not visited[best_g]:
                                tps[c].append((score, 1))
                                visited[best_g] = True
                            else:
                                fps[c].append((score, 1))
                    else:
                        fps[c].append((score, 1))
        # mAP
        aps, n_cls = [], 0
        for c in range(class_num):
            if c == bg or pos[c] == 0:
                continue
            n_cls += 1
            if not tps[c] and not fps[c]:
                aps.append(0.0)
                continue
            events = [(s, 1, f) for s, f in tps[c]] + \
                [(s, 0, f) for s, f in fps[c]]
            events.sort(key=lambda e: -e[0])
            tp_c = np.cumsum([e[1] * e[2] for e in events])
            fp_c = np.cumsum([(1 - e[1]) * e[2] for e in events])
            prec = tp_c / np.maximum(tp_c + fp_c, 1e-10)
            rec = tp_c / pos[c]
            if ap_type == "11point":
                ap = 0.0
                for t in np.arange(0, 1.01, 0.1):
                    p = prec[rec >= t].max() if np.any(rec >= t) else 0.0
                    ap += p / 11.0
            else:
                mrec = np.concatenate([[0], rec])
                ap = float(np.sum((mrec[1:] - mrec[:-1]) * prec))
            aps.append(float(ap))
        m_ap = float(np.mean(aps)) if aps else 0.0
        # pack state back to fixed shapes
        pc_o = pos.reshape(class_num, 1).astype(np.float32)
        tp_o = np.full((class_num, S, 2), -1.0, np.float32)
        fp_o = np.full((class_num, S, 2), -1.0, np.float32)
        for c in range(class_num):
            if len(tps[c]) > S or len(fps[c]) > S:
                # fixed-shape state cannot hold the full event list —
                # the next accumulation step would under-count recall.
                # Keep the HIGHEST-scored events (they dominate the AP
                # integral) and tell the user to raise the capacity.
                import warnings
                warnings.warn(
                    f"detection_map: class {c} accumulated "
                    f"{len(tps[c])} TP / {len(fps[c])} FP events but "
                    f"state_capacity={S}; keeping the top-{S} by score "
                    f"— raise attr state_capacity for exact "
                    f"accumulated mAP", RuntimeWarning)
                tps[c] = sorted(tps[c], key=lambda e: -e[0])[:S]
                fps[c] = sorted(fps[c], key=lambda e: -e[0])[:S]
            for j, (s, f) in enumerate(tps[c][:S]):
                tp_o[c, j] = (s, f)
            for j, (s, f) in enumerate(fps[c][:S]):
                fp_o[c, j] = (s, f)
        return (pc_o, tp_o, fp_o, np.float32(m_ap))

    shapes = (jax.ShapeDtypeStruct((class_num, 1), jnp.float32),
              jax.ShapeDtypeStruct((class_num, S, 2), jnp.float32),
              jax.ShapeDtypeStruct((class_num, S, 2), jnp.float32),
              jax.ShapeDtypeStruct((), jnp.float32))
    args = [det, label,
            has_state if has_state is not None else jnp.zeros((1,),
                                                              jnp.int32),
            pos_in if pos_in is not None else jnp.zeros(
                (class_num, 1), jnp.float32),
            tp_in if tp_in is not None else jnp.full(
                (class_num, S, 2), -1.0, jnp.float32),
            fp_in if fp_in is not None else jnp.full(
                (class_num, S, 2), -1.0, jnp.float32)]
    pc, tp, fp, m_ap = jax.pure_callback(host, shapes, *args)
    return {"AccumPosCount": pc, "AccumTruePos": tp,
            "AccumFalsePos": fp, "MAP": m_ap.reshape(1)}

"""Large-vocab sampled-loss family: nce / hierarchical_sigmoid /
sample_logits.

Reference: /root/reference/paddle/fluid/operators/nce_op.cc:316 +
nce_op.h:84 (sampled sigmoid with NCE correction),
hierarchical_sigmoid_op.cc:60 + hierarchical_sigmoid_op.h:70 (binary-tree
logistic path loss over math/matrix_bit_code.h SimpleCode),
sample_logits_op.cc (per-row class subsampling feeding
softmax_with_cross_entropy).

TPU-native design: each op is ONE traceable jax function — sampling uses
the per-op folded rng key (ctx.key), so the auto-vjp grad replay draws
the SAME negatives as the forward (the reference instead materializes
SampleLabels and threads it to the grad kernel).  The batched
gather+einsum over sampled rows maps onto the MXU as a tall-skinny
matmul; nothing touches the full [B, V] logits except sample_logits,
whose contract (reference parity) takes precomputed logits.
"""
from __future__ import annotations

import math as _pymath

import jax
import jax.numpy as jnp

from ..registry import register_op


def _as_2d_labels(label):
    lab = label.astype(jnp.int32)
    if lab.ndim == 1:
        lab = lab[:, None]
    return lab


def _log_uniform_sample(key, shape, vocab):
    """Zipfian sampler (reference math/sampler.cc LogUniformSampler):
    P(k) = log((k+2)/(k+1)) / log(V+1); inverse-CDF draw."""
    u = jax.random.uniform(key, shape)
    s = jnp.exp(u * _pymath.log(vocab + 1.0)) - 1.0
    return jnp.clip(s.astype(jnp.int32), 0, vocab - 1)


def _log_uniform_prob(k, vocab):
    kf = k.astype(jnp.float32)
    return jnp.log((kf + 2.0) / (kf + 1.0)) / _pymath.log(vocab + 1.0)


@register_op("nce",
             inputs=["Input", "Label!", "Weight", "Bias?",
                     "SampleWeight?!", "CustomDistProbs?!",
                     "CustomDistAlias?!", "CustomDistAliasProbs?!"],
             outputs=["Cost", "SampleLogits", "SampleLabels!"])
def nce(ins, attrs, ctx):
    """nce_op.h:84 — per (row, sampled class): o = sigmoid(x·w_c + b_c),
    b = P(c)·S; cost = -log(o/(o+b)) for true classes,
    -log(b/(o+b)) for negatives; Cost[i] sums the row."""
    x = ins["Input"]                       # [B, D]
    labels = _as_2d_labels(ins["Label"])   # [B, T]
    w = ins["Weight"]                      # [V, D]
    bias = ins.get("Bias")
    vocab = int(attrs["num_total_classes"])
    num_neg = int(attrs.get("num_neg_samples", 10) or 10)
    sampler = int(attrs.get("sampler", 0) or 0)
    bsz, num_true = labels.shape

    key = ctx.key(attrs)
    if sampler == 1:  # log_uniform
        negs = _log_uniform_sample(key, (bsz, num_neg), vocab)
    elif sampler == 2 and ins.get("CustomDistProbs") is not None:
        probs = ins["CustomDistProbs"].astype(jnp.float32)
        negs = jax.random.categorical(
            key, jnp.log(jnp.maximum(probs, 1e-20))[None, :],
            shape=(bsz, num_neg)).astype(jnp.int32)
    else:  # uniform
        negs = jax.random.randint(key, (bsz, num_neg), 0, vocab,
                                  dtype=jnp.int32)
    sample_labels = jnp.concatenate([labels, negs], axis=1)  # [B, T+S]

    w_rows = jnp.take(w, sample_labels, axis=0)              # [B, T+S, D]
    logits = jnp.einsum("bd,bsd->bs", x, w_rows)
    if bias is not None:
        logits = logits + jnp.take(
            bias.reshape(-1), sample_labels, axis=0)
    o = jax.nn.sigmoid(logits)

    if sampler == 1:
        p = _log_uniform_prob(sample_labels, vocab)
    elif sampler == 2 and ins.get("CustomDistProbs") is not None:
        p = jnp.take(ins["CustomDistProbs"].astype(jnp.float32),
                     sample_labels, axis=0)
    else:
        p = jnp.full(sample_labels.shape, 1.0 / vocab, jnp.float32)
    b = (p * num_neg).astype(o.dtype)

    eps = jnp.asarray(1e-12, o.dtype)
    cost_true = -jnp.log(o / (o + b) + eps)
    cost_neg = -jnp.log(b / (o + b) + eps)
    is_true = jnp.arange(sample_labels.shape[1]) < num_true
    cost = jnp.where(is_true[None, :], cost_true, cost_neg)
    total = jnp.sum(cost, axis=1, keepdims=True)             # [B, 1]
    sw = ins.get("SampleWeight")
    if sw is not None:
        total = total * sw.reshape(bsz, 1).astype(total.dtype)
    return {"Cost": total, "SampleLogits": o,
            "SampleLabels": sample_labels.astype(jnp.int64)}


@register_op("hierarchical_sigmoid",
             inputs=["X", "W", "Label!", "PathTable?!", "PathCode?!",
                     "Bias?"],
             outputs=["Out", "PreOut"])
def hierarchical_sigmoid(ins, attrs, ctx):
    """hierarchical_sigmoid_op.h:70 — logistic loss over each label's
    root-to-leaf path in a complete binary tree (SimpleCode,
    matrix_bit_code.h:106: code = label + num_classes, weight row j =
    (code >> (j+1)) - 1, branch bit j = (code >> j) & 1), or over an
    explicit PathTable/PathCode (CustomCode).  Out-of-path positions keep
    pre_out = 0 and contribute log(2) exactly like the reference (the
    kernel's documented TODO — kept for numerical parity)."""
    x = ins["X"]                           # [B, D]
    w = ins["W"]                           # [num_nodes, D]
    label = ins["Label"].reshape(-1).astype(jnp.int32)   # [B]
    bias = ins.get("Bias")
    path_table = ins.get("PathTable")
    if path_table is not None:
        idx = path_table.astype(jnp.int32)               # [B, L]
        bits = ins["PathCode"].astype(x.dtype)           # [B, L]
        valid = idx >= 0
        idx_safe = jnp.where(valid, idx, 0)
    else:
        num_classes = int(attrs["num_classes"])
        code_len = (num_classes - 1).bit_length()  # FindLastSet(V-1)
        c = label + num_classes                    # [B]
        j = jnp.arange(code_len)                   # [L]
        idx = (c[:, None] >> (j[None, :] + 1)) - 1
        valid = (c[:, None] >> (j[None, :] + 1)) > 0
        bits = ((c[:, None] >> j[None, :]) & 1).astype(x.dtype)
        idx_safe = jnp.where(valid, idx, 0)

    w_rows = jnp.take(w, idx_safe, axis=0)               # [B, L, D]
    pre = jnp.einsum("bd,bld->bl", x, w_rows)
    if bias is not None:
        pre = pre + jnp.take(bias.reshape(-1), idx_safe, axis=0)
    pre = jnp.clip(pre, -40.0, 40.0)
    pre = jnp.where(valid, pre, jnp.zeros_like(pre))     # PreOut
    # Σ_j log(1 + e^p) - Σ_{bit_j=1} p  (softrelu CE, reference :118-124)
    loss = jnp.sum(jnp.log1p(jnp.exp(pre)), axis=1, keepdims=True) \
        - jnp.sum(jnp.where(valid, bits * pre, jnp.zeros_like(pre)),
                  axis=1, keepdims=True)
    return {"Out": loss, "PreOut": pre}


@register_op("sample_logits",
             inputs=["Logits", "Labels!", "CustomizedSamples?!",
                     "CustomizedProbabilities?!"],
             outputs=["Samples!", "Probabilities!", "SampledLogits",
                      "SampledLabels!"])
def sample_logits(ins, attrs, ctx):
    """sample_logits_op.cc — subsample num_samples classes per row
    (log-uniform), gather their logits, subtract log Q(class) (sampled
    softmax correction), and remap labels to their position in the
    sampled set.  Feeds softmax_with_cross_entropy for the full
    sampled-softmax loss."""
    logits = ins["Logits"]                 # [B, V]
    labels = _as_2d_labels(ins["Labels"])  # [B, T]
    vocab = logits.shape[1]
    num_samples = int(attrs.get("num_samples", 100) or 100)
    use_custom = ins.get("CustomizedSamples") is not None
    bsz, num_true = labels.shape

    if use_custom:
        samples = ins["CustomizedSamples"].astype(jnp.int32)
        probs = ins["CustomizedProbabilities"].astype(logits.dtype)
    else:
        key = ctx.key(attrs)
        negs = _log_uniform_sample(key, (bsz, num_samples), vocab)
        samples = jnp.concatenate([labels, negs], axis=1)   # [B, T+S]
        probs = _log_uniform_prob(samples, vocab).astype(logits.dtype)

    # NOTE divergence from the reference: negatives are drawn WITH
    # replacement (the reference's uniq=True dedups per row); duplicate
    # columns slightly over-weight their class in the softmax
    # denominator.  Static shapes rule out per-row unique sets; callers
    # needing exact uniq semantics pass CustomizedSamples.
    sampled_logits = jnp.take_along_axis(logits, samples, axis=1)
    if attrs.get("remove_accidental_hits", True):
        # negatives that equal a true label get -1e20 so softmax ignores
        hit = (samples[:, :, None] ==
               labels[:, None, :]).any(-1)
        is_true_col = jnp.arange(samples.shape[1]) < num_true
        kill = hit & ~is_true_col[None, :]
        sampled_logits = jnp.where(kill,
                                   jnp.asarray(-1e20, sampled_logits.dtype),
                                   sampled_logits)
    # sampled-softmax correction: subtract log Q
    sampled_logits = sampled_logits - jnp.log(
        jnp.maximum(probs, jnp.asarray(1e-20, probs.dtype)))
    sampled_labels = jnp.tile(jnp.arange(num_true, dtype=jnp.int64),
                              (bsz, 1))
    return {"Samples": samples.astype(jnp.int64), "Probabilities": probs,
            "SampledLogits": sampled_logits,
            "SampledLabels": sampled_labels}

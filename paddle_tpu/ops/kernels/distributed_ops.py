"""Parameter-server graph ops: send / recv / fetch_barrier.

Reference: /root/reference/paddle/fluid/operators/distributed_ops/
{send_op.cc, recv_op.cc, fetch_barrier_op.cc} — the transpiled trainer
program carries its PS communication as ops (grads flow out through `send`,
fresh params flow in through `recv`).

TPU-native redesign: the trainer step stays ONE jitted XLA computation;
the RPC plane is reached through `jax.experimental.io_callback`
(ordered=True), so XLA schedules the host round-trip inside the step with
send → barrier → recv ordering preserved.  The wire protocol is the
host-side KV service (distributed/ps/kv_server.py), not gRPC — same
capability, one less moving part.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..registry import register_op

_CLIENTS: Dict[Tuple[str, ...], object] = {}


def _client(endpoints, trainer_id=None):
    key = tuple(endpoints)
    if key not in _CLIENTS:
        import os
        from ...distributed.ps.kv_server import KVClient
        c = KVClient(list(endpoints))
        c.wait_server_ready()
        if trainer_id is None:
            # fall back to the launcher env contract if the graph didn't
            # carry the id (hand-built programs)
            trainer_id = os.environ.get("PADDLE_TRAINER_ID")
        if trainer_id is not None:
            c.start_heartbeat(int(trainer_id))
        _CLIENTS[key] = c
    return _CLIENTS[key]


def _reset_clients():
    for c in _CLIENTS.values():
        c.close()  # stops the heartbeat thread too
    _CLIENTS.clear()


@register_op("send", inputs=["X*", "LearningRate?"], outputs=["Dummy?"],
             grad=None, side_effect=True)
def send(ins, attrs, ctx):
    """Push grads (mode grad_sync/grad_async: server applies SGD), initial
    params (mode init: first writer wins), or geo deltas (mode delta)."""
    names = list(attrs["send_varnames"])
    endpoints = tuple(attrs["endpoints"])
    mode = attrs.get("mode", "grad_sync")
    lr_attr = float(attrs.get("lr", 0.01))
    trainer_id = attrs.get("trainer_id")
    xs = list(ins["X"] or [])
    lr_in = ins.get("LearningRate")
    lr_arr = (lr_in.reshape(()) if lr_in is not None
              else jnp.asarray(lr_attr, jnp.float32))

    if mode in ("sparse_grad", "init_sparse"):
        return _send_sparse(names, endpoints, mode, trainer_id, xs, lr_arr,
                            grad_scale=float(attrs.get("grad_scale", 1.0)),
                            sync=bool(attrs.get("sync", False)),
                            sparse_opt=attrs.get("sparse_opt"))

    def host(lr, *arrs):
        c = _client(endpoints, trainer_id)
        for n, a in zip(names, arrs):
            a = np.asarray(a)
            if mode == "init":
                c.init_param(n, a)
            elif mode == "delta":
                c.push_delta(n, a)
            else:
                c.push_grad(n, a, float(lr), sync=(mode == "grad_sync"))
        return np.zeros((1,), np.float32)

    dummy = io_callback(host, jax.ShapeDtypeStruct((1,), jnp.float32),
                        lr_arr, *xs, ordered=True)
    return {"Dummy": dummy}


def _send_sparse(names, endpoints, mode, trainer_id, xs, lr_arr,
                 grad_scale=1.0, sync=False, sparse_opt=None):
    """Row-sharded table traffic: init pushes the full local init split
    across pservers (and installs the server-resident optimizer when
    sparse_opt = {type, beta1, beta2, epsilon} is attached); sparse_grad
    pushes SelectedRows {rows, values} the embedding backward produced
    (reference distributed_lookup_table_op.cc + SelectedRows send path).
    sync=True routes through the server's accumulate-then-apply fanin
    (OP_PUSH_ROWS_SYNC) so averaging no longer trusts client-side
    grad_scale."""
    from ...core.selected_rows import SelectedRows
    flats = []
    for x in xs:
        if isinstance(x, SelectedRows):
            flats.extend([x.rows, x.values])
        elif mode == "init_sparse":
            flats.extend([jnp.zeros((0,), jnp.int32), x])
        else:
            # a dense grad here means the SelectedRows path was lost
            # (densified by an aggregation/pass) — dropping it would
            # silently stop the table from training
            raise TypeError(
                "sparse_grad send expects a SelectedRows gradient; got a "
                "dense array — build the embedding with is_sparse=True "
                "and keep its gradient un-densified")

    def host(lr, *arrs):
        c = _client(endpoints, trainer_id)
        for n, i in zip(names, range(0, len(arrs), 2)):
            rows, vals = np.asarray(arrs[i]), np.asarray(arrs[i + 1])
            if mode == "init_sparse":
                c.init_sparse_table(n, vals)
                if sparse_opt:
                    c.config_sparse_optimizer(
                        n, optimizer=sparse_opt.get("type", "sgd"),
                        beta1=float(sparse_opt.get("beta1", 0.9)),
                        beta2=float(sparse_opt.get("beta2", 0.999)),
                        epsilon=float(sparse_opt.get("epsilon", 1e-8)))
            elif rows.size or sync:
                # sync: even an empty push must reach every shard so the
                # server-side fanin completes
                c.push_sparse(n, rows, vals, float(lr),
                              grad_scale=grad_scale, sync=sync)
        return np.zeros((1,), np.float32)

    dummy = io_callback(host, jax.ShapeDtypeStruct((1,), jnp.float32),
                        lr_arr, *flats, ordered=True)
    return {"Dummy": dummy}


@register_op("recv", inputs=["Dummy?!"], outputs=["Out*"], grad=None,
             side_effect=True)
def recv(ins, attrs, ctx):
    """Pull fresh parameter values; outputs write the param vars (the
    executor threads persistable outputs into the step's new state, same
    path optimizer ops use)."""
    names = list(attrs["recv_varnames"])
    endpoints = tuple(attrs["endpoints"])
    trainer_id = attrs.get("trainer_id")
    shapes = [tuple(s) for s in attrs["shapes"]]
    dtypes = [np.dtype(d) for d in attrs["dtypes"]]

    def host():
        c = _client(endpoints, trainer_id)
        return tuple(np.asarray(c.pull(n), dtype=d)
                     for n, d in zip(names, dtypes))

    result = [jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)]
    outs = io_callback(host, tuple(result), ordered=True)
    return {"Out": list(outs)}


@register_op("distributed_lookup_table", inputs=["Ids!", "W!"],
             outputs=["Out"], grad=None, side_effect=True)
def distributed_lookup_table(ins, attrs, ctx):
    """distributed_lookup_table_op.cc — embedding forward whose table
    lives row-sharded on the pservers: pull exactly the rows this batch
    touches.  The local W shadow supplies shape/dtype only; the grad op
    stays the ordinary lookup_table_grad (SelectedRows), which the
    transpiler routes into a sparse `send` (server applies the row SGD).
    Non-differentiable itself: the transpiled program decouples forward
    pulls from backward pushes exactly like the reference."""
    ids, w = ins["Ids"], ins["W"]
    endpoints = tuple(attrs["endpoints"])
    table_name = attrs["table_name"]
    trainer_id = attrs.get("trainer_id")
    squeeze = ids.ndim > 1 and ids.shape[-1] == 1
    ids_eff = jnp.squeeze(ids, -1) if squeeze else ids
    dim = w.shape[1]
    n_flat = int(np.prod(ids_eff.shape))
    # padding handling matches _embedding (ops/kernels/nn.py): padded
    # positions must return ZERO rows, and their (possibly negative) ids
    # must never hit the modulo sharding
    padding_idx = attrs.get("padding_idx", -1)
    pad_mask = None
    if padding_idx is not None and padding_idx != -1:
        pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
        pad_mask = ids_eff == pid
        ids_eff = jnp.where(pad_mask, 0, ids_eff)

    def host(ids_arr):
        c = _client(endpoints, trainer_id)
        rows = c.pull_sparse(table_name,
                             np.asarray(ids_arr).reshape(-1))
        return rows.astype(np.float32)

    flat = io_callback(host,
                       jax.ShapeDtypeStruct((n_flat, dim), jnp.float32),
                       ids_eff, ordered=True)
    out = flat.reshape(tuple(ids_eff.shape) + (dim,)).astype(w.dtype)
    if pad_mask is not None:
        out = jnp.where(pad_mask[..., None], jnp.zeros_like(out), out)
    return {"Out": out}


# ---------------------------------------------------------------------------
# named-queue ops + the heter activation relay
# ---------------------------------------------------------------------------
# Reference: framework/blocking_queue.h + operators/controlflow/
# queue_generator (enqueue/dequeue used by pipeline/heter trainers), and
# the HeterWrapper activation handoff
# (/root/reference/paddle/fluid/framework/fleet/heter_wrapper.h:54 —
# CPU workers own the sparse side, device workers the dense compute,
# bridged by RPC).  TPU redesign: the queues live on the KV service;
# graph ops reach them through ordered io_callback, so the handoff IS
# part of the compiled step.

@register_op("queue_generator", inputs=[], outputs=[], grad=None,
             side_effect=True)
def queue_generator(ins, attrs, ctx):
    """Declares queue names (attrs[names]); queues materialize lazily on
    the KV server at first push, so this is a declaration-only op kept
    for program parity (reference queue_generator_op.cc)."""
    return {}


@register_op("enqueue", inputs=["X"], outputs=["Out?"], grad=None,
             side_effect=True)
def enqueue(ins, attrs, ctx):
    """enqueue_op.cc analog: push X onto the named KV-server queue."""
    endpoints = tuple(attrs["endpoints"])
    qname = attrs["queue_name"]

    def host(x):
        _client(endpoints).q_push(qname, np.asarray(x))
        return np.zeros((1,), np.float32)

    return {"Out": io_callback(host,
                               jax.ShapeDtypeStruct((1,), jnp.float32),
                               ins["X"], ordered=True)}


@register_op("dequeue", inputs=["Dummy?!"], outputs=["Out"], grad=None,
             side_effect=True)
def dequeue(ins, attrs, ctx):
    """dequeue_op.cc analog: blocking pop (shape/dtype from attrs)."""
    endpoints = tuple(attrs["endpoints"])
    qname = attrs["queue_name"]
    shape = tuple(attrs["shape"])
    dtype = np.dtype(attrs.get("dtype", "float32"))
    timeout = float(attrs.get("timeout", 60.0))

    def host():
        arr = _client(endpoints).q_pop(qname, timeout=timeout)
        return np.ascontiguousarray(arr, dtype=dtype).reshape(shape)

    return {"Out": io_callback(host, jax.ShapeDtypeStruct(shape, dtype),
                               ordered=True)}


@register_op("heter_send", inputs=["X*"], outputs=["Dummy?"], grad=None,
             side_effect=True)
def heter_send(ins, attrs, ctx):
    """Heter handoff, sending side: ship the boundary tensors (CPU
    worker's activations, or the device worker's activation grads) to
    the peer section through per-variable KV queues."""
    endpoints = tuple(attrs["endpoints"])
    names = list(attrs["send_varnames"])
    channel = attrs.get("channel", "heter")
    xs = list(ins["X"] or [])

    def host(*arrs):
        c = _client(endpoints)
        for n, a in zip(names, arrs):
            c.q_push(f"{channel}/{n}", np.asarray(a))
        return np.zeros((1,), np.float32)

    return {"Dummy": io_callback(host,
                                 jax.ShapeDtypeStruct((1,), jnp.float32),
                                 *xs, ordered=True)}


@register_op("heter_recv", inputs=["Dummy?!"], outputs=["Out*"],
             grad=None, side_effect=True)
def heter_recv(ins, attrs, ctx):
    """Heter handoff, receiving side: blocking-pop the peer section's
    boundary tensors."""
    endpoints = tuple(attrs["endpoints"])
    names = list(attrs["recv_varnames"])
    channel = attrs.get("channel", "heter")
    shapes = [tuple(s) for s in attrs["shapes"]]
    dtypes = [np.dtype(d) for d in attrs["dtypes"]]
    timeout = float(attrs.get("timeout", 60.0))

    def host():
        c = _client(endpoints)
        return tuple(
            np.ascontiguousarray(
                c.q_pop(f"{channel}/{n}", timeout=timeout),
                dtype=d).reshape(s)
            for n, s, d in zip(names, shapes, dtypes))

    result = [jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)]
    return {"Out": list(io_callback(host, tuple(result), ordered=True))}


# ---------------------------------------------------------------------------
# large-scale sparse-table op family (pslib analog)
# ---------------------------------------------------------------------------
# Reference: /root/reference/paddle/fluid/operators/distributed_ops/
# lookup_sparse_table_{init,read,write,merge_op,grad_split,fuse_adam,
# fuse_sgd}_op.cc — ops the reference pserver executes against its
# large_scale_kv tables.  Here the live KV server implements the same math
# natively (kv_server.py _apply_sparse_rows); these kernels register the
# op names with identical semantics over in-graph dense tables, so
# pserver-side programs and tests can express the update as ops.

def _selected(ins, rows_key, vals_key):
    from ...core.selected_rows import SelectedRows
    g = ins.get("Grad")
    if isinstance(g, SelectedRows):
        return g.rows.astype(jnp.int32), g.values
    return ins[rows_key].reshape(-1).astype(jnp.int32), ins[vals_key]


def _merge_rows(rows, vals, height):
    """Sum duplicate row ids into a dense [height, D] delta + touched
    mask — the scatter-add phrasing of the reference's MergeAdd pass.
    Negative ids (the -1 padding lookup_sparse_table_merge emits) are
    masked out: JAX negative indexing would wrap them onto the last
    row."""
    valid = rows >= 0
    safe = jnp.where(valid, rows, 0)
    vmask = valid.reshape((-1,) + (1,) * (vals.ndim - 1))
    dense = jnp.zeros((height,) + tuple(vals.shape[1:]), vals.dtype)
    dense = dense.at[safe].add(jnp.where(vmask, vals, 0))
    touched = jnp.zeros((height,), jnp.bool_).at[safe].max(valid)
    return dense, touched


@register_op("lookup_sparse_table_init", inputs=["W"], outputs=["Out"],
             grad=None)
def lookup_sparse_table_init(ins, attrs, ctx):
    """lookup_sparse_table_init_op.cc — zero-init the value table."""
    return {"Out": jnp.zeros_like(ins["W"])}


@register_op("lookup_sparse_table_read", inputs=["W", "Ids!"],
             outputs=["Out"], grad=None)
def lookup_sparse_table_read(ins, attrs, ctx):
    return {"Out": jnp.take(ins["W"], ins["Ids"].reshape(-1).astype(
        jnp.int32), axis=0)}


@register_op("lookup_sparse_table_write", inputs=["W", "Ids!", "Value"],
             outputs=["Out"], grad=None)
def lookup_sparse_table_write(ins, attrs, ctx):
    ids = ins["Ids"].reshape(-1).astype(jnp.int32)
    return {"Out": ins["W"].at[ids].set(ins["Value"])}


@register_op("lookup_sparse_table_merge", inputs=["Ids!", "Value"],
             outputs=["OutIds", "Out"], grad=None)
def lookup_sparse_table_merge(ins, attrs, ctx):
    """Merge duplicate row grads (sum) — fixed-shape variant: output ids
    are the sorted unique ids padded with -1, values aligned."""
    ids = ins["Ids"].reshape(-1).astype(jnp.int32)
    vals = ins["Value"]
    uids, inv = jnp.unique(ids, return_inverse=True, size=ids.shape[0],
                           fill_value=-1)
    merged = jnp.zeros_like(vals).at[inv].add(vals)
    return {"OutIds": uids, "Out": merged}


@register_op("lookup_sparse_table_grad_split",
             inputs=["Grad?", "Row?!", "Value?"],
             outputs=["Row", "Value"], grad=None)
def lookup_sparse_table_grad_split(ins, attrs, ctx):
    """Split a SelectedRows grad into its (rows, values) wire parts."""
    rows, vals = _selected(ins, "Row", "Value")
    return {"Row": rows.astype(jnp.int64), "Value": vals}


@register_op("lookup_sparse_table_fuse_sgd",
             inputs=["Grad?", "Rows?!", "Value?", "Param",
                     "LearningRate!"],
             outputs=["ParamOut"], grad=None, side_effect=True)
def lookup_sparse_table_fuse_sgd(ins, attrs, ctx):
    """lookup_sparse_table_fuse_sgd_op.cc — row SGD on the table named by
    attrs[tablename]; the table rides through ins[Param]."""
    rows, vals = _selected(ins, "Rows", "Value")
    w = ins["Param"]
    lr = jnp.reshape(ins["LearningRate"], ())
    dense, _ = _merge_rows(rows, vals, w.shape[0])
    return {"ParamOut": w - lr * dense}


@register_op("lookup_sparse_table_fuse_adam",
             inputs=["Grad?", "Rows?!", "Value?", "Param", "Moment1",
                     "Moment2", "Beta1Pow!", "Beta2Pow!",
                     "LearningRate!"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out",
                      "Beta1PowOut", "Beta2PowOut"],
             grad=None, side_effect=True)
def lookup_sparse_table_fuse_adam(ins, attrs, ctx):
    """lookup_sparse_table_fuse_adam_op.cc:145 — lazy sparse Adam: only
    touched rows update their moments (mask).  Bias correction uses the
    INPUT beta powers (the reference computes
    lr' = lr * sqrt(1 - beta2_pow) / (1 - beta1_pow) before advancing
    them — same convention as this repo's dense adam kernel, whose
    accumulators start at beta1/beta2)."""
    rows, vals = _selected(ins, "Rows", "Value")
    w = ins["Param"]
    m1, m2 = ins["Moment1"], ins["Moment2"]
    b1p = jnp.reshape(ins["Beta1Pow"], ())
    b2p = jnp.reshape(ins["Beta2Pow"], ())
    lr = jnp.reshape(ins["LearningRate"], ())
    b1 = float(attrs.get("beta1", 0.9))
    b2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g, touched = _merge_rows(rows, vals, w.shape[0])
    mask = touched[:, None]
    m1n = jnp.where(mask, b1 * m1 + (1 - b1) * g, m1)
    m2n = jnp.where(mask, b2 * m2 + (1 - b2) * g * g, m2)
    lr_t = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
    wn = jnp.where(mask, w - lr_t * m1n / (jnp.sqrt(m2n) + eps), w)
    return {"ParamOut": wn, "Moment1Out": m1n, "Moment2Out": m2n,
            "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2}


@register_op("fetch_barrier", inputs=["X*!"], outputs=[], grad=None,
             side_effect=True)
def fetch_barrier(ins, attrs, ctx):
    """fetch_barrier_op.cc parity: ordering marker between send and recv.
    io_callback(ordered=True) already serializes the host round-trips, so
    this is a no-op that exists so transpiled programs keep the reference's
    op sequence."""
    return {}

"""Parameter-server graph ops: send / recv / fetch_barrier.

Reference: /root/reference/paddle/fluid/operators/distributed_ops/
{send_op.cc, recv_op.cc, fetch_barrier_op.cc} — the transpiled trainer
program carries its PS communication as ops (grads flow out through `send`,
fresh params flow in through `recv`).

TPU-native redesign: the trainer step stays ONE jitted XLA computation;
the RPC plane is reached through `jax.experimental.io_callback`
(ordered=True), so XLA schedules the host round-trip inside the step with
send → barrier → recv ordering preserved.  The wire protocol is the
host-side KV service (distributed/ps/kv_server.py), not gRPC — same
capability, one less moving part.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from ..registry import register_op

_CLIENTS: Dict[Tuple[str, ...], object] = {}


def _client(endpoints, trainer_id=None):
    key = tuple(endpoints)
    if key not in _CLIENTS:
        import os
        from ...distributed.ps.kv_server import KVClient
        c = KVClient(list(endpoints))
        c.wait_server_ready()
        if trainer_id is None:
            # fall back to the launcher env contract if the graph didn't
            # carry the id (hand-built programs)
            trainer_id = os.environ.get("PADDLE_TRAINER_ID")
        if trainer_id is not None:
            c.start_heartbeat(int(trainer_id))
        _CLIENTS[key] = c
    return _CLIENTS[key]


def _reset_clients():
    for c in _CLIENTS.values():
        c.close()  # stops the heartbeat thread too
    _CLIENTS.clear()


@register_op("send", inputs=["X*", "LearningRate?"], outputs=["Dummy?"],
             grad=None, side_effect=True)
def send(ins, attrs, ctx):
    """Push grads (mode grad_sync/grad_async: server applies SGD), initial
    params (mode init: first writer wins), or geo deltas (mode delta)."""
    names = list(attrs["send_varnames"])
    endpoints = tuple(attrs["endpoints"])
    mode = attrs.get("mode", "grad_sync")
    lr_attr = float(attrs.get("lr", 0.01))
    trainer_id = attrs.get("trainer_id")
    xs = list(ins["X"] or [])
    lr_in = ins.get("LearningRate")
    lr_arr = (lr_in.reshape(()) if lr_in is not None
              else jnp.asarray(lr_attr, jnp.float32))

    def host(lr, *arrs):
        c = _client(endpoints, trainer_id)
        for n, a in zip(names, arrs):
            a = np.asarray(a)
            if mode == "init":
                c.init_param(n, a)
            elif mode == "delta":
                c.push_delta(n, a)
            else:
                c.push_grad(n, a, float(lr), sync=(mode == "grad_sync"))
        return np.zeros((1,), np.float32)

    dummy = io_callback(host, jax.ShapeDtypeStruct((1,), jnp.float32),
                        lr_arr, *xs, ordered=True)
    return {"Dummy": dummy}


@register_op("recv", inputs=["Dummy?!"], outputs=["Out*"], grad=None,
             side_effect=True)
def recv(ins, attrs, ctx):
    """Pull fresh parameter values; outputs write the param vars (the
    executor threads persistable outputs into the step's new state, same
    path optimizer ops use)."""
    names = list(attrs["recv_varnames"])
    endpoints = tuple(attrs["endpoints"])
    trainer_id = attrs.get("trainer_id")
    shapes = [tuple(s) for s in attrs["shapes"]]
    dtypes = [np.dtype(d) for d in attrs["dtypes"]]

    def host():
        c = _client(endpoints, trainer_id)
        return tuple(np.asarray(c.pull(n), dtype=d)
                     for n, d in zip(names, dtypes))

    result = [jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)]
    outs = io_callback(host, tuple(result), ordered=True)
    return {"Out": list(outs)}


@register_op("fetch_barrier", inputs=["X*!"], outputs=[], grad=None,
             side_effect=True)
def fetch_barrier(ins, attrs, ctx):
    """fetch_barrier_op.cc parity: ordering marker between send and recv.
    io_callback(ordered=True) already serializes the host round-trips, so
    this is a no-op that exists so transpiled programs keep the reference's
    op sequence."""
    return {}

"""Registry-diff mop-up: the last exact-name reference forward ops.

Each kernel cites its reference .cc; `tools/registry_diff.py` is the
scripted check that keeps this residue at zero.  Grouped:

  * contrib CTR ops: batch_fc, rank_attention
  * vision: bilateral_slice, multiclass_nms2 (alias — ours already
    returns Index)
  * quantization tail: dequantize_abs_max, dequantize_log,
    fake_quantize_range_abs_max, lookup_table_dequant
  * DGC sub-ops: dgc_clip_by_norm, dgc_momentum
  * fill family: fill, fill_zeros_like2, gaussian_random_batch_size_like,
    fake_init
  * LoD/array tail: tensor_array_to_tensor, split_selected_rows,
    merge_ids, merge_lod_tensor_infer (alias), conditional_block_infer
    (alias), recurrent (alias of static_rnn — same lax.scan lowering)
  * program plumbing: run_program, delete_var, get_places, send_barrier
  * pslib/BoxPS wire ops: pull_sparse(_v2)/push_sparse(_v2)/push_dense
    (FleetWrapper RPC surface over the KV tier), pull_box_sparse/
    push_box_sparse(+extended) (BoxPS redesigned: on TPU the
    "device-resident PS" IS a dense HBM table param — gather/scatter,
    shardable by the TP machinery), recv_save, send_and_recv
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import get_op_info, register_op


# ---------------------------------------------------------------------------
# contrib CTR ops
# ---------------------------------------------------------------------------
@register_op("batch_fc", inputs=["Input", "W", "Bias"], outputs=["Out"])
def batch_fc(ins, attrs, ctx):
    """batch_fc_op.cc:146 — per-slot batched GEMM:
    Input [S, B, in] x W [S, in, out] + Bias [S, out]."""
    x, w, b = ins["Input"], ins["W"], ins["Bias"]
    return {"Out": jnp.einsum("sbi,sio->sbo", x, w) + b[:, None, :]}


@register_op("rank_attention", inputs=["X", "RankOffset!", "RankParam"],
             outputs=["InputHelp?", "Out", "InsRank?"])
def rank_attention(ins, attrs, ctx):
    """rank_attention_op.cc:167 (CUDA kernels rank_attention.cu.h) — CTR
    rank-aware attention.  RankOffset [ins, 2*max_rank+1] int rows:
    (rank, faster_1, index_1, ..., faster_k, index_k), 1-based with 0 =
    absent.  Each instance gathers max_rank input rows (InputHelp) and a
    per-(rank, faster) block of RankParam
    [max_rank*max_rank*fea, para_col]; Out = sum over blocks."""
    x, ro, param = ins["X"], ins["RankOffset"], ins["RankParam"]
    max_rank = int(attrs.get("MaxRank", 3))
    ins_num, fea = x.shape
    para_col = param.shape[1]
    ro = ro.astype(jnp.int32)
    rank = ro[:, 0]                       # [ins]
    faster = ro[:, 1::2]                  # [ins, max_rank]
    index = ro[:, 2::2]                   # [ins, max_rank]
    valid = (rank > 0)[:, None] & (faster > 0)
    gathered = jnp.where(valid[:, :, None],
                         x[jnp.clip(index, 0, ins_num - 1)], 0.0)
    input_help = gathered.reshape(ins_num, max_rank * fea)
    start = (rank[:, None] - 1) * max_rank + (faster - 1)  # [ins, mr]
    p3 = param.reshape(max_rank * max_rank, fea, para_col)
    pe = p3[jnp.clip(start, 0, p3.shape[0] - 1)]  # [ins, mr, fea, col]
    pe = jnp.where(valid[:, :, None, None], pe, 0.0)
    out = jnp.einsum("imf,imfc->ic", gathered, pe)
    return {"InputHelp": input_help, "Out": out,
            "InsRank": rank.astype(x.dtype)[:, None]}


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------
@register_op("bilateral_slice", inputs=["X", "Grid", "Guide"],
             outputs=["Out"])
def bilateral_slice(ins, attrs, ctx):
    """bilateral_slice_op.cc (HDRNet) — trilinearly sample a bilateral
    grid of affine coefficients at (x, y, guide(x,y)) and apply them to
    the input.  X [N, Ci, H, W], Guide [N, H, W],
    Grid [N, Cg, gd, gh, gw] with Cg = Co*(Ci+1) when has_offset else
    Co*Ci."""
    x, grid, guide = ins["X"], ins["Grid"], ins["Guide"]
    has_offset = bool(attrs.get("has_offset", False))
    n, ci, h, w = x.shape
    cg, gd, gh, gw = grid.shape[1:]
    co = cg // (ci + 1) if has_offset else cg // ci

    gx = (jnp.arange(w, dtype=jnp.float32) + 0.5) * gw / w - 0.5
    gy = (jnp.arange(h, dtype=jnp.float32) + 0.5) * gh / h - 0.5
    gz = guide.astype(jnp.float32) * gd - 0.5          # [N, H, W]

    def axis_weights(g, size):
        lo = jnp.floor(g).astype(jnp.int32)
        frac = g - lo
        return (jnp.clip(lo, 0, size - 1), jnp.clip(lo + 1, 0, size - 1),
                1.0 - frac, frac)

    x0, x1, wx0, wx1 = axis_weights(gx, gw)            # [W]
    y0, y1, wy0, wy1 = axis_weights(gy, gh)            # [H]
    z0, z1, wz0, wz1 = axis_weights(gz, gd)            # [N, H, W]

    def sample(zi):
        # grid[n, :, zi, yj, xk] for all 4 (y, x) corners -> [N,Cg,H,W]
        def corner(yj, xk, wy, wx):
            g = grid[jnp.arange(n)[:, None, None, None],
                     jnp.arange(cg)[None, :, None, None],
                     zi[:, None, :, :],
                     yj[None, None, :, None],
                     xk[None, None, None, :]]
            return g * wy[None, None, :, None] * wx[None, None, None, :]
        return (corner(y0, x0, wy0, wx0) + corner(y0, x1, wy0, wx1)
                + corner(y1, x0, wy1, wx0) + corner(y1, x1, wy1, wx1))

    coeff = sample(z0) * wz0[:, None] + sample(z1) * wz1[:, None]
    if has_offset:
        coeff = coeff.reshape(n, co, ci + 1, h, w)
        out = jnp.einsum("ncihw,nihw->nchw", coeff[:, :, :ci], x) \
            + coeff[:, :, ci]
    else:
        coeff = coeff.reshape(n, co, ci, h, w)
        out = jnp.einsum("ncihw,nihw->nchw", coeff, x)
    return {"Out": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# quantization tail
# ---------------------------------------------------------------------------
@register_op("dequantize_abs_max", inputs=["X!", "Scale!"],
             outputs=["Out"], grad=None)
def dequantize_abs_max(ins, attrs, ctx):
    """dequantize_abs_max_op.cc — int8 -> float via out = x*scale/range."""
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": ins["X"].astype(jnp.float32)
            * (ins["Scale"].reshape(()) / max_range)}


@register_op("dequantize_log", inputs=["X!", "Dict!"], outputs=["Out"],
             grad=None)
def dequantize_log(ins, attrs, ctx):
    """dequantize_log_op.cc — 4-bit log-quantized weights: negative codes
    index the dict with sign flip (x<0 -> -dict[x+128] else dict[x])."""
    x = ins["X"].astype(jnp.int32)
    d = ins["Dict"].reshape(-1)
    neg = x < 0
    idx = jnp.where(neg, x + 128, x)
    val = d[jnp.clip(idx, 0, d.shape[0] - 1)]
    return {"Out": jnp.where(neg, -val, val)}


@register_op("fake_quantize_range_abs_max",
             inputs=["X", "InScale!", "Iter?!"],
             outputs=["Out", "OutScale", "OutScales?", "OutIter?"],
             grad=None)
def fake_quantize_range_abs_max(ins, attrs, ctx):
    """fake_quantize_op.cc FakeQuantizeRangeAbsMax — windowed max-abs
    scale: at train the scale is max(cur_abs_max, in_scale); at is_test
    the recorded InScale is used unchanged."""
    x = ins["X"]
    bits = int(attrs.get("bit_length", 8))
    bound = float((1 << (bits - 1)) - 1)
    in_scale = ins["InScale"].reshape(())
    if attrs.get("is_test"):
        scale = in_scale
    else:
        scale = jnp.maximum(jnp.max(jnp.abs(x)), in_scale)
    q = jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-8) * bound),
                 -bound, bound) * scale / bound
    it = ins.get("Iter")
    outs = {"Out": q, "OutScale": scale.reshape((1,))}
    if it is not None:
        outs["OutIter"] = it + 1
    return outs


@register_op("lookup_table_dequant", inputs=["W!", "Ids!"],
             outputs=["Out"], grad=None)
def lookup_table_dequant(ins, attrs, ctx):
    """lookup_table_dequant_op.h:40 — embedding rows stored quantized:
    each float32 row = [min, max, packed uint8 codes...]; out =
    (max-min)/2^bits * code + min."""
    w, ids = ins["W"], ins["Ids"].reshape(-1).astype(jnp.int32)
    pow_2_bits = float(1 << int(attrs.get("quant_bits", 8)))
    rows = w[ids]                                    # [n, quant_number]
    mn, mx = rows[:, 0:1], rows[:, 1:2]
    codes = jax.lax.bitcast_convert_type(
        rows[:, 2:], jnp.uint8).reshape(rows.shape[0], -1)
    scale = (mx - mn) / pow_2_bits
    out = scale * codes.astype(jnp.float32) + mn
    return {"Out": out}


# ---------------------------------------------------------------------------
# DGC sub-ops
# ---------------------------------------------------------------------------
@register_op("dgc_clip_by_norm", inputs=["X", "current_step!"],
             outputs=["Out"], grad=None)
def dgc_clip_by_norm(ins, attrs, ctx):
    """dgc_clip_by_norm_op.cc — clip_by_norm that only engages after
    rampup_begin_step."""
    x = ins["X"]
    max_norm = float(attrs.get("max_norm", 1.0))
    begin = float(attrs.get("rampup_begin_step", 0.0))
    step = ins["current_step"].reshape(())
    norm = jnp.sqrt(jnp.sum(x * x))
    clipped = x * jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    return {"Out": jnp.where(step >= begin, clipped, x)}


@register_op("dgc_momentum",
             inputs=["Param", "Grad", "Velocity", "LearningRate!",
                     "current_step!", "nranks?!"],
             outputs=["ParamOut", "VelocityOut"], grad=None,
             side_effect=True)
def dgc_momentum(ins, attrs, ctx):
    """dgc_momentum_op.h:64 — momentum before rampup_begin_step, plain
    SGD after (DGC's sparse allreduce already folds momentum in)."""
    p, g, v = ins["Param"], ins["Grad"], ins["Velocity"]
    lr = ins["LearningRate"].reshape(())
    mu = float(attrs.get("mu", 0.9))
    nesterov = bool(attrs.get("use_nesterov", False))
    begin = float(attrs.get("rampup_begin_step", 0.0))
    step = ins["current_step"].reshape(())
    v_new = mu * v + g
    p_mom = p - lr * (g + mu * v_new if nesterov else v_new)
    p_sgd = p - lr * g
    use_mom = step < begin
    return {"ParamOut": jnp.where(use_mom, p_mom, p_sgd),
            "VelocityOut": jnp.where(use_mom, v_new, v)}


# ---------------------------------------------------------------------------
# fill family
# ---------------------------------------------------------------------------
@register_op("fill", inputs=[], outputs=["Out"], grad=None)
def fill(ins, attrs, ctx):
    """fill_op.cc — constant tensor from an attr-carried value list."""
    from ...core.dtype import np_dtype
    shape = [int(s) for s in attrs.get("shape", [1])]
    dtype = np_dtype(attrs.get("dtype", "float32"))
    value = np.asarray(attrs.get("value", [0.0]), dtype).reshape(shape)
    return {"Out": jnp.asarray(value)}


@register_op("fill_zeros_like2", inputs=["X"], outputs=["Out"], grad=None)
def fill_zeros_like2(ins, attrs, ctx):
    """fill_zeros_like2_op.cc — zeros_like with an explicit dtype attr."""
    from ...core.dtype import np_dtype
    dtype = attrs.get("dtype")
    x = ins["X"]
    return {"Out": (jnp.zeros_like(x) if not dtype
                    else jnp.zeros(x.shape, np_dtype(dtype)))}


@register_op("gaussian_random_batch_size_like", inputs=["Input!"],
             outputs=["Out"], grad=None)
def gaussian_random_batch_size_like(ins, attrs, ctx):
    """gaussian_random_batch_size_like_op.cc — N(mean, std) with the
    batch dim copied from Input."""
    shape = [int(s) for s in attrs.get("shape", [])]
    in_idx = int(attrs.get("input_dim_idx", 0))
    out_idx = int(attrs.get("output_dim_idx", 0))
    shape[out_idx] = ins["Input"].shape[in_idx]
    key = ctx.key(attrs)
    out = jnp.asarray(attrs.get("mean", 0.0), jnp.float32) + \
        jnp.asarray(attrs.get("std", 1.0), jnp.float32) * \
        jax.random.normal(key, tuple(shape), jnp.float32)
    return {"Out": out}


@register_op("fake_init", inputs=[], outputs=["Out"], grad=None)
def fake_init(ins, attrs, ctx):
    """fake_init_op.cc — placeholder init for PS-resident tables (the
    trainer never materializes real values; shape-only zeros here)."""
    from ...core.dtype import np_dtype
    shape = [int(s) for s in attrs.get("shape", [1])]
    return {"Out": jnp.zeros(tuple(shape),
                             np_dtype(attrs.get("dtype", "float32")))}


# ---------------------------------------------------------------------------
# LoD / array / SelectedRows tail
# ---------------------------------------------------------------------------
@register_op("tensor_array_to_tensor", inputs=["X"],
             outputs=["Out", "OutIndex?"], grad=None)
def tensor_array_to_tensor(ins, attrs, ctx):
    """tensor_array_to_tensor_op.cc — stack/concat the array's elements
    into one dense tensor (+ per-element sizes)."""
    from .tensor_array import TensorArrayVal
    arr = ins["X"]
    buf = arr.buffer if isinstance(arr, TensorArrayVal) else \
        jnp.asarray(arr)
    axis = int(attrs.get("axis", 0))
    if attrs.get("use_stack", False):
        out = jnp.moveaxis(buf, 0, axis) if axis else buf
        sizes = jnp.ones((buf.shape[0],), jnp.int32)
    else:
        out = jnp.concatenate(list(buf), axis=axis)
        sizes = jnp.full((buf.shape[0],), buf.shape[1 + axis]
                         if buf.ndim > 1 else 1, jnp.int32)
    return {"Out": out, "OutIndex": sizes}


@register_op("split_selected_rows", inputs=["X"], outputs=["Out*"],
             grad=None)
def split_selected_rows(ins, attrs, ctx):
    """split_selected_rows_op.cc — route a SelectedRows' rows into
    height-section shards (masked full-shape per shard: XLA-static)."""
    from ...core.selected_rows import SelectedRows
    x = ins["X"]
    sections = [int(s) for s in attrs.get("height_sections", [])]
    if not isinstance(x, SelectedRows):
        raise TypeError("split_selected_rows expects a SelectedRows")
    outs = []
    start = 0
    for sec in sections:
        m = (x.rows >= start) & (x.rows < start + sec)
        rows = jnp.where(m, x.rows - start, 0)
        vals = jnp.where(m.reshape((-1,) + (1,) * (x.values.ndim - 1)),
                         x.values, 0)
        outs.append(SelectedRows(rows, vals, sec))
        start += sec
    return {"Out": outs}


@register_op("merge_ids", inputs=["Ids*!", "Rows*!", "X*"],
             outputs=["Out*"], grad=None)
def merge_ids(ins, attrs, ctx):
    """merge_ids_op.cc — after sharded lookups, realign the per-shard row
    values back to each original Ids order."""
    rows_all = jnp.concatenate([r.reshape(-1) for r in ins["Rows"]])
    vals_all = jnp.concatenate([v for v in ins["X"]])
    order = jnp.argsort(rows_all)
    sorted_rows = rows_all[order]
    outs = []
    for ids in ins["Ids"]:
        flat = ids.reshape(-1)
        pos = jnp.searchsorted(sorted_rows, flat)
        pos = jnp.clip(pos, 0, sorted_rows.shape[0] - 1)
        outs.append(vals_all[order[pos]])
    return {"Out": outs}


def _alias(new_name, of, inputs, outputs, grad=None, side_effect=False):
    info = get_op_info(of)
    register_op(new_name, inputs=inputs, outputs=outputs, grad=grad,
                side_effect=side_effect)(info.kernel)


# the is_test variants run the same lowering here (masking/selection is
# already branch-free) and `recurrent` is the C++ registration name of
# the StaticRNN op (recurrent_op.cc) — same attrs, same lax.scan kernel
_alias("conditional_block_infer", "conditional_block",
       inputs=["Cond!", "Input*"], outputs=["Out*"])
_alias("merge_lod_tensor_infer", "merge_lod_tensor",
       inputs=["X?", "Mask!", "InTrue", "InFalse"], outputs=["Out"])
_alias("multiclass_nms2", "multiclass_nms",
       inputs=["BBoxes", "Scores"],
       outputs=["Out", "Index?", "NmsRoisNum?"])
_alias("recurrent", "static_rnn", inputs=["X*"], outputs=["Out*"])


# ---------------------------------------------------------------------------
# program plumbing
# ---------------------------------------------------------------------------
@register_op("run_program", inputs=["X*", "Params*?"],
             outputs=["Out*", "OutScope?"], side_effect=True)
def run_program(ins, attrs, ctx):
    """run_program_op.cc — execute a sub-block as one op (the reference's
    @to_static ProgramTranslator path; dy2static here records programs
    directly, so this op exists for loaded/translated programs)."""
    from ...static.executor import BlockTracer
    program = getattr(ctx, "program", None)
    if program is None:
        raise RuntimeError("run_program needs a Program on the OpContext")
    sub = program.blocks[int(attrs["sub_block"])]
    env = dict(zip(attrs.get("x_names", []), ins.get("X") or []))
    env.update(zip(attrs.get("param_names", []),
                   ins.get("Params") or []))
    BlockTracer(sub).run(env, ctx)
    return {"Out": [env[n] for n in attrs.get("out_names", [])]}


@register_op("delete_var", inputs=["X*?"], outputs=[], grad=None,
             side_effect=True)
def delete_var(ins, attrs, ctx):
    """delete_var_op.cc frees scope memory mid-program; env entries here
    are SSA values XLA liveness-frees, so this is correct as a no-op."""
    return {}


@register_op("get_places", inputs=[], outputs=["Out"], grad=None)
def get_places(ins, attrs, ctx):
    """get_places_op.cc returned a host PlaceList for ParallelDo; the
    TPU analog of "how many devices" is the mesh/device count."""
    n = int(attrs.get("device_count", 0)) or len(jax.devices())
    return {"Out": jnp.asarray([n], jnp.int64)}


@register_op("send_barrier", inputs=["X*?"], outputs=["Out?"], grad=None,
             side_effect=True)
def send_barrier(ins, attrs, ctx):
    """send_barrier_op.cc — ordering marker between send rounds; ordered
    io_callback already serializes the KV round-trips (fetch_barrier
    doctrine)."""
    return {"Out": jnp.zeros((1,), jnp.float32)}


# ---------------------------------------------------------------------------
# pslib FleetWrapper wire ops (KV-tier lowering) + BoxPS redesign
# ---------------------------------------------------------------------------
def _kv_client(attrs):
    from .distributed_ops import _client
    return _client(tuple(attrs["endpoints"]), attrs.get("trainer_id"))


def _pull_sparse_impl(ins, attrs, ctx):
    """pull_sparse(_v2)_op.cc — FleetWrapper::PullSparseVarsSync
    (fleet_wrapper.h:66): gather rows for every Ids input from the
    PS-resident table.  KV-tier lowering of the pslib RPC."""
    from jax.experimental import io_callback
    names = list(attrs.get("table_names", []))
    dim = int(attrs["EmbeddingDim"]) if "EmbeddingDim" in attrs else \
        int(attrs.get("embedding_dim", 8))
    idss = ins["Ids"]

    outs = []
    for i, ids in enumerate(idss):
        tname = names[i] if i < len(names) else names[0]
        n_flat = int(np.prod(ids.shape))

        def host(ids_arr, tname=tname):
            c = _kv_client(attrs)
            rows = c.pull_sparse(tname,
                                 np.asarray(ids_arr).reshape(-1))
            return rows.astype(np.float32)

        flat = io_callback(
            host, jax.ShapeDtypeStruct((n_flat, dim), jnp.float32),
            ids, ordered=True)
        outs.append(flat.reshape(tuple(ids.shape) + (dim,)))
    return {"Out": outs}


register_op("pull_sparse", inputs=["Ids*!", "W*?!"], outputs=["Out*"],
            grad=None, side_effect=True)(_pull_sparse_impl)
register_op("pull_sparse_v2", inputs=["Ids*!", "W*?!"], outputs=["Out*"],
            grad=None, side_effect=True)(_pull_sparse_impl)


def _push_sparse_impl(ins, attrs, ctx):
    """push_sparse(_v2)_op.cc — FleetWrapper::PushSparseVarsAsync: ship
    per-id grads to the PS table (server applies its optimizer)."""
    from jax.experimental import io_callback
    names = list(attrs.get("table_names", []))
    lr = float(attrs.get("lr", attrs.get("learning_rate", 0.01)))
    flats = []
    for ids, g in zip(ins["Ids"], ins["Grads"]):
        flats += [ids, g]

    def host(*arrs):
        c = _kv_client(attrs)
        for i in range(0, len(arrs), 2):
            tname = names[i // 2] if i // 2 < len(names) else names[0]
            ids = np.asarray(arrs[i]).reshape(-1)
            g = np.asarray(arrs[i + 1]).reshape(ids.size, -1)
            if ids.size:
                c.push_sparse(tname, ids, g, lr)
        return np.zeros((1,), np.float32)

    return {"Out": io_callback(
        host, jax.ShapeDtypeStruct((1,), jnp.float32), *flats,
        ordered=True)}


register_op("push_sparse", inputs=["Ids*!", "Grads*"], outputs=["Out?"],
            grad=None, side_effect=True)(_push_sparse_impl)
register_op("push_sparse_v2", inputs=["Ids*!", "Grads*"],
            outputs=["Out?"], grad=None,
            side_effect=True)(_push_sparse_impl)


@register_op("push_dense", inputs=["Ids*?!", "Grads*"], outputs=[],
             grad=None, side_effect=True)
def push_dense(ins, attrs, ctx):
    """push_dense_op.cc — FleetWrapper::PushDenseVarsAsync: dense grads
    to the PS (server-side SGD), KV push_grad lowering."""
    from jax.experimental import io_callback
    names = list(attrs.get("param_names",
                           attrs.get("table_names", [])))
    lr = float(attrs.get("lr", 0.01))

    def host(*arrs):
        c = _kv_client(attrs)
        for n, g in zip(names, arrs):
            c.push_grad(n, np.asarray(g), lr, sync=False)
        return np.zeros((1,), np.float32)

    io_callback(host, jax.ShapeDtypeStruct((1,), jnp.float32),
                *list(ins["Grads"]), ordered=True)
    return {}


def _box_pull_impl(ins, attrs, ctx):
    """pull_box_sparse_op.cc — BoxPS kept embeddings resident in GPU
    memory (box_wrapper.h:333).  TPU redesign: the "device-resident PS"
    is simply a dense HBM table parameter — pull = gather (and the table
    shards across chips through the ordinary TP machinery instead of a
    bespoke PS runtime)."""
    w = ins["W"]
    return {"Out": [jnp.take(w, ids.reshape(-1).astype(jnp.int32),
                             axis=0).reshape(tuple(ids.shape)
                                             + (w.shape[1],))
                    for ids in ins["Ids"]]}


register_op("pull_box_sparse", inputs=["Ids*!", "W"], outputs=["Out*"],
            grad=None)(_box_pull_impl)
register_op("pull_box_extended_sparse", inputs=["Ids*!", "W"],
            outputs=["Out*"], grad=None)(_box_pull_impl)


def _box_push_impl(ins, attrs, ctx):
    """push_box_sparse_op.cc — the matching scatter-apply: rows -= lr*g
    onto the HBM-resident table (one fused XLA scatter-add)."""
    w = ins["W"]
    lr = float(attrs.get("lr", 1.0))
    for ids, g in zip(ins["Ids"], ins["Grads"]):
        w = w.at[ids.reshape(-1).astype(jnp.int32)].add(
            -lr * g.reshape(-1, w.shape[1]).astype(w.dtype))
    return {"Out": w}


register_op("push_box_sparse", inputs=["Ids*!", "Grads*", "W"],
            outputs=["Out"], grad=None, side_effect=True)(_box_push_impl)
register_op("push_box_extended_sparse",
            inputs=["Ids*!", "Grads*", "W"], outputs=["Out"], grad=None,
            side_effect=True)(_box_push_impl)


@register_op("recv_save", inputs=[], outputs=[], grad=None,
             side_effect=True)
def recv_save(ins, attrs, ctx):
    """recv_save_op.cc — pull params straight from the pservers onto
    disk (large-model save path that never stages through the trainer
    graph)."""
    from jax.experimental import io_callback

    def host():
        import os
        c = _kv_client(attrs)
        path = attrs["file_path"]
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        blobs = {}
        for n in attrs.get("varnames", []):
            if attrs.get("is_sparse"):
                height = int(attrs.get("height", 0))
                blobs[n] = c.pull_sparse(n, np.arange(height))
            else:
                blobs[n] = c.pull(n)
        np.savez(path, **blobs)
        return np.zeros((1,), np.float32)

    io_callback(host, jax.ShapeDtypeStruct((1,), jnp.float32),
                ordered=True)
    return {}


@register_op("send_and_recv", inputs=["X*"], outputs=["Out*"],
             grad=None, side_effect=True)
def send_and_recv(ins, attrs, ctx):
    """send_and_recv_op.cc — the heter round trip as ONE op: ship the
    inputs to the peer section and block for its replies (KV named
    queues, the heter_send/heter_recv pair fused)."""
    from jax.experimental import io_callback
    send_names = list(attrs.get("send_var_name",
                                attrs.get("send_varnames", [])))
    recv_names = list(attrs.get("recv_var_name",
                                attrs.get("recv_varnames", [])))
    channel = attrs.get("channel", "heter")
    timeout = float(attrs.get("timeout", 60.0))
    shapes = [tuple(int(x) for x in s) for s in attrs["shapes"]]
    dtypes = [np.dtype(d) for d in attrs["dtypes"]]

    def host(*arrs):
        c = _kv_client(attrs)
        for n, a in zip(send_names, arrs):
            c.q_push(f"{channel}/{n}", np.asarray(a))
        return tuple(
            np.ascontiguousarray(
                c.q_pop(f"{channel}/{n}", timeout=timeout),
                dtype=d).reshape(s)
            for n, s, d in zip(recv_names, shapes, dtypes))

    result = [jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)]
    outs = io_callback(host, tuple(result), *list(ins["X"] or []),
                       ordered=True)
    return {"Out": list(outs)}


@register_op("deformable_psroi_pooling",
             inputs=["Input", "ROIs!", "Trans"],
             outputs=["Out", "TopCount?"])
def deformable_psroi_pooling(ins, attrs, ctx):
    """deformable_psroi_pooling_op.cc:323 (.h:58 CPU kernel) — deformable
    position-sensitive ROI pooling: each bin's sampling window shifts by
    a learned per-part offset (Trans), samples bilinearly
    sample_per_part^2 points and averages the in-bounds ones.  ROIs are
    [R, 5] (batch_idx, x1, y1, x2, y2) — the explicit-column LoD
    redesign shared with psroi_pool.  Trans [R, 2*num_classes, part_h,
    part_w]."""
    x, rois = ins["Input"], ins["ROIs"]
    trans = ins.get("Trans")
    no_trans = bool(attrs.get("no_trans", trans is None))
    scale = float(attrs.get("spatial_scale", 1.0))
    out_dim = int(attrs.get("output_dim"))
    gh_n, gw_n = [int(v) for v in attrs.get("group_size", [1, 1])]
    ph_n = int(attrs.get("pooled_height", 7))
    pw_n = int(attrs.get("pooled_width", 7))
    pth, ptw = [int(v) for v in attrs.get("part_size", [ph_n, pw_n])]
    spp = int(attrs.get("sample_per_part", 4))
    trans_std = float(attrs.get("trans_std", 0.1))
    N, C, H, W = x.shape
    num_classes = 1 if no_trans else trans.shape[1] // 2
    ch_per_class = max(1, out_dim // num_classes)

    octop = jnp.arange(out_dim)
    ph = jnp.arange(ph_n)
    pw = jnp.arange(pw_n)
    part_h = jnp.clip((ph * pth) // ph_n, 0, pth - 1)         # [PH]
    part_w = jnp.clip((pw * ptw) // pw_n, 0, ptw - 1)         # [PW]
    gh = jnp.clip((ph * gh_n) // ph_n, 0, gh_n - 1)
    gw = jnp.clip((pw * gw_n) // pw_n, 0, gw_n - 1)
    chan = (octop[:, None, None] * gh_n + gh[None, :, None]) * gw_n \
        + gw[None, None, :]                                    # [OC,PH,PW]
    class_id = octop // ch_per_class                           # [OC]

    def one(roi, tr):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * scale - 0.5
        y1 = jnp.round(roi[2]) * scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_h, bin_w = rh / ph_n, rw / pw_n
        sub_h, sub_w = bin_h / spp, bin_w / spp
        if no_trans:
            tx = jnp.zeros((out_dim, ph_n, pw_n))
            ty = jnp.zeros((out_dim, ph_n, pw_n))
        else:
            t4 = tr.reshape(num_classes, 2, pth, ptw)
            tx = t4[class_id[:, None, None], 0,
                    part_h[None, :, None],
                    part_w[None, None, :]] * trans_std
            ty = t4[class_id[:, None, None], 1,
                    part_h[None, :, None],
                    part_w[None, None, :]] * trans_std
        wstart = pw[None, None, :] * bin_w + x1 + tx * rw     # [OC,PH,PW]
        hstart = ph[None, :, None] * bin_h + y1 + ty * rh
        iw = jnp.arange(spp)
        wpts = wstart[..., None, None] + iw[None, :] * sub_w
        hpts = hstart[..., None, None] + iw[:, None] * sub_h
        valid = (wpts >= -0.5) & (wpts <= W - 0.5) & \
            (hpts >= -0.5) & (hpts <= H - 0.5)
        wc = jnp.clip(wpts, 0.0, W - 1.0)
        hc = jnp.clip(hpts, 0.0, H - 1.0)
        x1i = jnp.floor(wc).astype(jnp.int32)
        y1i = jnp.floor(hc).astype(jnp.int32)
        x2i = jnp.clip(x1i + 1, 0, W - 1)
        y2i = jnp.clip(y1i + 1, 0, H - 1)
        dx = wc - x1i
        dy = hc - y1i
        fm = x[b][chan].reshape(out_dim, ph_n, pw_n, H * W)

        def at(yy, xx):
            idx = (yy * W + xx).reshape(out_dim, ph_n, pw_n, spp * spp)
            return jnp.take_along_axis(fm, idx, axis=3) \
                .reshape(out_dim, ph_n, pw_n, spp, spp)

        val = (at(y1i, x1i) * (1 - dx) * (1 - dy)
               + at(y1i, x2i) * dx * (1 - dy)
               + at(y2i, x1i) * (1 - dx) * dy
               + at(y2i, x2i) * dx * dy)
        val = jnp.where(valid, val, 0.0)
        cnt = jnp.sum(valid, axis=(-2, -1)).astype(x.dtype)
        out = jnp.sum(val, axis=(-2, -1)) / jnp.maximum(cnt, 1.0)
        return out * (cnt > 0), cnt

    tr_in = (jnp.zeros((rois.shape[0], 2, pth, ptw), x.dtype)
             if no_trans else trans)
    out, cnt = jax.vmap(one)(rois, tr_in)
    return {"Out": out.astype(x.dtype), "TopCount": cnt}

"""Core math ops (reference: /root/reference/paddle/fluid/operators/
matmul_op.cc, mul_op.cc, bmm_op.cc, dot_op.cc, sum_op.cc, scale_op.cc,
mean_op.cc, clip_op.cc, cumsum_op.cc, ...).  All kernels are pure jnp —
matmuls land on the MXU; `preferred_element_type` keeps bf16 inputs
accumulating in f32."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op


def _matmul(x, y):
    # Low-precision dots run PLAIN (bf16 x bf16 -> bf16): the TPU MXU
    # accumulates bf16 dots in f32 internally and rounds the output, so an
    # explicit preferred_element_type=f32 + astype round-trip produces
    # IDENTICAL forward numerics — but its vjp routes the cotangent
    # through the f32 convert, silently turning every backward matmul
    # into f32 (measured: 34/51 bench dots f32 = the whole backward,
    # ~4x off bf16 MXU peak on v5e).
    return jnp.matmul(x, y)


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"])
def matmul(ins, attrs, ctx):
    x, y = ins["X"], ins["Y"]
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = _matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": out}


@register_op("matmul_v2", inputs=["X", "Y"], outputs=["Out"])
def matmul_v2(ins, attrs, ctx):
    x, y = ins["X"], ins["Y"]
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2)
    return {"Out": _matmul(x, y)}


@register_op("mul", inputs=["X", "Y"], outputs=["Out"])
def mul(ins, attrs, ctx):
    # flatten X to 2-D at x_num_col_dims, Y at y_num_col_dims (mul_op.cc)
    x, y = ins["X"], ins["Y"]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xnc])), -1))
    y2 = y.reshape((int(np.prod(ys[:ync])), -1))
    out = _matmul(x2, y2)
    return {"Out": out.reshape(xs[:xnc] + ys[ync:])}


@register_op("bmm", inputs=["X", "Y"], outputs=["Out"])
def bmm(ins, attrs, ctx):
    return {"Out": _matmul(ins["X"], ins["Y"])}


@register_op("mv", inputs=["X", "Vec"], outputs=["Out"])
def mv(ins, attrs, ctx):
    return {"Out": _matmul(ins["X"], ins["Vec"])}


@register_op("dot", inputs=["X", "Y"], outputs=["Out"])
def dot(ins, attrs, ctx):
    # dot_op.cc InferShape: out dims = x dims with last dim -> 1
    x, y = ins["X"], ins["Y"]
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=True)}


@register_op("addmm", inputs=["Input", "X", "Y"], outputs=["Out"])
def addmm(ins, attrs, ctx):
    alpha = attrs.get("Alpha", 1.0)
    beta = attrs.get("Beta", 1.0)
    out = alpha * _matmul(ins["X"], ins["Y"]) + beta * ins["Input"]
    return {"Out": out.astype(ins["X"].dtype)}


@register_op("kron", inputs=["X", "Y"], outputs=["Out"])
def kron(ins, attrs, ctx):
    return {"Out": jnp.kron(ins["X"], ins["Y"])}


@register_op("scale", inputs=["X"], outputs=["Out"])
def scale(ins, attrs, ctx):
    from ...core.selected_rows import SelectedRows
    x = ins["X"]
    if isinstance(x, SelectedRows):
        # scale a sparse gradient in place (bias would densify; the only
        # framework use on grads is pure scaling)
        if attrs.get("bias", 0.0) != 0.0:
            raise ValueError("scale(bias!=0) on SelectedRows would densify")
        s = jnp.asarray(attrs.get("scale", 1.0), x.values.dtype)
        return {"Out": SelectedRows(x.rows, x.values * s, x.height)}
    s = jnp.asarray(attrs.get("scale", 1.0), x.dtype)
    b = jnp.asarray(attrs.get("bias", 0.0), x.dtype)
    if attrs.get("bias_after_scale", True):
        return {"Out": x * s + b}
    return {"Out": (x + b) * s}


@register_op("sum", inputs=["X*"], outputs=["Out"])
def sum_op(ins, attrs, ctx):
    from ...core.selected_rows import SelectedRows
    xs = ins["X"]
    if any(isinstance(x, SelectedRows) for x in xs):
        if all(isinstance(x, SelectedRows) for x in xs):
            # gradient aggregation of two sparse lookups on the same table
            # (selected_rows_functor.cc MergeAdd): concatenation IS the sum
            # under scatter-add semantics
            return {"Out": SelectedRows(
                jnp.concatenate([x.rows for x in xs]),
                jnp.concatenate([x.values for x in xs]),
                xs[0].height)}
        xs = [x.to_dense() if isinstance(x, SelectedRows) else x for x in xs]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("mean", inputs=["X"], outputs=["Out"])
def mean(ins, attrs, ctx):
    return {"Out": jnp.mean(ins["X"])}


@register_op("minus", inputs=["X", "Y"], outputs=["Out"])
def minus(ins, attrs, ctx):
    return {"Out": ins["X"] - ins["Y"]}


@register_op("clip", inputs=["X", "Min?!", "Max?!"], outputs=["Out"])
def clip(ins, attrs, ctx):
    lo = ins.get("Min")
    hi = ins.get("Max")
    lo = attrs.get("min", -np.inf) if lo is None else lo
    hi = attrs.get("max", np.inf) if hi is None else hi
    return {"Out": jnp.clip(ins["X"], lo, hi)}


@register_op("clip_by_norm", inputs=["X"], outputs=["Out"])
def clip_by_norm(ins, attrs, ctx):
    x = ins["X"]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32))))
    factor = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": (x.astype(jnp.float32) * factor).astype(x.dtype)}


@register_op("sign", inputs=["X"], outputs=["Out"], grad=None)
def sign(ins, attrs, ctx):
    return {"Out": jnp.sign(ins["X"])}


@register_op("cumsum", inputs=["X"], outputs=["Out"])
def cumsum(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
        if attrs.get("exclusive", False):
            out = out - x
    return {"Out": out}


@register_op("logsumexp", inputs=["X"], outputs=["Out"])
def logsumexp(ins, attrs, ctx):
    axis = attrs.get("axis", None) or attrs.get("dim", None)
    keepdim = attrs.get("keepdim", False)
    if attrs.get("reduce_all", False):
        axis = None
    elif isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    return {"Out": jax.scipy.special.logsumexp(ins["X"], axis=axis,
                                               keepdims=keepdim)}


@register_op("trace", inputs=["Input"], outputs=["Out"])
def trace(ins, attrs, ctx):
    return {"Out": jnp.trace(ins["Input"], offset=attrs.get("offset", 0),
                             axis1=attrs.get("axis1", 0),
                             axis2=attrs.get("axis2", 1))}


@register_op("tril_triu", inputs=["X"], outputs=["Out"])
def tril_triu(ins, attrs, ctx):
    x = ins["X"]
    diag = attrs.get("diagonal", 0)
    if attrs.get("lower", True):
        return {"Out": jnp.tril(x, diag)}
    return {"Out": jnp.triu(x, diag)}


@register_op("cholesky", inputs=["X"], outputs=["Out"])
def cholesky(ins, attrs, ctx):
    x = ins["X"]
    out = jnp.linalg.cholesky(x)
    if not attrs.get("upper", False):
        return {"Out": out}
    return {"Out": jnp.swapaxes(out, -1, -2)}


@register_op("inverse", inputs=["Input"], outputs=["Output"])
def inverse(ins, attrs, ctx):
    return {"Output": jnp.linalg.inv(ins["Input"])}


@register_op("cross", inputs=["X", "Y"], outputs=["Out"])
def cross(ins, attrs, ctx):
    dim = attrs.get("dim", None)
    if dim is None or dim == -100:  # DefaultDim sentinel in reference
        # first axis of size 3
        dim = next(i for i, s in enumerate(ins["X"].shape) if s == 3)
    return {"Out": jnp.cross(ins["X"], ins["Y"], axis=dim)}


@register_op("dist", inputs=["X", "Y"], outputs=["Out"])
def dist(ins, attrs, ctx):
    p = attrs.get("p", 2.0)
    d = (ins["X"] - ins["Y"]).ravel()
    if p == np.inf:
        return {"Out": jnp.max(jnp.abs(d))}
    if p == -np.inf:
        return {"Out": jnp.min(jnp.abs(d))}
    if p == 0:
        return {"Out": jnp.sum(d != 0).astype(d.dtype)}
    return {"Out": jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)}


@register_op("cos_sim", inputs=["X", "Y"], outputs=["Out", "XNorm", "YNorm"])
def cos_sim(ins, attrs, ctx):
    x, y = ins["X"], ins["Y"]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / (xn * yn)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


@register_op("p_norm", inputs=["X"], outputs=["Out"])
def p_norm(ins, attrs, ctx):
    x = ins["X"]
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keepdim = attrs.get("keepdim", False)
    if attrs.get("asvector", False):
        x, axis = x.ravel(), 0
    out = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis,
                            keepdims=keepdim), 1.0 / p)
    return {"Out": out}


@register_op("norm", inputs=["X"], outputs=["Out", "Norm"])
def norm(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / n, "Norm": n}


@register_op("frobenius_norm", inputs=["X"], outputs=["Out"])
def frobenius_norm(ins, attrs, ctx):
    axis = attrs.get("dim", None)
    keepdim = attrs.get("keep_dim", False)
    if attrs.get("reduce_all", False) or axis is None:
        axis = None
    else:
        axis = tuple(axis)
    return {"Out": jnp.sqrt(jnp.sum(jnp.square(ins["X"]), axis=axis,
                                    keepdims=keepdim))}


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def squared_l2_norm(ins, attrs, ctx):
    return {"Out": jnp.sum(jnp.square(ins["X"])).reshape(1)}


@register_op("squared_l2_distance", inputs=["X", "Y"],
             outputs=["sub_result", "Out"])
def squared_l2_distance(ins, attrs, ctx):
    sub = ins["X"] - ins["Y"]
    out = jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)),
                  keepdims=False).reshape(-1, 1)
    return {"sub_result": sub, "Out": out}


@register_op("l1_norm", inputs=["X"], outputs=["Out"])
def l1_norm(ins, attrs, ctx):
    return {"Out": jnp.sum(jnp.abs(ins["X"]))}


@register_op("increment", inputs=["X"], outputs=["Out"], grad=None)
def increment(ins, attrs, ctx):
    return {"Out": ins["X"] + jnp.asarray(attrs.get("step", 1.0),
                                          ins["X"].dtype)}


@register_op("bilinear_tensor_product", inputs=["X", "Y", "Weight", "Bias?"],
             outputs=["Out"])
def bilinear_tensor_product(ins, attrs, ctx):
    x, y, w = ins["X"], ins["Y"], ins["Weight"]
    # w: [out, dx, dy]; out[b,o] = x[b]^T w[o] y[b]
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if ins.get("Bias") is not None:
        out = out + ins["Bias"]
    return {"Out": out}


@register_op("histogram", inputs=["X!"], outputs=["Out"], grad=None)
def histogram(ins, attrs, ctx):
    x = ins["X"].ravel()
    bins = attrs.get("bins", 100)
    lo, hi = attrs.get("min", 0), attrs.get("max", 0)
    out, _ = jnp.histogram(x, bins=bins,
                           range=None if lo == hi == 0 else (lo, hi))
    return {"Out": out.astype(jnp.int64)}


@register_op("allclose", inputs=["Input!", "Other!"], outputs=["Out"],
             grad=None)
def allclose(ins, attrs, ctx):
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    return {"Out": jnp.allclose(ins["Input"], ins["Other"], rtol=rtol,
                                atol=atol,
                                equal_nan=attrs.get("equal_nan", False))}


@register_op("isfinite", inputs=["X!"], outputs=["Out"], grad=None)
def isfinite(ins, attrs, ctx):
    return {"Out": jnp.all(jnp.isfinite(ins["X"])).reshape(1)}


@register_op("isfinite_v2", inputs=["X!"], outputs=["Out"], grad=None)
def isfinite_v2(ins, attrs, ctx):
    return {"Out": jnp.isfinite(ins["X"])}


@register_op("isinf_v2", inputs=["X!"], outputs=["Out"], grad=None)
def isinf_v2(ins, attrs, ctx):
    return {"Out": jnp.isinf(ins["X"])}


@register_op("isnan_v2", inputs=["X!"], outputs=["Out"], grad=None)
def isnan_v2(ins, attrs, ctx):
    return {"Out": jnp.isnan(ins["X"])}


@register_op("einsum", inputs=["Operands*"], outputs=["Out"])
def einsum_op(ins, attrs, ctx):
    """paddle.einsum lowering: one jnp.einsum per equation (XLA emits
    the optimal contraction on the MXU)."""
    return {"Out": jnp.einsum(attrs["equation"], *ins["Operands"])}

"""Reductions (reference: /root/reference/paddle/fluid/operators/reduce_ops/).
Attrs follow the reference: `dim` (list), `keep_dim`, `reduce_all`."""
from __future__ import annotations

import jax.numpy as jnp

from ..registry import register_op


def _axes(x, attrs):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d % x.ndim for d in dim)


def _reduce(name, fn, grad="auto"):
    @register_op(name, inputs=["X"], outputs=["Out"], grad=grad)
    def kernel(ins, attrs, ctx, _fn=fn):
        x = ins["X"]
        out = _fn(x, axis=_axes(x, attrs), keepdims=attrs.get("keep_dim", False))
        return {"Out": out}
    return kernel


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", lambda x, axis, keepdims: jnp.all(x, axis=axis,
                                                        keepdims=keepdims),
        grad=None)
_reduce("reduce_any", lambda x, axis, keepdims: jnp.any(x, axis=axis,
                                                        keepdims=keepdims),
        grad=None)

"""Control/graph-plumbing ops: feed/fetch, compare, logical, select.
(reference: /root/reference/paddle/fluid/operators/controlflow/ — feed_op.cc,
fetch_op.cc, compare_op.cc, logical_op.cc; while/conditional_block are
handled natively by the executor via lax.while_loop/cond, see
core/executor.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..registry import register_op


@register_op("feed", inputs=[], outputs=["Out"], grad=None, side_effect=True)
def feed(ins, attrs, ctx):
    raise RuntimeError("feed op is handled by the executor")


@register_op("fetch", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def fetch(ins, attrs, ctx):
    return {"Out": ins["X"]}


def _cmp(name, fn):
    @register_op(name, inputs=["X!", "Y!"], outputs=["Out"], grad=None)
    def kernel(ins, attrs, ctx, _fn=fn):
        return {"Out": _fn(ins["X"], ins["Y"])}
    return kernel


_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)


@register_op("equal_all", inputs=["X!", "Y!"], outputs=["Out"], grad=None)
def equal_all(ins, attrs, ctx):
    return {"Out": jnp.array_equal(ins["X"], ins["Y"])}


def _logical(name, fn, binary=True):
    ins_spec = ["X!", "Y!"] if binary else ["X!"]

    @register_op(name, inputs=ins_spec, outputs=["Out"], grad=None)
    def kernel(ins, attrs, ctx, _fn=fn, _binary=binary):
        if _binary:
            return {"Out": _fn(ins["X"], ins["Y"])}
        return {"Out": _fn(ins["X"])}
    return kernel


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, binary=False)


@register_op("select_input", inputs=["X*", "Mask!"], outputs=["Out"])
def select_input(ins, attrs, ctx):
    idx = ins["Mask"].reshape(()).astype(jnp.int32)
    xs = ins["X"]
    out = xs[0]
    for i in range(1, len(xs)):
        out = jnp.where(idx == i, xs[i], out)
    return {"Out": out}


@register_op("print", inputs=["In"], outputs=["Out"], grad=None,
             side_effect=True)
def print_op(ins, attrs, ctx):
    # debug print survives jit via jax.debug
    import jax
    jax.debug.print(attrs.get("message", "") + " {}", ins["In"])
    return {"Out": ins["In"]}


@register_op("assert", inputs=["Cond!", "Data*?"], outputs=[], grad=None,
             side_effect=True)
def assert_op(ins, attrs, ctx):
    return {}


@register_op("optimization_barrier", inputs=["X*"], outputs=["Out*"],
             grad=None, side_effect=True)
def optimization_barrier(ins, attrs, ctx):
    """Identity that XLA cannot CSE/reorder through (jax.lax
    .optimization_barrier).  Used by the recompute rewrite to keep replayed
    forward segments distinct from the original forward pass, which is what
    turns graph-level replay into real rematerialization (reference
    backward.py:689 replays ops; on TPU the barrier is what makes XLA
    actually recompute instead of reusing the live value)."""
    import jax
    xs = ins["X"]
    if not xs:
        return {"Out": []}
    outs = jax.lax.optimization_barrier(tuple(xs))
    return {"Out": list(outs)}


@register_op("listen_and_serv", inputs=["X*"], outputs=[], grad=None,
             side_effect=True)
def listen_and_serv(ins, attrs, ctx):
    """Marker op (reference: operators/distributed_ops/listen_and_serv_op.cc).
    The executor intercepts programs carrying _ps_server_config and serves
    the KV store host-side; reaching this kernel directly is a no-op."""
    return {}

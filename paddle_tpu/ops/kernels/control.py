"""Control-flow & graph-plumbing ops.

Reference: /root/reference/paddle/fluid/operators/controlflow/ —
while_op.cc:1, conditional_block_op.cc:1, feed_op.cc, fetch_op.cc,
compare_op.cc, logical_op.cc; operators/recurrent_op.cc (StaticRNN).

The sub-block ops (`while`, `cond`, `conditional_block`, `static_rnn`)
recursively trace their sub-Block with BlockTracer — the OpContext carries
the owning Program (set by BlockTracer.run_op) — and lower to XLA-native
control flow: lax.while_loop / lax.cond / masked select / lax.scan.  The
builders live in static/control_flow.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _host_check(ok, message):
    """Ordered host-side check that raises `message` when `ok` is false.
    io_callback has no JVP/VJP rules, so the callback is wrapped in a
    custom_vjp identity over a float flag — the check survives inside
    differentiated computations (assert in a trainable sub-block, strict
    bounded while under _while_grad) where a bare io_callback would crash
    jax.vjp with 'IO callbacks do not support JVP'."""
    from jax.experimental import io_callback

    def _emit(flag_f):
        def _die(f):
            import numpy as _np
            if float(f) < 0.5:
                raise AssertionError(message)
            return _np.bool_(True)

        io_callback(_die, jax.ShapeDtypeStruct((), jnp.bool_), flag_f,
                    ordered=True)

    @jax.custom_vjp
    def chk(flag_f):
        _emit(flag_f)
        return flag_f

    def fwd(flag_f):
        _emit(flag_f)
        return flag_f, None

    def bwd(_, g):
        return (jnp.zeros_like(g),)

    chk.defvjp(fwd, bwd)
    chk(jnp.asarray(ok).astype(jnp.float32).reshape(()))


def _sub_tracer(ctx, block_idx):
    from ...static.executor import BlockTracer
    program = getattr(ctx, "program", None)
    if program is None:
        raise RuntimeError(
            "sub-block op executed without a Program on the OpContext — "
            "control-flow ops must run through BlockTracer")
    return BlockTracer(program.blocks[block_idx])


def _scalar_bool(x):
    return jnp.reshape(x, ()).astype(jnp.bool_)


def _env_map(names, vals, op_type):
    """zip names->values, refusing silent misalignment (the registry drops
    inputs missing from the env, which would shift everything after)."""
    if len(names) != len(vals):
        raise ValueError(
            f"{op_type}: expected values for {names}, got {len(vals)} — "
            "some referenced var is missing from the environment")
    return dict(zip(names, vals))


def _while_grad(ins, attrs, ctx):
    """WhileGradOp analog (while_op.cc:167).  Only the bounded form is
    reverse-differentiable: with max_iters set the forward lowers to a
    masked lax.scan, and this kernel is the auto-vjp of that scan (the
    reference instead replays per-iteration scopes off a tape — a
    host-side structure that has no XLA equivalent)."""
    if int(attrs.get("max_iters", 0) or 0) <= 0:
        raise ValueError(
            "while is not reverse-differentiable without an iteration "
            "bound: build it as While(cond, max_iters=N) / "
            "layers.while_loop(..., max_iters=N) (lowered to a masked "
            "lax.scan), or use StaticRNN for fixed-length recurrence")
    from ..registry import _make_vjp_grad_kernel, get_op_info
    return _make_vjp_grad_kernel(get_op_info("while"))(ins, attrs, ctx)


@register_op("while", inputs=["Condition!", "X*"], outputs=["Out*"],
             grad=_while_grad, side_effect=True)
def while_op(ins, attrs, ctx):
    """while_op.cc:1 — run the sub-block until the condition var (updated
    by the body) is false.

    Lowering: without a bound, jax.lax.while_loop over the dict of
    loop-carried vars (not reverse-differentiable).  With attrs[max_iters]
    set, a masked lax.scan of fixed length: every step computes the body,
    `where(alive, new, old)` freezes the carry once the condition drops —
    same results for any trip count <= max_iters, and reverse-mode
    differentiable (grad: _while_grad), the TPU replacement for the
    reference's scope-tape WhileGradOp."""
    tracer = _sub_tracer(ctx, attrs["sub_block"])
    x_names = attrs["x_names"]
    carry_names = attrs["carry_names"]
    cond_name = attrs["cond_name"]
    env0 = _env_map(x_names, ins["X"], "while")
    # carry inits may live under snapshot names (@PRELOOP, see
    # append_while_op) when the loop is differentiable
    carry_srcs = attrs.get("carry_srcs") or carry_names
    cond_src = (carry_srcs[carry_names.index(cond_name)]
                if cond_name in carry_names else cond_name)
    env0.setdefault(cond_src, ins["Condition"])
    missing = [s for s in carry_srcs if s not in env0 or env0[s] is None]
    if missing:
        raise ValueError(
            f"while: loop-carried vars {missing} have no value before the "
            "loop — assign them first (fluid requires this too)")
    init = {n: env0[s] for n, s in zip(carry_names, carry_srcs)}
    max_iters = int(attrs.get("max_iters", 0) or 0)

    def body(carry):
        e = dict(env0)
        e.update(carry)
        tracer.run(e, ctx)
        return {n: e[n] for n in carry_names}

    if max_iters > 0:
        def step(carry, _):
            alive = _scalar_bool(carry[cond_name])
            # lax.cond, not where-masking: dead iterations must not
            # execute the body at all — a body that is only valid while
            # the condition holds (z / i with i hitting 0) would emit
            # inf/NaN whose cotangent poisons every gradient even though
            # the primal is masked (0 * inf = NaN in reverse mode)
            return jax.lax.cond(alive, body, lambda c: c, carry), None

        final, _ = jax.lax.scan(step, init, None, length=max_iters)
        # truncation detector: if the condition is STILL true after
        # max_iters, results differ from the unbounded semantics.
        # strict_truncation (ADVICE r3): abort the step with a host-side
        # error so training cannot silently proceed on truncated values;
        # default: a runtime warning print.
        truncated = _scalar_bool(final[cond_name])
        if bool(attrs.get("strict_truncation", False)) or \
                bool(attrs.get("strict", False)):
            _host_check(
                jnp.logical_not(truncated),
                f"while(max_iters={max_iters}) truncated: the loop "
                "condition was still true at the bound — raise "
                "max_iters (strict_truncation=True)")
        else:
            jax.lax.cond(
                truncated,
                lambda: jax.debug.print(
                    "WARNING: while(max_iters={m}) stopped with its "
                    "condition still true — the loop was truncated; "
                    "raise max_iters", m=max_iters),
                lambda: None)
        return {"Out": [final[n] for n in carry_names]}

    def cond_f(carry):
        return _scalar_bool(carry[cond_name])

    try:
        final = jax.lax.while_loop(cond_f, body, init)
    except TypeError as e:
        if "pytree" in str(e) or "structure" in str(e) or "shape" in str(e):
            raise TypeError(
                "while: a loop-carried value changes shape/structure "
                "between iterations.  Common cause: the FIRST "
                "write_to_array to a TensorArray happens inside the loop "
                "body (the empty array's buffer is reallocated at first "
                "write).  Do the first array_write(..., max_len=N) before "
                f"the loop.  Original error: {e}") from e
        raise
    return {"Out": [final[n] for n in attrs["carry_names"]]}


@register_op("cond", inputs=["Cond!", "Input*"], outputs=["Out*"])
def cond_op(ins, attrs, ctx):
    """Two-branch conditional -> jax.lax.cond (XLA Conditional).  Reference
    builds this from two conditional_block ops + select_input
    (control_flow.py:1976); here it is one op so XLA sees a real
    Conditional and only materializes the taken branch."""
    tb = _sub_tracer(ctx, attrs["true_block"])
    fb = _sub_tracer(ctx, attrs["false_block"])
    env0 = _env_map(attrs["input_names"], ins["Input"], "cond")
    # branches may read the predicate variable itself
    if attrs.get("cond_name"):
        env0.setdefault(attrs["cond_name"], ins["Cond"])

    def run(tracer, out_names):
        def f(env):
            e = dict(env)
            tracer.run(e, ctx)
            return tuple(e[n] for n in out_names)
        return f

    outs = jax.lax.cond(_scalar_bool(ins["Cond"]),
                        run(tb, attrs["true_outs"]),
                        run(fb, attrs["false_outs"]), env0)
    return {"Out": list(outs)}


@register_op("conditional_block", inputs=["Cond!", "Input*"],
             outputs=["Out*"])
def conditional_block_op(ins, attrs, ctx):
    """Single-branch guarded block (conditional_block_op.cc:1), used by
    Switch.  TPU lowering: the body computes unconditionally and
    where(cond, new, old) merges — XLA select semantics (see
    fleet/meta_optimizers/rewrite_utils.py for why this beats host
    branching on TPU).  The guarded bodies are tiny (LR updates, param
    averaging), so computing both sides is the right trade."""
    tracer = _sub_tracer(ctx, attrs["sub_block"])
    env0 = _env_map(attrs["input_names"], ins["Input"],
                    "conditional_block")
    pred = _scalar_bool(ins["Cond"])
    e = dict(env0)
    tracer.run(e, ctx)
    outs = []
    for n in attrs["out_names"]:
        new = e[n]
        old = env0.get(n)
        if old is None:
            raise ValueError(
                f"conditional_block writes {n!r} which has no value before "
                "the block — initialize it first")
        outs.append(jnp.where(pred, new, old))
    return {"Out": outs}


@register_op("static_rnn", inputs=["X*"], outputs=["Out*"])
def static_rnn_op(ins, attrs, ctx):
    """StaticRNN (recurrent_op.cc) -> jax.lax.scan over the time-major
    leading axis: compiled recurrence, O(1) graph size in T, reverse-mode
    differentiable (scan has a VJP; while_loop does not)."""
    tracer = _sub_tracer(ctx, attrs["sub_block"])
    env0 = _env_map(attrs["x_names"], ins["X"], "static_rnn")
    memories = attrs["memories"]          # [boot, pre, updated]
    scan_inputs = attrs["scan_inputs"]    # [parent_name, in_block_name]
    step_outputs = attrs["step_outputs"]

    carry0 = {pre: env0[boot] for boot, pre, _ in memories}
    xs = {inb: env0[pn] for pn, inb in scan_inputs}

    def f(carry, x_slice):
        e = dict(env0)
        e.update(carry)
        e.update(x_slice)
        tracer.run(e, ctx)
        new_carry = {pre: e[upd] for _, pre, upd in memories}
        ys = tuple(e[n] for n in step_outputs)
        return new_carry, ys

    _, ys = jax.lax.scan(f, carry0, xs)
    return {"Out": list(ys)}


@register_op("dynamic_rnn", inputs=["X*"], outputs=["Out*"])
def dynamic_rnn_op(ins, attrs, ctx):
    """DynamicRNN (the while + lod_tensor_to_array + shrink_rnn_memory
    pipeline of /root/reference/python/paddle/fluid/layers/
    control_flow.py:2938) collapsed into ONE masked lax.scan.

    The reference sorts sequences by length and physically shrinks the
    batch as short sequences finish — ragged per-step shapes XLA cannot
    compile.  TPU lowering: scan over the padded time axis with the FULL
    batch every step; `step < lengths` masks the recurrence instead of
    shrinking it — memories freeze at a sequence's last real step (so
    sequence_last_step reads the same value the reference produces) and
    step outputs are zeroed in the padding.  Row-wise step bodies (fc /
    gru_unit / lstm_unit ...) make masked rows independent of live rows,
    which is exactly the contract the reference's shrinking gives."""
    tracer = _sub_tracer(ctx, attrs["sub_block"])
    env0 = _env_map(attrs["x_names"], ins["X"], "dynamic_rnn")
    memories = attrs["memories"]          # [boot, pre, updated]
    scan_inputs = attrs["scan_inputs"]    # [parent_name, in_block_name]
    step_outputs = attrs["step_outputs"]

    lengths = jnp.reshape(env0[attrs["lengths_name"]], (-1,)) \
        .astype(jnp.int32)
    carry0 = {pre: env0[boot] for boot, pre, _ in memories}
    # [B, T, ...] -> time-major [T, B, ...] for the scan axis
    xs = {inb: jnp.moveaxis(env0[pn], 1, 0) for pn, inb in scan_inputs}
    n_steps = next(iter(xs.values())).shape[0]

    def _mask(active, like):
        return active.reshape((-1,) + (1,) * (like.ndim - 1))

    def f(carry, step_x):
        t, x_slice = step_x
        e = dict(env0)
        e.update(carry)
        e.update(x_slice)
        tracer.run(e, ctx)
        active = t < lengths
        new_carry = {pre: jnp.where(_mask(active, e[upd]), e[upd],
                                    carry[pre])
                     for _, pre, upd in memories}
        ys = tuple(jnp.where(_mask(active, e[n]), e[n],
                             jnp.zeros_like(e[n]))
                   for n in step_outputs)
        return new_carry, ys

    _, ys = jax.lax.scan(f, carry0, (jnp.arange(n_steps), xs))
    return {"Out": [jnp.moveaxis(y, 0, 1) for y in ys]}


@register_op("feed", inputs=[], outputs=["Out"], grad=None, side_effect=True)
def feed(ins, attrs, ctx):
    raise RuntimeError("feed op is handled by the executor")


@register_op("fetch", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def fetch(ins, attrs, ctx):
    return {"Out": ins["X"]}


def _cmp(name, fn):
    @register_op(name, inputs=["X!", "Y!"], outputs=["Out"], grad=None)
    def kernel(ins, attrs, ctx, _fn=fn):
        return {"Out": _fn(ins["X"], ins["Y"])}
    return kernel


_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)


@register_op("equal_all", inputs=["X!", "Y!"], outputs=["Out"], grad=None)
def equal_all(ins, attrs, ctx):
    return {"Out": jnp.array_equal(ins["X"], ins["Y"])}


def _logical(name, fn, binary=True):
    ins_spec = ["X!", "Y!"] if binary else ["X!"]

    @register_op(name, inputs=ins_spec, outputs=["Out"], grad=None)
    def kernel(ins, attrs, ctx, _fn=fn, _binary=binary):
        if _binary:
            return {"Out": _fn(ins["X"], ins["Y"])}
        return {"Out": _fn(ins["X"])}
    return kernel


_logical("logical_and", jnp.logical_and)
_logical("logical_or", jnp.logical_or)
_logical("logical_xor", jnp.logical_xor)
_logical("logical_not", jnp.logical_not, binary=False)


@register_op("select_input", inputs=["X*", "Mask!"], outputs=["Out"])
def select_input(ins, attrs, ctx):
    idx = ins["Mask"].reshape(()).astype(jnp.int32)
    xs = ins["X"]
    out = xs[0]
    for i in range(1, len(xs)):
        out = jnp.where(idx == i, xs[i], out)
    return {"Out": out}


@register_op("print", inputs=["In"], outputs=["Out"], grad=None,
             side_effect=True)
def print_op(ins, attrs, ctx):
    # debug print survives jit via jax.debug
    import jax
    jax.debug.print(attrs.get("message", "") + " {}", ins["In"])
    return {"Out": ins["In"]}


@register_op("assert", inputs=["Cond!", "Data*?"], outputs=[], grad=None,
             side_effect=True)
def assert_op(ins, attrs, ctx):
    """assert_op.cc parity: host-side check that aborts the step when the
    condition is false.  Ordered io_callback (custom_vjp-shielded, see
    _host_check) so it survives DCE under jit, composes with
    differentiation, and the AssertionError propagates to whoever
    consumes the step's results (the reference op PADDLE_ENFORCEs at
    run time)."""
    _host_check(jnp.all(jnp.asarray(ins["Cond"])),
                attrs.get("message", "Assert failed"))
    return {}


@register_op("optimization_barrier", inputs=["X*"], outputs=["Out*"],
             grad=None, side_effect=True)
def optimization_barrier(ins, attrs, ctx):
    """Identity that XLA cannot CSE/reorder through (jax.lax
    .optimization_barrier).  Used by the recompute rewrite to keep replayed
    forward segments distinct from the original forward pass, which is what
    turns graph-level replay into real rematerialization (reference
    backward.py:689 replays ops; on TPU the barrier is what makes XLA
    actually recompute instead of reusing the live value)."""
    import jax
    xs = ins["X"]
    if not xs:
        return {"Out": []}
    outs = jax.lax.optimization_barrier(tuple(xs))
    return {"Out": list(outs)}


@register_op("listen_and_serv", inputs=["X*"], outputs=[], grad=None,
             side_effect=True)
def listen_and_serv(ins, attrs, ctx):
    """Marker op (reference: operators/distributed_ops/listen_and_serv_op.cc).
    The executor intercepts programs carrying _ps_server_config and serves
    the KV store host-side; reaching this kernel directly is a no-op."""
    return {}

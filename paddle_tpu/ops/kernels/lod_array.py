"""LoD rank-table / tensor-array bridge ops — the DynamicRNN substrate.

Reference ops:
  /root/reference/paddle/fluid/operators/lod_rank_table_op.cc:32
  /root/reference/paddle/fluid/operators/max_sequence_len_op.cc:1
  /root/reference/paddle/fluid/operators/lod_tensor_to_array_op.cc:1
  /root/reference/paddle/fluid/operators/array_to_lod_tensor_op.cc:1
  /root/reference/paddle/fluid/operators/shrink_rnn_memory_op.cc:1
  /root/reference/paddle/fluid/operators/reorder_lod_tensor_by_rank_op.cc:1
  /root/reference/paddle/fluid/operators/split_lod_tensor_op.cc:1
  /root/reference/paddle/fluid/operators/merge_lod_tensor_op.cc:1
  /root/reference/paddle/fluid/operators/recurrent_op.cc (rnn_memory_helper)

TPU redesign (NOT a translation).  The reference walks LoD offset tables
and *shrinks* the batch as short sequences finish, producing per-step
tensors of shrinking row counts — ragged shapes XLA cannot compile.  Here
variable length lives in an explicit lengths vector next to a padded
dense tensor (io/bucketing.py doctrine), and:

  * the rank table is a dense int32 [2, B] tensor — row 0 the stable
    argsort of lengths descending (the reference's rank order), row 1 the
    lengths in that order;
  * lod_tensor_to_array gathers rows into rank order and flips
    [B, T, ...] -> time-major, returning a TensorArrayVal whose buffer IS
    the time-major tensor, so `array_read(arr, step)` yields the full
    [B, ...] step slice — the batch never shrinks, masking replaces
    shrinking (see `dynamic_rnn` in control.py);
  * shrink_rnn_memory keeps every row (identity): finished sequences are
    frozen by `where(step < len, new, old)` masking instead of dropped,
    which preserves the reference's numerics for the surviving rows while
    keeping one static shape for all steps;
  * split/merge_lod_tensor keep full shape with inactive rows zeroed —
    the masked-select trade (both sides live, `where` picks), which is
    exactly how XLA wants data-dependent row routing phrased.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op
from .tensor_array import TensorArrayVal


def _lengths_1d(length):
    return jnp.reshape(length, (-1,)).astype(jnp.int32)


def _rank_rows(table):
    """(indices, lengths) int32 [B] each from a [2, B] rank table."""
    t = jnp.asarray(table)
    return t[0].astype(jnp.int32), t[1].astype(jnp.int32)


def _row_mask(mask, like):
    """[B] bool row mask broadcast against a [B, ...] tensor."""
    m = jnp.reshape(mask, (-1,)).astype(jnp.bool_)
    return m.reshape((-1,) + (1,) * (like.ndim - 1))


@register_op("lod_rank_table", inputs=["X?", "Length!"], outputs=["Out"],
             grad=None)
def lod_rank_table(ins, attrs, ctx):
    """lod_rank_table_op.cc:32 — sort sequence indices by length
    descending (stable, so equal lengths keep input order, matching the
    reference's std::stable_sort).  X is accepted for API parity but the
    lengths vector is the LoD here."""
    lens = _lengths_1d(ins["Length"])
    # stable descending sort: argsort ascending on negated lengths
    order = jnp.argsort(-lens, stable=True).astype(jnp.int32)
    return {"Out": jnp.stack([order, lens[order]])}


@register_op("max_sequence_len", inputs=["RankTable!"], outputs=["Out"],
             grad=None)
def max_sequence_len(ins, attrs, ctx):
    """max_sequence_len_op.cc — the scan length of the dynamic RNN."""
    _, lens = _rank_rows(ins["RankTable"])
    return {"Out": jnp.max(lens).astype(jnp.int64).reshape((1,))}


def _to_rank_time_major(x, order):
    """[B, T, ...] -> rank-ordered time-major array value."""
    tm = jnp.moveaxis(jnp.take(x, order, axis=0), 1, 0)
    return TensorArrayVal(tm, jnp.asarray(tm.shape[0], jnp.int32))


def _from_rank_time_major(arr, order):
    """Rank-ordered time-major -> [B, T, ...] in input order."""
    buf = arr.buffer if isinstance(arr, TensorArrayVal) else jnp.asarray(arr)
    inv = jnp.argsort(order)
    return jnp.take(jnp.moveaxis(buf, 0, 1), inv, axis=0)


def _lod_to_array_grad(ins, attrs, ctx):
    """The two transforms are mutually inverse permutations, so each
    grad is the other transform applied to the cotangent (explicit
    kernels: auto-vjp cannot type float cotangents for the int32 `size`
    leaf of TensorArrayVal)."""
    order, _ = _rank_rows(ins["RankTable"])
    g = ins.get("Out@GRAD")
    if g is None:
        return {"X@GRAD": jnp.zeros_like(ins["X"])}
    return {"X@GRAD": _from_rank_time_major(g, order)}


@register_op("lod_tensor_to_array", inputs=["X", "RankTable!"],
             outputs=["Out"], grad=_lod_to_array_grad)
def lod_tensor_to_array(ins, attrs, ctx):
    """lod_tensor_to_array_op.cc — padded [B, T, ...] -> time-major array.

    Rows are gathered into rank order, then time moves to the front; the
    result is a TensorArrayVal of T full-batch step slices (no per-step
    shrinking — masking downstream replaces it)."""
    order, _ = _rank_rows(ins["RankTable"])
    return {"Out": _to_rank_time_major(ins["X"], order)}


def _array_to_lod_grad(ins, attrs, ctx):
    order, _ = _rank_rows(ins["RankTable"])
    g = ins.get("Out@GRAD")
    x = ins["X"]
    if g is None:
        buf = x.buffer if isinstance(x, TensorArrayVal) else jnp.asarray(x)
        return {"X@GRAD": TensorArrayVal(
            jnp.zeros_like(buf), jnp.asarray(buf.shape[0], jnp.int32))}
    return {"X@GRAD": _to_rank_time_major(g, order)}


@register_op("array_to_lod_tensor", inputs=["X", "RankTable!"],
             outputs=["Out"], grad=_array_to_lod_grad)
def array_to_lod_tensor(ins, attrs, ctx):
    """array_to_lod_tensor_op.cc — inverse of lod_tensor_to_array: stack
    the step slices back to [B, T, ...] and undo the rank permutation so
    rows return to input order."""
    order, _ = _rank_rows(ins["RankTable"])
    return {"Out": _from_rank_time_major(ins["X"], order)}


@register_op("reorder_lod_tensor_by_rank", inputs=["X", "RankTable!"],
             outputs=["Out"])
def reorder_lod_tensor_by_rank(ins, attrs, ctx):
    """reorder_lod_tensor_by_rank_op.cc — gather rows into rank order
    (static_input's reorder; its auto-vjp is the reference's grad op,
    which scatters back)."""
    order, _ = _rank_rows(ins["RankTable"])
    return {"Out": jnp.take(ins["X"], order, axis=0)}


@register_op("shrink_rnn_memory", inputs=["X", "RankTable?!", "I?!"],
             outputs=["Out"])
def shrink_rnn_memory(ins, attrs, ctx):
    """shrink_rnn_memory_op.cc — reference drops the rows of sequences
    already finished at step I.  TPU redesign: keep every row (identity);
    the dynamic_rnn scan freezes finished rows with where-masking, so the
    surviving rows see identical values and the shape stays static."""
    return {"Out": ins["X"]}


@register_op("rnn_memory_helper", inputs=["X"], outputs=["Out"])
def rnn_memory_helper(ins, attrs, ctx):
    """recurrent_op.cc rnn_memory_helper — differentiable identity used to
    give RNN memories a gradient slot."""
    return {"Out": ins["X"]}


@register_op("split_lod_tensor", inputs=["X", "Mask!"],
             outputs=["OutTrue", "OutFalse"])
def split_lod_tensor(ins, attrs, ctx):
    """split_lod_tensor_op.cc — reference routes rows into two ragged
    tensors by a [B] bool mask.  TPU redesign: both outputs keep the full
    [B, ...] shape with non-selected rows zeroed, so
    merge_lod_tensor(split(...)) round-trips exactly and both branches of
    an IfElse stay statically shaped."""
    x = ins["X"]
    m = _row_mask(ins["Mask"], x)
    zero = jnp.zeros_like(x)
    return {"OutTrue": jnp.where(m, x, zero),
            "OutFalse": jnp.where(m, zero, x)}


@register_op("merge_lod_tensor", inputs=["X?", "Mask!", "InTrue", "InFalse"],
             outputs=["Out"])
def merge_lod_tensor(ins, attrs, ctx):
    """merge_lod_tensor_op.cc — row-select InTrue where mask else InFalse
    (X carried for API parity only; shapes are already aligned here)."""
    it, if_ = ins["InTrue"], ins["InFalse"]
    m = _row_mask(ins["Mask"], it)
    return {"Out": jnp.where(m, it, if_)}

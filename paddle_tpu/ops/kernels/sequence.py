"""Sequence ops.

The reference's sequence family operates on LoD (ragged) tensors
(/root/reference/paddle/fluid/operators/sequence_ops/,
 framework/lod_tensor.h:62).  XLA requires static shapes, so the TPU-native
representation is dense padded batches + explicit length tensors (SURVEY.md
§5.7): `sequence_mask` produces masks from lengths, `sequence_pad/unpad`
convert between ragged-host and padded-device forms, and reductions take the
mask into account.  Ops whose reference semantics are inherently ragged-rank
(lod_reset etc.) live on the host side in io/lod.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


@register_op("sequence_mask", inputs=["X!", "MaxLenTensor?!"], outputs=["Y"],
             grad=None)
def sequence_mask(ins, attrs, ctx):
    x = ins["X"]
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        maxlen = int(x.max()) if not isinstance(x, jax.core.Tracer) else None
        if maxlen is None:
            raise ValueError("sequence_mask inside jit needs static maxlen")
    from ...core.dtype import np_dtype
    rng = jnp.arange(maxlen)
    mask = rng[None, :] < x.reshape(-1, 1)
    mask = mask.reshape(x.shape + (maxlen,))
    return {"Y": mask.astype(np_dtype(attrs.get("out_dtype", "int64")))}


@register_op("sequence_pad", inputs=["X", "PadValue", "Length?!"],
             outputs=["Out", "Length"])
def sequence_pad(ins, attrs, ctx):
    # dense path: X already [batch, maxlen, ...]; passthrough with lengths
    x = ins["X"]
    length = ins.get("Length")
    if length is None:
        length = jnp.full((x.shape[0],), x.shape[1], jnp.int64)
    return {"Out": x, "Length": length}


@register_op("sequence_unpad", inputs=["X", "Length!"], outputs=["Out"])
def sequence_unpad(ins, attrs, ctx):
    # on-device we keep padded; masking happens in consumers
    return {"Out": ins["X"]}


@register_op("sequence_pool", inputs=["X", "Length?!"],
             outputs=["Out", "MaxIndex?"])
def sequence_pool(ins, attrs, ctx):
    """Padded-batch pooling: X [batch, maxlen, d], optional Length [batch]."""
    x = ins["X"]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    length = ins.get("Length")
    if length is not None:
        mask = (jnp.arange(x.shape[1])[None, :] <
                length.reshape(-1, 1)).astype(x.dtype)
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    else:
        mask = jnp.ones(x.shape[:2] + (1,) * (x.ndim - 2), x.dtype)
    cnt = jnp.maximum(jnp.sum(mask, axis=1), 1.0)
    if ptype == "SUM":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(x * mask, axis=1) / cnt
    elif ptype == "SQRT":
        out = jnp.sum(x * mask, axis=1) / jnp.sqrt(cnt)
    elif ptype == "MAX":
        neg = jnp.asarray(-1e38, x.dtype)
        out = jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    elif ptype == "LAST":
        idx = (jnp.sum(mask, axis=1) - 1).astype(jnp.int32)
        out = jnp.take_along_axis(x, idx[:, None].reshape(
            (-1, 1) + (1,) * (x.ndim - 2)), axis=1).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(ptype)
    return {"Out": out}


@register_op("sequence_softmax", inputs=["X", "Length?!"], outputs=["Out"])
def sequence_softmax(ins, attrs, ctx):
    x = ins["X"]
    length = ins.get("Length")
    if length is None:
        return {"Out": jax.nn.softmax(x, axis=-1)}
    mask = jnp.arange(x.shape[1])[None, :] < length.reshape(-1, 1)
    neg = jnp.asarray(-1e38, x.dtype)
    return {"Out": jax.nn.softmax(jnp.where(mask, x, neg), axis=1) *
            mask.astype(x.dtype)}


@register_op("sequence_expand", inputs=["X", "Y!"], outputs=["Out"])
def sequence_expand(ins, attrs, ctx):
    # dense analog: broadcast X rows to Y's time dim
    x, y = ins["X"], ins["Y"]
    if x.ndim < y.ndim:
        x = jnp.expand_dims(x, 1)
    return {"Out": jnp.broadcast_to(x, y.shape[:2] + x.shape[2:])}


@register_op("sequence_expand_as", inputs=["X", "Y!"], outputs=["Out"])
def sequence_expand_as(ins, attrs, ctx):
    return sequence_expand(ins, attrs, ctx)


@register_op("sequence_reverse", inputs=["X", "Length?!"], outputs=["Y"])
def sequence_reverse(ins, attrs, ctx):
    x = ins["X"]
    length = ins.get("Length")
    if length is None:
        return {"Y": jnp.flip(x, axis=1)}
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    L = length.reshape(-1, 1)
    rev_idx = jnp.where(idx < L, L - 1 - idx, idx)
    return {"Y": jnp.take_along_axis(
        x, rev_idx.reshape(rev_idx.shape + (1,) * (x.ndim - 2))
        .astype(jnp.int32), axis=1)}


@register_op("sequence_concat", inputs=["X*"], outputs=["Out"])
def sequence_concat(ins, attrs, ctx):
    return {"Out": jnp.concatenate(ins["X"], axis=1)}


@register_op("sequence_conv", inputs=["X", "Filter", "PaddingData?"],
             outputs=["Out"])
def sequence_conv(ins, attrs, ctx):
    # context window conv over time: X [b, t, d], Filter [ctx*d, m]
    x, w = ins["X"], ins["Filter"]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    b, t, d = x.shape
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(x, -off, axis=1)
        if off < 0:
            m = jnp.arange(t)[None, :, None] >= -off
        else:
            m = jnp.arange(t)[None, :, None] < t - off
        cols.append(jnp.where(m, shifted, 0.0))
    col = jnp.concatenate(cols, axis=-1)  # [b, t, ctx*d]
    return {"Out": jnp.einsum("btc,cm->btm", col, w)}


@register_op("sequence_enumerate", inputs=["X!"], outputs=["Out"], grad=None)
def sequence_enumerate(ins, attrs, ctx):
    x = ins["X"]
    win = attrs["win_size"]
    pad = attrs.get("pad_value", 0)
    t = x.shape[-1] if x.ndim > 1 else x.shape[0]
    flat = x.reshape(-1, t)
    outs = []
    for i in range(win):
        shifted = jnp.concatenate(
            [flat[:, i:], jnp.full((flat.shape[0], i), pad, x.dtype)], axis=1)
        outs.append(shifted)
    return {"Out": jnp.stack(outs, axis=-1).reshape(x.shape + (win,))}


@register_op("sequence_erase", inputs=["X!", "Length?!"],
             outputs=["Out", "OutLength?"], grad=None)
def sequence_erase(ins, attrs, ctx):
    """sequence_erase_op.cc — drop the listed token ids from each
    sequence.  The reference compacts the LoD rows (data-dependent
    shape); the padded redesign keeps [B, T], left-compacts the
    survivors per row, fills the tail with pad_value, and emits the new
    per-row lengths — the same fixed-shape contract as sequence_pad."""
    x = jnp.asarray(ins["X"])
    tokens = attrs.get("tokens", [])
    pad_value = attrs.get("pad_value", 0)
    squeeze = x.ndim == 1
    if squeeze:
        x = x[None]
    B, T = x.shape[0], x.shape[-1]
    flat = x.reshape(-1, T)
    erase = jnp.zeros(flat.shape, bool)
    for t in tokens:
        erase = erase | (flat == t)
    length_in = ins.get("Length")
    if length_in is not None:
        valid = jnp.arange(T)[None, :] < \
            jnp.asarray(length_in).reshape(-1, 1)
        erase = erase | ~valid
    keep = ~erase
    # stable left-compaction: sort by (erased, position)
    order = jnp.argsort(jnp.where(keep, jnp.arange(T)[None, :], T),
                        axis=1)
    gathered = jnp.take_along_axis(flat, order, axis=1)
    new_len = jnp.sum(keep, axis=1)
    live = jnp.arange(T)[None, :] < new_len[:, None]
    out = jnp.where(live, gathered, jnp.asarray(pad_value, x.dtype))
    out = out.reshape(x.shape)
    if squeeze:
        out = out[0]
    return {"Out": out,
            "OutLength": new_len.reshape(x.shape[:-1]).astype(jnp.int64)}


@register_op("sequence_slice", inputs=["X", "Offset!", "Length!"],
             outputs=["Out"])
def sequence_slice(ins, attrs, ctx):
    x = ins["X"]
    off = jnp.asarray(ins["Offset"]).reshape(-1)[0]
    ln = int(jnp.asarray(ins["Length"]).reshape(-1)[0])
    return {"Out": jax.lax.dynamic_slice_in_dim(x, off, ln, axis=1)}


@register_op("sequence_reshape", inputs=["X"], outputs=["Out"])
def sequence_reshape(ins, attrs, ctx):
    x = ins["X"]
    new_dim = attrs["new_dim"]
    return {"Out": x.reshape(x.shape[0], -1, new_dim)}


@register_op("im2sequence", inputs=["X", "Y?!"], outputs=["Out"])
def im2sequence(ins, attrs, ctx):
    x = ins["X"]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])])
    oh = (x.shape[2] - kh) // sh + 1
    ow = (x.shape[3] - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(0, 0), (0, 0)])
    # patches: [n, c*kh*kw, oh, ow] -> [n*oh*ow, c*kh*kw]
    out = jnp.moveaxis(patches, 1, -1).reshape(n * oh * ow, c * kh * kw)
    return {"Out": out}


@register_op("row_conv", inputs=["X", "Filter"], outputs=["Out"])
def row_conv(ins, attrs, ctx):
    # lookahead conv: X [b, t, d], Filter [future_ctx, d]
    x, w = ins["X"], ins["Filter"]
    fut = w.shape[0]
    t = x.shape[1]
    out = jnp.zeros_like(x)
    for i in range(fut):
        shifted = jnp.roll(x, -i, axis=1)
        mask = (jnp.arange(t) < t - i)[None, :, None]
        out = out + jnp.where(mask, shifted, 0.0) * w[i][None, None, :]
    return {"Out": out}

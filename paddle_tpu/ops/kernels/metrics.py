"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


@register_op("accuracy", inputs=["Out", "Indices!", "Label!"],
             outputs=["Accuracy", "Correct", "Total"], grad=None)
def accuracy(ins, attrs, ctx):
    idx, label = ins["Indices"], ins["Label"]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label
    else:
        label = label.reshape(-1, 1)
    correct = jnp.any(idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    return {"Accuracy": (num_correct / total).astype(jnp.float32).reshape(1),
            "Correct": num_correct.reshape(1), "Total": total.reshape(1)}


@register_op("auc",
             inputs=["Predict!", "Label!", "StatPos!", "StatNeg!"],
             outputs=["AUC", "StatPosOut", "StatNegOut"], grad=None,
             side_effect=True)
def auc(ins, attrs, ctx):
    pred, label = ins["Predict"], ins["Label"].ravel()
    stat_pos, stat_neg = ins["StatPos"], ins["StatNeg"]
    num_thresholds = attrs.get("num_thresholds", 4095)
    pos_prob = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
        else pred.ravel()
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label > 0)
    stat_pos = stat_pos.at[bucket].add(is_pos.astype(stat_pos.dtype))
    stat_neg = stat_neg.at[bucket].add((~is_pos).astype(stat_neg.dtype))
    # AUC via trapezoid over cumulative TP/FP (descending threshold)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc_val = jnp.where(tot_pos * tot_neg > 0,
                        area / (tot_pos * tot_neg), 0.0)
    return {"AUC": auc_val.astype(jnp.float64), "StatPosOut": stat_pos,
            "StatNegOut": stat_neg}


@register_op("precision_recall",
             inputs=["MaxProbs!", "Indices!", "Labels!", "Weights?",
                     "StatesInfo?"],
             outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
             grad=None)
def precision_recall(ins, attrs, ctx):
    cls_num = attrs["class_number"]
    idx = ins["Indices"].ravel().astype(jnp.int32)
    labels = ins["Labels"].ravel().astype(jnp.int32)
    states = ins.get("StatesInfo")
    if states is None:
        states = jnp.zeros((cls_num, 4), jnp.float32)
    correct = idx == labels
    tp = jnp.zeros(cls_num).at[labels].add(correct.astype(jnp.float32))
    fp = jnp.zeros(cls_num).at[idx].add((~correct).astype(jnp.float32))
    fn = jnp.zeros(cls_num).at[labels].add((~correct).astype(jnp.float32))
    tn = jnp.zeros(cls_num)
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)
    acc_states = states + batch_states

    def metrics(s):
        tp_, fp_, tn_, fn_ = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12),
                       0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        w = tp_ + fn_
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        micro = jnp.stack([jnp.sum(prec * w) / wsum, jnp.sum(rec * w) / wsum,
                           jnp.sum(f1 * w) / wsum])
        return jnp.concatenate([macro, micro])

    return {"BatchMetrics": metrics(batch_states),
            "AccumMetrics": metrics(acc_states),
            "AccumStatesInfo": acc_states}


@register_op("mean_iou", inputs=["Predictions!", "Labels!"],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"], grad=None)
def mean_iou(ins, attrs, ctx):
    num_classes = attrs["num_classes"]
    pred = ins["Predictions"].ravel().astype(jnp.int32)
    label = ins["Labels"].ravel().astype(jnp.int32)
    correct = jnp.zeros(num_classes, jnp.int32).at[
        jnp.where(pred == label, pred, num_classes - 1)].add(
        (pred == label).astype(jnp.int32))
    wrong_pred = jnp.zeros(num_classes, jnp.int32).at[pred].add(
        (pred != label).astype(jnp.int32))
    wrong_label = jnp.zeros(num_classes, jnp.int32).at[label].add(
        (pred != label).astype(jnp.int32))
    union = correct + wrong_pred + wrong_label
    iou = jnp.where(union > 0, correct / jnp.maximum(union, 1), 0.0)
    valid = jnp.sum((union > 0).astype(jnp.float32))
    mean_iou_val = jnp.sum(iou) / jnp.maximum(valid, 1.0)
    return {"OutMeanIou": mean_iou_val.astype(jnp.float32),
            "OutWrong": wrong_pred + wrong_label, "OutCorrect": correct}

"""Metric ops (reference: /root/reference/paddle/fluid/operators/metrics/
accuracy_op.cc, auc_op.cc, precision_recall_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


@register_op("accuracy", inputs=["Out", "Indices!", "Label!"],
             outputs=["Accuracy", "Correct", "Total"], grad=None)
def accuracy(ins, attrs, ctx):
    idx, label = ins["Indices"], ins["Label"]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label
    else:
        label = label.reshape(-1, 1)
    correct = jnp.any(idx == label, axis=1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = jnp.asarray(idx.shape[0], jnp.int32)
    return {"Accuracy": (num_correct / total).astype(jnp.float32).reshape(1),
            "Correct": num_correct.reshape(1), "Total": total.reshape(1)}


@register_op("auc",
             inputs=["Predict!", "Label!", "StatPos!", "StatNeg!"],
             outputs=["AUC", "StatPosOut", "StatNegOut"], grad=None,
             side_effect=True)
def auc(ins, attrs, ctx):
    pred, label = ins["Predict"], ins["Label"].ravel()
    # the fluid layer materialises stats as [1, T+1] (auc_op.cc output
    # shape); the bucket math is 1-d — flatten in, restore on the way out
    stat_shape = ins["StatPos"].shape
    stat_pos, stat_neg = ins["StatPos"].ravel(), ins["StatNeg"].ravel()
    num_thresholds = attrs.get("num_thresholds", 4095)
    pos_prob = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
        else pred.ravel()
    bucket = jnp.clip((pos_prob * num_thresholds).astype(jnp.int32), 0,
                      num_thresholds)
    is_pos = (label > 0)
    stat_pos = stat_pos.at[bucket].add(is_pos.astype(stat_pos.dtype))
    stat_neg = stat_neg.at[bucket].add((~is_pos).astype(stat_neg.dtype))
    # AUC via trapezoid over cumulative TP/FP (descending threshold)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tp0 = jnp.concatenate([jnp.zeros(1, tp.dtype), tp[:-1]])
    fp0 = jnp.concatenate([jnp.zeros(1, fp.dtype), fp[:-1]])
    area = jnp.sum((fp - fp0) * (tp + tp0) / 2.0)
    auc_val = jnp.where(tot_pos * tot_neg > 0,
                        area / (tot_pos * tot_neg), 0.0)
    return {"AUC": auc_val.astype(jnp.float64),
            "StatPosOut": stat_pos.reshape(stat_shape),
            "StatNegOut": stat_neg.reshape(stat_shape)}


@register_op("precision_recall",
             inputs=["MaxProbs!", "Indices!", "Labels!", "Weights?",
                     "StatesInfo?"],
             outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
             grad=None)
def precision_recall(ins, attrs, ctx):
    cls_num = attrs["class_number"]
    idx = ins["Indices"].ravel().astype(jnp.int32)
    labels = ins["Labels"].ravel().astype(jnp.int32)
    states = ins.get("StatesInfo")
    if states is None:
        states = jnp.zeros((cls_num, 4), jnp.float32)
    correct = idx == labels
    tp = jnp.zeros(cls_num).at[labels].add(correct.astype(jnp.float32))
    fp = jnp.zeros(cls_num).at[idx].add((~correct).astype(jnp.float32))
    fn = jnp.zeros(cls_num).at[labels].add((~correct).astype(jnp.float32))
    tn = jnp.zeros(cls_num)
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)
    acc_states = states + batch_states

    def metrics(s):
        tp_, fp_, tn_, fn_ = s[:, 0], s[:, 1], s[:, 2], s[:, 3]
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12),
                       0.0)
        macro = jnp.stack([jnp.mean(prec), jnp.mean(rec), jnp.mean(f1)])
        w = tp_ + fn_
        wsum = jnp.maximum(jnp.sum(w), 1e-12)
        micro = jnp.stack([jnp.sum(prec * w) / wsum, jnp.sum(rec * w) / wsum,
                           jnp.sum(f1 * w) / wsum])
        return jnp.concatenate([macro, micro])

    return {"BatchMetrics": metrics(batch_states),
            "AccumMetrics": metrics(acc_states),
            "AccumStatesInfo": acc_states}


@register_op("mean_iou", inputs=["Predictions!", "Labels!"],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"], grad=None)
def mean_iou(ins, attrs, ctx):
    num_classes = attrs["num_classes"]
    pred = ins["Predictions"].ravel().astype(jnp.int32)
    label = ins["Labels"].ravel().astype(jnp.int32)
    correct = jnp.zeros(num_classes, jnp.int32).at[
        jnp.where(pred == label, pred, num_classes - 1)].add(
        (pred == label).astype(jnp.int32))
    wrong_pred = jnp.zeros(num_classes, jnp.int32).at[pred].add(
        (pred != label).astype(jnp.int32))
    wrong_label = jnp.zeros(num_classes, jnp.int32).at[label].add(
        (pred != label).astype(jnp.int32))
    union = correct + wrong_pred + wrong_label
    iou = jnp.where(union > 0, correct / jnp.maximum(union, 1), 0.0)
    valid = jnp.sum((union > 0).astype(jnp.float32))
    mean_iou_val = jnp.sum(iou) / jnp.maximum(valid, 1.0)
    return {"OutMeanIou": mean_iou_val.astype(jnp.float32),
            "OutWrong": wrong_pred + wrong_label, "OutCorrect": correct}


# ---------------------------------------------------------------------------
# chunk_eval (chunk_eval_op.h:41 GetSegments / :89 ChunkEnd / :102
# ChunkBegin) — sequence chunking precision/recall/F1 over IOB / IOE /
# IOBES / plain tag schemes.  Pure host-side metric: segment extraction is
# inherently sequential python, so it runs through jax.pure_callback with
# scalar outputs (the reference's CPU-only kernel has the same shape).
# ---------------------------------------------------------------------------
_CHUNK_SCHEMES = {
    # scheme: (num_tag_types, tag_begin, tag_inside, tag_end, tag_single)
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_segments(seq, num_chunk_types, scheme):
    import numpy as np
    ntag, t_b, t_i, t_e, t_s = _CHUNK_SCHEMES[scheme]
    other = num_chunk_types

    def chunk_end(ptag, ptype, tag, typ):
        if ptype == other:
            return False
        if typ == other or typ != ptype:
            return True
        if ptag == t_b or ptag == t_i:
            return tag == t_b or tag == t_s
        if ptag == t_e or ptag == t_s:
            return True
        return False

    def chunk_begin(ptag, ptype, tag, typ):
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == t_b or tag == t_s:
            return True
        if tag == t_i or tag == t_e:
            return ptag == t_e or ptag == t_s
        return False

    segments = []
    start, in_chunk = 0, False
    tag, typ = -1, other
    for i, lab in enumerate(np.asarray(seq).tolist()):
        ptag, ptype = tag, typ
        tag, typ = int(lab) % ntag, int(lab) // ntag
        if in_chunk and chunk_end(ptag, ptype, tag, typ):
            segments.append((start, i - 1, ptype))
            in_chunk = False
        if chunk_begin(ptag, ptype, tag, typ):
            start, in_chunk = i, True
    if in_chunk:
        segments.append((start, len(np.asarray(seq)) - 1, typ))
    return segments


def _chunk_counts(inf, lab, lengths, num_chunk_types, scheme, excluded):
    import numpy as np
    inf, lab = np.asarray(inf), np.asarray(lab)
    n_inf = n_lab = n_corr = 0
    for b in range(inf.shape[0]):
        L = int(lengths[b]) if lengths is not None else inf.shape[1]
        segs_i = {s for s in _chunk_segments(inf[b, :L], num_chunk_types,
                                             scheme)
                  if s[2] not in excluded}
        segs_l = {s for s in _chunk_segments(lab[b, :L], num_chunk_types,
                                             scheme)
                  if s[2] not in excluded}
        n_inf += len(segs_i)
        n_lab += len(segs_l)
        n_corr += len(segs_i & segs_l)
    return (np.int64(n_inf), np.int64(n_lab), np.int64(n_corr))


@register_op("chunk_eval",
             inputs=["Inference!", "Label!", "SeqLength?!"],
             outputs=["Precision", "Recall", "F1-Score",
                      "NumInferChunks", "NumLabelChunks",
                      "NumCorrectChunks"], grad=None)
def chunk_eval(ins, attrs, ctx):
    inf, lab = ins["Inference"], ins["Label"]
    if inf.ndim == 3 and inf.shape[-1] == 1:
        inf, lab = jnp.squeeze(inf, -1), jnp.squeeze(lab, -1)
    lengths = ins.get("SeqLength")
    scheme = attrs.get("chunk_scheme", "IOB")
    if scheme not in _CHUNK_SCHEMES:
        raise ValueError(f"unknown chunk scheme {scheme!r}")
    num_chunk_types = int(attrs["num_chunk_types"])
    excluded = set(attrs.get("excluded_chunk_types", []) or [])

    from jax import dtypes as _dtypes
    idt = _dtypes.canonicalize_dtype(jnp.int64)  # int32 w/o x64

    def host(inf_a, lab_a, len_a):
        import numpy as np
        c = _chunk_counts(inf_a, lab_a,
                          None if len_a.shape == (0,) else len_a,
                          num_chunk_types, scheme, excluded)
        return tuple(np.asarray(v, idt) for v in c)

    len_arg = (lengths if lengths is not None
               else jnp.zeros((0,), jnp.int32))
    n_inf, n_lab, n_corr = jax.pure_callback(
        host, (jax.ShapeDtypeStruct((), idt),
               jax.ShapeDtypeStruct((), idt),
               jax.ShapeDtypeStruct((), idt)),
        inf, lab, len_arg)
    n_inf_f = n_inf.astype(jnp.float32)
    n_lab_f = n_lab.astype(jnp.float32)
    n_corr_f = n_corr.astype(jnp.float32)
    precision = jnp.where(n_inf_f > 0, n_corr_f / jnp.maximum(n_inf_f, 1),
                          0.0)
    recall = jnp.where(n_lab_f > 0, n_corr_f / jnp.maximum(n_lab_f, 1),
                       0.0)
    f1 = jnp.where(precision + recall > 0,
                   2 * precision * recall
                   / jnp.maximum(precision + recall, 1e-12), 0.0)
    return {"Precision": precision, "Recall": recall, "F1-Score": f1,
            "NumInferChunks": n_inf, "NumLabelChunks": n_lab,
            "NumCorrectChunks": n_corr}

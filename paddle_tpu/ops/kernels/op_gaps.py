"""Final registry-diff gap batch — the implementable remainder of
`REGISTER_OPERATOR` sites the reference has and this registry lacked
(systematic diff, round 4): label_smooth_op.cc, unfold_op.cc,
segment_pool (incubate segment_{sum,mean,max,min}), partial_concat_op.cc,
partial_sum_op.cc, pool_with_index_op.cc (3d), conv2d_transpose_op.cc
(depthwise variant), lod_reset_op.cc, controlflow/select_output,
get_tensor_from_selected_rows_op.cc, merge_selected_rows_op.cc,
save_op.cc / load_op.cc / save_combine_op.cc / load_combine_op.cc,
correlation (contrib optical-flow cost volume).

Deliberately NOT here (documented descopes): mkldnn/x86 fusion_* ops and
cudnn_lstm (XLA owns fusion), tensorrt/lite engines, quantize/dequantize
mkldnn trio, BoxPS pull/push family + rank_attention + bilateral_slice
(CUDA-only industrial tail, C24 descope), LoD array conversion ops
(padded redesign replaces LoD), run_program (jit.partial_program covers
the capability architecturally).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op


# ---------------------------------------------------------------------------
# label_smooth / unfold
# ---------------------------------------------------------------------------

@register_op("label_smooth", inputs=["X", "PriorDist?!"], outputs=["Out"])
def label_smooth(ins, attrs, ctx):
    """label_smooth_op.cc — (1-eps)*label + eps*prior (uniform 1/C when
    no PriorDist)."""
    x = jnp.asarray(ins["X"])
    eps = attrs.get("epsilon", 0.0)
    prior = ins.get("PriorDist")
    if prior is not None:
        p = jnp.asarray(prior).reshape(1, -1)
    else:
        p = 1.0 / x.shape[-1]
    return {"Out": (1.0 - eps) * x + eps * p}


@register_op("unfold", inputs=["X"], outputs=["Y"])
def unfold(ins, attrs, ctx):
    """unfold_op.cc (im2col as the 2.0 API): X [N,C,H,W] ->
    Y [N, C*kh*kw, L] with L the number of sliding positions."""
    x = jnp.asarray(ins["X"])
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    dh, dw = attrs.get("dilations", [1, 1])
    n, c, h, w = x.shape
    x = jnp.pad(x, [(0, 0), (0, 0), (p[0], p[2] if len(p) == 4 else p[0]),
                    (p[1] if len(p) == 4 else p[1],
                     p[3] if len(p) == 4 else p[1])])
    oh = (x.shape[2] - (dh * (kh - 1) + 1)) // sh + 1
    ow = (x.shape[3] - (dw * (kw - 1) + 1)) // sw + 1
    # gather patches: [N, C, kh, kw, oh, ow]
    rows = (jnp.arange(oh)[:, None] * sh +
            jnp.arange(kh)[None, :] * dh)         # [oh, kh]
    cols = (jnp.arange(ow)[:, None] * sw +
            jnp.arange(kw)[None, :] * dw)         # [ow, kw]
    patches = x[:, :, rows[:, :, None, None], cols[None, None]]
    # [N, C, oh, kh, ow, kw] -> [N, C, kh, kw, oh*ow]
    patches = jnp.transpose(patches, (0, 1, 3, 5, 2, 4))
    return {"Y": patches.reshape(n, c * kh * kw, oh * ow)}


# ---------------------------------------------------------------------------
# segment_pool / partial_concat / partial_sum
# ---------------------------------------------------------------------------

@register_op("segment_pool", inputs=["X", "SegmentIds!"],
             outputs=["Out", "SummedIds?"])
def segment_pool(ins, attrs, ctx):
    """segment_pool_op (incubate segment_{sum,mean,max,min}): pool rows
    of X by SegmentIds.  Output rows = attrs['num_segments'] when given
    (static-shape contract), else X's row count (ids < N always)."""
    x = jnp.asarray(ins["X"])
    ids = jnp.asarray(ins["SegmentIds"]).reshape(-1).astype(jnp.int32)
    pool = attrs.get("pooltype", "SUM").upper()
    n_seg = int(attrs.get("num_segments", x.shape[0]))
    counts = jnp.zeros((n_seg,), x.dtype).at[ids].add(1.0)
    if pool in ("SUM", "MEAN"):
        out = jnp.zeros((n_seg,) + x.shape[1:], x.dtype).at[ids].add(x)
        if pool == "MEAN":
            out = out / jnp.maximum(counts, 1.0).reshape(
                (-1,) + (1,) * (x.ndim - 1))
    elif pool == "MAX":
        out = jnp.full((n_seg,) + x.shape[1:], -jnp.inf, x.dtype) \
            .at[ids].max(x)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    elif pool == "MIN":
        out = jnp.full((n_seg,) + x.shape[1:], jnp.inf, x.dtype) \
            .at[ids].min(x)
        out = jnp.where(jnp.isfinite(out), out, 0.0)
    else:
        raise ValueError(f"segment_pool: unknown pooltype {pool!r}")
    return {"Out": out, "SummedIds": counts.reshape(-1, 1)}


@register_op("partial_concat", inputs=["X*"], outputs=["Out"])
def partial_concat(ins, attrs, ctx):
    """partial_concat_op.cc — concat a [start:start+length] column slice
    of every input (CTR feature slicing)."""
    xs = [jnp.asarray(v) for v in ins["X"]]
    start = int(attrs.get("start_index", 0))
    length = int(attrs.get("length", -1))
    parts = []
    for x in xs:
        s = start + x.shape[1] if start < 0 else start
        end = x.shape[1] if length < 0 else s + length
        parts.append(x[:, s:end])
    return {"Out": jnp.concatenate(parts, axis=1)}


@register_op("partial_sum", inputs=["X*"], outputs=["Out"])
def partial_sum(ins, attrs, ctx):
    """partial_sum_op.cc — elementwise sum of the same column slice of
    every input."""
    xs = [jnp.asarray(v) for v in ins["X"]]
    start = int(attrs.get("start_index", 0))
    length = int(attrs.get("length", -1))
    out = None
    for x in xs:
        s = start + x.shape[1] if start < 0 else start
        end = x.shape[1] if length < 0 else s + length
        sl = x[:, s:end]
        out = sl if out is None else out + sl
    return {"Out": out}


# ---------------------------------------------------------------------------
# max_pool3d_with_index / depthwise_conv2d_transpose
# ---------------------------------------------------------------------------

@register_op("max_pool3d_with_index", inputs=["X"], outputs=["Out", "Mask"])
def max_pool3d_with_index(ins, attrs, ctx):
    """pool_with_index_op.cc (3d) — max pool + flat argmax indices over
    each [D,H,W] volume, the 3-d sibling of nn.py max_pool2d_with_index:
    a paired (value, index) reduce_window stays O(input) memory (no
    kd*kh*kw patch blowup) and breaks ties toward the smallest index
    like the reference's scan order.  Padded cells carry the init
    (-inf, sentinel) so they never win and Mask always indexes the
    UNPADDED volume."""
    x = jnp.asarray(ins["X"])
    ksize = list(attrs["ksize"])
    if attrs.get("global_pooling", False):
        ksize = list(x.shape[2:])
    strides = list(attrs.get("strides", ksize))
    pads = list(attrs.get("paddings", [0, 0, 0]))
    if attrs.get("adaptive", False):
        raise NotImplementedError(
            "max_pool3d_with_index adaptive=True: use pool3d(adaptive) "
            "when indices are not needed")
    n, c, d, h, w = x.shape
    idx_map = jnp.broadcast_to(
        jnp.arange(d * h * w, dtype=jnp.int32).reshape(1, 1, d, h, w),
        x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        pick_b = (bv > av) | ((bv == av) & (bi < ai))
        return (jnp.where(pick_b, bv, av), jnp.where(pick_b, bi, ai))

    init_v = jnp.array(-jnp.inf, x.dtype) \
        if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.array(jnp.iinfo(x.dtype).min, x.dtype)
    pad_cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    out, mask = jax.lax.reduce_window(
        (x, idx_map), (init_v, jnp.array(d * h * w, jnp.int32)), reducer,
        (1, 1) + tuple(ksize), (1, 1) + tuple(strides), pad_cfg)
    return {"Out": out, "Mask": mask.astype(jnp.int64)}


@register_op("depthwise_conv2d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def depthwise_conv2d_transpose(ins, attrs, ctx):
    """conv2d_transpose_op.cc depthwise variant (groups == channels).
    Per-channel transposed conv = spatially-flipped depthwise conv with
    lhs dilation; Filter [C, 1, kh, kw] is already OIHW for
    feature_group_count=C, so no group reshuffle is needed."""
    x = jnp.asarray(ins["Input"])
    w = jnp.asarray(ins["Filter"])
    strides = tuple(attrs.get("strides", [1, 1]))
    dilations = tuple(attrs.get("dilations", [1, 1]))
    pads = attrs.get("paddings", [0, 0])
    c = x.shape[1]
    padding = []
    for i in range(2):
        lo = pads[i] if len(pads) == 2 else pads[2 * i]
        hi = pads[i] if len(pads) == 2 else pads[2 * i + 1]
        k = (w.shape[2 + i] - 1) * dilations[i] + 1
        padding.append((k - 1 - lo, k - 1 - hi))
    out = jax.lax.conv_general_dilated(
        x, jnp.flip(w, axis=(-1, -2)), (1, 1), padding,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=c)
    return {"Output": out.astype(x.dtype)}


# ---------------------------------------------------------------------------
# lod_reset / select_output / SelectedRows utilities
# ---------------------------------------------------------------------------

@register_op("lod_reset", inputs=["X", "Y?!"], outputs=["Out", "Length?"])
def lod_reset(ins, attrs, ctx):
    """lod_reset_op.cc — in the padded redesign data never moves; the op
    re-emits X with the NEW per-sequence lengths (from Y's lengths or
    the target_lod attr converted from offsets)."""
    x = jnp.asarray(ins["X"])
    y = ins.get("Y")
    if y is not None:
        length = jnp.asarray(y).reshape(-1)
    else:
        lod = list(attrs.get("target_lod", []))
        length = jnp.asarray(np.diff(np.asarray(lod, np.int64))
                             if len(lod) > 1 else [x.shape[0]])
    return {"Out": x, "Length": length.astype(jnp.int64)}


@register_op("select_output", inputs=["X", "Mask!"], outputs=["Out*"],
             grad=None)
def select_output(ins, attrs, ctx):
    """controlflow/select_output — route X to output branch Mask; the
    other branches carry zeros (static-shape stand-in for the
    reference's empty un-selected vars)."""
    x = jnp.asarray(ins["X"])
    mask = jnp.asarray(ins["Mask"]).reshape(()).astype(jnp.int32)
    n = int(attrs.get("num_outputs", 2))
    return {"Out": [jnp.where(mask == i, x, jnp.zeros_like(x))
                    for i in range(n)]}


@register_op("get_tensor_from_selected_rows", inputs=["X"],
             outputs=["Out"], grad=None)
def get_tensor_from_selected_rows(ins, attrs, ctx):
    """get_tensor_from_selected_rows_op.cc — densify: scatter-add the
    rows into a [height, ...] tensor."""
    from ...core.selected_rows import SelectedRows
    x = ins["X"]
    if not isinstance(x, SelectedRows):
        return {"Out": jnp.asarray(x)}
    vals = jnp.asarray(x.values)
    dense = jnp.zeros((x.height,) + vals.shape[1:], vals.dtype)
    return {"Out": dense.at[jnp.asarray(x.rows).astype(jnp.int32)]
            .add(vals)}


@register_op("merge_selected_rows", inputs=["X"], outputs=["Out"],
             grad=None)
def merge_selected_rows(ins, attrs, ctx):
    """merge_selected_rows_op.cc — combine duplicate row ids by adding
    their values.  Static-shape form: densify then re-emit as arange
    rows over the full height (duplicates merged by the scatter-add;
    the reference's compacted unique-row output has a data-dependent
    shape)."""
    from ...core.selected_rows import SelectedRows
    x = ins["X"]
    if not isinstance(x, SelectedRows):
        return {"Out": x}
    vals = jnp.asarray(x.values)
    dense = jnp.zeros((x.height,) + vals.shape[1:], vals.dtype) \
        .at[jnp.asarray(x.rows).astype(jnp.int32)].add(vals)
    return {"Out": SelectedRows(jnp.arange(x.height, dtype=jnp.int32),
                                dense, x.height)}


# ---------------------------------------------------------------------------
# save / load ops ("save/load IS a program", reference io contract)
# ---------------------------------------------------------------------------

def _io_path(attrs):
    p = attrs.get("file_path", "")
    if not p:
        raise ValueError("save/load op needs a file_path attr")
    return p


@register_op("save", inputs=["X"], outputs=[], grad=None,
             side_effect=True)
def save_op(ins, attrs, ctx):
    """save_op.cc — persist the input tensor to file_path; ordered host
    callback so it composes with the jitted whole-block executor."""
    from jax.experimental import io_callback
    path = _io_path(attrs)

    def host(arr):
        import os as _os
        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        np.save(path if path.endswith(".npy") else path + ".npy",
                np.asarray(arr))
        return np.bool_(True)

    io_callback(host, jax.ShapeDtypeStruct((), jnp.bool_),
                jnp.asarray(ins["X"]), ordered=True)
    return {}


@register_op("save_combine", inputs=["X*"], outputs=[], grad=None,
             side_effect=True)
def save_combine_op(ins, attrs, ctx):
    """save_combine_op.cc — persist all inputs into ONE file (npz)."""
    from jax.experimental import io_callback
    path = _io_path(attrs)
    names = attrs.get("var_names") or [
        f"v{i}" for i in range(len(ins["X"]))]
    if len(names) != len(ins["X"]):
        raise ValueError(
            f"save_combine: {len(ins['X'])} inputs but "
            f"{len(names)} var_names — a silent zip-truncate would "
            "drop tensors from the checkpoint")

    def host(*arrs):
        import os as _os
        _os.makedirs(_os.path.dirname(path) or ".", exist_ok=True)
        np.savez(path, **{n: np.asarray(a)
                          for n, a in zip(names, arrs)})
        return np.bool_(True)

    io_callback(host, jax.ShapeDtypeStruct((), jnp.bool_),
                *[jnp.asarray(v) for v in ins["X"]], ordered=True)
    return {}


@register_op("load", inputs=[], outputs=["Out"], grad=None,
             side_effect=True)
def load_op(ins, attrs, ctx):
    """load_op.cc — read a tensor saved by the save op.  The file is
    read at TRACE time (output shapes must be static; load ops run in
    startup/restore programs that are traced per execution, matching the
    reference's run-once usage)."""
    path = _io_path(attrs)
    arr = np.load(path if path.endswith(".npy") else path + ".npy")
    return {"Out": jnp.asarray(arr)}


@register_op("load_combine", inputs=[], outputs=["Out*"], grad=None,
             side_effect=True)
def load_combine_op(ins, attrs, ctx):
    """load_combine_op.cc — read the save_combine npz back, in
    var_names order."""
    path = _io_path(attrs)
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    names = attrs.get("var_names") or list(data.files)
    return {"Out": [jnp.asarray(data[n]) for n in names]}


# ---------------------------------------------------------------------------
# correlation (contrib optical-flow cost volume)
# ---------------------------------------------------------------------------

@register_op("correlation", inputs=["Input1", "Input2"], outputs=["Output"])
def correlation(ins, attrs, ctx):
    """correlation_op.cc/.cu (FlowNet cost volume): one output channel
    per displacement (di, dj) on the stride2 grid within
    max_displacement, each the k x k x C patch inner product of x1 with
    x2 shifted by the displacement, normalized by k*k*C
    (correlation_op.cu:113 nelems).  Output spatial size follows
    GetOutputSize (correlation_op.cc:32-45): centers start border_radius
    = kernel_radius + max_displacement into the zero-padded inputs and
    step by stride1.  Shifts are zero-padded slices (no wrap-around);
    the patch sum is one reduce_window per displacement — dense batched
    math, no gathers."""
    x1 = jnp.asarray(ins["Input1"])
    x2 = jnp.asarray(ins["Input2"])
    pad = int(attrs.get("pad_size", 0))
    k = int(attrs.get("kernel_size", 1))
    max_d = int(attrs.get("max_displacement", 1))
    s1 = int(attrs.get("stride1", 1))
    s2 = int(attrs.get("stride2", 1))
    n, c, h, w = x1.shape
    rad = (k - 1) // 2
    border = rad + max_d
    ph, pw = h + 2 * pad, w + 2 * pad
    oh = max(1, -(-(ph - 2 * border) // s1))
    ow = max(1, -(-(pw - 2 * border) // s1))
    x1p = jnp.pad(x1, [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    # extra max_d halo on x2 so every displacement is a plain slice of
    # ZEROS beyond the (already padded) image — never a wrap
    x2p = jnp.pad(x2, [(0, 0), (0, 0), (pad + max_d, pad + max_d),
                       (pad + max_d, pad + max_d)])
    # reference grid: 2*(max_d // s2) + 1 per axis, ALWAYS including the
    # zero displacement (correlation_op.cc:36 output_channel)
    d_rad = max_d // s2
    disp = [i * s2 for i in range(-d_rad, d_rad + 1)]
    nelems = float(k * k * c)
    # output centers in the padded frame; window STARTS rad earlier
    r0 = border - rad
    rows = r0 + jnp.arange(oh) * s1
    cols = r0 + jnp.arange(ow) * s1
    outs = []
    for di in disp:
        for dj in disp:
            shifted = jax.lax.dynamic_slice(
                x2p, (0, 0, max_d + di, max_d + dj), x1p.shape)
            prod = jnp.sum(x1p * shifted, axis=1)       # [n, ph, pw]
            win = jax.lax.reduce_window(
                prod, 0.0, jax.lax.add, (1, k, k), (1, 1, 1),
                [(0, 0), (0, 0), (0, 0)])               # window starts
            outs.append(win[:, rows[:, None], cols[None, :]] / nelems)
    return {"Output": jnp.stack(outs, axis=1)}

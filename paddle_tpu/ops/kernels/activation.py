"""Activation family (reference macro FOR_EACH_ACTIVATION_OP,
/root/reference/paddle/fluid/operators/activation_op.cc).  Pure VPU ops —
XLA fuses them into producers; gradients come from the registry's auto-vjp."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _act(name, fn, grad="auto"):
    @register_op(name, inputs=["X"], outputs=["Out"], grad=grad)
    def kernel(ins, attrs, ctx, _fn=fn):
        return {"Out": _fn(ins["X"], attrs)}
    return kernel


_act("sigmoid", lambda x, a: jax.nn.sigmoid(x))
_act("logsigmoid", lambda x, a: jax.nn.log_sigmoid(x))
_act("relu", lambda x, a: jax.nn.relu(x))
_act("relu6", lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
_act("brelu", lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
_act("soft_relu", lambda x, a: jnp.log1p(jnp.exp(jnp.clip(
    x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))))
_act("tanh", lambda x, a: jnp.tanh(x))
_act("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_act("stanh", lambda x, a: a.get("scale_b", 1.7159) *
     jnp.tanh(a.get("scale_a", 0.67) * x))
_act("hard_shrink", lambda x, a: jnp.where(
    jnp.abs(x) > a.get("threshold", 0.5), x, 0.0))
_act("softshrink", lambda x, a: jnp.where(
    x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
    jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)))
_act("hard_sigmoid", lambda x, a: jnp.clip(
    a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0))
_act("hard_swish", lambda x, a: x * jnp.clip(
    x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)) /
    a.get("scale", 6.0))
_act("swish", lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
_act("softplus", lambda x, a: jax.nn.softplus(x))
_act("softsign", lambda x, a: x / (1 + jnp.abs(x)))
_act("sqrt", lambda x, a: jnp.sqrt(x))
_act("rsqrt", lambda x, a: jax.lax.rsqrt(x))
_act("abs", lambda x, a: jnp.abs(x))
_act("ceil", lambda x, a: jnp.ceil(x), grad=None)
_act("floor", lambda x, a: jnp.floor(x), grad=None)
_act("round", lambda x, a: jnp.round(x), grad=None)
_act("reciprocal", lambda x, a: 1.0 / x)
_act("log", lambda x, a: jnp.log(x))
_act("log1p", lambda x, a: jnp.log1p(x))
_act("log2", lambda x, a: jnp.log2(x))
_act("log10", lambda x, a: jnp.log10(x))
_act("square", lambda x, a: jnp.square(x))
_act("exp", lambda x, a: jnp.exp(x))
_act("sin", lambda x, a: jnp.sin(x))
_act("cos", lambda x, a: jnp.cos(x))
_act("sinh", lambda x, a: jnp.sinh(x))
_act("cosh", lambda x, a: jnp.cosh(x))
_act("tan", lambda x, a: jnp.tan(x))
_act("asin", lambda x, a: jnp.arcsin(x))
_act("acos", lambda x, a: jnp.arccos(x))
_act("atan", lambda x, a: jnp.arctan(x))
_act("thresholded_relu", lambda x, a: jnp.where(
    x > a.get("threshold", 1.0), x, 0.0))
_act("pow", lambda x, a: jnp.power(x, a.get("factor", 1.0)))
_act("erf", lambda x, a: jax.scipy.special.erf(x))
_act("gelu", lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate",
                                                           False)))
_act("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_act("selu", lambda x, a: a.get("scale", 1.0507009873554805) * jnp.where(
    x > 0, x, a.get("alpha", 1.6732632423543772) * (jnp.exp(x) - 1)))
_act("silu", lambda x, a: jax.nn.silu(x))
def _log_softmax(x, a):
    # fp32 internals for low-precision inputs (see softmax in nn.py)
    from .loss import _compute_dtype
    return jax.nn.log_softmax(x.astype(_compute_dtype(x)),
                              axis=a.get("axis", -1)).astype(x.dtype)


_act("log_softmax", _log_softmax)


@register_op("prelu", inputs=["X", "Alpha"], outputs=["Out"])
def prelu(ins, attrs, ctx):
    x, alpha = ins["X"], ins["Alpha"]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "all":
        alpha = alpha.reshape(())
    return {"Out": jnp.where(x > 0, x, alpha * x)}


@register_op("leaky_relu", inputs=["X"], outputs=["Out"])
def leaky_relu(ins, attrs, ctx):
    alpha = attrs.get("alpha", 0.02)
    return {"Out": jax.nn.leaky_relu(ins["X"], alpha)}


@register_op("elu", inputs=["X"], outputs=["Out"])
def elu(ins, attrs, ctx):
    return {"Out": jax.nn.elu(ins["X"], attrs.get("alpha", 1.0))}


@register_op("maxout", inputs=["X"], outputs=["Out"])
def maxout(ins, attrs, ctx):
    x = ins["X"]
    groups = attrs["groups"]
    axis = attrs.get("axis", 1)
    if axis < 0:
        axis += x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1:]
    return {"Out": jnp.max(x.reshape(new_shape), axis=axis + 1)}

"""Beam-search decode ops + py_func.

Reference: /root/reference/paddle/fluid/operators/beam_search_op.cc (one
step of beam selection over LoD-grouped candidates),
beam_search_decode_op.cc (walks the step-by-step LoD arrays back into full
hypotheses), gather_tree_op.cc, py_func_op.cc (:1 host-python op).

TPU redesign: the reference threads beams through LoD levels; here beams
are a dense [batch, beam] axis.  One `beam_search` op consumes
[batch*beam, V] scores and emits the top-`beam` continuations per batch
group (top_k over the flattened beam*V axis — one XLA fusion, no
host-side candidate lists).  Full-sequence reconstruction is gather_tree
(a lax.scan walking parent pointers), matching the paddle 2.x
fluid.layers.gather_tree contract.  py_func lowers to
jax.pure_callback — the host function runs under jit without breaking the
traced graph.
"""
from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np
import jax
import jax.numpy as jnp

from ..registry import register_op

_NEG = -1e30


@register_op("beam_search",
             inputs=["pre_ids!", "pre_scores", "scores", "ids?!"],
             outputs=["selected_ids", "selected_scores", "parent_idx?"],
             grad=None)
def beam_search(ins, attrs, ctx):
    """One decode step.  pre_ids [B*W, 1], pre_scores [B*W, 1],
    scores [B*W, V] log-probs for the next token.  Emits the top-W
    (id, score, parent beam) per batch group.  Finished beams (pre_id ==
    end_id) are frozen: they re-emit end_id with unchanged score."""
    beam_size = int(attrs["beam_size"])
    end_id = int(attrs.get("end_id", 1))
    pre_ids = ins["pre_ids"].reshape(-1)
    pre_scores = ins["pre_scores"].reshape(-1).astype(jnp.float32)
    scores = ins["scores"].astype(jnp.float32)
    BW, V = scores.shape
    B = BW // beam_size
    finished = pre_ids == end_id
    # frozen beams contribute exactly one candidate: end_id at the old
    # score; live beams add log-probs
    cand = pre_scores[:, None] + jnp.where(finished[:, None], _NEG, scores)
    keep_end = jnp.where(finished, pre_scores, _NEG)
    cand = cand.at[:, end_id].max(keep_end)
    # first step convention: only beam 0 of each group is live (the rest
    # duplicate it); detect via attr
    if attrs.get("first_step", False):
        mask = (jnp.arange(BW) % beam_size) == 0
        cand = jnp.where(mask[:, None], cand, _NEG)
    flat = cand.reshape(B, beam_size * V)
    top_s, top_i = jax.lax.top_k(flat, beam_size)      # [B, W]
    parent = top_i // V
    token = top_i % V
    parent_global = parent + jnp.arange(B)[:, None] * beam_size
    return {"selected_ids": token.reshape(-1, 1).astype(jnp.int64),
            "selected_scores": top_s.reshape(-1, 1),
            "parent_idx": parent_global.reshape(-1).astype(jnp.int64)}


@register_op("gather_tree", inputs=["Ids!", "Parents!"],
             outputs=["Out"], grad=None)
def gather_tree(ins, attrs, ctx):
    """gather_tree_op.cc — [T, B, W] step ids + parent beam indices ->
    full sequences by walking parents backward from the last step."""
    ids, parents = ins["Ids"], ins["Parents"]
    T, B, W = ids.shape
    beams0 = jnp.broadcast_to(jnp.arange(W, dtype=parents.dtype), (B, W))

    def step(beam, t):
        tok = jnp.take_along_axis(ids[t], beam, axis=1)
        beam_prev = jnp.take_along_axis(parents[t], beam, axis=1)
        return beam_prev, tok

    _, toks_rev = jax.lax.scan(step, beams0, jnp.arange(T)[::-1])
    return {"Out": toks_rev[::-1]}


@register_op("beam_search_decode",
             inputs=["Ids!", "Scores", "ParentIdx!", "SequenceLength?!"],
             outputs=["SentenceIds", "SentenceScores"], grad=None)
def beam_search_decode(ins, attrs, ctx):
    """beam_search_decode_op.cc — final hypotheses: gather_tree the id
    tree, then trim everything after the first end_id (padded with
    end_id)."""
    ids, parents = ins["Ids"], ins["ParentIdx"]
    scores = ins["Scores"]
    end_id = int(attrs.get("end_id", 1))
    out = gather_tree({"Ids": ids, "Parents": parents}, attrs, ctx)["Out"]
    # trim strictly AFTER the first end_id: a position is dead iff an
    # end_id appeared at any earlier step
    c = jnp.cumsum((out == end_id).astype(jnp.int32), axis=0)
    prev_ended = jnp.concatenate(
        [jnp.zeros_like(c[:1]), c[:-1]], axis=0) > 0
    out = jnp.where(prev_ended, end_id, out)
    final_scores = scores[-1] if scores.ndim == 3 else scores
    return {"SentenceIds": out, "SentenceScores": final_scores}


# ---------------------------------------------------------------------------
# py_func — host-python op via pure_callback
# ---------------------------------------------------------------------------
_PY_FUNCS: List[Callable] = []
_PY_FUNC_IDS: Dict[int, int] = {}  # id(fn) -> slot (dedup across rebuilds)


def register_py_func(fn: Callable) -> int:
    """Register a host function; returns the id carried in op attrs
    (py_func_op.cc PyFuncRegistry).  Registering the same function object
    again returns the same slot, so rebuilding a program keeps its
    fingerprint (and the executor's jit cache) stable."""
    key = id(fn)
    slot = _PY_FUNC_IDS.get(key)
    if slot is not None and _PY_FUNCS[slot] is fn:
        return slot
    _PY_FUNCS.append(fn)
    _PY_FUNC_IDS[key] = len(_PY_FUNCS) - 1
    return len(_PY_FUNCS) - 1


def _py_func_kernel(ins, attrs, ctx):
    fn = _PY_FUNCS[int(attrs["func_id"])]
    xs = ins["X"] or []
    shapes = attrs["out_shapes"]
    dtypes = attrs["out_dtypes"]
    # resolve symbolic batch dims (-1) against the first input's batch
    batch = xs[0].shape[0] if xs else 1
    shapes = [[batch if d == -1 else d for d in s] for s in shapes]
    result_shape = [jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
                    for s, d in zip(shapes, dtypes)]

    def host(*arrs):
        out = fn(*arrs)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(np.asarray(o, dtype=np.dtype(d))
                     for o, d in zip(out, dtypes))

    outs = jax.pure_callback(host, result_shape, *xs)
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    return {"Out": list(outs)}


def _py_func_grad(ins, attrs, ctx):
    """Paddle py_func backward contract: backward_func receives forward
    inputs + forward outputs + output grads, minus the positions named in
    skip_vars_in_backward_input (encoded as skip indices at build time)."""
    bid = attrs.get("backward_func_id", -1)
    if bid < 0:
        return {}
    fn = _PY_FUNCS[int(bid)]
    xs = list(ins["X"] or [])
    outs = list(ins.get("Out") or [])
    gs = [g for g in (ins.get("Out@GRAD") or [])]
    skip = set(attrs.get("backward_skip_ins", []))
    call_args = [a for i, a in enumerate(xs + outs) if i not in skip] + gs
    shapes = [tuple(x.shape) for x in xs]
    dtypes = [np.dtype(str(x.dtype)) for x in xs]
    result_shape = [jax.ShapeDtypeStruct(s, d)
                    for s, d in zip(shapes, dtypes)]

    def host(*arrs):
        out = fn(*arrs)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(np.asarray(o, dtype=d)
                     for o, d in zip(out, dtypes))

    douts = jax.pure_callback(host, result_shape, *call_args)
    if not isinstance(douts, (list, tuple)):
        douts = (douts,)
    return {"X@GRAD": list(douts)}


register_op("py_func", inputs=["X*"], outputs=["Out*"],
            grad=_py_func_grad)(_py_func_kernel)

"""Collective ops (reference: /root/reference/paddle/fluid/operators/collective/
c_allreduce_op.h:124 ncclAllReduce dispatch, c_broadcast_op, c_allgather_op,
c_reducescatter_op, barrier_op; ring ids from
platform/collective_helper.h:62 NCCLCommContext).

TPU-native lowering: when the executor traces the program under shard_map over
a jax.sharding.Mesh, ctx.collective_axes(ring_id) names the mesh axes and the
ops become XLA collectives over ICI (psum/all_gather/psum_scatter/ppermute).
Outside any mesh (single-chip), world size is 1 and they are identities —
the same degenerate behaviour the reference has with one trainer.

The c_sync_*_stream ops are no-ops: XLA owns scheduling, there are no user
streams to sync (reference needed them because NCCL ran on separate CUDA
streams).  c_comm_init/c_gen_nccl_id have no TPU equivalent: mesh formation is
jax.distributed initialization; they are registered as no-ops for program
compatibility."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _axes(ctx, attrs):
    return ctx.collective_axes(attrs.get("ring_id", 0))


def _axis_size(ax) -> int:
    """Static size of a mesh axis from inside shard_map.  jax.lax.axis_size
    only exists in newer jax; psum of a python 1 is constant-folded to the
    axis size at trace time on every version."""
    import jax as _jax
    if hasattr(_jax.lax, "axis_size"):
        return _jax.lax.axis_size(ax)
    return int(_jax.lax.psum(1, ax))


def _c_allreduce(name, op):
    @register_op(name, inputs=["X"], outputs=["Out"], grad="auto",
                 side_effect=True)
    def kernel(ins, attrs, ctx, _op=op):
        from ...core.selected_rows import SelectedRows
        x = ins["X"]
        axes = _axes(ctx, attrs)
        if not axes:
            return {"Out": x}
        if isinstance(x, SelectedRows):
            # sparse allreduce (reference allgathers SelectedRows grads):
            # psum would sum the int32 row INDICES across replicas —
            # all_gather rows+values instead; concatenation is the sum
            # under scatter-add semantics
            if _op != "sum":
                raise NotImplementedError(
                    f"{_op} allreduce over SelectedRows")
            rows, vals = x.rows, x.values
            for ax in ([axes] if isinstance(axes, str) else axes):
                rows = jax.lax.all_gather(rows, ax, tiled=True)
                vals = jax.lax.all_gather(vals, ax, tiled=True)
            return {"Out": SelectedRows(rows, vals, x.height)}
        if _op == "sum":
            return {"Out": jax.lax.psum(x, axes)}
        if _op == "max":
            return {"Out": jax.lax.pmax(x, axes)}
        if _op == "min":
            return {"Out": jax.lax.pmin(x, axes)}
        if _op == "prod":
            return {"Out": jnp.exp(jax.lax.psum(jnp.log(x), axes))}
        raise ValueError(_op)
    return kernel


_c_allreduce("c_allreduce_sum", "sum")
_c_allreduce("c_allreduce_max", "max")
_c_allreduce("c_allreduce_min", "min")
_c_allreduce("c_allreduce_prod", "prod")
_c_allreduce("allreduce", "sum")  # legacy distributed_ops/allreduce_op
_c_allreduce("c_reduce_sum", "sum")   # reduce-to-root approximated as
_c_allreduce("c_reduce_max", "max")   # allreduce (root semantics preserved
_c_allreduce("c_reduce_min", "min")   # for the root rank's value)
_c_allreduce("c_reduce_prod", "prod")


@register_op("c_broadcast", inputs=["X"], outputs=["Out"], side_effect=True)
def c_broadcast(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    root = attrs.get("root", 0)
    # broadcast root's value: select root's shard and psum the rest to it
    idx = jax.lax.axis_index(axes if isinstance(axes, str) else axes[0])
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return {"Out": jax.lax.psum(masked, axes)}


@register_op("broadcast", inputs=["X"], outputs=["Out"], side_effect=True)
def broadcast_legacy(ins, attrs, ctx):
    return c_broadcast(ins, attrs, ctx)


@register_op("c_allgather", inputs=["X"], outputs=["Out"], side_effect=True)
def c_allgather(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    out = jax.lax.all_gather(x, ax, axis=0, tiled=True)
    return {"Out": out}


@register_op("c_reducescatter", inputs=["X"], outputs=["Out"],
             side_effect=True)
def c_reducescatter(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    return {"Out": jax.lax.psum_scatter(x, ax, scatter_dimension=0,
                                        tiled=True)}


@register_op("c_scatter", inputs=["X"], outputs=["Out"], side_effect=True)
def c_scatter(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    n = _axis_size(ax)
    idx = jax.lax.axis_index(ax)
    # only the root's buffer is meaningful — broadcast it first so non-root
    # ranks may contribute an arbitrary (e.g. zero) full-shaped buffer
    root = attrs.get("root", 0)
    x = jax.lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)), axes)
    shard = x.shape[0] // n
    return {"Out": jax.lax.dynamic_slice_in_dim(x, idx * shard, shard, 0)}


@register_op("barrier", inputs=["X?"], outputs=["Out?"], grad=None,
             side_effect=True)
def barrier(ins, attrs, ctx):
    # XLA collectives synchronise implicitly; a psum of a scalar is a true
    # cross-replica barrier when one is explicitly requested
    axes = _axes(ctx, attrs)
    x = ins.get("X")
    if x is None:
        x = jnp.zeros((1,), jnp.float32)
    if axes:
        x = x + 0 * jax.lax.psum(jnp.ones((), x.dtype), axes)
    return {"Out": x}


@register_op("c_embedding", inputs=["W", "Ids!"], outputs=["Out"],
             side_effect=True)
def c_embedding(ins, attrs, ctx):
    # model-parallel embedding shard: rows [start, start+n) live here
    w, ids = ins["W"], ins["Ids"].astype(jnp.int32)
    start = attrs.get("start_index", 0)
    local = ids - start
    valid = (local >= 0) & (local < w.shape[0])
    out = jnp.take(w, jnp.clip(local, 0, w.shape[0] - 1), axis=0)
    out = jnp.where(valid[..., None], out, jnp.zeros_like(out))
    axes = _axes(ctx, attrs)
    if axes:
        out = jax.lax.psum(out, axes)
    return {"Out": out}


@register_op("c_concat", inputs=["X"], outputs=["Out"], side_effect=True)
def c_concat(ins, attrs, ctx):
    # tensor-parallel allgather along last dim
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    return {"Out": jax.lax.all_gather(x, ax, axis=x.ndim - 1, tiled=True)}


@register_op("c_split", inputs=["X"], outputs=["Out"], side_effect=True)
def c_split(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    n = _axis_size(ax)
    idx = jax.lax.axis_index(ax)
    shard = x.shape[-1] // n
    return {"Out": jax.lax.dynamic_slice_in_dim(x, idx * shard, shard,
                                                x.ndim - 1)}


def _mp_allreduce_grad(ins, attrs, ctx):
    """Megatron g-operator backward: the forward psum's cotangent is
    replicated, and each shard's input contributed once — identity (NOT
    another psum, which would scale grads by the tp degree)."""
    return {"X@GRAD": ins["Out@GRAD"]}


@register_op("mp_allreduce_sum", inputs=["X"], outputs=["Out"],
             grad=_mp_allreduce_grad, side_effect=True)
def mp_allreduce_sum(ins, attrs, ctx):
    """Model-parallel partial-sum reduction (paddle mp_allreduce_sum):
    same forward as c_allreduce_sum, differentiable with identity
    backward."""
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    return {"Out": jax.lax.psum(x, axes)}


def _c_identity_grad(ins, attrs, ctx):
    """Reference model-parallel semantics (_c_identity in paddle's mp
    helpers): identity forward, allreduce backward over the bound ring —
    the Megatron f-operator guarding a column-parallel layer's input."""
    g = ins["Out@GRAD"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"X@GRAD": g}
    return {"X@GRAD": jax.lax.psum(g, axes)}


@register_op("c_identity", inputs=["X"], outputs=["Out"],
             grad=_c_identity_grad, side_effect=True)
def c_identity(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("c_sync_calc_stream", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def c_sync_calc_stream(ins, attrs, ctx):
    return {"Out": ins["X"]}  # no user streams under XLA


@register_op("c_sync_comm_stream", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def c_sync_comm_stream(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("c_comm_init", inputs=["X?"], outputs=[], grad=None,
             side_effect=True)
def c_comm_init(ins, attrs, ctx):
    return {}  # mesh formation happens in jax.distributed / Mesh creation


@register_op("c_comm_init_all", inputs=[], outputs=[], grad=None,
             side_effect=True)
def c_comm_init_all(ins, attrs, ctx):
    return {}


@register_op("c_gen_nccl_id", inputs=[], outputs=["Out?"], grad=None,
             side_effect=True)
def c_gen_nccl_id(ins, attrs, ctx):
    return {}  # no NCCL id on TPU; kept for program compatibility


@register_op("c_wait_comm", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def c_wait_comm(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("c_wait_compute", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def c_wait_compute(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("partial_allgather", inputs=["X"], outputs=["Out"],
             side_effect=True)
def partial_allgather(ins, attrs, ctx):
    return c_allgather(ins, attrs, ctx)


@register_op("alltoall", inputs=["X"], outputs=["Out"], side_effect=True)
def alltoall(ins, attrs, ctx):
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    n = _axis_size(ax)
    xs = x.reshape((n, x.shape[0] // n) + x.shape[1:])
    out = jax.lax.all_to_all(xs, ax, split_axis=0, concat_axis=0, tiled=False)
    return {"Out": out.reshape(x.shape)}


@register_op("p_send", inputs=["X"], outputs=["Out?"], grad=None,
             side_effect=True)
def p_send(ins, attrs, ctx):
    """Point-to-point send half.  Under SPMD tracing the send/recv pair is a
    single collective_permute, realised on the recv side; the send is an
    identity marker (reference: operators/collective send_v2 over NCCL)."""
    return {"Out": ins["X"]}


@register_op("p_recv", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def p_recv(ins, attrs, ctx):
    """Point-to-point recv: lax.ppermute from `peer` along the ring axis.
    Degenerates to identity outside a mesh (world of 1)."""
    x = ins["X"]
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": x}
    ax = axes if isinstance(axes, str) else axes[0]
    n = _axis_size(ax)
    peer = attrs.get("peer", 0)
    me = attrs.get("me", None)
    # permutation sending peer -> this rank; built statically over the ring
    perm = [(peer, i) for i in range(n)] if me is None else [(peer, me)]
    return {"Out": jax.lax.ppermute(x, ax, [(s % n, d % n)
                                            for s, d in perm])}


@register_op("elastic_commit_mask", inputs=["X"], outputs=["Out"],
             grad=None, side_effect=True)
def elastic_commit_mask(ins, attrs, ctx):
    """Commit mask for the elastic schedule (distributed/elastic.py):
    True when the post-increment micro-step counter completes a window of
    K = logical_dp / mesh-world micro-steps.  K is resolved HERE at trace
    time, so the same program serves every world size; off-mesh the world
    is 1 and a single process walks all N logical micro-steps."""
    cnt = ins["X"]
    n = int(attrs["logical_dp"])
    axes = _axes(ctx, attrs)
    m = 1
    if axes:
        ax = axes if isinstance(axes, str) else axes[0]
        m = _axis_size(ax)
    if m < 1 or n % m != 0:
        raise ValueError(
            f"elastic logical_dp={n} is not divisible by the mesh dp "
            f"degree {m}; an elastic mesh must be a divisor of the "
            "logical world")
    k = n // m
    return {"Out": jnp.mod(cnt, k) == 0}


@register_op("c_elastic_fold", inputs=["X", "Acc"], outputs=["Out"],
             grad=None, side_effect=True)
def c_elastic_fold(ins, attrs, ctx):
    """World-size-invariant ordered reduction (distributed/elastic.py):
    all_gather the per-rank values, then continue an EXPLICIT unrolled
    left-fold from the accumulator — micro-step j of an M-device mesh
    adds logical ranks jM..jM+M-1 in rank order, so after a full window
    the result is (((v0+v1)+v2)+...)+v_{N-1} for every factorization of
    the logical world.  psum must not be used here: its reduction order
    is implementation-defined and XLA may reassociate psum(a+b) into
    psum(a)+psum(b), both of which break bitwise topology invariance.
    Off-mesh this degrades to acc + x (a world of one logical rank per
    micro-step).

    ``pre_reduced=True`` (the elastic × ZeRO-1 composition,
    distributed/elastic.py): X is ALREADY a cross-rank reduction — the
    1/N reduce-scattered gradient shard — so the gather half is skipped
    and the op is the accumulator continuation ``acc + x`` on every
    mesh.  The explicit fold order (hence bitwise topology invariance)
    is traded away there; the composition's contract is allclose, not
    bitwise (docs/elastic.md)."""
    x, acc = ins["X"], ins["Acc"]
    if attrs.get("pre_reduced"):
        return {"Out": acc + x}
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": acc + x}
    ax = axes if isinstance(axes, str) else axes[0]
    gathered = jax.lax.all_gather(x, ax, axis=0, tiled=False)
    out = acc
    for i in range(gathered.shape[0]):
        out = out + gathered[i]
    return {"Out": out}


@register_op("scale_by_world_size", inputs=["X"], outputs=["Out"], grad=None,
             side_effect=True)
def scale_by_world_size(ins, attrs, ctx):
    """Divide by the collective world size (used after c_allreduce_sum for
    gradient averaging — the reference's ScaleLossGradOpHandle /
    GradientScaleStrategy.CoeffNumDevice, details/scale_loss_grad_op_handle)."""
    axes = _axes(ctx, attrs)
    if not axes:
        return {"Out": ins["X"]}
    from ...core.selected_rows import SelectedRows
    n = jax.lax.psum(1, axes)
    x = ins["X"]
    if isinstance(x, SelectedRows):
        return {"Out": SelectedRows(
            x.rows, x.values / jnp.asarray(n, x.values.dtype), x.height)}
    return {"Out": (x / jnp.asarray(n, x.dtype))}

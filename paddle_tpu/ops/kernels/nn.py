"""NN ops: conv / pool / norm / softmax / dropout / embedding.

Reference kernels: /root/reference/paddle/fluid/operators/conv_op.cc (cuDNN),
pool_op.cc, batch_norm_op.cu, layer_norm_op.cu, softmax_op.cc, dropout_op.cu,
lookup_table_op.cu.  Here convs/matmuls lower to lax.conv_general_dilated /
MXU; norms are jnp compositions XLA fuses into single kernels; dropout uses
the counter-based PRNG from OpContext (mask recomputed in backward, never
stored — saves HBM versus the reference's cached-mask design)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op
from ...core.dtype import np_dtype



def _cdt(x):
    """f32 accumulation for half types; preserve f32/f64."""
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype

# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------
def _conv_padding(paddings, algo, ndims):
    if algo == "SAME":
        return "SAME"
    if algo == "VALID":
        return [(0, 0)] * ndims
    p = list(paddings)
    if len(p) == ndims:
        return [(pi, pi) for pi in p]
    if len(p) == 2 * ndims:
        return [(p[2 * i], p[2 * i + 1]) for i in range(ndims)]
    raise ValueError(f"bad paddings {paddings}")


def _conv(x, w, attrs, ndims, feature_group_count=None, transpose=False):
    strides = tuple(attrs.get("strides", [1] * ndims))
    dilations = tuple(attrs.get("dilations", [1] * ndims))
    padding = _conv_padding(attrs.get("paddings", [0] * ndims),
                            attrs.get("padding_algorithm", "EXPLICIT"), ndims)
    groups = attrs.get("groups", 1) if feature_group_count is None \
        else feature_group_count
    fmt = attrs.get("data_format", "NCHW")
    if fmt in ("NHWC", "NDHWC"):
        x = jnp.moveaxis(x, -1, 1)
    spatial = "DHW"[3 - ndims:]
    dn = jax.lax.conv_dimension_numbers(
        x.shape, w.shape,
        (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}"))
    # no preferred_element_type=f32 + astype: the MXU already
    # f32-accumulates low-precision convs, and the explicit round-trip
    # forces the conv's vjp into f32 (see math._matmul)
    if transpose:
        out = _conv_transpose_nd(x, w, attrs, ndims)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, strides, padding, rhs_dilation=dilations,
            dimension_numbers=dn, feature_group_count=groups)
    if fmt in ("NHWC", "NDHWC"):
        out = jnp.moveaxis(out, 1, -1)
    return out


def _conv_transpose_nd(x, w, attrs, ndims):
    """Transpose conv as gradient-of-conv (lhs dilation), any spatial
    rank.  paddle filter layout [Cin, Cout/groups, k...]; paddle pads
    CROP the output: out = (D-1)*s - 2p + (k-1)*d + 1, so explicit pads
    become (k-1)*d - p on the dilated input."""
    strides = tuple(attrs.get("strides", [1] * ndims))
    dilations = tuple(attrs.get("dilations", [1] * ndims))
    pads = _conv_padding(attrs.get("paddings", [0] * ndims),
                         attrs.get("padding_algorithm", "EXPLICIT"), ndims)
    groups = attrs.get("groups", 1)
    spatial = "DHW"[3 - ndims:]
    cin, cog = w.shape[0], w.shape[1]
    # [Cin, Cout/g, k...] -> [Cout, Cin/g, k...]: split Cin into
    # (g, Cin/g), swap the per-group channel axes, merge (g, Cout/g)
    wk = w.reshape((groups, cin // groups, cog) + w.shape[2:])
    wk = jnp.swapaxes(wk, 1, 2).reshape(
        (groups * cog, cin // groups) + w.shape[2:])
    wk = jnp.flip(wk, axis=tuple(range(2, 2 + ndims)))
    dn = jax.lax.conv_dimension_numbers(
        x.shape, wk.shape,
        (f"NC{spatial}", f"OI{spatial}", f"NC{spatial}"))
    if isinstance(pads, str):
        padding = pads
    else:
        padding = [((wk.shape[2 + i] - 1) * dilations[i] - lo,
                    (wk.shape[2 + i] - 1) * dilations[i] - hi)
                   for i, (lo, hi) in enumerate(pads)]
    out = jax.lax.conv_general_dilated(
        x, wk, (1,) * ndims, padding, lhs_dilation=strides,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups)
    osize = attrs.get("output_size") or []
    if osize:
        # transpose-conv output is ambiguous up to stride-1: the
        # reference's output_size attr selects the exact size; sizes
        # beyond the natural one are end-padded zeros (the extra input
        # positions a larger forward conv would have consumed)
        pads = [(0, 0), (0, 0)]
        for i, want in enumerate(osize):
            have = out.shape[2 + i]
            if not have <= want < have + strides[i]:
                raise ValueError(
                    f"conv_transpose output_size[{i}]={want} invalid: "
                    f"must be in [{have}, {have + strides[i] - 1}]")
            pads.append((0, want - have))
        out = jnp.pad(out, pads)
    return out.astype(x.dtype)


@register_op("conv2d", inputs=["Input", "Filter", "Bias?"], outputs=["Output"])
def conv2d(ins, attrs, ctx):
    out = _conv(ins["Input"], ins["Filter"], attrs, 2)
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].reshape(1, -1, 1, 1)
    return {"Output": out}


@register_op("conv3d", inputs=["Input", "Filter"], outputs=["Output"])
def conv3d(ins, attrs, ctx):
    return {"Output": _conv(ins["Input"], ins["Filter"], attrs, 3)}


@register_op("depthwise_conv2d", inputs=["Input", "Filter"],
             outputs=["Output"])
def depthwise_conv2d(ins, attrs, ctx):
    x, w = ins["Input"], ins["Filter"]
    # paddle filter: [C*mult, 1, kh, kw]; lax wants [C*mult, 1, kh, kw] with
    # feature_group_count = C
    c_in = x.shape[1] if attrs.get("data_format", "NCHW") == "NCHW" \
        else x.shape[-1]
    return {"Output": _conv(x, w, attrs, 2, feature_group_count=c_in)}


@register_op("conv2d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def conv2d_transpose(ins, attrs, ctx):
    return {"Output": _conv_transpose_nd(ins["Input"], ins["Filter"],
                                         attrs, 2)}


@register_op("conv3d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def conv3d_transpose(ins, attrs, ctx):
    return {"Output": _conv(ins["Input"], ins["Filter"], attrs, 3,
                            transpose=True)}


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------
def _pool(x, attrs, ndims):
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [2] * ndims))
    strides = list(attrs.get("strides", ksize))
    pads = attrs.get("paddings", [0] * ndims)
    if attrs.get("global_pooling", False) or attrs.get("adaptive", False) \
            and all(k == 1 for k in ksize):
        axes = tuple(range(2, 2 + ndims))
        red = jnp.max if ptype == "max" else jnp.mean
        return red(x, axis=axes, keepdims=True)
    if attrs.get("adaptive", False):
        # adaptive pooling: output spatial = ksize
        out_hw = ksize
        slices = []
        for d, o in enumerate(out_hw):
            in_sz = x.shape[2 + d]
            ksize[d] = in_sz // o
            strides[d] = in_sz // o
        pads = [0] * ndims
    window = (1, 1) + tuple(ksize)
    stride = (1, 1) + tuple(strides)
    p = _conv_padding(pads, attrs.get("padding_algorithm", "EXPLICIT"), ndims)
    if isinstance(p, str):
        padding = p
    else:
        padding = [(0, 0), (0, 0)] + list(p)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, stride,
                                     padding)
    ones = jnp.ones_like(x)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, padding)
    if attrs.get("exclusive", True):
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride,
                                    padding)
    else:
        cnt = float(np.prod(ksize))
    return s / cnt


@register_op("pool2d", inputs=["X"], outputs=["Out"])
def pool2d(ins, attrs, ctx):
    return {"Out": _pool(ins["X"], attrs, 2)}


@register_op("pool3d", inputs=["X"], outputs=["Out"])
def pool3d(ins, attrs, ctx):
    return {"Out": _pool(ins["X"], attrs, 3)}


@register_op("max_pool2d_with_index", inputs=["X"], outputs=["Out", "Mask"])
def max_pool2d_with_index(ins, attrs, ctx):
    x = ins["X"]
    attrs2 = dict(attrs)
    attrs2["pooling_type"] = "max"
    out = _pool(x, attrs2, 2)
    # argmax indices via a paired (value, -index) reduce_window: the variadic
    # reduce computes max on value and, on ties, the smallest flat index —
    # exact for arbitrary float values (a single packed-float trick is not)
    n, c, h, w = x.shape
    ksize = list(attrs.get("ksize", [2, 2]))
    strides = list(attrs.get("strides", ksize))
    idx_map = jnp.arange(h * w, dtype=jnp.int32).reshape(1, 1, h, w)
    idx_map = jnp.broadcast_to(idx_map, x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        pick_b = (bv > av) | ((bv == av) & (bi < ai))
        return (jnp.where(pick_b, bv, av), jnp.where(pick_b, bi, ai))

    init_v = jnp.array(-jnp.inf, x.dtype) \
        if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.array(jnp.iinfo(x.dtype).min, x.dtype)
    _, mask = jax.lax.reduce_window(
        (x, idx_map), (init_v, jnp.array(h * w, jnp.int32)), reducer,
        (1, 1) + tuple(ksize), (1, 1) + tuple(strides), [(0, 0)] * 4)
    return {"Out": out, "Mask": mask.astype(jnp.int64)}


@register_op("spp", inputs=["X"], outputs=["Out"])
def spp(ins, attrs, ctx):
    # spatial pyramid pooling
    x = ins["X"]
    levels = attrs.get("pyramid_height", 2)
    ptype = attrs.get("pooling_type", "max")
    outs = []
    n, c = x.shape[:2]
    for l in range(levels):
        bins = 2 ** l
        a = {"pooling_type": ptype, "ksize": [bins, bins], "adaptive": True}
        outs.append(_pool(x, a, 2).reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


@register_op("unpool", inputs=["X", "Indices!"], outputs=["Out"])
def unpool(ins, attrs, ctx):
    x, idx = ins["X"], ins["Indices"].astype(jnp.int32)
    n, c, h, w = x.shape
    out_h, out_w = attrs.get("output_size", [h * 2, w * 2])[-2:]
    out = jnp.zeros((n, c, out_h * out_w), x.dtype)
    flat_idx = idx.reshape(n, c, -1)
    out = out.at[jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
                 flat_idx].set(x.reshape(n, c, -1))
    return {"Out": out.reshape(n, c, out_h, out_w)}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
@register_op("batch_norm",
             inputs=["X", "Scale", "Bias", "Mean", "Variance"],
             outputs=["Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance", "ReserveSpace?"])
def batch_norm(ins, attrs, ctx):
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean, var = ins["Mean"], ins["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    fmt = attrs.get("data_format", "NCHW")
    is_test = attrs.get("is_test", False) or ctx.is_test
    caxis = 1 if fmt == "NCHW" and x.ndim > 1 else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    bshape = tuple(x.shape[caxis] if i == caxis else 1 for i in range(x.ndim))

    xf = x.astype(_cdt(x))
    if is_test or attrs.get("use_global_stats", False):
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        m = jnp.mean(xf, axis=axes)
        v = jnp.var(xf, axis=axes)
        mean_out = mean * momentum + m * (1 - momentum)
        var_out = var * momentum + v * (1 - momentum)
    inv = jax.lax.rsqrt(v + eps)
    y = (xf - m.reshape(bshape)) * inv.reshape(bshape) \
        * scale.reshape(bshape) + bias.reshape(bshape)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out, "SavedMean": m, "SavedVariance": inv}


@register_op("layer_norm", inputs=["X", "Scale?", "Bias?"],
             outputs=["Y", "Mean", "Variance"])
def layer_norm(ins, attrs, ctx):
    x = ins["X"]
    eps = attrs.get("epsilon", 1e-5)
    bna = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(_cdt(x))
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].astype(_cdt(x)).reshape(
            (1,) * bna + x.shape[bna:])
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].astype(_cdt(x)).reshape(
            (1,) * bna + x.shape[bna:])
    flat = int(np.prod(x.shape[:bna]))
    return {"Y": y.astype(x.dtype), "Mean": m.reshape(flat),
            "Variance": v.reshape(flat)}


@register_op("sync_batch_norm",
             inputs=["X", "Scale", "Bias", "Mean", "Variance"],
             outputs=["Y", "MeanOut", "VarianceOut", "SavedMean",
                      "SavedVariance", "ReserveSpace?"])
def sync_batch_norm(ins, attrs, ctx):
    """Cross-replica batch norm (reference:
    /root/reference/paddle/fluid/operators/sync_batch_norm_op.cu — NCCL
    allreduce of partial sums).  TPU-native: when traced under a mesh the
    per-device sums are combined with one psum over the data-parallel axes;
    degenerates to plain batch_norm on a single device."""
    x = ins["X"]
    scale, bias = ins["Scale"], ins["Bias"]
    mean, var = ins["Mean"], ins["Variance"]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    fmt = attrs.get("data_format", "NCHW")
    is_test = attrs.get("is_test", False) or ctx.is_test
    caxis = 1 if fmt == "NCHW" and x.ndim > 1 else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != caxis)
    bshape = tuple(x.shape[caxis] if i == caxis else 1 for i in range(x.ndim))
    xf = x.astype(_cdt(x))
    if is_test:
        m, v = mean, var
        mean_out, var_out = mean, var
    else:
        s1 = jnp.sum(xf, axis=axes)
        s2 = jnp.sum(xf * xf, axis=axes)
        cnt = float(np.prod([x.shape[i] for i in axes]))
        mesh_axes = ctx.collective_axes(attrs.get("ring_id", 0))
        if mesh_axes:
            s1 = jax.lax.psum(s1, mesh_axes)
            s2 = jax.lax.psum(s2, mesh_axes)
            cnt = cnt * jax.lax.psum(1, mesh_axes)
        m = s1 / cnt
        v = s2 / cnt - m * m
        mean_out = mean * momentum + m * (1 - momentum)
        var_out = var * momentum + v * (1 - momentum)
    inv = jax.lax.rsqrt(v + eps)
    y = (xf - m.reshape(bshape)) * inv.reshape(bshape) \
        * scale.reshape(bshape) + bias.reshape(bshape)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out, "SavedMean": m, "SavedVariance": inv}


@register_op("instance_norm", inputs=["X", "Scale?", "Bias?"],
             outputs=["Y", "SavedMean", "SavedVariance"])
def instance_norm(ins, attrs, ctx):
    x = ins["X"]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    xf = x.astype(_cdt(x))
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    c = x.shape[1]
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].reshape(bshape)
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].reshape(bshape)
    n = x.shape[0]
    return {"Y": y.astype(x.dtype), "SavedMean": m.reshape(n * c),
            "SavedVariance": jax.lax.rsqrt(v + eps).reshape(n * c)}


@register_op("group_norm", inputs=["X", "Scale?", "Bias?"],
             outputs=["Y", "Mean", "Variance"])
def group_norm(ins, attrs, ctx):
    x = ins["X"]
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[:2]
    spatial = x.shape[2:]
    xf = x.astype(_cdt(x)).reshape((n, g, c // g) + spatial)
    axes = tuple(range(2, xf.ndim))
    m = jnp.mean(xf, axis=axes, keepdims=True)
    v = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - m) * jax.lax.rsqrt(v + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].reshape(bshape)
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].reshape(bshape)
    return {"Y": y.astype(x.dtype), "Mean": m.reshape(n, g),
            "Variance": v.reshape(n, g)}


@register_op("lrn", inputs=["X"], outputs=["Out", "MidOut"])
def lrn(ins, attrs, ctx):
    x = ins["X"]
    n_size = attrs.get("n", 5)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    k = attrs.get("k", 1.0)
    sq = jnp.square(x)
    half = n_size // 2
    pads = [(0, 0), (half, half), (0, 0), (0, 0)]
    sq_pad = jnp.pad(sq, pads)
    acc = sum(sq_pad[:, i:i + x.shape[1]] for i in range(n_size))
    mid = k + alpha * acc
    return {"Out": x / jnp.power(mid, beta), "MidOut": mid}


@register_op("data_norm", inputs=["X", "BatchSize", "BatchSum",
                                  "BatchSquareSum", "scale_w?", "bias?"],
             outputs=["Y", "Means", "Scales"])
def data_norm(ins, attrs, ctx):
    x = ins["X"]
    bsize, bsum, bsq = ins["BatchSize"], ins["BatchSum"], ins["BatchSquareSum"]
    means = bsum / bsize
    scales = jnp.sqrt(bsize / bsq)
    # stats are per-CHANNEL; reshape so they broadcast along the layout's
    # channel axis, not blindly along the last axis
    layout = attrs.get("data_layout", "NCHW")
    caxis = x.ndim - 1 if (layout == "NHWC" or x.ndim <= 2) else 1
    bshape = [1] * x.ndim
    bshape[caxis] = means.shape[0]
    m = means.reshape(bshape)
    s = scales.reshape(bshape)
    y = (x - m) * s
    if ins.get("scale_w") is not None:
        y = y * ins["scale_w"].reshape(bshape)
    if ins.get("bias") is not None:
        y = y + ins["bias"].reshape(bshape)
    return {"Y": y, "Means": means, "Scales": scales}


@register_op("spectral_norm", inputs=["Weight", "U", "V"], outputs=["Out"])
def spectral_norm(ins, attrs, ctx):
    w, u, v = ins["Weight"], ins["U"], ins["V"]
    dim = attrs.get("dim", 0)
    power_iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    w_mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(power_iters):
        v = w_mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w_mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ w_mat @ v
    return {"Out": w / sigma}


# ---------------------------------------------------------------------------
# softmax / dropout / embedding
# ---------------------------------------------------------------------------
@register_op("softmax", inputs=["X"], outputs=["Out"])
def softmax(ins, attrs, ctx):
    # bf16 in/out with fp32 internals: the max-subtract/exp/sum runs in
    # fp32 registers (XLA fuses the casts), so bf16 graphs keep fp32
    # numerics without materializing fp32 copies of the activations —
    # this is the attention-score hot path under AMP
    x = ins["X"]
    out = jax.nn.softmax(x.astype(_cdt(x)), axis=attrs.get("axis", -1))
    return {"Out": out.astype(x.dtype)}


@register_op("dropout", inputs=["X", "Seed?!"], outputs=["Out", "Mask"])
def dropout(ins, attrs, ctx):
    x = ins["X"]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False) or ctx.is_test:
        out = x if impl == "upscale_in_train" \
            else x * jnp.asarray(1.0 - p, x.dtype)
        return {"Out": out, "Mask": jnp.ones_like(x, dtype=jnp.uint8)}
    key = ctx.key(attrs)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / jnp.asarray(max(1.0 - p, 1e-8), x.dtype),
                        jnp.zeros_like(x))
    else:
        out = jnp.where(keep, x, jnp.zeros_like(x))
    return {"Out": out, "Mask": keep.astype(jnp.uint8)}


def _lookup_table_grad(squeeze_trailing):
    """Explicit embedding gradient (lookup_table_grad op,
    lookup_table_op.cc / SelectedRows path selected_rows_functor.cc).
    is_sparse=True emits a SelectedRows {rows, values} pair — the dense
    [vocab, width] gradient is never materialized; the optimizer
    scatter-adds it straight into the parameter."""

    def grad_kernel(ins, attrs, ctx):
        from ...core.selected_rows import SelectedRows
        w, ids, og = ins["W"], ins["Ids"], ins["Out@GRAD"]
        if squeeze_trailing and ids.shape[-1] == 1:
            ids = jnp.squeeze(ids, -1)
        rows = ids.reshape(-1).astype(jnp.int32)
        vals = og.reshape((-1,) + tuple(w.shape[1:])).astype(w.dtype)
        padding_idx = attrs.get("padding_idx", -1)
        if padding_idx is not None and padding_idx != -1:
            pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            vals = jnp.where((rows != pid)[:, None], vals,
                             jnp.zeros_like(vals))
        if attrs.get("is_sparse", False):
            return {"W@GRAD": SelectedRows(rows, vals, w.shape[0])}
        return {"W@GRAD": jnp.zeros_like(w).at[rows].add(vals)}

    return grad_kernel


@register_op("lookup_table", inputs=["W", "Ids!"], outputs=["Out"],
             grad=_lookup_table_grad(squeeze_trailing=True))
def lookup_table(ins, attrs, ctx):
    w, ids = ins["W"], ins["Ids"]
    ids = jnp.squeeze(ids, -1) if ids.shape[-1] == 1 else ids
    out = _embedding(w, ids, attrs)
    return {"Out": out}


@register_op("lookup_table_v2", inputs=["W", "Ids!"], outputs=["Out"],
             grad=_lookup_table_grad(squeeze_trailing=False))
def lookup_table_v2(ins, attrs, ctx):
    return {"Out": _embedding(ins["W"], ins["Ids"], attrs)}


def _embedding(w, ids, attrs):
    padding_idx = attrs.get("padding_idx", -1)
    ids = ids.astype(jnp.int32)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx != -1:
        pid = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
        mask = (ids != pid)[..., None]
        out = jnp.where(mask, out, jnp.zeros_like(out))
    return out


@register_op("embedding", inputs=["W", "Ids!"], outputs=["Out"],
             grad=_lookup_table_grad(squeeze_trailing=False))
def embedding(ins, attrs, ctx):
    return {"Out": _embedding(ins["W"], ins["Ids"], attrs)}


# ---------------------------------------------------------------------------
# misc nn
# ---------------------------------------------------------------------------
@register_op("fc", inputs=["Input", "W", "Bias?"], outputs=["Out"])
def fc(ins, attrs, ctx):
    x, w = ins["Input"], ins["W"]
    in_num_col_dims = attrs.get("in_num_col_dims", 1)
    x2 = x.reshape((int(np.prod(x.shape[:in_num_col_dims])), -1))
    # plain dot: bf16 vjp stays bf16 (see math._matmul)
    out = jnp.matmul(x2, w)
    if ins.get("Bias") is not None:
        out = out + ins["Bias"]
    act = attrs.get("activation_type", "")
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "tanh":
        out = jnp.tanh(out)
    return {"Out": out.reshape(x.shape[:in_num_col_dims] + (w.shape[1],))}


@register_op("add_position_encoding", inputs=["X"], outputs=["Out"])
def add_position_encoding(ins, attrs, ctx):
    x = ins["X"]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, seq, d = x.shape
    pos = jnp.arange(seq)[:, None].astype(jnp.float32)
    i = jnp.arange((d + 1) // 2)[None, :].astype(jnp.float32)
    freq = jnp.power(10000.0, -2.0 * i / d)
    # interleaved layout: enc[:, 2i] = sin, enc[:, 2i+1] = cos (reference
    # add_position_encoding_op.h); handles odd d by truncation
    sin = jnp.sin(pos * freq)
    cos = jnp.cos(pos * freq)
    enc = jnp.stack([sin, cos], axis=-1).reshape(seq, -1)[:, :d]
    return {"Out": alpha * x + beta * enc[None].astype(x.dtype)}


@register_op("pixel_shuffle", inputs=["X"], outputs=["Out"])
def pixel_shuffle(ins, attrs, ctx):
    x = ins["X"]
    r = attrs.get("upscale_factor", 1)
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": x.reshape(n, c // (r * r), h * r, w * r)}


@register_op("space_to_depth", inputs=["X"], outputs=["Out"])
def space_to_depth(ins, attrs, ctx):
    x = ins["X"]
    bs = attrs["blocksize"]
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // bs, bs, w // bs, bs)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return {"Out": x.reshape(n, c * bs * bs, h // bs, w // bs)}


@register_op("temporal_shift", inputs=["X"], outputs=["Out"])
def temporal_shift(ins, attrs, ctx):
    x = ins["X"]
    seg = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    n = nt // seg
    x = x.reshape(n, seg, c, h, w)
    c1 = int(c * ratio)
    c2 = int(c * 2 * ratio)
    pad = jnp.pad(x, [(0, 0), (1, 1), (0, 0), (0, 0), (0, 0)])
    out = jnp.concatenate([pad[:, :-2, :c1],          # shift left
                           pad[:, 2:, c1:c2],         # shift right
                           x[:, :, c2:]], axis=2)
    return {"Out": out.reshape(nt, c, h, w)}


@register_op("shuffle_channel", inputs=["X"], outputs=["Out"])
def shuffle_channel(ins, attrs, ctx):
    x = ins["X"]
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    return {"Out": jnp.transpose(x.reshape(n, g, c // g, h, w),
                                 (0, 2, 1, 3, 4)).reshape(x.shape)}

"""Industrial sparse-feature ops — the CTR feature plumbing that rides the
parameter-server tier (VERDICT r3 missing #2).  TPU redesigns of
/root/reference/paddle/fluid/operators/{cvm_op.h, shuffle_batch_op.h,
filter_by_instag_op.h, hash_op.h, pyramid_hash_op.cc, tdm_child_op.h,
tdm_sampler_op.h}.

LoD redesign notes: the reference ops consume ragged LoD rows; here each
op takes padded fixed-shape tensors (pad id 0 / tag -1) plus masks, so a
CTR graph (sparse slots -> distributed embedding -> cvm -> fc -> auc)
compiles to one XLA computation.  The reference's XXH32/XXH64 hashing is
replaced by an on-device avalanche mix (fmix32 finalizer) — hash VALUES
differ from the reference by design (any stable well-distributed hash is
a valid feature hash), the contract (deterministic, seed-indexed,
mod-bounded) is preserved.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op


# ---------------------------------------------------------------------------
# cvm (cvm_op.h) — show/click feature transform
# ---------------------------------------------------------------------------

def _cvm_grad(ins, attrs, ctx):
    """cvm_op.h CvmGradComputeKernel: pass-through on the feature tail;
    the show/click slots receive the CVM input values themselves (not a
    true gradient — the reference feeds the raw counters back so the
    embedding rows learn the counter scale)."""
    x = jnp.asarray(ins["X"])
    cvm = jnp.asarray(ins["CVM"])
    dy = jnp.asarray(ins["Y@GRAD"])
    use_cvm = bool(attrs.get("use_cvm", True))
    if use_cvm:
        dx = jnp.concatenate(
            [jnp.broadcast_to(cvm[:, :2], (x.shape[0], 2)).astype(x.dtype),
             dy[:, 2:]], axis=1)
    else:
        dx = jnp.concatenate(
            [jnp.broadcast_to(cvm[:, :2], (x.shape[0], 2)).astype(x.dtype),
             dy], axis=1)
    return {"X@GRAD": dx, "CVM@GRAD": jnp.zeros_like(cvm)}


@register_op("cvm", inputs=["X", "CVM!"], outputs=["Y"], grad=_cvm_grad)
def cvm(ins, attrs, ctx):
    """cvm_op.h — X rows lead with (show, click) counters.  use_cvm=True:
    y = [log(show+1), log(click+1)-log(show+1), features...]; False: the
    two counter slots are dropped."""
    x = jnp.asarray(ins["X"])
    use_cvm = bool(attrs.get("use_cvm", True))
    if use_cvm:
        show = jnp.log(x[:, 0:1] + 1.0)
        click = jnp.log(x[:, 1:2] + 1.0) - show
        return {"Y": jnp.concatenate([show, click, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


# ---------------------------------------------------------------------------
# shuffle_batch (shuffle_batch_op.h)
# ---------------------------------------------------------------------------

def _shuffle_batch_grad(ins, attrs, ctx):
    idx = jnp.asarray(ins["ShuffleIdx"])
    dy = jnp.asarray(ins["Out@GRAD"])
    lead = idx.shape[0]
    flat = dy.reshape(lead, -1)
    # forward scattered x[i] -> out[idx[i]]; grad gathers back
    dx = flat[idx]
    return {"X@GRAD": dx.reshape(dy.shape),
            "Seed@GRAD": jnp.zeros((1,), jnp.int64)}


@register_op("shuffle_batch", inputs=["X", "Seed?!"],
             outputs=["Out", "ShuffleIdx", "SeedOut"],
             grad=_shuffle_batch_grad)
def shuffle_batch(ins, attrs, ctx):
    """shuffle_batch_op.h — permute rows (all-but-last dims flattened)
    with a seeded engine: out[perm[i]] = x[i]; ShuffleIdx records perm so
    the grad (and cross-feature alignment) can invert it; SeedOut chains
    the RNG for the next step."""
    x = jnp.asarray(ins["X"])
    seed_in = ins.get("Seed")
    lead = int(np.prod(x.shape[:-1]))
    emb = x.shape[-1]
    if seed_in is not None:
        seed = jnp.asarray(seed_in).reshape(-1)[0].astype(jnp.uint32)
    else:
        seed = jnp.asarray(attrs.get("startup_seed", 0), jnp.uint32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             jnp.uint32(attrs.get("op_uid", 0)))
    perm = jax.random.permutation(key, lead)
    out = jnp.zeros((lead, emb), x.dtype).at[perm].set(x.reshape(lead, emb))
    new_seed = jax.random.randint(
        jax.random.fold_in(key, 1), (1,), 0, np.iinfo(np.int32).max)
    return {"Out": out.reshape(x.shape),
            "ShuffleIdx": perm.astype(jnp.int64),
            "SeedOut": new_seed.astype(jnp.int64)}


# ---------------------------------------------------------------------------
# filter_by_instag (filter_by_instag_op.h)
# ---------------------------------------------------------------------------

def _filter_by_instag_grad(ins, attrs, ctx):
    dy = jnp.asarray(ins["Out@GRAD"])
    lw = jnp.asarray(ins["LossWeight"])
    return {"Ins@GRAD": dy * lw.reshape(-1, *([1] * (dy.ndim - 1)))}


@register_op("filter_by_instag",
             inputs=["Ins", "Ins_tag!", "Filter_tag!"],
             outputs=["Out", "LossWeight", "IndexMap"],
             grad=_filter_by_instag_grad)
def filter_by_instag(ins, attrs, ctx):
    """filter_by_instag_op.h — keep instances whose tag list intersects
    the filter set.  Padded redesign: instead of compacting rows (dynamic
    shape), kept rows pass through and dropped rows are zeroed
    (out_val_if_empty), with LossWeight 1/0 flagging them — downstream
    losses multiply by LossWeight so the numerics match the reference's
    compacted batch.  Ins [B, D]; Ins_tag [B, T] (-1 padded);
    Filter_tag [F] (-1 padded)."""
    x = jnp.asarray(ins["Ins"])
    tags = jnp.asarray(ins["Ins_tag"])
    filt = jnp.asarray(ins["Filter_tag"]).reshape(-1)
    fill = attrs.get("out_val_if_empty", 0)
    hit = (tags[:, :, None] == filt[None, None, :]) & \
        (tags[:, :, None] >= 0) & (filt[None, None, :] >= 0)
    keep = jnp.any(hit, axis=(1, 2))
    out = jnp.where(keep.reshape(-1, *([1] * (x.ndim - 1))), x,
                    jnp.asarray(fill, x.dtype))
    lw = keep.astype(jnp.float32)[:, None]
    B = x.shape[0]
    rows = jnp.arange(B)
    index_map = jnp.stack(
        [rows, rows, keep.astype(rows.dtype)], axis=1).astype(jnp.int64)
    return {"Out": out, "LossWeight": lw, "IndexMap": index_map}


# ---------------------------------------------------------------------------
# hash (hash_op.h) — multi-seed feature hashing
# ---------------------------------------------------------------------------

def _fmix32(h):
    """murmur3 fmix32 avalanche finalizer — the on-device stand-in for
    the reference's XXH64 (hash_op.h:XXH64); uint32 lattice ops only so
    it vectorises on TPU."""
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _hash_ids(ids, seed):
    """Combine an integer vector (last axis) into one uint32 hash with a
    per-seed initial state (boost-style hash_combine over fmix32)."""
    h = jnp.full(ids.shape[:-1], 0x9E3779B9, jnp.uint32) ^ \
        jnp.asarray(seed, jnp.uint32)
    for j in range(ids.shape[-1]):
        h = _fmix32(h ^ _fmix32(ids[..., j].astype(jnp.uint32) +
                                jnp.uint32(j + 1)))
    return h


@register_op("hash", inputs=["X!"], outputs=["Out"], grad=None)
def hash_op(ins, attrs, ctx):
    """hash_op.h — X [..., K] int ids -> Out [..., num_hash, 1]:
    num_hash independent hashes of the K-id tuple, each mod mod_by.
    Values differ from the reference's XXH64 by design (see module
    docstring); distribution/determinism contract preserved."""
    x = jnp.asarray(ins["X"])
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))
    outs = [(_hash_ids(x, s) % jnp.uint32(mod_by)).astype(x.dtype)
            for s in range(num_hash)]
    out = jnp.stack(outs, axis=-1)[..., None]
    return {"Out": out}


# ---------------------------------------------------------------------------
# pyramid_hash (pyramid_hash_op.cc) — search-aware pyramid text hashing
# ---------------------------------------------------------------------------

def _pyramid_hash_grad(ins, attrs, ctx):
    """Scatter-add the window grads back onto the hashed weight chunks —
    mirror of hash_embedding_bp (pyramid_hash_op.cc:300s)."""
    x = jnp.asarray(ins["X"])
    w = jnp.asarray(ins["W"])
    dy = jnp.asarray(ins["Out@GRAD"])
    num_emb = int(attrs["num_emb"])
    rand_len = int(attrs.get("rand_len", 16))
    space_len = int(attrs["space_len"])
    layers = int(attrs.get("pyramid_layer", 2))
    lr = attrs.get("lr", 1.0)
    B, S = x.shape
    n_chunks = num_emb // rand_len
    dw = jnp.zeros_like(w)
    row_off = 0
    # one batched scatter-add per (layer, chunk) — windows are stacked
    # into a tensor axis, not unrolled into the graph
    for lay in range(1, layers + 1):
        wl = S - lay + 1
        win = jnp.stack([x[:, off:off + wl] for off in range(lay)],
                        axis=-1)                          # [B, Wl, lay]
        valid = jnp.all(win > 0, axis=-1)
        g = dy[:, row_off:row_off + wl] * \
            valid[..., None].astype(dy.dtype) * lr        # [B, Wl, E]
        for j in range(n_chunks):
            pos = (_hash_ids(win, j) % jnp.uint32(space_len)) \
                .astype(jnp.int32)                        # [B, Wl]
            idx = pos[..., None] + jnp.arange(rand_len)
            seg = g[..., j * rand_len:(j + 1) * rand_len]
            dw = dw.at[idx.reshape(-1)].add(seg.reshape(-1))
        row_off += wl
    return {"X@GRAD": jnp.zeros_like(x), "W@GRAD": dw}


@register_op("pyramid_hash",
             inputs=["X!", "W", "WhiteList?!", "BlackList?!"],
             outputs=["Out", "DropPos?", "X_Temp_Out?"],
             grad=_pyramid_hash_grad)
def pyramid_hash(ins, attrs, ctx):
    """pyramid_hash_op.cc hash_embedding_ff — for every token n-gram
    window (pyramid layers 1..pyramid_layer), build a num_emb embedding
    by concatenating rand_len-sized slices of the flat weight table W
    [space_len + rand_len] at seed-indexed hash offsets.  Padded
    redesign: X [B, S] (0 = pad); output rows are fixed
    [B, n_windows, num_emb] (n_windows = sum_l (S-l+1)) with invalid
    windows (touching pad) zeroed — DropPos marks live rows.  White/black
    bloom filters are host-side data prep in this design (descoped here;
    accepted and ignored when passed)."""
    x = jnp.asarray(ins["X"])
    w = jnp.asarray(ins["W"]).reshape(-1)
    num_emb = int(attrs["num_emb"])
    rand_len = int(attrs.get("rand_len", 16))
    space_len = int(attrs["space_len"])
    layers = int(attrs.get("pyramid_layer", 2))
    B, S = x.shape
    assert num_emb % rand_len == 0, "num_emb must divide into rand_len"
    n_chunks = num_emb // rand_len
    rows = []
    live = []
    # all windows of one layer ride a tensor axis (lay slices to build,
    # then ONE batched gather per chunk) — the graph is O(layers*chunks),
    # not O(windows), so long sequences compile fast
    for lay in range(1, layers + 1):
        wl = S - lay + 1
        win = jnp.stack([x[:, off:off + wl] for off in range(lay)],
                        axis=-1)                        # [B, Wl, lay]
        valid = jnp.all(win > 0, axis=-1)               # [B, Wl]
        chunks = []
        for j in range(n_chunks):
            pos = (_hash_ids(win, j) % jnp.uint32(space_len)) \
                .astype(jnp.int32)                      # [B, Wl]
            idx = pos[..., None] + jnp.arange(rand_len)
            chunks.append(w[idx])                       # [B, Wl, rand]
        emb = jnp.concatenate(chunks, axis=-1)          # [B, Wl, E]
        rows.append(emb * valid[..., None].astype(emb.dtype))
        live.append(valid)
    out = jnp.concatenate(rows, axis=1)                 # [B, NW, E]
    drop = (~jnp.concatenate(live, axis=1)).astype(jnp.int32)
    return {"Out": out, "DropPos": drop}


# ---------------------------------------------------------------------------
# tdm_child (tdm_child_op.h)
# ---------------------------------------------------------------------------

@register_op("tdm_child", inputs=["X!", "TreeInfo!"],
             outputs=["Child", "LeafMask"], grad=None)
def tdm_child(ins, attrs, ctx):
    """tdm_child_op.h — TreeInfo rows are (item_id, layer_id,
    ancestor_id, child_0..child_{C-1}); for each input node id emit its
    child ids and an is-leaf-item mask (item_id != 0); id 0 or childless
    nodes emit zeros."""
    x = jnp.asarray(ins["X"])
    info = jnp.asarray(ins["TreeInfo"])
    child_nums = int(attrs.get("child_nums", 2))
    flat = x.reshape(-1).astype(jnp.int32)
    node = info[flat]                                   # [N, 3+C]
    has_child = (flat != 0) & (node[:, 3] != 0)
    children = node[:, 3:3 + child_nums].astype(jnp.int32)
    children = jnp.where(has_child[:, None], children, 0)
    is_item = (info[children.reshape(-1), 0] != 0).astype(x.dtype) \
        .reshape(children.shape)
    is_item = jnp.where(has_child[:, None], is_item, 0)
    shape = tuple(x.shape) + (child_nums,)
    return {"Child": children.astype(x.dtype).reshape(shape),
            "LeafMask": is_item.reshape(shape)}


# ---------------------------------------------------------------------------
# tdm_sampler (tdm_sampler_op.h)
# ---------------------------------------------------------------------------

@register_op("tdm_sampler", inputs=["X!", "Travel!", "Layer!"],
             outputs=["Out", "Labels", "Mask"], grad=None)
def tdm_sampler(ins, attrs, ctx):
    """tdm_sampler_op.h — per input item: walk its tree path
    (Travel[item] = node id per layer, 0 = padding) and at each layer
    emit the positive node (label 1) plus neg_samples_num_list[l]
    uniform negatives from that layer (label 0), never equal to the
    positive and without replacement.  Padding layers emit zeros with
    mask 0.  Layer is the padded node table [n_layers, max_nodes]
    (0-padded; reference keeps a LoD list); layer_node_num_list gives
    true per-layer sizes."""
    x = jnp.asarray(ins["X"])
    travel = jnp.asarray(ins["Travel"])      # [n_items, L]
    layer = jnp.asarray(ins["Layer"])        # [L, max_nodes]
    negs = [int(n) for n in attrs["neg_samples_num_list"]]
    node_nums = [int(n) for n in attrs["layer_node_num_list"]]
    out_pos = bool(attrs.get("output_positive", True))
    L = len(negs)
    ids = x.reshape(-1).astype(jnp.int32)
    N = ids.shape[0]
    res_len = sum(n + int(out_pos) for n in negs)
    key = ctx.key(attrs)

    outs, labels, masks = [], [], []
    for li in range(L):
        pos_node = travel[ids, li]                      # [N]
        alive = pos_node != 0
        if out_pos:
            outs.append(pos_node[:, None])
            labels.append(jnp.ones((N, 1), jnp.int32) * alive[:, None])
            masks.append(alive[:, None].astype(jnp.int32))
        k_layer = jax.random.fold_in(key, li)
        nn = node_nums[li]
        cand = layer[li, :nn]                           # [nn]
        # uniform sample without replacement, excluding the positive:
        # random priorities per candidate, positive forced to -inf
        pri = jax.random.uniform(k_layer, (N, nn))
        pri = jnp.where(cand[None, :] == pos_node[:, None], -jnp.inf, pri)
        k = min(negs[li], nn - 1)
        _, sel = jax.lax.top_k(pri, max(k, 1))
        neg_nodes = cand[sel[:, :k]] if k > 0 else \
            jnp.zeros((N, 0), cand.dtype)
        if k > 0:
            neg_nodes = jnp.where(alive[:, None], neg_nodes, 0)
            outs.append(neg_nodes)
            labels.append(jnp.zeros((N, k), jnp.int32))
            masks.append(jnp.broadcast_to(alive[:, None].astype(jnp.int32),
                                          (N, k)))
        # pad if layer has fewer candidates than requested
        pad = negs[li] - k
        if pad > 0:
            outs.append(jnp.zeros((N, pad), cand.dtype))
            labels.append(jnp.zeros((N, pad), jnp.int32))
            masks.append(jnp.zeros((N, pad), jnp.int32))
    out = jnp.concatenate(outs, axis=1).astype(x.dtype)
    lbl = jnp.concatenate(labels, axis=1).astype(x.dtype)
    msk = jnp.concatenate(masks, axis=1).astype(x.dtype)
    assert out.shape[1] == res_len
    return {"Out": out, "Labels": lbl, "Mask": msk}


# ---------------------------------------------------------------------------
# switch_moe — MoE as a first-class framework op (VERDICT r3 weak #8)
# ---------------------------------------------------------------------------

@register_op("switch_moe",
             inputs=["X", "GateW", "W1", "B1", "W2", "B2"],
             outputs=["Out", "AuxLoss"])
def switch_moe_op(ins, attrs, ctx):
    """Top-1 switch MoE feed-forward as a Program-IR op, sharing the
    incubate/moe.py core (static-shape dispatch, batched expert einsum,
    optional all-to-all expert parallelism).  X [..., D]; expert weights
    carry a leading E axis.  Under a mesh executor, attrs['ep_ring_id']
    maps through OpContext.dist_info to the `ep` axis so dispatch rides
    all_to_all over ICI; single device runs all experts locally."""
    from ...incubate.moe import switch_moe as moe_core
    x = jnp.asarray(ins["X"])
    gate_w = jnp.asarray(ins["GateW"])
    w1, b1 = jnp.asarray(ins["W1"]), jnp.asarray(ins["B1"])
    w2, b2 = jnp.asarray(ins["W2"]), jnp.asarray(ins["B2"])
    cap = float(attrs.get("capacity_factor", 1.25))
    axis_name = None
    ring = attrs.get("ep_ring_id")
    if ring is not None and ctx.mesh_axes:
        axes = ctx.collective_axes(int(ring))
        axis_name = axes if isinstance(axes, str) else axes[0]
    lead = x.shape[:-1]
    flat = x.reshape(-1, x.shape[-1])
    out, aux = moe_core(flat, gate_w, w1, b1, w2, b2,
                        capacity_factor=cap, axis_name=axis_name)
    return {"Out": out.reshape(*lead, x.shape[-1]),
            "AuxLoss": aux.reshape(())}

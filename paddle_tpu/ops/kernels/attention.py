"""Attention ops for the static graph (flash + ring kernels as registry
ops; see ops/attention.py for the Pallas/ring implementations and the
reference-capability notes)."""
from __future__ import annotations

from ..registry import register_op
from ..attention import (flash_attention, ring_attention,
                         reference_attention, SP_RING_ID)


@register_op("flash_attention", inputs=["Q", "K", "V"], outputs=["Out"],
             grad="auto")
def flash_attention_op(ins, attrs, ctx):
    """Blockwise Pallas attention.  Q/K/V: [B, H, S, D] (full sequence —
    refuses to run under a sequence-parallel mesh, where shard-local
    attention would be silently wrong; use ring_attention there)."""
    if ctx.collective_axes(SP_RING_ID):
        raise RuntimeError(
            "flash_attention op under a sequence-parallel mesh would "
            "attend only within the local shard; use the ring_attention "
            "op (ring_id=SP_RING_ID) instead")
    return {"Out": flash_attention(ins["Q"], ins["K"], ins["V"],
                                   causal=attrs.get("causal", False))}


@register_op("ring_attention", inputs=["Q", "K", "V"], outputs=["Out"],
             grad="auto", side_effect=True)
def ring_attention_op(ins, attrs, ctx):
    """Sequence-parallel attention over the mesh axis bound to ring_id 1.

    Q/K/V: [B, S, H*D] with attr num_heads — head split/merge happens
    INSIDE the kernel where shapes are the local shard's (graph-level
    reshapes would bake the global sequence length and break under the sp
    shard; same reason the reference fuses multihead_matmul,
    operators/fused/multihead_matmul_op.cu).  Outside any mesh this is
    plain attention (degenerate world of 1).
    """
    import jax.numpy as jnp
    q, k, v = ins["Q"], ins["K"], ins["V"]
    h = attrs.get("num_heads", 1)
    causal = attrs.get("causal", False)

    def split(x):
        b, s, hd = x.shape
        return jnp.transpose(x.reshape(b, s, h, hd // h), (0, 2, 1, 3))

    def merge(x):
        b, hh, s, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b, s, hh * d)

    qh, kh, vh = split(q), split(k), split(v)
    axes = ctx.collective_axes(attrs.get("ring_id", SP_RING_ID))
    if not axes:
        out = reference_attention(qh, kh, vh, causal=causal)
    else:
        ax = axes if isinstance(axes, str) else axes[0]
        out = ring_attention(qh, kh, vh, ax, causal=causal)
    return {"Out": merge(out)}

"""Attention ops for the static graph (flash + ring kernels as registry
ops; see ops/attention.py for the Pallas/ring implementations and the
reference-capability notes)."""
from __future__ import annotations

from ..registry import register_op
from ..attention import (flash_attention, ring_attention,
                         reference_attention, SP_RING_ID)


@register_op("flash_attention", inputs=["Q", "K", "V"], outputs=["Out"],
             grad="auto")
def flash_attention_op(ins, attrs, ctx):
    """Blockwise Pallas attention.  Q/K/V: [B, H, S, D] (full sequence —
    refuses to run under a sequence-parallel mesh, where shard-local
    attention would be silently wrong; use ring_attention there)."""
    if ctx.collective_axes(SP_RING_ID):
        raise RuntimeError(
            "flash_attention op under a sequence-parallel mesh would "
            "attend only within the local shard; use the ring_attention "
            "op (ring_id=SP_RING_ID) instead")
    return {"Out": flash_attention(ins["Q"], ins["K"], ins["V"],
                                   causal=attrs.get("causal", False))}


@register_op("ring_attention", inputs=["Q", "K", "V"], outputs=["Out"],
             grad="auto", side_effect=True)
def ring_attention_op(ins, attrs, ctx):
    """Sequence-parallel attention over the mesh axis bound to ring_id 1.

    Q/K/V: [B, S, H*D] with attr num_heads — head split/merge happens
    INSIDE the kernel where shapes are the local shard's (graph-level
    reshapes would bake the global sequence length and break under the sp
    shard; same reason the reference fuses multihead_matmul,
    operators/fused/multihead_matmul_op.cu).  Outside any mesh this is
    plain attention (degenerate world of 1).
    """
    import jax.numpy as jnp
    q, k, v = ins["Q"], ins["K"], ins["V"]
    h = attrs.get("num_heads", 1)
    causal = attrs.get("causal", False)

    def split(x):
        b, s, hd = x.shape
        return jnp.transpose(x.reshape(b, s, h, hd // h), (0, 2, 1, 3))

    def merge(x):
        b, hh, s, d = x.shape
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b, s, hh * d)

    qh, kh, vh = split(q), split(k), split(v)
    axes = ctx.collective_axes(attrs.get("ring_id", SP_RING_ID))
    if not axes:
        out = reference_attention(qh, kh, vh, causal=causal)
    else:
        ax = axes if isinstance(axes, str) else axes[0]
        out = ring_attention(qh, kh, vh, ax, causal=causal)
    return {"Out": merge(out)}


@register_op("multihead_matmul",
             inputs=["Input", "WQ", "BQ?", "WK", "BK?", "WV", "BV?",
                     "BiasQK?"],
             outputs=["Out"], grad=None)
def multihead_matmul_op(ins, attrs, ctx):
    """Fused Q/K/V projection + scaled-dot-product attention
    (/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cc:1
    — the reference predictor's BERT fusion; its W packs QKV into one
    tensor, here the three projection weights ride in separate slots so
    the fusion pass never has to rewrite the loaded scope).

    Input [B, L, D] -> Out [B, L, D] (merged heads, pre-out-projection).
    Lowering: one einsum per projection, then the SHARED attention core —
    Pallas flash when no additive mask and the static sequence length
    clears the crossover, XLA softmax(QK^T)V otherwise.  attrs:
    head_number, alpha (logit scale)."""
    import math as _math

    import jax.numpy as jnp

    from ..attention import (flash_attention, reference_attention,
                             use_flash_for)
    x = ins["Input"]
    h = int(attrs["head_number"])
    b, l, d = x.shape

    def proj(w, bias):
        y = jnp.einsum("bld,dk->blk", x, w)
        if bias is not None:
            y = y + bias.reshape((1, 1, -1))
        return jnp.transpose(y.reshape(b, l, h, -1), (0, 2, 1, 3))

    q = proj(ins["WQ"], ins.get("BQ"))
    k = proj(ins["WK"], ins.get("BK"))
    v = proj(ins["WV"], ins.get("BV"))
    scale = float(attrs.get("alpha", 1.0 / _math.sqrt(q.shape[-1])))
    bias_qk = ins.get("BiasQK")
    if bias_qk is None and use_flash_for(l) and \
            abs(scale - 1.0 / _math.sqrt(q.shape[-1])) < 1e-9:
        out = flash_attention(q, k, v)
    else:
        if bias_qk is not None:
            # broadcastable to [B, H, L, L]: [L, L] masks gain leading
            # axes, a [B, L, L] mask gains the head axis
            if bias_qk.ndim <= 2:
                while bias_qk.ndim < 4:
                    bias_qk = bias_qk[None]
            elif bias_qk.ndim == 3:
                bias_qk = bias_qk[:, None]
        out = reference_attention(q, k, v, bias=bias_qk, scale=scale)
    return {"Out": jnp.transpose(out, (0, 2, 1, 3)).reshape(b, l, d)}


@register_op("fused_embedding_eltwise_layernorm",
             inputs=["Ids*!", "Embs*", "Scale?", "Bias?"],
             outputs=["Out"], grad=None)
def fused_embedding_eltwise_layernorm_op(ins, attrs, ctx):
    """operators/fused/fused_embedding_eltwise_layernorm_op.cc — BERT's
    input block as one op: sum of N embedding lookups, then layer_norm.
    Lowering: gathers + one fused normalization; XLA fuses the adds into
    the gather consumers, so this is one HBM pass over the [B, L, D]
    activations instead of N+2.

    Per-leaf semantics ride in attrs (captured by the fuse pass):
    leaf_types (lookup_table squeezes a trailing 1-dim, v2/embedding do
    not) and padding_idxs (padded rows read as zero) — the lookups run
    through the SAME _embedding helper the standalone kernels use."""
    import jax
    import jax.numpy as jnp

    from .nn import _embedding
    embs = ins["Embs"]
    if len(ins["Ids"]) != len(embs):
        # fail fast like the unfused lookups would on a missing feed:
        # a silent zip() truncation here would also misalign the per-leaf
        # attrs below
        raise ValueError(
            f"fused_embedding_eltwise_layernorm: {len(ins['Ids'])} Ids "
            f"inputs for {len(embs)} embedding tables")
    leaf_types = list(attrs.get("leaf_types",
                                ["lookup_table_v2"] * len(embs)))
    pads = list(attrs.get("padding_idxs", [-1] * len(embs)))
    total = None
    for i, (ids, emb) in enumerate(zip(ins["Ids"], embs)):
        if leaf_types[i] == "lookup_table" and ids.shape[-1] == 1:
            ids = jnp.squeeze(ids, -1)
        g = _embedding(emb, ids, {"padding_idx": pads[i]})
        total = g if total is None else total + g
    eps = float(attrs.get("epsilon", 1e-5))
    xf = total.astype(jnp.float32)
    m = jnp.mean(xf, axis=-1, keepdims=True)
    v = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - m) * jax.lax.rsqrt(v + eps)
    if ins.get("Scale") is not None:
        y = y * ins["Scale"].astype(jnp.float32)
    if ins.get("Bias") is not None:
        y = y + ins["Bias"].astype(jnp.float32)
    return {"Out": y.astype(total.dtype)}

"""Loss ops (reference: softmax_with_cross_entropy_op.cc, cross_entropy_op.cc,
bce_loss_op.cc, nll_loss_op.cc, huber_loss, smooth_l1_loss, log_loss,
kldiv_loss, sigmoid_cross_entropy_with_logits, mse ...)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _compute_dtype(x):
    """f32 accumulation for half types; preserve f32/f64."""
    return jnp.float32 if x.dtype in (jnp.bfloat16, jnp.float16) else x.dtype


@register_op("softmax_with_cross_entropy", inputs=["Logits", "Label!"],
             outputs=["Softmax", "Loss"])
def softmax_with_cross_entropy(ins, attrs, ctx):
    logits, label = ins["Logits"], ins["Label"]
    axis = attrs.get("axis", -1) % logits.ndim
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    # Pallas fused path (FLAGS_fused_xent, ops/fused_xent.py): one online
    # pass over the vocab, softmax never materialized; the Softmax output
    # slot then carries a zero placeholder (graphs fetching it must run
    # with the flag off — the bench/training path only consumes Loss)
    from ..fused_xent import maybe_fused_xent
    fused = maybe_fused_xent(logits, label, axis, soft_label,
                             ignore_index)
    if fused is not None:
        # Loss stays f32 like the base branch (bf16 rounding before the
        # reduction would break the fused-vs-base A/B); the Softmax
        # placeholder is DCE'd under jit (the fused path only engages
        # when traced)
        return {"Softmax": jnp.zeros_like(logits), "Loss": fused}
    cdt = _compute_dtype(logits)
    lf = logits.astype(cdt)
    logp = jax.nn.log_softmax(lf, axis=axis)
    sm = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label.astype(cdt) * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis)
        lbl = lbl.astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(jnp.clip(lbl, 0, logits.shape[axis] - 1),
                                  axis), axis=axis)
        loss = -picked
        # ignored rows zero out REGARDLESS of the index's sign (the
        # reference default is -100; softmax_with_cross_entropy_op.h
        # compares equality, not sign)
        loss = jnp.where(jnp.expand_dims(lbl, axis) == ignore_index,
                         jnp.zeros_like(loss), loss)
    return {"Softmax": sm.astype(logits.dtype), "Loss": loss}


@register_op("cross_entropy", inputs=["X", "Label!"], outputs=["Y"])
def cross_entropy(ins, attrs, ctx):
    x, label = ins["X"], ins["Label"]
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    eps = 1e-12
    if soft_label:
        y = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
            lbl = jnp.squeeze(lbl, -1)
        lbl = lbl.astype(jnp.int32)
        p = jnp.take_along_axis(x, jnp.expand_dims(
            jnp.clip(lbl, 0, x.shape[-1] - 1), -1), axis=-1)
        y = -jnp.log(p + eps)
        y = jnp.where(jnp.expand_dims(lbl, -1) == ignore_index,
                      jnp.zeros_like(y), y)
    return {"Y": y}


@register_op("cross_entropy2", inputs=["X", "Label!"],
             outputs=["Y", "XShape", "MatchX"])
def cross_entropy2(ins, attrs, ctx):
    out = cross_entropy(ins, attrs, ctx)
    x = ins["X"]
    lbl = ins["Label"]
    if lbl.ndim == x.ndim and lbl.shape[-1] == 1:
        lbl = jnp.squeeze(lbl, -1)
    matchx = jnp.take_along_axis(x, jnp.expand_dims(
        jnp.clip(lbl.astype(jnp.int32), 0, x.shape[-1] - 1), -1), axis=-1)
    return {"Y": out["Y"], "XShape": jnp.zeros((0,) + x.shape, x.dtype),
            "MatchX": matchx}


@register_op("bce_loss", inputs=["X", "Label"], outputs=["Out"])
def bce_loss(ins, attrs, ctx):
    x, label = ins["X"], ins["Label"]
    eps = 1e-12
    out = -(label * jnp.log(x + eps) + (1 - label) * jnp.log(1 - x + eps))
    return {"Out": out}


@register_op("nll_loss", inputs=["X", "Label!", "Weight?"],
             outputs=["Out", "Total_weight"])
def nll_loss(ins, attrs, ctx):
    x, label = ins["X"], ins["Label"].astype(jnp.int32)
    weight = ins.get("Weight")
    reduction = attrs.get("reduction", "mean")
    ignore_index = attrs.get("ignore_index", -100)
    n, c = x.shape[0], x.shape[1]
    picked = -jnp.take_along_axis(
        x, jnp.expand_dims(jnp.clip(label, 0, c - 1), 1), axis=1).squeeze(1)
    w = jnp.ones_like(picked) if weight is None \
        else jnp.take(weight, jnp.clip(label, 0, c - 1))
    valid = label != ignore_index
    picked = jnp.where(valid, picked * w, 0.0)
    w = jnp.where(valid, w, 0.0)
    tw = jnp.sum(w)
    if reduction == "mean":
        return {"Out": jnp.sum(picked) / jnp.maximum(tw, 1e-12),
                "Total_weight": tw}
    if reduction == "sum":
        return {"Out": jnp.sum(picked), "Total_weight": tw}
    return {"Out": picked, "Total_weight": tw}


@register_op("hinge_loss", inputs=["Logits", "Labels!"], outputs=["Loss"])
def hinge_loss(ins, attrs, ctx):
    logits, labels = ins["Logits"], ins["Labels"]
    return {"Loss": jnp.maximum(0.0, 1.0 - (2 * labels - 1) * logits)}


@register_op("huber_loss", inputs=["X", "Y"], outputs=["Residual", "Out"])
def huber_loss(ins, attrs, ctx):
    delta = attrs.get("delta", 1.0)
    r = ins["Y"] - ins["X"]
    ab = jnp.abs(r)
    out = jnp.where(ab <= delta, 0.5 * r * r, delta * (ab - 0.5 * delta))
    return {"Residual": r, "Out": out}


@register_op("smooth_l1_loss", inputs=["X", "Y", "InsideWeight?",
                                       "OutsideWeight?"],
             outputs=["Diff", "Out"])
def smooth_l1_loss(ins, attrs, ctx):
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    d = ins["X"] - ins["Y"]
    if ins.get("InsideWeight") is not None:
        d = d * ins["InsideWeight"]
    ab = jnp.abs(d)
    loss = jnp.where(ab < 1.0 / s2, 0.5 * d * d * s2, ab - 0.5 / s2)
    if ins.get("OutsideWeight") is not None:
        loss = loss * ins["OutsideWeight"]
    out = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Diff": d, "Out": out}


@register_op("log_loss", inputs=["Predicted", "Labels"], outputs=["Loss"])
def log_loss(ins, attrs, ctx):
    eps = attrs.get("epsilon", 1e-4)
    p, l = ins["Predicted"], ins["Labels"]
    return {"Loss": -l * jnp.log(p + eps) - (1 - l) * jnp.log(1 - p + eps)}


@register_op("kldiv_loss", inputs=["X", "Target"], outputs=["Loss"])
def kldiv_loss(ins, attrs, ctx):
    x, t = ins["X"], ins["Target"]
    reduction = attrs.get("reduction", "mean")
    loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-12)) - x), 0.0)
    if reduction == "mean":
        return {"Loss": jnp.mean(loss)}
    if reduction == "sum":
        return {"Loss": jnp.sum(loss)}
    if reduction == "batchmean":
        return {"Loss": jnp.sum(loss) / x.shape[0]}
    return {"Loss": loss}


@register_op("sigmoid_cross_entropy_with_logits", inputs=["X", "Label"],
             outputs=["Out"])
def sigmoid_cross_entropy_with_logits(ins, attrs, ctx):
    x, label = ins["X"], ins["Label"]
    ignore_index = attrs.get("ignore_index", -100)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    mask = label != ignore_index
    loss = jnp.where(mask, loss, 0.0)
    if attrs.get("normalize", False):
        loss = loss / jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
    return {"Out": loss}


@register_op("sigmoid_focal_loss", inputs=["X", "Label!", "FgNum!"],
             outputs=["Out"])
def sigmoid_focal_loss(ins, attrs, ctx):
    x, label, fg = ins["X"], ins["Label"].astype(jnp.int32), ins["FgNum"]
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    n, c = x.shape
    # per-class binary target: label in [0, C]; 0 = background
    tgt = jax.nn.one_hot(label.ravel() - 1, c, dtype=x.dtype)
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0.0) - x * tgt + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * tgt + (1 - p) * (1 - tgt)
    a_t = alpha * tgt + (1 - alpha) * (1 - tgt)
    loss = a_t * jnp.power(1 - p_t, gamma) * ce / jnp.maximum(
        fg.astype(x.dtype), 1.0)
    return {"Out": loss}


@register_op("mse_loss", inputs=["X", "Y"], outputs=["Out"])
def mse_loss(ins, attrs, ctx):
    return {"Out": jnp.square(ins["X"] - ins["Y"])}


@register_op("rank_loss", inputs=["Label!", "Left", "Right"], outputs=["Out"])
def rank_loss(ins, attrs, ctx):
    label, left, right = ins["Label"], ins["Left"], ins["Right"]
    d = left - right
    return {"Out": jnp.log1p(jnp.exp(d)) - label * d}


@register_op("margin_rank_loss", inputs=["Label!", "X1", "X2"],
             outputs=["Out", "Activated"])
def margin_rank_loss(ins, attrs, ctx):
    margin = attrs.get("margin", 0.0)
    label, x1, x2 = ins["Label"], ins["X1"], ins["X2"]
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": out, "Activated": (out > 0).astype(x1.dtype)}


@register_op("bpr_loss", inputs=["X", "Label!"], outputs=["Y"])
def bpr_loss(ins, attrs, ctx):
    x, label = ins["X"], ins["Label"].astype(jnp.int32)
    n, c = x.shape
    if label.ndim == 2:
        label = label.squeeze(-1)
    pos = jnp.take_along_axis(x, label[:, None], axis=1)
    diff = x - pos
    # exclude the positive column itself
    mask = jax.nn.one_hot(label, c, dtype=x.dtype)
    loss = -jnp.sum(jnp.log(jax.nn.sigmoid(-diff) + 1e-12) * (1 - mask),
                    axis=1, keepdims=True) / (c - 1)
    return {"Y": loss}


@register_op("center_loss", inputs=["X", "Label!", "Centers", "CenterUpdateRate!"],
             outputs=["CentersOut", "SampleCenterDiff", "Loss"])
def center_loss(ins, attrs, ctx):
    x, label, centers = ins["X"], ins["Label"].astype(jnp.int32).ravel(), \
        ins["Centers"]
    alpha = ins["CenterUpdateRate"].reshape(())
    picked = jnp.take(centers, label, axis=0)
    diff = x - picked
    loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
    if attrs.get("need_update", True):
        counts = jnp.zeros((centers.shape[0],), x.dtype).at[label].add(1.0)
        upd = jnp.zeros_like(centers).at[label].add(diff)
        centers = centers + alpha * upd / (counts[:, None] + 1.0)
    return {"CentersOut": centers, "SampleCenterDiff": diff, "Loss": loss}

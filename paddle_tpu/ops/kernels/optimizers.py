"""Optimizer ops (reference: /root/reference/paddle/fluid/operators/optimizers/
sgd_op.cc, momentum_op.cc, adam_op.cc, adamax, adagrad, adadelta, rmsprop,
lamb_op.cc, lars_momentum_op.cc, ftrl_op.cc).

These are in-place updates in the reference; here the "Out" slots are new
functional values — the executor rebinds the persistable var names, and XLA's
buffer donation makes the update in-place on device.  All moments accumulate
in the parameter's own dtype unless a master-weight input is given (AMP)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _lr(ins):
    return ins["LearningRate"].reshape(()).astype(jnp.float32)


def _is_selected_rows(g):
    from ...core.selected_rows import SelectedRows
    return isinstance(g, SelectedRows)


@register_op("sgd", inputs=["Param", "LearningRate!", "Grad"],
             outputs=["ParamOut"], grad=None, side_effect=True)
def sgd(ins, attrs, ctx):
    p, g = ins["Param"], ins["Grad"]
    if _is_selected_rows(g):
        # SelectedRows path (sgd_op.h SparseSGDFunctor): scatter-add the
        # row updates into the donated param — the [height, width] dense
        # gradient never exists
        upd = (-_lr(ins)) * g.values.astype(jnp.float32)
        return {"ParamOut":
                p.astype(jnp.float32).at[g.rows].add(upd).astype(p.dtype)}
    return {"ParamOut": (p.astype(jnp.float32) -
                         _lr(ins) * g.astype(jnp.float32)).astype(p.dtype)}


@register_op("momentum",
             inputs=["Param", "Grad", "Velocity", "LearningRate!"],
             outputs=["ParamOut", "VelocityOut"], grad=None, side_effect=True)
def momentum(ins, attrs, ctx):
    p, g, v = ins["Param"], ins["Grad"], ins["Velocity"]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    use_nesterov = attrs.get("use_nesterov", False)
    if _is_selected_rows(g):
        # momentum_op.h SparseMomentumFunctor semantics: velocity decays
        # everywhere, gradient lands only on touched rows
        g = g.to_dense()
    pf, gf, vf = (x.astype(jnp.float32) for x in (p, g, v))
    v_out = mu * vf + gf
    if use_nesterov:
        p_out = pf - (gf + mu * v_out) * lr
    else:
        p_out = pf - lr * v_out
    return {"ParamOut": p_out.astype(p.dtype),
            "VelocityOut": v_out.astype(v.dtype)}


@register_op("lars_momentum",
             inputs=["Param", "Grad", "Velocity", "LearningRate!"],
             outputs=["ParamOut", "VelocityOut"], grad=None, side_effect=True)
def lars_momentum(ins, attrs, ctx):
    p, g, v = (ins[k].astype(jnp.float32) for k in ("Param", "Grad",
                                                    "Velocity"))
    mu = attrs.get("mu", 0.9)
    lars_coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    lr = _lr(ins)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lr * lars_coeff * p_norm / (g_norm + wd * p_norm + eps), lr)
    v_out = mu * v + local_lr * (g + wd * p)
    p_out = p - v_out
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "VelocityOut": v_out.astype(ins["Velocity"].dtype)}


@register_op("adam",
             inputs=["Param", "Grad", "LearningRate!", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow", "MasterParam?"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
                      "Beta2PowOut", "MasterParamOut?"],
             grad=None, side_effect=True)
def adam(ins, attrs, ctx):
    p, g = ins["Param"], ins["Grad"]
    m1, m2 = ins["Moment1"], ins["Moment2"]
    b1p, b2p = ins["Beta1Pow"].astype(jnp.float32), \
        ins["Beta2Pow"].astype(jnp.float32)
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    master = ins.get("MasterParam")
    pf = (master if master is not None else p).astype(jnp.float32)
    row_mask = None
    if _is_selected_rows(g):
        # adam_op.h SparseAdamFunctor: lazy_mode touches only looked-up
        # rows (moments + param); non-lazy treats missing rows as zero
        # gradient (moments still decay).  Duplicate rows are merged by
        # the scatter-add in to_dense().
        if attrs.get("lazy_mode", False):
            row_mask = g.row_mask()[(...,) + (None,) * (g.values.ndim - 1)]
        g = g.to_dense()
    gf = g.astype(jnp.float32)
    m1f, m2f = m1.astype(jnp.float32), m2.astype(jnp.float32)
    m1_out = beta1 * m1f + (1 - beta1) * gf
    m2_out = beta2 * m2f + (1 - beta2) * jnp.square(gf)
    if row_mask is not None:
        m1_out = jnp.where(row_mask, m1_out, m1f)
        m2_out = jnp.where(row_mask, m2_out, m2f)
    lr_t = lr * jnp.sqrt(1 - b2p.reshape(())) / (1 - b1p.reshape(()))
    p_out = pf - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    if row_mask is not None:
        p_out = jnp.where(row_mask, p_out, pf)
    outs = {"ParamOut": p_out.astype(p.dtype),
            "Moment1Out": m1_out.astype(m1.dtype),
            "Moment2Out": m2_out.astype(m2.dtype),
            "Beta1PowOut": (b1p * beta1).astype(ins["Beta1Pow"].dtype),
            "Beta2PowOut": (b2p * beta2).astype(ins["Beta2Pow"].dtype)}
    if master is not None:
        outs["MasterParamOut"] = p_out
    return outs


@register_op("adamw",
             inputs=["Param", "Grad", "LearningRate!", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow", "MasterParam?"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
                      "Beta2PowOut", "MasterParamOut?"],
             grad=None, side_effect=True)
def adamw(ins, attrs, ctx):
    coeff = attrs.get("coeff", 0.01)
    lr = _lr(ins)
    p = ins["Param"]
    master = ins.get("MasterParam")
    pf = (master if master is not None else p).astype(jnp.float32)
    decayed = pf * (1.0 - lr * coeff)
    ins2 = dict(ins)
    if master is not None:
        ins2["MasterParam"] = decayed
    else:
        ins2["Param"] = decayed.astype(p.dtype)
    return adam(ins2, attrs, ctx)


@register_op("adamax",
             inputs=["Param", "Grad", "LearningRate!", "Moment", "InfNorm",
                     "Beta1Pow"],
             outputs=["ParamOut", "MomentOut", "InfNormOut", "Beta1PowOut"],
             grad=None, side_effect=True)
def adamax(ins, attrs, ctx):
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    m, u = ins["Moment"].astype(jnp.float32), ins["InfNorm"].astype(jnp.float32)
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins)
    b1p = ins["Beta1Pow"].reshape(()).astype(jnp.float32)
    m_out = beta1 * m + (1 - beta1) * g
    u_out = jnp.maximum(beta2 * u, jnp.abs(g))
    p_out = p - (lr / (1 - b1p)) * m_out / (u_out + eps)
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "MomentOut": m_out.astype(ins["Moment"].dtype),
            "InfNormOut": u_out.astype(ins["InfNorm"].dtype),
            "Beta1PowOut": (b1p * beta1).reshape(
                ins["Beta1Pow"].shape).astype(ins["Beta1Pow"].dtype)}


@register_op("adagrad",
             inputs=["Param", "Grad", "Moment", "LearningRate!"],
             outputs=["ParamOut", "MomentOut"], grad=None, side_effect=True)
def adagrad(ins, attrs, ctx):
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    m = ins["Moment"].astype(jnp.float32)
    eps = attrs.get("epsilon", 1e-6)
    m_out = m + jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "MomentOut": m_out.astype(ins["Moment"].dtype)}


@register_op("decayed_adagrad",
             inputs=["Param", "Grad", "Moment", "LearningRate!"],
             outputs=["ParamOut", "MomentOut"], grad=None, side_effect=True)
def decayed_adagrad(ins, attrs, ctx):
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    m = ins["Moment"].astype(jnp.float32)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * jnp.square(g)
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "MomentOut": m_out.astype(ins["Moment"].dtype)}


@register_op("adadelta",
             inputs=["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
             outputs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
             grad=None, side_effect=True)
def adadelta(ins, attrs, ctx):
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    sg = ins["AvgSquaredGrad"].astype(jnp.float32)
    su = ins["AvgSquaredUpdate"].astype(jnp.float32)
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    sg_out = rho * sg + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((su + eps) / (sg_out + eps)) * g
    su_out = rho * su + (1 - rho) * jnp.square(upd)
    return {"ParamOut": (p + upd).astype(ins["Param"].dtype),
            "AvgSquaredGradOut": sg_out.astype(jnp.float32),
            "AvgSquaredUpdateOut": su_out.astype(jnp.float32)}


@register_op("rmsprop",
             inputs=["Param", "Grad", "MeanSquare", "MeanGrad", "Moment",
                     "LearningRate!"],
             outputs=["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
             grad=None, side_effect=True)
def rmsprop(ins, attrs, ctx):
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    ms = ins["MeanSquare"].astype(jnp.float32)
    mg = ins["MeanGrad"].astype(jnp.float32)
    mom = ins["Moment"].astype(jnp.float32)
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum_ = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    lr = _lr(ins)
    ms_out = rho * ms + (1 - rho) * jnp.square(g)
    if centered:
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - jnp.square(mg_out) + eps
    else:
        mg_out = mg
        denom = ms_out + eps
    mom_out = momentum_ * mom + lr * g / jnp.sqrt(denom)
    return {"ParamOut": (p - mom_out).astype(ins["Param"].dtype),
            "MomentOut": mom_out, "MeanSquareOut": ms_out,
            "MeanGradOut": mg_out}


@register_op("ftrl",
             inputs=["Param", "SquaredAccumulator", "LinearAccumulator",
                     "Grad", "LearningRate!"],
             outputs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
             grad=None, side_effect=True)
def ftrl(ins, attrs, ctx):
    p = ins["Param"].astype(jnp.float32)
    sq = ins["SquaredAccumulator"].astype(jnp.float32)
    lin = ins["LinearAccumulator"].astype(jnp.float32)
    g = ins["Grad"].astype(jnp.float32)
    l1 = attrs.get("l1", 0.0) + 1e-10
    l2 = attrs.get("l2", 0.0) + 1e-10
    lr_power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p_out = pre / denom
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "SquaredAccumOut": new_sq, "LinearAccumOut": new_lin}


@register_op("lamb",
             inputs=["Param", "Grad", "LearningRate!", "Moment1", "Moment2",
                     "Beta1Pow", "Beta2Pow"],
             outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
                      "Beta2PowOut"],
             grad=None, side_effect=True)
def lamb(ins, attrs, ctx):
    p = ins["Param"].astype(jnp.float32)
    g = ins["Grad"].astype(jnp.float32)
    m1 = ins["Moment1"].astype(jnp.float32)
    m2 = ins["Moment2"].astype(jnp.float32)
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    lr = _lr(ins)
    b1p = ins["Beta1Pow"].reshape(()).astype(jnp.float32)
    b2p = ins["Beta2Pow"].reshape(()).astype(jnp.float32)
    m1_out = beta1 * m1 + (1 - beta1) * g
    m2_out = beta2 * m2 + (1 - beta2) * jnp.square(g)
    m1_hat = m1_out / (1 - b1p)
    m2_hat = m2_out / (1 - b2p)
    r = m1_hat / (jnp.sqrt(m2_hat) + eps) + wd * p
    sq_p = jnp.sum(jnp.square(p))
    sq_r = jnp.sum(jnp.square(r))
    # ZeRO-1 sharded update (distributed/sharding.py): p and r are this
    # rank's 1/world shard of one flat parameter, but the trust ratio is
    # defined on the WHOLE parameter's norms — psum the squared norms
    # over the ring.  Zero bucket padding contributes zero to both sums.
    ring = attrs.get("reduce_norms_ring_id")
    if ring is not None:
        axes = ctx.collective_axes(ring)
        if axes:
            sq_p = jax.lax.psum(sq_p, axes)
            sq_r = jax.lax.psum(sq_r, axes)
    p_norm = jnp.sqrt(sq_p)
    r_norm = jnp.sqrt(sq_r)
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    p_out = p - lr * trust * r
    return {"ParamOut": p_out.astype(ins["Param"].dtype),
            "Moment1Out": m1_out, "Moment2Out": m2_out,
            "Beta1PowOut": (b1p * beta1).astype(ins["Beta1Pow"].dtype),
            "Beta2PowOut": (b2p * beta2).astype(ins["Beta2Pow"].dtype)}


@register_op("dpsgd", inputs=["Param", "Grad", "LearningRate!"],
             outputs=["ParamOut"], grad=None, side_effect=True)
def dpsgd(ins, attrs, ctx):
    import jax
    p, g = ins["Param"].astype(jnp.float32), ins["Grad"].astype(jnp.float32)
    clip = attrs.get("clip", 10.0)
    batch_size = attrs.get("batch_size", 16.0)
    sigma = attrs.get("sigma", 1.0)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(g_norm, 1e-12))
    noise = sigma * clip * jax.random.normal(ctx.key(attrs), g.shape)
    p_out = p - _lr(ins) * (g + noise / batch_size)
    return {"ParamOut": p_out.astype(ins["Param"].dtype)}


@register_op("average_accumulates",
             inputs=["param", "in_sum_1", "in_sum_2", "in_sum_3",
                     "in_num_accumulates!", "in_old_num_accumulates!",
                     "in_num_updates!"],
             outputs=["out_sum_1", "out_sum_2", "out_sum_3",
                      "out_num_accumulates", "out_old_num_accumulates",
                      "out_num_updates"],
             grad=None, side_effect=True)
def average_accumulates(ins, attrs, ctx):
    """ModelAverage support op — EXACT reference semantics
    (operators/average_accumulates_op.h:84): each step sum_1 += param;
    every kMaxNumAccumulates updates sum_1 spills into sum_2
    (precision shuffle); when the window completes, sum_3 is REPLACED
    by sum_1+sum_2, both are cleared, and the window count moves to
    old_num_accumulates.  apply-time average is
    (s1+s2+s3)/(num_accumulates+old_num_accumulates)."""
    k_max_num_accumulates = 16384
    p = ins["param"]
    s1, s2, s3 = ins["in_sum_1"], ins["in_sum_2"], ins["in_sum_3"]
    na = ins["in_num_accumulates"].reshape(())
    ona = ins["in_old_num_accumulates"].reshape(())
    nu = ins["in_num_updates"].reshape(())
    avg_window = attrs.get("average_window", 10.0)
    max_avg = attrs.get("max_average_window", 10000)
    min_avg = attrs.get("min_average_window", 10000)
    na = na + 1
    nu = nu + 1
    s1 = s1 + p
    spill = (nu % k_max_num_accumulates) == 0
    s2 = jnp.where(spill, s2 + s1, s2)
    s1 = jnp.where(spill, jnp.zeros_like(s1), s1)
    window_full = (na >= min_avg) & (na >= jnp.minimum(
        max_avg, nu * avg_window))
    s3_new = jnp.where(window_full, s1 + s2, s3)
    s2_new = jnp.where(window_full, jnp.zeros_like(s2), s2)
    s1_new = jnp.where(window_full, jnp.zeros_like(s1), s1)
    ona_new = jnp.where(window_full, na, ona)
    na_new = jnp.where(window_full, jnp.zeros_like(na), na)
    return {"out_sum_1": s1_new, "out_sum_2": s2_new, "out_sum_3": s3_new,
            "out_num_accumulates": na_new.reshape(
                ins["in_num_accumulates"].shape),
            "out_old_num_accumulates": ona_new.reshape(
                ins["in_old_num_accumulates"].shape),
            "out_num_updates": nu.reshape(ins["in_num_updates"].shape)}


@register_op("dgc",
             inputs=["U", "Grad", "Param?"],
             outputs=["UOut", "EncodedGrad", "GradOut"],
             grad=None, side_effect=True)
def dgc(ins, attrs, ctx):
    """Deep Gradient Compression sparsifier (reference:
    operators/dgc_op.* + details/sparse_all_reduce_op_handle — top-k
    gradient selection with local residual accumulation, arXiv:1712.01887).

    TPU redesign: the sparse encode/allgather path has no win over ICI's
    dense allreduce bandwidth for typical layer sizes, so the kernel keeps
    DGC's NUMERICS (momentum correction + top-k masking + residual) but
    emits a dense masked gradient that the normal c_allreduce_sum handles;
    XLA fuses mask+reduce.  attrs: m (momentum), sparsity in [0,1).
    """
    u, g = ins["U"], ins["Grad"]
    m = attrs.get("m", 0.9)
    sparsity = float(attrs.get("sparsity", 0.999))
    gf = g.astype(jnp.float32)
    # momentum correction: u accumulates the velocity locally
    u_new = m * u.astype(jnp.float32) + gf
    flat = u_new.ravel()
    n = flat.shape[0]
    k = max(1, int(n * (1.0 - sparsity)))
    thresh = jax.lax.top_k(jnp.abs(flat), k)[0][-1]
    mask = jnp.abs(u_new) >= thresh
    encoded = jnp.where(mask, u_new, 0.0)
    u_out = jnp.where(mask, 0.0, u_new)  # residual stays local
    return {"UOut": u_out.astype(u.dtype),
            "EncodedGrad": encoded.astype(g.dtype),
            "GradOut": encoded.astype(g.dtype)}

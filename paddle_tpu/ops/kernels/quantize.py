"""Fake-quantization ops (reference: /root/reference/paddle/fluid/operators/
fake_quantize_op.cc — FakeQuantizeAbsMax, FakeChannelWiseQuantizeAbsMax,
FakeQuantizeMovingAverageAbsMax, MovingAverageAbsMaxScale,
fake_dequantize_op.cc FakeDequantizeMaxAbs; straight-through-estimator
gradients registered by the QAT passes, quantization_pass.py).

TPU design: quantization is SIMULATED in float (quant→round→dequant in one
fused XLA computation) during QAT and calibrated inference; the freeze pass
(slim/quantization.py) stores weights as real int8 with a dequantize op in
front — XLA folds the dequant into the consuming matmul/conv.  Gradients of
the quant_dequant ops are straight-through (identity inside the clip range),
matching the reference QAT training semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _bin(attrs):
    # bit_length=8 -> 127 (reference: (1 << (bit_length - 1)) - 1)
    return float((1 << (attrs.get("bit_length", 8) - 1)) - 1)


def _abs_max(x):
    s = jnp.max(jnp.abs(x))
    return jnp.maximum(s, 1e-8)


def _quant(x, scale, b):
    """THE quantization grid — single source of truth for round/clip.
    `scale` may be scalar or broadcastable (channel-wise)."""
    return jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-8) * b), -b, b)


def _quant_dequant(x, scale, b):
    return (_quant(x, scale, b) * (jnp.maximum(scale, 1e-8) / b)) \
        .astype(x.dtype)


def _ste_grad(ins, attrs, ctx):
    """Straight-through estimator: pass the cotangent through the
    quant-dequant unchanged inside the representable range."""
    x, g = ins["X"], ins["Out@GRAD"]
    if g is None:
        return {}
    return {"X@GRAD": g}


# -- quantize-only (inference/freeze path) ----------------------------------
@register_op("fake_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=None)
def fake_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    scale = _abs_max(x)
    q = _quant(x, scale, b)
    return {"Out": q, "OutScale": scale.reshape((1,))}


@register_op("fake_channel_wise_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=None)
def fake_channel_wise_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    q = _quant(x, scale.reshape(shape), b)
    return {"Out": q, "OutScale": scale}


@register_op("fake_dequantize_max_abs", inputs=["X", "Scale!"],
             outputs=["Out"], grad=None)
def fake_dequantize_max_abs(ins, attrs, ctx):
    x, scale = ins["X"], ins["Scale"]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x.astype(jnp.float32) * (scale.reshape(()) / max_range)}


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=["X", "Scales*"], outputs=["Out"], grad=None)
def fake_channel_wise_dequantize_max_abs(ins, attrs, ctx):
    x = ins["X"]
    scales = ins["Scales"]
    axis = attrs.get("quant_axis", 0)
    qb = float(attrs.get("max_range", 127.0))
    shape = [1] * x.ndim
    shape[axis] = -1
    out = x.astype(jnp.float32) * (scales[0].reshape(shape) / qb)
    if len(scales) > 1:  # second-level (activation) scale
        out = out * (scales[1].reshape(()) / qb)
    return {"Out": out}


# -- quant+dequant (QAT simulated path, STE gradient) -----------------------
@register_op("fake_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=_ste_grad)
def fake_quantize_dequantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    scale = _abs_max(x)
    out = _quant_dequant(x, scale, b)
    return {"Out": out, "OutScale": scale.reshape((1,))}


@register_op("fake_channel_wise_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=_ste_grad)
def fake_channel_wise_quantize_dequantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = _quant_dequant(x, scale.reshape(shape), b)
    return {"Out": out, "OutScale": scale}


def _moving_average(ins, attrs, x):
    """scale tracking: state = rho*state + 1; accum = rho*accum + absmax;
    scale = accum / state (fake_quantize_op.cc FindMovingAverageAbsMax)."""
    rho = attrs.get("moving_rate", 0.9)
    cur = _abs_max(x)
    in_state = ins.get("InState")
    in_accum = ins.get("InAccum")
    state = (rho * in_state.reshape(()) + 1.0) if in_state is not None \
        else jnp.asarray(1.0)
    accum = (rho * in_accum.reshape(()) + cur) if in_accum is not None \
        else cur
    return accum / state, state, accum


@register_op("fake_quantize_moving_average_abs_max",
             inputs=["X", "InScale!", "InState?!", "InAccum?!"],
             outputs=["Out", "OutScale", "OutState?", "OutAccum?"],
             grad=None)
def fake_quantize_moving_average_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    if attrs.get("is_test", False) or ctx.is_test:
        scale = ins["InScale"].reshape(())
        q = _quant(x, scale, b)
        return {"Out": q, "OutScale": scale.reshape((1,))}
    scale, state, accum = _moving_average(ins, attrs, x)
    q = _quant(x, scale, b)
    return {"Out": q, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=["X", "InScale!", "InState?!", "InAccum?!"],
             outputs=["Out", "OutScale", "OutState?", "OutAccum?"],
             grad=_ste_grad)
def fake_quantize_dequantize_moving_average_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    if attrs.get("is_test", False) or ctx.is_test:
        scale = ins["InScale"].reshape(())
        out = _quant_dequant(x, scale, b)
        return {"Out": out, "OutScale": scale.reshape((1,))}
    scale, state, accum = _moving_average(ins, attrs, x)
    out = _quant_dequant(x, scale, b)
    return {"Out": out, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}


@register_op("moving_average_abs_max_scale",
             inputs=["X", "InState?!", "InAccum?!"],
             outputs=["Out?", "OutScale", "OutState?", "OutAccum?"],
             grad=_ste_grad)
def moving_average_abs_max_scale(ins, attrs, ctx):
    """Observer op: identity on X, tracks the output scale (used by the
    freeze pass for activation out_threshold attrs)."""
    x = ins["X"]
    if attrs.get("is_test", False) or ctx.is_test:
        return {"Out": x}
    scale, state, accum = _moving_average(ins, attrs, x)
    return {"Out": x, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}

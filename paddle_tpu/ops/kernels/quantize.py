"""Fake-quantization ops (reference: /root/reference/paddle/fluid/operators/
fake_quantize_op.cc — FakeQuantizeAbsMax, FakeChannelWiseQuantizeAbsMax,
FakeQuantizeMovingAverageAbsMax, MovingAverageAbsMaxScale,
fake_dequantize_op.cc FakeDequantizeMaxAbs; straight-through-estimator
gradients registered by the QAT passes, quantization_pass.py).

TPU design: quantization is SIMULATED in float (quant→round→dequant in one
fused XLA computation) during QAT and calibrated inference; the freeze pass
(slim/quantization.py) stores weights as real int8 with a dequantize op in
front — XLA folds the dequant into the consuming matmul/conv.  Gradients of
the quant_dequant ops are straight-through (identity inside the clip range),
matching the reference QAT training semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _bin(attrs):
    # bit_length=8 -> 127 (reference: (1 << (bit_length - 1)) - 1)
    return float((1 << (attrs.get("bit_length", 8) - 1)) - 1)


def _abs_max(x):
    s = jnp.max(jnp.abs(x))
    return jnp.maximum(s, 1e-8)


def _quant(x, scale, b):
    """THE quantization grid — single source of truth for round/clip.
    `scale` may be scalar or broadcastable (channel-wise)."""
    return jnp.clip(jnp.round(x / jnp.maximum(scale, 1e-8) * b), -b, b)


def _quant_dequant(x, scale, b):
    return (_quant(x, scale, b) * (jnp.maximum(scale, 1e-8) / b)) \
        .astype(x.dtype)


def _ste_grad(ins, attrs, ctx):
    """Straight-through estimator: pass the cotangent through the
    quant-dequant unchanged inside the representable range."""
    x, g = ins["X"], ins["Out@GRAD"]
    if g is None:
        return {}
    return {"X@GRAD": g}


# -- quantize-only (inference/freeze path) ----------------------------------
@register_op("fake_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=None)
def fake_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    scale = _abs_max(x)
    q = _quant(x, scale, b)
    return {"Out": q, "OutScale": scale.reshape((1,))}


@register_op("fake_channel_wise_quantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=None)
def fake_channel_wise_quantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    q = _quant(x, scale.reshape(shape), b)
    return {"Out": q, "OutScale": scale}


@register_op("fake_dequantize_max_abs", inputs=["X", "Scale!"],
             outputs=["Out"], grad=None)
def fake_dequantize_max_abs(ins, attrs, ctx):
    x, scale = ins["X"], ins["Scale"]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": x.astype(jnp.float32) * (scale.reshape(()) / max_range)}


@register_op("fake_channel_wise_dequantize_max_abs",
             inputs=["X", "Scales*"], outputs=["Out"], grad=None)
def fake_channel_wise_dequantize_max_abs(ins, attrs, ctx):
    x = ins["X"]
    scales = ins["Scales"]
    axis = attrs.get("quant_axis", 0)
    qb = float(attrs.get("max_range", 127.0))
    shape = [1] * x.ndim
    shape[axis] = -1
    out = x.astype(jnp.float32) * (scales[0].reshape(shape) / qb)
    if len(scales) > 1:  # second-level (activation) scale
        out = out * (scales[1].reshape(()) / qb)
    return {"Out": out}


# -- REAL int8 execution path -----------------------------------------------
# Reference: /root/reference/paddle/fluid/operators/quantize_op.cc:52,
# dequantize_op.cc, requantize_op.cc (the mkldnn int8 inference chain) —
# scale-MULTIPLY convention: q = round(x * scale), x = q / scale.

@register_op("quantize", inputs=["Input"], outputs=["Output"], grad=None)
def quantize_op(ins, attrs, ctx):
    """quantize_op.cc:52 — fp32 -> int8 (or uint8 when the input is known
    non-negative, e.g. post-relu): q = round(x * scale)."""
    x = ins["Input"].astype(jnp.float32)
    scale = float(attrs.get("Scale", attrs.get("scale", 1.0)))
    neg = bool(attrs.get("is_negative_input", True))
    q = jnp.round(x * scale)
    if neg:
        return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}
    return {"Output": jnp.clip(q, 0, 255).astype(jnp.uint8)}


@register_op("dequantize", inputs=["Input"], outputs=["Output"],
             grad=None)
def dequantize_op(ins, attrs, ctx):
    """dequantize_op.cc — int8/uint8 -> fp32: x = q / scale."""
    scale = float(attrs.get("Scale", attrs.get("scale", 1.0)))
    return {"Output": ins["Input"].astype(jnp.float32) / scale}


@register_op("requantize", inputs=["Input"], outputs=["Output"],
             grad=None)
def requantize_op(ins, attrs, ctx):
    """requantize_op.cc — re-scale an int8 tensor between two quantized
    domains without a float round trip: q' = round(q * s_out / s_in)."""
    s_in = float(attrs.get("Scale_in", attrs.get("scale_in", 1.0)))
    s_out = float(attrs.get("Scale_out", attrs.get("scale_out", 1.0)))
    q = jnp.round(ins["Input"].astype(jnp.float32) * (s_out / s_in))
    return {"Output": jnp.clip(q, -128, 127).astype(jnp.int8)}


@register_op("int8_matmul", inputs=["X", "W!", "WScale!", "Bias?"],
             outputs=["Out"], grad=None)
def int8_matmul(ins, attrs, ctx):
    """The int8 execution core the quant_int8_pass rewrites frozen
    fake_dequantize→mul/fc chains onto (replacing the reference's mkldnn
    int8 mul/fc kernels, operators/mkldnn/mul_mkldnn_op.cc).

    One fused kernel: dynamic per-tensor activation quantization, int8 x
    int8 dot accumulated in int32 (preferred_element_type — this is the
    dot XLA lowers onto the v5e MXU int8 path at 2x bf16 rate), then one
    combined dequant multiply.  W is the frozen int8 weight [K, N];
    WScale the freeze-time abs-max (per-tensor [1] or per-out-channel
    [N]), dequant convention w_f = w_q * scale / max_range matching
    fake_dequantize_max_abs."""
    x, w, ws = ins["X"], ins["W"], ins["WScale"]
    max_range = float(attrs.get("max_range", 127.0))
    xf = x.astype(jnp.float32)
    absmax = jnp.maximum(jnp.max(jnp.abs(xf)), 1e-8)
    xs = 127.0 / absmax
    xq = jnp.clip(jnp.round(xf * xs), -127, 127).astype(jnp.int8)
    x2 = xq.reshape((-1, xq.shape[-1]))
    acc = jax.lax.dot_general(
        x2, w.astype(jnp.int8), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    wscale = ws.astype(jnp.float32).reshape(-1) / max_range
    deq = wscale.reshape(()) if wscale.size == 1 else wscale[None, :]
    out = acc.astype(jnp.float32) * deq / xs
    out = out.reshape(tuple(x.shape[:-1]) + (w.shape[1],))
    if ins.get("Bias") is not None:
        out = out + ins["Bias"].astype(jnp.float32)
    return {"Out": out.astype(ins["X"].dtype if x.dtype != jnp.int8
                              else jnp.float32)}


# -- quant+dequant (QAT simulated path, STE gradient) -----------------------
@register_op("fake_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=_ste_grad)
def fake_quantize_dequantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    scale = _abs_max(x)
    out = _quant_dequant(x, scale, b)
    return {"Out": out, "OutScale": scale.reshape((1,))}


@register_op("fake_channel_wise_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], grad=_ste_grad)
def fake_channel_wise_quantize_dequantize_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    axis = attrs.get("quant_axis", 0)
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.maximum(jnp.max(jnp.abs(x), axis=red), 1e-8)
    shape = [1] * x.ndim
    shape[axis] = -1
    out = _quant_dequant(x, scale.reshape(shape), b)
    return {"Out": out, "OutScale": scale}


def _moving_average(ins, attrs, x):
    """scale tracking: state = rho*state + 1; accum = rho*accum + absmax;
    scale = accum / state (fake_quantize_op.cc FindMovingAverageAbsMax)."""
    rho = attrs.get("moving_rate", 0.9)
    cur = _abs_max(x)
    in_state = ins.get("InState")
    in_accum = ins.get("InAccum")
    state = (rho * in_state.reshape(()) + 1.0) if in_state is not None \
        else jnp.asarray(1.0)
    accum = (rho * in_accum.reshape(()) + cur) if in_accum is not None \
        else cur
    return accum / state, state, accum


@register_op("fake_quantize_moving_average_abs_max",
             inputs=["X", "InScale!", "InState?!", "InAccum?!"],
             outputs=["Out", "OutScale", "OutState?", "OutAccum?"],
             grad=None)
def fake_quantize_moving_average_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    if attrs.get("is_test", False) or ctx.is_test:
        scale = ins["InScale"].reshape(())
        q = _quant(x, scale, b)
        return {"Out": q, "OutScale": scale.reshape((1,))}
    scale, state, accum = _moving_average(ins, attrs, x)
    q = _quant(x, scale, b)
    return {"Out": q, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}


@register_op("fake_quantize_dequantize_moving_average_abs_max",
             inputs=["X", "InScale!", "InState?!", "InAccum?!"],
             outputs=["Out", "OutScale", "OutState?", "OutAccum?"],
             grad=_ste_grad)
def fake_quantize_dequantize_moving_average_abs_max(ins, attrs, ctx):
    x = ins["X"]
    b = _bin(attrs)
    if attrs.get("is_test", False) or ctx.is_test:
        scale = ins["InScale"].reshape(())
        out = _quant_dequant(x, scale, b)
        return {"Out": out, "OutScale": scale.reshape((1,))}
    scale, state, accum = _moving_average(ins, attrs, x)
    out = _quant_dequant(x, scale, b)
    return {"Out": out, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}


@register_op("moving_average_abs_max_scale",
             inputs=["X", "InState?!", "InAccum?!"],
             outputs=["Out?", "OutScale", "OutState?", "OutAccum?"],
             grad=_ste_grad)
def moving_average_abs_max_scale(ins, attrs, ctx):
    """Observer op: identity on X, tracks the output scale (used by the
    freeze pass for activation out_threshold attrs)."""
    x = ins["X"]
    if attrs.get("is_test", False) or ctx.is_test:
        return {"Out": x}
    scale, state, accum = _moving_average(ins, attrs, x)
    return {"Out": x, "OutScale": scale.reshape((1,)),
            "OutState": state.reshape((1,)), "OutAccum": accum.reshape((1,))}

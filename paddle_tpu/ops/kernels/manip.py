"""Tensor manipulation ops (reference: reshape_op.cc, transpose_op.cc,
concat_op.cc, split_op.cc, gather_op.cc, slice_op.cc, cast_op.cc,
fill_constant_op.cc, one_hot_op.cc, top_k_op.cc, arg_min_max_op_base.h, ...).

Note on dynamic-shape ops: `masked_select`, `where_index`, `unique` have
data-dependent output shapes, which XLA cannot compile into a static program.
They work in eager/dygraph mode; inside a jitted static program they must be
used as fetch boundaries (the reference had the same split between device ops
and host-side logic for these)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op
from ...core.dtype import np_dtype


def _resolve_shape(shape, x):
    """reshape semantics: 0 -> copy input dim, -1 -> infer."""
    shape = list(shape)
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return tuple(shape)


@register_op("reshape2", inputs=["X", "Shape?!", "ShapeTensor*?!"],
             outputs=["Out", "XShape"])
def reshape2(ins, attrs, ctx):
    x = ins["X"]
    shape = _resolve_shape(attrs.get("shape", []), x)
    return {"Out": x.reshape(shape),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("reshape", inputs=["X", "Shape?!"], outputs=["Out"])
def reshape(ins, attrs, ctx):
    x = ins["X"]
    return {"Out": x.reshape(_resolve_shape(attrs.get("shape", []), x))}


@register_op("squeeze2", inputs=["X"], outputs=["Out", "XShape"])
def squeeze2(ins, attrs, ctx):
    x = ins["X"]
    axes = attrs.get("axes", [])
    if not axes:
        axes = [i for i, s in enumerate(x.shape) if s == 1]
    axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
    return {"Out": jnp.squeeze(x, axis=axes),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("squeeze", inputs=["X"], outputs=["Out"])
def squeeze(ins, attrs, ctx):
    return {"Out": squeeze2(ins, attrs, ctx)["Out"]}


@register_op("unsqueeze2", inputs=["X"], outputs=["Out", "XShape"])
def unsqueeze2(ins, attrs, ctx):
    x = ins["X"]
    out = x
    for a in sorted(attrs.get("axes", [])):
        out = jnp.expand_dims(out, a)
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("unsqueeze", inputs=["X"], outputs=["Out"])
def unsqueeze(ins, attrs, ctx):
    return {"Out": unsqueeze2(ins, attrs, ctx)["Out"]}


@register_op("flatten2", inputs=["X"], outputs=["Out", "XShape"])
def flatten2(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", 1)
    out = x.reshape((int(np.prod(x.shape[:axis])), -1))
    return {"Out": out, "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("flatten", inputs=["X"], outputs=["Out"])
def flatten(ins, attrs, ctx):
    return {"Out": flatten2(ins, attrs, ctx)["Out"]}


@register_op("flatten_contiguous_range", inputs=["X"], outputs=["Out", "XShape"])
def flatten_contiguous_range(ins, attrs, ctx):
    x = ins["X"]
    start = attrs.get("start_axis", 1) % max(x.ndim, 1)
    stop = attrs.get("stop_axis", -1) % max(x.ndim, 1)
    shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return {"Out": x.reshape(shape),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("transpose2", inputs=["X"], outputs=["Out", "XShape"])
def transpose2(ins, attrs, ctx):
    x = ins["X"]
    return {"Out": jnp.transpose(x, attrs["axis"]),
            "XShape": jnp.zeros((0,) + x.shape, x.dtype)}


@register_op("transpose", inputs=["X"], outputs=["Out"])
def transpose(ins, attrs, ctx):
    return {"Out": jnp.transpose(ins["X"], attrs["axis"])}


@register_op("concat", inputs=["X*", "AxisTensor?!"], outputs=["Out"])
def concat(ins, attrs, ctx):
    return {"Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))}


@register_op("split", inputs=["X"], outputs=["Out*"])
def split(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        total = x.shape[axis]
        sections = list(sections)
        if -1 in sections:
            known = sum(s for s in sections if s != -1)
            sections[sections.index(-1)] = total - known
        idx = np.cumsum(sections[:-1])
        return {"Out": jnp.split(x, idx, axis=axis)}
    return {"Out": jnp.split(x, num, axis=axis)}


@register_op("stack", inputs=["X*"], outputs=["Y"])
def stack(ins, attrs, ctx):
    return {"Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))}


@register_op("unstack", inputs=["X"], outputs=["Y*"])
def unstack(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    n = attrs.get("num", x.shape[axis])
    return {"Y": [jnp.squeeze(s, axis)
                  for s in jnp.split(x, n, axis=axis)]}


@register_op("unbind", inputs=["X"], outputs=["Out*"])
def unbind(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.squeeze(s, axis)
                    for s in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("gather", inputs=["X", "Index!", "Axis?!"], outputs=["Out"])
def gather(ins, attrs, ctx):
    axis = attrs.get("axis", 0)
    if ins.get("Axis") is not None:
        axis = int(ins["Axis"])
    return {"Out": jnp.take(ins["X"], ins["Index"].astype(jnp.int32),
                            axis=axis)}


@register_op("gather_nd", inputs=["X", "Index!"], outputs=["Out"])
def gather_nd(ins, attrs, ctx):
    x, idx = ins["X"], ins["Index"].astype(jnp.int32)
    k = idx.shape[-1]
    return {"Out": x[tuple(jnp.moveaxis(idx, -1, 0))] if k == x.ndim
            else x[tuple(jnp.moveaxis(idx, -1, 0))]}


@register_op("scatter", inputs=["X", "Ids!", "Updates"], outputs=["Out"])
def scatter(ins, attrs, ctx):
    x, ids, upd = ins["X"], ins["Ids"].astype(jnp.int32).ravel(), ins["Updates"]
    if attrs.get("overwrite", True):
        return {"Out": x.at[ids].set(upd)}
    return {"Out": x.at[ids].add(upd)}


@register_op("scatter_nd_add", inputs=["X", "Index!", "Updates"],
             outputs=["Out"])
def scatter_nd_add(ins, attrs, ctx):
    x, idx, upd = ins["X"], ins["Index"].astype(jnp.int32), ins["Updates"]
    return {"Out": x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)}


@register_op("slice", inputs=["Input", "StartsTensor?!", "EndsTensor?!"],
             outputs=["Out"])
def slice_op(ins, attrs, ctx):
    x = ins["Input"]
    axes = attrs["axes"]
    starts = list(attrs.get("starts", []))
    ends = list(attrs.get("ends", []))
    sl = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s = max(s + dim, 0) if s < 0 else min(s, dim)
        e = max(e + dim, 0) if e < 0 else min(e, dim)
        sl[a] = slice(s, e)
    out = x[tuple(sl)]
    for a in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": out}


@register_op("strided_slice", inputs=["Input"], outputs=["Out"])
def strided_slice(ins, attrs, ctx):
    x = ins["Input"]
    sl = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"],
                           attrs["strides"]):
        sl[a] = slice(s, e, st)
    out = x[tuple(sl)]
    for a in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, axis=a)
    return {"Out": out}


@register_op("index_select", inputs=["X", "Index!"], outputs=["Out"])
def index_select(ins, attrs, ctx):
    return {"Out": jnp.take(ins["X"], ins["Index"].astype(jnp.int32),
                            axis=attrs.get("dim", 0))}


@register_op("index_sample", inputs=["X", "Index!"], outputs=["Out"])
def index_sample(ins, attrs, ctx):
    x, idx = ins["X"], ins["Index"].astype(jnp.int32)
    return {"Out": jnp.take_along_axis(x, idx, axis=1)}


@register_op("masked_select", inputs=["X", "Mask!"], outputs=["Y"])
def masked_select(ins, attrs, ctx):
    # data-dependent shape: eager-mode only
    return {"Y": ins["X"][ins["Mask"]]}


@register_op("where", inputs=["Condition!", "X", "Y"], outputs=["Out"])
def where(ins, attrs, ctx):
    return {"Out": jnp.where(ins["Condition"], ins["X"], ins["Y"])}


@register_op("where_index", inputs=["Condition!"], outputs=["Out"], grad=None)
def where_index(ins, attrs, ctx):
    # data-dependent shape: eager-mode only
    return {"Out": jnp.stack(jnp.nonzero(ins["Condition"]), axis=1)
            .astype(jnp.int64)}


def _expand(x, times):
    return jnp.tile(x, tuple(times))


@register_op("expand", inputs=["X"], outputs=["Out"])
def expand(ins, attrs, ctx):
    return {"Out": _expand(ins["X"], attrs["expand_times"])}


@register_op("expand_v2", inputs=["X"], outputs=["Out"])
def expand_v2(ins, attrs, ctx):
    x = ins["X"]
    shape = list(attrs["shape"])
    if len(shape) > x.ndim:
        x = x.reshape((1,) * (len(shape) - x.ndim) + x.shape)
    shape = [x.shape[i] if s == -1 else s for i, s in enumerate(shape)]
    return {"Out": jnp.broadcast_to(x, tuple(shape))}


@register_op("expand_as", inputs=["X", "target_tensor!"], outputs=["Out"])
def expand_as(ins, attrs, ctx):
    return {"Out": jnp.broadcast_to(ins["X"], ins["target_tensor"].shape)}


@register_op("expand_as_v2", inputs=["X", "Y?!"], outputs=["Out"])
def expand_as_v2(ins, attrs, ctx):
    shape = attrs.get("target_shape")
    if shape is None:
        shape = ins["Y"].shape
    return {"Out": jnp.broadcast_to(ins["X"], tuple(shape))}


@register_op("tile", inputs=["X"], outputs=["Out"])
def tile(ins, attrs, ctx):
    return {"Out": jnp.tile(ins["X"], tuple(attrs["repeat_times"]))}


@register_op("flip", inputs=["X"], outputs=["Out"])
def flip(ins, attrs, ctx):
    return {"Out": jnp.flip(ins["X"], axis=tuple(attrs["axis"]))}


@register_op("roll", inputs=["X"], outputs=["Out"])
def roll(ins, attrs, ctx):
    shifts = attrs["shifts"]
    axis = attrs.get("axis", attrs.get("dims", None))
    if axis is None or (isinstance(axis, (list, tuple)) and not axis):
        return {"Out": jnp.roll(ins["X"].ravel(), shifts[0] if
                                isinstance(shifts, (list, tuple)) else shifts)
                .reshape(ins["X"].shape)}
    return {"Out": jnp.roll(ins["X"], tuple(shifts), tuple(axis))}


@register_op("reverse", inputs=["X"], outputs=["Out"])
def reverse(ins, attrs, ctx):
    return {"Out": jnp.flip(ins["X"], axis=tuple(attrs["axis"]))}


@register_op("pad", inputs=["X"], outputs=["Out"])
def pad(ins, attrs, ctx):
    x = ins["X"]
    p = attrs["paddings"]
    widths = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, widths, constant_values=attrs.get("pad_value",
                                                                0.0))}


@register_op("pad2d", inputs=["X"], outputs=["Out"])
def pad2d(ins, attrs, ctx):
    x = ins["X"]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        widths = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        widths = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    mode_map = {"constant": "constant", "reflect": "reflect", "edge": "edge"}
    kw = {"constant_values": attrs.get("pad_value", 0.0)} \
        if mode == "constant" else {}
    return {"Out": jnp.pad(x, widths, mode=mode_map[mode], **kw)}


@register_op("pad3d", inputs=["X"], outputs=["Out"])
def pad3d(ins, attrs, ctx):
    x = ins["X"]
    p = attrs["paddings"]  # [front,back,top,bottom,left,right] NCDHW
    mode = attrs.get("mode", "constant")
    widths = [(0, 0), (0, 0), (p[4], p[5]), (p[2], p[3]), (p[0], p[1])]
    kw = {"constant_values": attrs.get("value", 0.0)} \
        if mode == "constant" else {}
    mode_map = {"constant": "constant", "reflect": "reflect",
                "replicate": "edge", "circular": "wrap"}
    return {"Out": jnp.pad(x, widths, mode=mode_map[mode], **kw)}


@register_op("pad_constant_like", inputs=["X!", "Y"], outputs=["Out"])
def pad_constant_like(ins, attrs, ctx):
    x, y = ins["X"], ins["Y"]
    widths = [(0, xi - yi) for xi, yi in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, widths,
                           constant_values=attrs.get("pad_value", 0.0))}


@register_op("cast", inputs=["X"], outputs=["Out"])
def cast(ins, attrs, ctx):
    from ...core.selected_rows import SelectedRows
    x = ins["X"]
    if isinstance(x, SelectedRows):
        # fp16_allreduce meta-optimizer casts gradients; cast the row
        # values, keep the int32 row indices
        return {"Out": SelectedRows(
            x.rows, x.values.astype(np_dtype(attrs["out_dtype"])),
            x.height)}
    return {"Out": x.astype(np_dtype(attrs["out_dtype"]))}


@register_op("assign", inputs=["X"], outputs=["Out"])
def assign(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("share_data", inputs=["X"], outputs=["Out"])
def share_data(ins, attrs, ctx):
    return {"Out": ins["X"]}


@register_op("assign_value", inputs=[], outputs=["Out"], grad=None)
def assign_value(ins, attrs, ctx):
    values = attrs.get("fp32_values") or attrs.get("int32_values") \
        or attrs.get("int64_values") or attrs.get("values")
    return {"Out": jnp.asarray(values, np_dtype(attrs.get("dtype", "float32")))
            .reshape(tuple(attrs["shape"]))}


@register_op("fill_constant", inputs=["ShapeTensor?!", "ValueTensor?!"],
             outputs=["Out"], grad=None)
def fill_constant(ins, attrs, ctx):
    shape = tuple(attrs.get("shape", []))
    dt = np_dtype(attrs.get("dtype", "float32"))
    value = attrs.get("value", 0.0)
    if isinstance(value, str):
        value = float(value)
    if ins.get("ValueTensor") is not None:
        value = ins["ValueTensor"].reshape(())
    return {"Out": jnp.full(shape, value, dt)}


@register_op("fill_constant_batch_size_like", inputs=["Input!"],
             outputs=["Out"], grad=None)
def fill_constant_batch_size_like(ins, attrs, ctx):
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ins["Input"].shape[in_idx]
    return {"Out": jnp.full(tuple(shape), attrs.get("value", 0.0),
                            np_dtype(attrs.get("dtype", "float32")))}


@register_op("fill_any_like", inputs=["X!"], outputs=["Out"], grad=None)
def fill_any_like(ins, attrs, ctx):
    x = ins["X"]
    dt = attrs.get("dtype", None)
    dt = x.dtype if dt in (None, -1) else np_dtype(dt)
    return {"Out": jnp.full(x.shape, attrs.get("value", 0.0), dt)}


@register_op("fill_zeros_like", inputs=["X!"], outputs=["Out"], grad=None)
def fill_zeros_like(ins, attrs, ctx):
    return {"Out": jnp.zeros_like(ins["X"])}


@register_op("eye", inputs=[], outputs=["Out"], grad=None)
def eye(ins, attrs, ctx):
    rows = attrs["num_rows"]
    cols = attrs.get("num_columns", -1)
    cols = rows if cols in (None, -1) else cols
    return {"Out": jnp.eye(rows, cols,
                           dtype=np_dtype(attrs.get("dtype", "float32")))}


@register_op("linspace", inputs=["Start?!", "Stop?!", "Num?!"],
             outputs=["Out"], grad=None)
def linspace(ins, attrs, ctx):
    if ins.get("Num") is not None:
        n = int(np.asarray(ins["Num"]).item())
        s, e = ins["Start"].reshape(()), ins["Stop"].reshape(())
        return {"Out": jnp.linspace(s, e, n)}
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.linspace(attrs["start"], attrs["stop"],
                                int(attrs["num"]), dtype=dt)}


@register_op("range", inputs=["Start?!", "End?!", "Step?!"], outputs=["Out"],
             grad=None)
def range_op(ins, attrs, ctx):
    # bounds come as input tensors (fluid style) or attrs (2.0 arange);
    # either way they must be host constants (static shapes on TPU)
    if ins.get("Start") is not None:
        s = np.asarray(ins["Start"]).item()
        e = np.asarray(ins["End"]).item()
        st = np.asarray(ins["Step"]).item()
        dt = ins["Start"].dtype
    else:
        s, e, st = attrs["start"], attrs["end"], attrs["step"]
        dt = np_dtype(attrs.get("dtype", "int64"))
    return {"Out": jnp.arange(s, e, st, dtype=dt)}


@register_op("one_hot", inputs=["X!"], outputs=["Out"], grad=None)
def one_hot(ins, attrs, ctx):
    x = ins["X"]
    depth = attrs["depth"]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = jnp.squeeze(x, -1)
    return {"Out": jax.nn.one_hot(x.astype(jnp.int32), depth,
                                  dtype=jnp.float32)}


@register_op("one_hot_v2", inputs=["X!"], outputs=["Out"], grad=None)
def one_hot_v2(ins, attrs, ctx):
    return {"Out": jax.nn.one_hot(ins["X"].astype(jnp.int32), attrs["depth"],
                                  dtype=jnp.float32)}


@register_op("arg_max", inputs=["X!"], outputs=["Out"], grad=None)
def arg_max(ins, attrs, ctx):
    x = ins["X"].reshape(-1) if attrs.get("flatten") else ins["X"]
    axis = attrs.get("axis", -1) if not attrs.get("flatten") else 0
    out = jnp.argmax(x, axis=axis, keepdims=attrs.get("keepdims", False))
    return {"Out": out.astype(np_dtype(attrs.get("dtype", "int64")))}


@register_op("arg_min", inputs=["X!"], outputs=["Out"], grad=None)
def arg_min(ins, attrs, ctx):
    x = ins["X"].reshape(-1) if attrs.get("flatten") else ins["X"]
    axis = attrs.get("axis", -1) if not attrs.get("flatten") else 0
    out = jnp.argmin(x, axis=axis, keepdims=attrs.get("keepdims", False))
    return {"Out": out.astype(np_dtype(attrs.get("dtype", "int64")))}


@register_op("argsort", inputs=["X"], outputs=["Out", "Indices"])
def argsort(ins, attrs, ctx):
    x = ins["X"]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": out, "Indices": idx.astype(jnp.int64)}


@register_op("top_k", inputs=["X", "K?!"], outputs=["Out", "Indices"])
def top_k(ins, attrs, ctx):
    x = ins["X"]
    k = attrs.get("k", 1)
    if ins.get("K") is not None:
        k = int(np.asarray(ins["K"]).item())
    vals, idx = jax.lax.top_k(x, k)
    return {"Out": vals, "Indices": idx.astype(jnp.int64)}


@register_op("top_k_v2", inputs=["X", "K?!"], outputs=["Out", "Indices"])
def top_k_v2(ins, attrs, ctx):
    x = ins["X"]
    k = attrs.get("k", 1)
    axis = attrs.get("axis", -1)
    largest = attrs.get("largest", True)
    x_ = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(x_ if largest else -x_, k)
    if not largest:
        vals = -vals
    return {"Out": jnp.moveaxis(vals, -1, axis),
            "Indices": jnp.moveaxis(idx, -1, axis).astype(jnp.int64)}


@register_op("unique", inputs=["X!"],
             outputs=["Out", "Indices", "Index", "Counts"], grad=None)
def unique(ins, attrs, ctx):
    # data-dependent shape: eager-mode only.  v2 slots: Indices = first
    # occurrence positions, Index = inverse map, Counts = multiplicities.
    out, first, inv, cnt = jnp.unique(
        ins["X"], return_index=True, return_inverse=True, return_counts=True)
    dt = np_dtype(attrs.get("dtype", "int64"))
    return {"Out": out, "Indices": first.astype(dt),
            "Index": inv.astype(dt), "Counts": cnt.astype(dt)}


@register_op("unique_with_counts", inputs=["X!"],
             outputs=["Out", "Index", "Count"], grad=None)
def unique_with_counts(ins, attrs, ctx):
    # fluid v1 semantics: uniques in FIRST-OCCURRENCE order
    # (unique_with_counts_op.h hash-map insertion), unlike the sorted
    # paddle-2.x `unique` above
    out, first, inv, cnt = jnp.unique(ins["X"], return_index=True,
                                      return_inverse=True,
                                      return_counts=True)
    order = jnp.argsort(first)
    rank = jnp.argsort(order)
    dt = np_dtype(attrs.get("dtype", "int64"))
    return {"Out": out[order], "Index": rank[inv].astype(dt),
            "Count": cnt[order].astype(dt)}


@register_op("shape", inputs=["Input!"], outputs=["Out"], grad=None)
def shape(ins, attrs, ctx):
    return {"Out": jnp.asarray(ins["Input"].shape, jnp.int32)}


@register_op("size", inputs=["Input!"], outputs=["Out"], grad=None)
def size(ins, attrs, ctx):
    return {"Out": jnp.asarray(ins["Input"].size, jnp.int64)}


@register_op("is_empty", inputs=["X!"], outputs=["Out"], grad=None)
def is_empty(ins, attrs, ctx):
    return {"Out": jnp.asarray(ins["X"].size == 0)}


@register_op("diag", inputs=["Diagonal"], outputs=["Out"])
def diag(ins, attrs, ctx):
    return {"Out": jnp.diag(ins["Diagonal"])}


@register_op("diag_v2", inputs=["X"], outputs=["Out"])
def diag_v2(ins, attrs, ctx):
    x = ins["X"]
    offset = attrs.get("offset", 0)
    out = jnp.diag(x, offset)
    pv = attrs.get("padding_value", 0.0)
    if x.ndim == 1 and pv != 0:
        n = x.shape[0] + abs(offset)
        base = jnp.full((n, n), pv, x.dtype)
        mask = jnp.eye(n, k=offset, dtype=bool)
        out = jnp.where(mask, jnp.diag(x, offset), base)
    return {"Out": out}


@register_op("diag_embed", inputs=["Input"], outputs=["Out"])
def diag_embed(ins, attrs, ctx):
    x = ins["Input"]
    offset = attrs.get("offset", 0)
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    if offset >= 0:
        out = out.at[..., idx, idx + offset].set(x)
    else:
        out = out.at[..., idx - offset, idx].set(x)
    return {"Out": out}


@register_op("meshgrid", inputs=["X*"], outputs=["Out*"])
def meshgrid(ins, attrs, ctx):
    return {"Out": list(jnp.meshgrid(*ins["X"], indexing="ij"))}


@register_op("multiplex", inputs=["Ids!", "X*"], outputs=["Out"])
def multiplex(ins, attrs, ctx):
    ids = ins["Ids"].astype(jnp.int32).ravel()
    stacked = jnp.stack(ins["X"], axis=0)  # [n, batch, ...]
    rows = jnp.arange(stacked.shape[1])
    return {"Out": stacked[ids, rows]}


@register_op("empty", inputs=[], outputs=["Out"], grad=None)
def empty(ins, attrs, ctx):
    return {"Out": jnp.zeros(tuple(attrs["shape"]),
                             np_dtype(attrs.get("dtype", "float32")))}


@register_op("shard_index", inputs=["X!"], outputs=["Out"], grad=None)
def shard_index(ins, attrs, ctx):
    x = ins["X"]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    size = (index_num + nshards - 1) // nshards
    in_shard = (x // size) == shard_id
    return {"Out": jnp.where(in_shard, x % size, ignore_value)}


@register_op("coalesce_tensor", inputs=["Input*"],
             outputs=["Output*", "FusedOutput"], grad=None)
def coalesce_tensor(ins, attrs, ctx):
    # grad-fusion buffer op; XLA already fuses collectives, so this is
    # semantically a flatten+concat view (details/coalesce_grad_tensor_pass)
    xs = ins["Input"]
    flat = jnp.concatenate([x.ravel() for x in xs])
    return {"Output": list(xs), "FusedOutput": flat}

"""Op-coverage stragglers (VERDICT r3 missing #5) — TPU redesigns of
/root/reference/paddle/fluid/operators/{crop_op.h, crop_tensor_op.h,
optimizers/proximal_adagrad_op.h, optimizers/proximal_gd_op.h,
modified_huber_loss_op.h, teacher_student_sigmoid_loss_op.h,
positive_negative_pair_op.h, sequence_ops/sequence_scatter_op.cc,
sequence_ops/sequence_topk_avg_pooling_op.h, fsp_op.h, inplace_abn_op.cc,
conv_shift_op.cc, attention_lstm_op.cc, match_matrix_tensor_op.cc,
var_conv_2d_op.cc, tree_conv_op.h + math/tree2col.cc,
similarity_focus_op.h}.

Padded-LoD contract as everywhere else in this kernel library: ragged
reference inputs become fixed-shape tensors with explicit length/mask
companions.  Sequential selection loops use fixed-trip-count fori_loops;
only tree_conv's data-dependent tree traversal runs host-side
(pure_callback — the reference kernel is CPU-only there too).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import register_op


# ---------------------------------------------------------------------------
# crop / crop_tensor
# ---------------------------------------------------------------------------

def _crop_common(x, offsets, shape):
    idx = tuple(jnp.asarray(o, jnp.int32).reshape(()) for o in offsets)
    return jax.lax.dynamic_slice(x, idx, tuple(int(s) for s in shape))


@register_op("crop", inputs=["X", "Y?!", "Offsets?!"], outputs=["Out"])
def crop(ins, attrs, ctx):
    """crop_op.h — slice a `shape`-sized window out of X at `offsets`
    (attr or tensor input); Y only contributes its shape."""
    x = jnp.asarray(ins["X"])
    y = ins.get("Y")
    shape = attrs.get("shape") or list(jnp.asarray(y).shape)
    off_in = ins.get("Offsets")
    if off_in is not None:
        offsets = list(jnp.asarray(off_in).reshape(-1))
    else:
        offsets = list(attrs.get("offsets", [0] * x.ndim))
    return {"Out": _crop_common(x, offsets, shape)}


@register_op("crop_tensor", inputs=["X", "Shape?!", "Offsets?!"],
             outputs=["Out"])
def crop_tensor(ins, attrs, ctx):
    """crop_tensor_op.h — crop with Shape/Offsets as attrs or tensors;
    shape entries of -1 mean 'to the end' (resolved statically, XLA needs
    static output shapes, so a TENSOR Shape input must be trace-time
    concrete)."""
    x = jnp.asarray(ins["X"])
    shp_in = ins.get("Shape")
    if shp_in is not None:
        shape = [int(v) for v in np.asarray(shp_in).reshape(-1)]
    else:
        shape = list(attrs.get("shape", list(x.shape)))
    off_in = ins.get("Offsets")
    if off_in is not None:
        offsets = list(jnp.asarray(off_in).reshape(-1))
    else:
        offsets = list(attrs.get("offsets", [0] * x.ndim))
    resolved = []
    for i, s in enumerate(shape):
        if s == -1:
            off = offsets[i]
            if isinstance(off, jax.core.Tracer):
                raise ValueError(
                    "crop_tensor: shape[-1] ('to the end') needs a "
                    "trace-time-constant offset on that axis — XLA "
                    "output shapes are static; pass a concrete offset "
                    "or an explicit size")
            resolved.append(x.shape[i] - int(np.asarray(off)))
        else:
            resolved.append(s)
    return {"Out": _crop_common(x, offsets, resolved)}


# ---------------------------------------------------------------------------
# proximal optimizers (FTRL-proximal family)
# ---------------------------------------------------------------------------

@register_op("proximal_gd",
             inputs=["Param!", "Grad!", "LearningRate!"],
             outputs=["ParamOut"], grad=None, side_effect=True)
def proximal_gd(ins, attrs, ctx):
    """proximal_gd_op.h — prox = p - lr*g; sign(prox) *
    max(|prox| - lr*l1, 0) / (1 + lr*l2)."""
    p = jnp.asarray(ins["Param"])
    g = jnp.asarray(ins["Grad"])
    lr = jnp.asarray(ins["LearningRate"]).reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    if l1 > 0:
        out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
            / (1.0 + lr * l2)
    else:
        out = prox / (1.0 + lr * l2)
    return {"ParamOut": out}


@register_op("proximal_adagrad",
             inputs=["Param!", "Moment!", "Grad!", "LearningRate!"],
             outputs=["ParamOut", "MomentOut"], grad=None,
             side_effect=True)
def proximal_adagrad(ins, attrs, ctx):
    """proximal_adagrad_op.h — adagrad accumulator + proximal step."""
    p = jnp.asarray(ins["Param"])
    m = jnp.asarray(ins["Moment"])
    g = jnp.asarray(ins["Grad"])
    lr = jnp.asarray(ins["LearningRate"]).reshape(())
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    m_out = m + g * g
    prox = p - lr * g / jnp.sqrt(m_out)
    if l1 > 0:
        out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
            / (1.0 + lr * l2)
    else:
        out = prox / (1.0 + lr * l2)
    return {"ParamOut": out, "MomentOut": m_out}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------

@register_op("modified_huber_loss", inputs=["X", "Y!"],
             outputs=["Out", "IntermediateVal"])
def modified_huber_loss(ins, attrs, ctx):
    """modified_huber_loss_op.h — binary labels {0,1} scaled to ±1;
    v = x*(2y-1); loss = -4v (v<-1), (1-v)^2 (-1<=v<1), 0 (v>=1)."""
    x = jnp.asarray(ins["X"]).reshape(-1)
    y = jnp.asarray(ins["Y"]).reshape(-1).astype(x.dtype)
    v = x * (2.0 * y - 1.0)
    loss = jnp.where(v < -1.0, -4.0 * v,
                     jnp.where(v < 1.0, (1.0 - v) ** 2, 0.0))
    shp = jnp.asarray(ins["X"]).shape
    return {"Out": loss.reshape(shp), "IntermediateVal": v.reshape(shp)}


@register_op("teacher_student_sigmoid_loss", inputs=["X", "Label!"],
             outputs=["Y"])
def teacher_student_sigmoid_loss(ins, attrs, ctx):
    """teacher_student_sigmoid_loss_op.h — distillation loss over the
    encoded label: label<-1 -> bce(x,0); label<0 -> bce(x,1);
    label in [0,1) -> bce(x,0)+bce_soft(x,label);
    label>=1 -> bce(x,1)+bce_soft(x,label-1).
    (soft_max_up/lower_bound attrs accepted; the reference applies them
    as gradient clamps — auto-vjp of this forward matches away from the
    clamp region.)"""
    x = jnp.asarray(ins["X"]).reshape(-1)
    lbl = jnp.asarray(ins["Label"]).reshape(-1).astype(x.dtype)

    def bce(z):
        # max(x,0) - x*z + log1p(exp(-|x|))
        return jnp.maximum(x, 0.0) - x * z + jnp.log1p(jnp.exp(-jnp.abs(x)))

    y = jnp.where(
        lbl < -1.0, bce(0.0),
        jnp.where(lbl < 0.0, bce(1.0),
                  jnp.where(lbl < 1.0, bce(0.0) + bce(lbl),
                            bce(1.0) + bce(lbl - 1.0))))
    return {"Y": y.reshape(jnp.asarray(ins["X"]).shape)}


# ---------------------------------------------------------------------------
# positive_negative_pair (LTR metric)
# ---------------------------------------------------------------------------

@register_op("positive_negative_pair",
             inputs=["Score!", "Label!", "QueryID!", "Weight?!",
                     "AccumulatePositivePair?!", "AccumulateNegativePair?!",
                     "AccumulateNeutralPair?!"],
             outputs=["PositivePair", "NegativePair", "NeutralPair"],
             grad=None)
def positive_negative_pair(ins, attrs, ctx):
    """positive_negative_pair_op.h — within each query, count ordered /
    misordered / tied score pairs among differently-labeled docs,
    weighted by the mean pair weight.  O(B^2) dense pairwise masks (the
    metric batch is small); accumulation inputs chain across batches."""
    score = jnp.asarray(ins["Score"])
    label = jnp.asarray(ins["Label"]).reshape(-1)
    query = jnp.asarray(ins["QueryID"]).reshape(-1)
    col = int(attrs.get("column", -1))
    if score.ndim == 2:
        col = col + score.shape[1] if col < 0 else col
        s = score[:, col]
    else:
        s = score.reshape(-1)
    w_in = ins.get("Weight")
    w = (jnp.asarray(w_in).reshape(-1).astype(s.dtype)
         if w_in is not None else jnp.ones_like(s))
    B = s.shape[0]
    same_q = query[:, None] == query[None, :]
    diff_l = label[:, None] != label[None, :]
    upper = jnp.triu(jnp.ones((B, B), bool), k=1)
    mask = same_q & diff_l & upper
    pw = (w[:, None] + w[None, :]) * 0.5
    ds = s[:, None] - s[None, :]
    dl = (label[:, None] - label[None, :]).astype(s.dtype)
    tied = ds == 0
    correct = (ds * dl) > 0
    pos = jnp.sum(jnp.where(mask & ~tied & correct, pw, 0.0))
    neg = jnp.sum(jnp.where(mask & ~tied & ~correct, pw, 0.0))
    neu = jnp.sum(jnp.where(mask & tied, pw, 0.0))
    acc_p = ins.get("AccumulatePositivePair")
    acc_n = ins.get("AccumulateNegativePair")
    acc_u = ins.get("AccumulateNeutralPair")
    if acc_p is not None:
        pos = pos + jnp.asarray(acc_p).reshape(())
    if acc_n is not None:
        neg = neg + jnp.asarray(acc_n).reshape(())
    if acc_u is not None:
        neu = neu + jnp.asarray(acc_u).reshape(())
    return {"PositivePair": pos.reshape(1), "NegativePair": neg.reshape(1),
            "NeutralPair": neu.reshape(1)}


# ---------------------------------------------------------------------------
# sequence_scatter / sequence_topk_avg_pooling
# ---------------------------------------------------------------------------

@register_op("sequence_scatter", inputs=["X", "Ids!", "Updates"],
             outputs=["Out"])
def sequence_scatter(ins, attrs, ctx):
    """sequence_scatter_op.cc — per batch row b, out[b, ids[b, s]] +=
    updates[b, s].  Padded redesign of the LoD rows: Ids/Updates
    [B, S] with id -1 padding."""
    x = jnp.asarray(ins["X"])
    ids = jnp.asarray(ins["Ids"])
    upd = jnp.asarray(ins["Updates"]).astype(x.dtype)
    if ids.ndim == 3:
        ids = ids[..., 0]
    if upd.ndim == 3:
        upd = upd[..., 0]
    valid = ids >= 0
    B = x.shape[0]
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], ids.shape)
    out = x.at[b_idx.reshape(-1),
               jnp.clip(ids, 0, x.shape[1] - 1).reshape(-1)].add(
        jnp.where(valid, upd, 0.0).reshape(-1))
    return {"Out": out}


@register_op("sequence_topk_avg_pooling",
             inputs=["X", "ROW!", "COLUMN!"],
             outputs=["Out", "pos?"])
def sequence_topk_avg_pooling(ins, attrs, ctx):
    """sequence_topk_avg_pooling_op.h — X [B, C, R, L] score maps (ROW/
    COLUMN carry the per-sequence lengths [B]); per (b, c, r): take the
    top-k column scores and emit the running average of the top-1..k
    prefix for every k in `topks`.  Out [B, R, C*K]; pos [B, R, C*max_k]
    records the chosen column indices (-1 pad)."""
    x = jnp.asarray(ins["X"])
    row_len = jnp.asarray(ins["ROW"]).reshape(-1)
    col_len = jnp.asarray(ins["COLUMN"]).reshape(-1)
    topks = [int(k) for k in attrs.get("topks", [1])]
    channel_num = int(attrs.get("channel_num", x.shape[1]))
    B, C, R, L = x.shape
    max_k = max(topks)
    kk = min(max_k, L)
    colmask = jnp.arange(L)[None, None, None, :] < \
        col_len[:, None, None, None]
    masked = jnp.where(colmask, x, -jnp.inf)
    top_v, top_i = jax.lax.top_k(masked, kk)        # [B, C, R, kk]
    live = jnp.isfinite(top_v)
    vals = jnp.where(live, top_v, 0.0)
    prefix = jnp.cumsum(vals, axis=-1)
    counts = jnp.cumsum(live.astype(x.dtype), axis=-1)
    outs = []
    for k in topks:
        k_eff = min(k, kk) - 1
        # reference divides by k (fixed), zero when no live entries
        avg = prefix[..., k_eff] / float(k)
        outs.append(avg)
    out = jnp.stack(outs, axis=-1)                  # [B, C, R, K]
    rowmask = jnp.arange(R)[None, None, :] < row_len[:, None, None]
    out = jnp.where(rowmask[..., None], out, 0.0)
    out = jnp.swapaxes(out, 1, 2).reshape(B, R, C * len(topks))
    pos = jnp.where(live, top_i, -1).astype(jnp.int32)
    pos = jnp.swapaxes(pos, 1, 2).reshape(B, R, C * kk)
    return {"Out": out, "pos": pos}


# ---------------------------------------------------------------------------
# fsp / inplace_abn / conv_shift / similarity_focus
# ---------------------------------------------------------------------------

@register_op("fsp", inputs=["X", "Y"], outputs=["Out"])
def fsp(ins, attrs, ctx):
    """fsp_op.h — flow-of-solution-procedure matrix for distillation:
    Out[b] = X_b(reshaped [Cx, HW]) @ Y_b([HW, Cy]) / HW."""
    x = jnp.asarray(ins["X"])
    y = jnp.asarray(ins["Y"])
    hw = x.shape[2] * x.shape[3]
    return {"Out": jnp.einsum("bchw,bdhw->bcd", x, y) / hw}


@register_op("inplace_abn",
             inputs=["X", "Scale", "Bias", "Mean!", "Variance!"],
             outputs=["Y", "MeanOut?", "VarianceOut?", "SavedMean?",
                      "SavedVariance?"])
def inplace_abn(ins, attrs, ctx):
    """inplace_abn_op.cc — batch norm + activation fused with in-place
    buffer reuse.  In-place-ness is the POINT on CUDA (activation
    overwrites the BN buffer to halve activation memory); under XLA the
    compiler owns buffers, so this is exactly batch_norm followed by the
    fused activation — same numerics, the memory win falls out of XLA's
    liveness analysis."""
    from . import nn as nn_kernels
    bn = nn_kernels.batch_norm(ins, attrs, ctx)
    act = attrs.get("activation", "identity")
    alpha = attrs.get("alpha", 0.01)
    y = bn["Y"]
    if act == "leaky_relu":
        y = jnp.where(y >= 0, y, alpha * y)
    elif act == "elu":
        y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1.0))
    elif act not in ("identity", ""):
        raise NotImplementedError(f"inplace_abn activation {act!r}")
    bn["Y"] = y
    return bn


@register_op("conv_shift", inputs=["X", "Y"], outputs=["Out"])
def conv_shift(ins, attrs, ctx):
    """conv_shift_op.cc — circular correlation (NTM addressing):
    out[b, i] = sum_j x[b, (i + j - (Wy-1)/2) mod Wx] * y[b, j]."""
    x = jnp.asarray(ins["X"])
    y = jnp.asarray(ins["Y"])
    Wx, Wy = x.shape[1], y.shape[1]
    half = (Wy - 1) // 2
    i = jnp.arange(Wx)[:, None]
    j = jnp.arange(Wy)[None, :]
    idx = (i + j - half + Wx) % Wx                  # [Wx, Wy]
    return {"Out": jnp.einsum("bij,bj->bi", x[:, idx], y)}


@register_op("similarity_focus", inputs=["X!"], outputs=["Out"],
             grad=None)
def similarity_focus(ins, attrs, ctx):
    """similarity_focus_op.h — for each chosen slice along `axis`,
    greedily pick value-descending cells whose row AND column are both
    unused (bipartite marking), then light those positions across the
    whole axis.  Fixed-trip fori_loop over the sorted cells, same
    pattern as greedy NMS."""
    x = jnp.asarray(ins["X"])
    axis = int(attrs["axis"])
    indexes = [int(i) for i in attrs["indexes"]]
    assert x.ndim == 4 and axis in (1, 2, 3)
    # move `axis` to dim 1 so the slice is always [d2, d3]
    perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
    xt = jnp.transpose(x, perm)
    B, A, D2, D3 = xt.shape
    n_pick = min(D2, D3)

    def one_slice(sl):
        flat = sl.reshape(-1)
        order = jnp.argsort(-flat, stable=True)

        def body(t, carry):
            used2, used3, sel = carry
            cell = order[t]
            r, c = cell // D3, cell % D3
            ok = (~used2[r]) & (~used3[c])
            used2 = used2.at[r].set(used2[r] | ok)
            used3 = used3.at[c].set(used3[c] | ok)
            sel = sel.at[r, c].set(sel[r, c] | ok)
            return used2, used3, sel

        _, _, sel = jax.lax.fori_loop(
            0, D2 * D3, body,
            (jnp.zeros((D2,), bool), jnp.zeros((D3,), bool),
             jnp.zeros((D2, D3), bool)))
        return sel

    mark = jnp.zeros((B, D2, D3), bool)
    for index in indexes:
        mark = mark | jax.vmap(one_slice)(xt[:, index])
    out_t = jnp.broadcast_to(mark[:, None], (B, A, D2, D3)) \
        .astype(x.dtype)
    inv = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 2, 3, 1)}[axis]
    return {"Out": jnp.transpose(out_t, inv)}


# ---------------------------------------------------------------------------
# attention_lstm (attention_lstm_op.cc)
# ---------------------------------------------------------------------------

@register_op("attention_lstm",
             inputs=["X", "C0", "H0?", "AttentionWeight",
                     "AttentionBias?", "AttentionScalar?",
                     "AttentionScalarBias?", "LSTMWeight", "LSTMBias",
                     "SeqLen?!"],
             outputs=["Hidden", "Cell", "AttentionedX?",
                      "AttentionFCOut?", "LSTMX?", "LSTMOUT?"])
def attention_lstm(ins, attrs, ctx):
    """attention_lstm_op.cc — at every step, score each time position by
    relu(x_t.w_x + c_prev.w_c [+ b]) (optionally rescaled + relu'd by
    AttentionScalar), softmax over the sequence, pool x by those weights,
    then one LSTM step on the pooled vector.  Gate layout (f, i, o, c~),
    LSTMWeight [(D+M), 4D] with the HIDDEN rows first.  Padded redesign:
    X [B, T, M] with optional SeqLen [B] masking the softmax."""
    x = jnp.asarray(ins["X"])                   # [B, T, M]
    c0 = jnp.asarray(ins["C0"])                 # [B, D]
    h0 = ins.get("H0")
    aw = jnp.asarray(ins["AttentionWeight"]).reshape(-1)   # [M+D]
    ab = ins.get("AttentionBias")
    a_scalar = ins.get("AttentionScalar")
    a_sbias = ins.get("AttentionScalarBias")
    lw = jnp.asarray(ins["LSTMWeight"])         # [D+M, 4D]
    lb = jnp.asarray(ins["LSTMBias"]).reshape(-1)
    seq_len = ins.get("SeqLen")
    B, T, M = x.shape
    D = c0.shape[1]
    _acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
             "relu": jax.nn.relu, "identity": lambda v: v}
    act_gate = _acts[attrs.get("gate_activation", "sigmoid")]
    act_cell = _acts[attrs.get("cell_activation", "tanh")]
    act_cand = _acts[attrs.get("candidate_activation", "tanh")]

    atten_x = x @ aw[:M]                        # [B, T]
    if ab is not None:
        atten_x = atten_x + jnp.asarray(ab).reshape(())
    mask = (jnp.arange(T)[None, :] <
            (jnp.asarray(seq_len).reshape(-1, 1) if seq_len is not None
             else jnp.full((B, 1), T)))

    h_init = (jnp.zeros((B, D), x.dtype) if h0 is None
              else jnp.asarray(h0))

    def step(carry, _):
        h, c = carry
        score = jax.nn.relu(atten_x + (c @ aw[M:])[:, None])   # [B, T]
        if a_scalar is not None:
            score = score * jnp.asarray(a_scalar).reshape(())
            if a_sbias is not None:
                score = jax.nn.relu(
                    score + jnp.asarray(a_sbias).reshape(()))
        score = jnp.where(mask, score, -jnp.inf)
        attn = jax.nn.softmax(score, axis=-1)
        pooled = jnp.einsum("bt,btm->bm", attn, x)             # [B, M]
        gates = pooled @ lw[D:] + h @ lw[:D] + lb              # [B, 4D]
        f, i, o, cand = (gates[:, :D], gates[:, D:2 * D],
                         gates[:, 2 * D:3 * D], gates[:, 3 * D:])
        c_new = act_gate(f) * c + act_gate(i) * act_cand(cand)
        h_new = act_gate(o) * act_cell(c_new)
        return (h_new, c_new), (h_new, c_new)

    (_, _), (hs, cs) = jax.lax.scan(step, (h_init, c0), None, length=T)
    hidden = jnp.swapaxes(hs, 0, 1)
    cell = jnp.swapaxes(cs, 0, 1)
    return {"Hidden": hidden, "Cell": cell, "AttentionedX": atten_x}


# ---------------------------------------------------------------------------
# match_matrix_tensor / var_conv_2d (text-matching CTR family)
# ---------------------------------------------------------------------------

@register_op("match_matrix_tensor",
             inputs=["X", "Y", "W", "XLen?!", "YLen?!"],
             outputs=["Out", "Tmp?"])
def match_matrix_tensor(ins, attrs, ctx):
    """match_matrix_tensor_op.cc — bilinear match tensor between two
    padded token-feature sequences: Out[b, t, i, j] =
    (x_i . W_t) . y_j.  X [B, Lx, D], Y [B, Ly, D], W [D, dim_t, D];
    optional lengths mask the padding."""
    x = jnp.asarray(ins["X"])
    y = jnp.asarray(ins["Y"])
    w = jnp.asarray(ins["W"])
    dim_t = int(attrs.get("dim_t", w.shape[1]))
    if w.ndim == 2:                 # packed [D, dim_t*D]
        w = w.reshape(x.shape[-1], dim_t, y.shape[-1])
    tmp = jnp.einsum("bld,dte->blte", x, w)
    out = jnp.einsum("blte,bre->btlr", tmp, y)
    x_len = ins.get("XLen")
    y_len = ins.get("YLen")
    if x_len is not None:
        m = jnp.arange(x.shape[1])[None, :] < \
            jnp.asarray(x_len).reshape(-1, 1)
        out = out * m[:, None, :, None]
    if y_len is not None:
        m = jnp.arange(y.shape[1])[None, :] < \
            jnp.asarray(y_len).reshape(-1, 1)
        out = out * m[:, None, None, :]
    return {"Out": out, "Tmp": tmp}


@register_op("var_conv_2d",
             inputs=["X", "W", "ROW?!", "COLUMN?!"],
             outputs=["Out", "Col?"])
def var_conv_2d(ins, attrs, ctx):
    """var_conv_2d_op.cc — conv2d over per-sequence variable-size score
    maps.  Padded redesign: X [B, C_in, H, W] zero-padded with optional
    ROW/COLUMN lengths; the conv is one lax.conv over the padded batch
    (XLA-batched, no per-sequence loop) and padding cells are re-zeroed
    after, which matches the reference because zero inputs already
    contribute nothing inside the valid region."""
    x = jnp.asarray(ins["X"])
    w = jnp.asarray(ins["W"])
    kh = int(attrs.get("kernel_h", 3))
    kw = int(attrs.get("kernel_w", 3))
    sh = int(attrs.get("stride_h", 1))
    sw = int(attrs.get("stride_w", 1))
    out_ch = int(attrs.get("output_channel", w.shape[0]))
    in_ch = x.shape[1]
    filt = w.reshape(out_ch, in_ch, kh, kw)
    out = jax.lax.conv_general_dilated(
        x, filt, window_strides=(sh, sw),
        padding=((kh // 2, kh // 2), (kw // 2, kw // 2)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    row = ins.get("ROW")
    col = ins.get("COLUMN")
    if row is not None:
        oh = out.shape[2]
        lim = (jnp.asarray(row).reshape(-1) + sh - 1) // sh
        out = out * (jnp.arange(oh)[None, None, :, None] <
                     lim[:, None, None, None])
    if col is not None:
        ow = out.shape[3]
        lim = (jnp.asarray(col).reshape(-1) + sw - 1) // sw
        out = out * (jnp.arange(ow)[None, None, None, :] <
                     lim[:, None, None, None])
    return {"Out": out}


# ---------------------------------------------------------------------------
# tree_conv (tree_conv_op.h + math/tree2col.cc) — TBCNN
# ---------------------------------------------------------------------------

def _tree2col_np(edges, n_nodes, max_depth):
    """math/tree2col.cc — per root node, DFS-collect the subtree down to
    max_depth with continuous position weights (eta_t top, eta_l left,
    eta_r right).  Returns [N, N, 3] weights: w[root, node, :]."""
    tr = [[] for _ in range(n_nodes + 1)]
    for u, v in edges:
        u, v = int(u), int(v)
        if u == 0 or v == 0:
            break
        tr[u].append(v)
    out = np.zeros((n_nodes, n_nodes, 3), np.float32)
    fd = float(max_depth)
    for root in range(1, n_nodes + 1):
        # node entries: (node, index(1-based), pclen, depth)
        patch = [(root, 1, 1, 0)]
        stack = [(root, 1, 1, 0)]
        visited = {root}
        while stack:
            node, _, _, depth = stack[-1]
            advanced = False
            sz = len(tr[node])
            for i, v in enumerate(tr[node]):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, sz, depth + 1))
                    patch.append((v, i + 1, sz, depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        for node, index, pclen, depth in patch:
            eta_t = (fd - depth) / fd
            tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
            eta_l = (1.0 - eta_t) * tmp
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            out[root - 1, node - 1, 0] += eta_l
            out[root - 1, node - 1, 1] += eta_r
            out[root - 1, node - 1, 2] += eta_t
    return out


@register_op("tree_conv",
             inputs=["NodesVector", "EdgeSet!", "Filter"],
             outputs=["Out"])
def tree_conv(ins, attrs, ctx):
    """tree_conv_op.h — Tree-Based CNN: tree2col gathers each node's
    depth-bounded subtree into (left, right, top) weighted feature sums,
    then contracts with Filter [feature, 3, out_size, num_filters].
    Tree traversal depends on edge VALUES, so the [N, N, 3] gather
    weights come from a host callback (the reference kernel is CPU-only
    too); the feature contraction itself stays one on-device einsum."""
    feats = jnp.asarray(ins["NodesVector"])     # [B, N, F]
    edges = jnp.asarray(ins["EdgeSet"])         # [B, E, 2] int32
    filt = jnp.asarray(ins["Filter"])           # [F, 3, out, nf]
    max_depth = int(attrs.get("max_depth", 2))
    B, N, F = feats.shape

    def host(e):
        e = np.asarray(e)
        return np.stack([_tree2col_np(e[b].reshape(-1, 2), N, max_depth)
                         for b in range(e.shape[0])])

    wgt = jax.pure_callback(
        host, jax.ShapeDtypeStruct((B, N, N, 3), jnp.float32), edges)
    # patch[b, root, k, f] = sum_node wgt[b,root,node,k] * feats[b,node,f]
    patch = jnp.einsum("brnk,bnf->brkf", wgt, feats.astype(jnp.float32))
    out = jnp.einsum("brkf,fkon->bron", patch, filt.astype(jnp.float32))
    return {"Out": out.astype(feats.dtype)}

"""CTC and linear-chain CRF ops.

Reference: /root/reference/paddle/fluid/operators/warpctc_op.cc (wraps the
warp-ctc CUDA/CPU library), ctc_align_op.cc, linear_chain_crf_op.cc (:23
the forward algorithm comments), crf_decoding_op.cc (Viterbi).

TPU redesign: the reference binds hand-written CUDA (warp-ctc) because
cuDNN-era frameworks couldn't differentiate through a dynamic-programming
recursion.  Under JAX the log-semiring recursions are plain `lax.scan`s —
the CTC/CRF gradients fall out of `jax.vjp` for free (no bespoke backward
kernels), and padded batches replace LoD with explicit length tensors.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op

_NEG = -1e30


def _logsumexp2(a, b):
    # double-where guard: when both args are -inf-like, the untaken branch
    # must still see finite inputs or jax.vjp poisons the grads with NaN
    m = jnp.maximum(a, b)
    ok = m > _NEG / 2
    m_safe = jnp.where(ok, m, 0.0)
    a_s = jnp.where(ok, a - m_safe, 0.0)
    b_s = jnp.where(ok, b - m_safe, 0.0)
    return jnp.where(ok, m_safe + jnp.log(jnp.exp(a_s) + jnp.exp(b_s)),
                     _NEG)


def _ctc_loss_one(logp, labels, T, L, blank):
    """CTC negative log-likelihood for one sequence.
    logp [Tmax, C] log-softmax; labels [Lmax] int; T, L actual lengths."""
    Lmax = labels.shape[0]
    S = 2 * Lmax + 1
    # extended label sequence: blank l1 blank l2 ... blank
    ext = jnp.full((S,), blank, jnp.int32)
    ext = ext.at[1::2].set(labels.astype(jnp.int32))
    pos = jnp.arange(S)
    live = pos < 2 * L + 1
    # can we skip from s-2? only onto non-blank positions whose label
    # differs from s-2's label
    ext_m2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), ext[:-2]])
    can_skip = (pos % 2 == 1) & (ext != ext_m2)

    alpha0 = jnp.full((S,), _NEG)
    alpha0 = alpha0.at[0].set(logp[0, ext[0]])
    alpha0 = alpha0.at[1].set(jnp.where(L > 0, logp[0, ext[1]], _NEG))

    def step(alpha, lp_t):
        t, lp = lp_t
        prev1 = jnp.concatenate([jnp.full((1,), _NEG), alpha[:-1]])
        prev2 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a = _logsumexp2(alpha, prev1)
        a = jnp.where(can_skip, _logsumexp2(a, prev2), a)
        a = a + lp[ext]
        a = jnp.where(live, a, _NEG)
        # frozen once past this sequence's length
        a = jnp.where(t < T, a, alpha)
        return a, None

    ts = jnp.arange(1, logp.shape[0])
    alpha, _ = jax.lax.scan(step, alpha0, (ts, logp[1:]))
    end1 = alpha[2 * L]
    end2 = jnp.where(L > 0, alpha[2 * L - 1], _NEG)
    return -_logsumexp2(end1, end2)


@register_op("warpctc",
             inputs=["Logits", "Label!", "LogitsLength?!",
                     "LabelLength?!"],
             outputs=["Loss", "WarpCTCGrad?"])
def warpctc(ins, attrs, ctx):
    """warpctc_op.cc parity.  Padded layout: Logits [B, Tmax, C] (or
    [Tmax, B, C] time-major like warp-ctc when LogitsLength is absent is
    NOT supported — lengths are required on TPU), Label [B, Lmax] padded
    with 0/ignored beyond LabelLength.  Loss [B, 1]."""
    logits = ins["Logits"]
    labels = ins["Label"]
    lo_len = ins.get("LogitsLength")
    la_len = ins.get("LabelLength")
    blank = attrs.get("blank", 0)
    norm = attrs.get("norm_by_times", False)
    if logits.ndim != 3:
        raise ValueError("warpctc expects padded [B, Tmax, C] logits")
    B, Tmax, C = logits.shape
    if labels.ndim == 3 and labels.shape[-1] == 1:
        labels = labels[..., 0]
    lo = (lo_len.reshape(-1).astype(jnp.int32) if lo_len is not None
          else jnp.full((B,), Tmax, jnp.int32))
    la = (la_len.reshape(-1).astype(jnp.int32) if la_len is not None
          else jnp.full((B,), labels.shape[1], jnp.int32))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    loss = jax.vmap(_ctc_loss_one, in_axes=(0, 0, 0, 0, None))(
        logp, labels, lo, la, blank)
    if norm:
        loss = loss / jnp.maximum(lo.astype(loss.dtype), 1.0)
    return {"Loss": loss[:, None]}


@register_op("ctc_align", inputs=["Input!", "InputLength?!"],
             outputs=["Output", "OutputLength?"], grad=None)
def ctc_align(ins, attrs, ctx):
    """ctc_align_op.cc — merge repeated tokens then drop blanks.
    Padded [B, T] in, padded [B, T] out (pad value attr) + lengths."""
    x = ins["Input"]
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
    xl = ins.get("InputLength")
    blank = attrs.get("blank", 0)
    pad = attrs.get("padding_value", 0)
    merge = attrs.get("merge_repeated", True)
    B, T = x.shape
    lens = (xl.reshape(-1).astype(jnp.int32) if xl is not None
            else jnp.full((B,), T, jnp.int32))

    def one(row, n):
        prev = jnp.concatenate([jnp.full((1,), -1, row.dtype), row[:-1]])
        keep = (row != blank) & (jnp.arange(T) < n)
        if merge:
            keep &= row != prev
        # stable compaction: target position = cumsum of keeps - 1
        tgt = jnp.cumsum(keep.astype(jnp.int32)) - 1
        out = jnp.full((T,), pad, row.dtype)
        out = out.at[jnp.where(keep, tgt, T)].set(row, mode="drop")
        return out, jnp.sum(keep).astype(jnp.int32)

    out, out_len = jax.vmap(one)(x, lens)
    return {"Output": out, "OutputLength": out_len[:, None]}


# ---------------------------------------------------------------------------
# linear-chain CRF
# ---------------------------------------------------------------------------
def _crf_scores(emission, transition):
    """Split the reference's [C+2, C] transition layout: row 0 = start,
    row 1 = end, rows 2.. = pairwise [C, C]."""
    start, end, trans = transition[0], transition[1], transition[2:]
    return start, end, trans


def _crf_logz_one(emis, start, end, trans, T):
    C = emis.shape[-1]
    a0 = start + emis[0]

    def step(alpha, te):
        t, e = te
        nxt = jax.nn.logsumexp(alpha[:, None] + trans, axis=0) + e
        nxt = jnp.where(t < T, nxt, alpha)
        return nxt, None

    ts = jnp.arange(1, emis.shape[0])
    alpha, _ = jax.lax.scan(step, a0, (ts, emis[1:]))
    return jax.nn.logsumexp(alpha + end)


def _crf_path_score_one(emis, label, start, end, trans, T):
    Tmax = emis.shape[0]
    idx = jnp.arange(Tmax)
    lbl = label.astype(jnp.int32)
    em = jnp.where(idx < T, emis[idx, lbl], 0.0).sum()
    prev = lbl[:-1]
    tr = jnp.where(idx[1:] < T, trans[prev, lbl[1:]], 0.0).sum()
    last = lbl[jnp.maximum(T - 1, 0)]
    return start[lbl[0]] + em + tr + end[last]


@register_op("linear_chain_crf",
             inputs=["Emission", "Transition", "Label!", "Length?!"],
             outputs=["LogLikelihood", "EmissionExps?", "TransitionExps?",
                      "Alpha?"])
def linear_chain_crf(ins, attrs, ctx):
    """linear_chain_crf_op.cc — log-likelihood of the gold path.
    Padded layout: Emission [B, Tmax, C], Label [B, Tmax], Length [B].
    Transition [C+2, C] with start/end rows (reference layout)."""
    emission = ins["Emission"].astype(jnp.float32)
    transition = ins["Transition"].astype(jnp.float32)
    label = ins["Label"]
    if label.ndim == 3 and label.shape[-1] == 1:
        label = label[..., 0]
    length = ins.get("Length")
    B, Tmax, C = emission.shape
    T = (length.reshape(-1).astype(jnp.int32) if length is not None
         else jnp.full((B,), Tmax, jnp.int32))
    start, end, trans = _crf_scores(emission, transition)
    logz = jax.vmap(_crf_logz_one, in_axes=(0, None, None, None, 0))(
        emission, start, end, trans, T)
    gold = jax.vmap(_crf_path_score_one,
                    in_axes=(0, 0, None, None, None, 0))(
        emission, label, start, end, trans, T)
    # reference returns negative log-likelihood as "LogLikelihood"
    return {"LogLikelihood": (logz - gold)[:, None]}


@register_op("crf_decoding",
             inputs=["Emission!", "Transition!", "Label?!", "Length?!"],
             outputs=["ViterbiPath"], grad=None)
def crf_decoding(ins, attrs, ctx):
    """crf_decoding_op.cc — Viterbi decode; with Label given, outputs a
    0/1 correctness mask per step (reference behaviour)."""
    emission = ins["Emission"].astype(jnp.float32)
    transition = ins["Transition"].astype(jnp.float32)
    label = ins.get("Label")
    length = ins.get("Length")
    B, Tmax, C = emission.shape
    T = (length.reshape(-1).astype(jnp.int32) if length is not None
         else jnp.full((B,), Tmax, jnp.int32))
    start, end, trans = _crf_scores(emission, transition)

    def one(emis, Tb):
        a0 = start + emis[0]

        def fwd(alpha, te):
            t, e = te
            cand = alpha[:, None] + trans            # [C, C]
            best = jnp.max(cand, axis=0) + e
            arg = jnp.argmax(cand, axis=0).astype(jnp.int32)
            best = jnp.where(t < Tb, best, alpha)
            arg = jnp.where(t < Tb, arg, jnp.arange(C, dtype=jnp.int32))
            return best, arg

        ts = jnp.arange(1, Tmax)
        alpha, back = jax.lax.scan(fwd, a0, (ts, emis[1:]))
        last = jnp.argmax(alpha + end).astype(jnp.int32)

        def bwd(tok, bk_t):
            t, bk = bk_t
            prev = bk[tok]
            tok_new = jnp.where(t < Tb, prev, tok)
            return tok_new, tok

        tok0, path_rev = jax.lax.scan(bwd, last, (ts[::-1], back[::-1]))
        # path_rev (reversed) = tokens at t=1..Tmax-1; tok0 = token at t=0
        path = jnp.concatenate([tok0[None], path_rev[::-1]])
        return jnp.where(jnp.arange(Tmax) < Tb, path, 0)

    path = jax.vmap(one)(emission, T)
    if label is not None:
        if label.ndim == 3 and label.shape[-1] == 1:
            label = label[..., 0]
        path = (path == label.astype(path.dtype)).astype(jnp.int64)
    return {"ViterbiPath": path}

"""Vision ops: interpolation, roi ops, grid sample, affine ops.
(reference: /root/reference/paddle/fluid/operators/interpolate_op.cc,
 detection/roi_align_op.cc, grid_sampler_op.cc, affine_channel_op.cc,
 affine_grid_op.cc)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


def _interp_size(x, attrs, ins):
    out_h = attrs.get("out_h", -1)
    out_w = attrs.get("out_w", -1)
    scale = attrs.get("scale", 0.0)
    if ins.get("OutSize") is not None:
        import numpy as np
        sz = np.asarray(ins["OutSize"]).ravel()
        out_h, out_w = int(sz[0]), int(sz[1])
    elif scale and scale > 0:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    return out_h, out_w


def _resize(x, oh, ow, method, align_corners):
    n, c, h, w = x.shape
    if method == "nearest":
        # paddle nearest with align_corners=False uses floor(i * scale)
        hs = h / oh
        ws = w / ow
        if align_corners:
            ridx = jnp.round(jnp.arange(oh) * (h - 1) / max(oh - 1, 1))
            cidx = jnp.round(jnp.arange(ow) * (w - 1) / max(ow - 1, 1))
        else:
            ridx = jnp.floor(jnp.arange(oh) * hs)
            cidx = jnp.floor(jnp.arange(ow) * ws)
        ridx = jnp.clip(ridx, 0, h - 1).astype(jnp.int32)
        cidx = jnp.clip(cidx, 0, w - 1).astype(jnp.int32)
        return x[:, :, ridx][:, :, :, cidx]
    # bilinear / bicubic / trilinear via jax.image
    meth = {"bilinear": "linear", "bicubic": "cubic",
            "trilinear": "trilinear"}[method]
    if align_corners:
        # jax.image doesn't support align_corners; emulate linear case
        ry = jnp.arange(oh) * (h - 1) / max(oh - 1, 1)
        rx = jnp.arange(ow) * (w - 1) / max(ow - 1, 1)
        y0 = jnp.floor(ry).astype(jnp.int32)
        x0 = jnp.floor(rx).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ry - y0)[None, None, :, None]
        wx = (rx - x0)[None, None, None, :]
        g = lambda yy, xx: x[:, :, yy][:, :, :, xx]
        out = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx) +
               g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
        return out.astype(x.dtype)
    return jax.image.resize(x, (n, c, oh, ow), method=meth).astype(x.dtype)


def _make_interp(name, method):
    @register_op(name, inputs=["X", "OutSize?!", "SizeTensor*?!", "Scale?!"],
                 outputs=["Out"])
    def kernel(ins, attrs, ctx, _m=method):
        x = ins["X"]
        oh, ow = _interp_size(x, attrs, ins)
        return {"Out": _resize(x, oh, ow, _m,
                               attrs.get("align_corners", True))}
    return kernel


_make_interp("bilinear_interp", "bilinear")
_make_interp("nearest_interp", "nearest")
_make_interp("bicubic_interp", "bicubic")
_make_interp("bilinear_interp_v2", "bilinear")
_make_interp("nearest_interp_v2", "nearest")
_make_interp("bicubic_interp_v2", "bicubic")


def _interp_size_nd(x, attrs, ins, keys):
    """Resolve target spatial dims for 1-d/3-d interp: OutSize tensor >
    scale attr > out_* attrs — the same precedence _interp_size applies
    for the 2-d family."""
    import numpy as np
    spatial = x.shape[2:]
    out = ins.get("OutSize")
    if out is not None:
        vals = [int(v) for v in np.asarray(out).reshape(-1)]
        if len(vals) == len(keys):
            return vals
    scale = attrs.get("scale", 0.0)
    scales = (list(scale) if isinstance(scale, (list, tuple))
              else [scale] * len(keys))
    if scales and all(s and float(s) > 0 for s in scales):
        return [int(dim * float(s)) for dim, s in zip(spatial, scales)]
    sizes = [int(attrs.get(k, -1)) for k in keys]
    if all(s > 0 for s in sizes):
        return sizes
    raise ValueError(
        "interp: no target size — give OutSize, positive scale, or "
        f"{keys}")


def _make_interp_1d(name):
    @register_op(name, inputs=["X", "OutSize?!", "Scale?!"],
                 outputs=["Out"])
    def kernel(ins, attrs, ctx):
        x = ins["X"]  # [n, c, w]
        (ow,) = _interp_size_nd(x, attrs, ins, ["out_w"])
        n, c, w = x.shape
        return {"Out": jax.image.resize(x, (n, c, ow),
                                        "linear").astype(x.dtype)}
    return kernel


def _make_interp_3d(name):
    @register_op(name, inputs=["X", "OutSize?!", "Scale?!"],
                 outputs=["Out"])
    def kernel(ins, attrs, ctx):
        x = ins["X"]  # [n, c, d, h, w]
        od, oh, ow = _interp_size_nd(x, attrs, ins,
                                     ["out_d", "out_h", "out_w"])
        n, c = x.shape[:2]
        return {"Out": jax.image.resize(x, (n, c, od, oh, ow),
                                        "trilinear").astype(x.dtype)}
    return kernel


linear_interp = _make_interp_1d("linear_interp")
_make_interp_1d("linear_interp_v2")
trilinear_interp = _make_interp_3d("trilinear_interp")
_make_interp_3d("trilinear_interp_v2")


@register_op("affine_channel", inputs=["X", "Scale", "Bias"], outputs=["Out"])
def affine_channel(ins, attrs, ctx):
    x = ins["X"]
    layout = attrs.get("data_layout", "NCHW")
    shape = (1, -1, 1, 1) if layout == "NCHW" else (1, 1, 1, -1)
    return {"Out": x * ins["Scale"].reshape(shape) +
            ins["Bias"].reshape(shape)}


@register_op("affine_grid", inputs=["Theta", "OutputShape?!"], outputs=["Output"])
def affine_grid(ins, attrs, ctx):
    theta = ins["Theta"]  # [n, 2, 3]
    shape = attrs.get("output_shape", [])
    if ins.get("OutputShape") is not None:
        import numpy as np
        shape = [int(s) for s in np.asarray(ins["OutputShape"]).ravel()]
    n, c, h, w = shape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [h*w, 3]
    grid = jnp.einsum("nij,pj->npi", theta, base)
    return {"Output": grid.reshape(n, h, w, 2)}


@register_op("grid_sampler", inputs=["X", "Grid"], outputs=["Output"])
def grid_sampler(ins, attrs, ctx):
    x, grid = ins["X"], ins["Grid"]  # x [n,c,h,w], grid [n,h',w',2] in [-1,1]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx = gx - x0
    wy = gy - y0

    def gather(yy, xx):
        yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        batch = jnp.arange(n)[:, None, None]
        return x[batch, :, yy, xx]  # [n, h', w', c]

    out = (gather(y0, x0) * ((1 - wy) * (1 - wx))[..., None] +
           gather(y0, x0 + 1) * ((1 - wy) * wx)[..., None] +
           gather(y0 + 1, x0) * (wy * (1 - wx))[..., None] +
           gather(y0 + 1, x0 + 1) * (wy * wx)[..., None])
    return {"Output": jnp.moveaxis(out, -1, 1).astype(x.dtype)}


@register_op("roi_align", inputs=["X", "ROIs!", "RoisNum?!"], outputs=["Out"])
def roi_align(ins, attrs, ctx):
    x, rois = ins["X"], ins["ROIs"]  # x [n,c,h,w]; rois [k, 4] (x1,y1,x2,y2)
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    sampling = max(attrs.get("sampling_ratio", -1), 1)
    n, c, h, w = x.shape
    k = rois.shape[0]

    def pool_one(roi):
        x1, y1, x2, y2 = roi * scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: ph*sampling x pw*sampling bilinear samples
        sy = y1 + (jnp.arange(ph * sampling) + 0.5) * bin_h / sampling
        sx = x1 + (jnp.arange(pw * sampling) + 0.5) * bin_w / sampling
        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        wy = (sy - y0)[:, None]
        wx = (sx - x0)[None, :]

        def g(yy, xx):
            yy = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xx = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            return x[0, :, yy][:, :, xx]  # [s_h, c? ...]

        # gather for batch 0 (single-image path; batched below via roi batch id)
        yy0 = jnp.clip(y0, 0, h - 1).astype(jnp.int32)
        xx0 = jnp.clip(x0, 0, w - 1).astype(jnp.int32)
        yy1 = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
        xx1 = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
        v00 = x[0][:, yy0][:, :, xx0]
        v01 = x[0][:, yy0][:, :, xx1]
        v10 = x[0][:, yy1][:, :, xx0]
        v11 = x[0][:, yy1][:, :, xx1]
        vals = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx +
                v10 * wy * (1 - wx) + v11 * wy * wx)  # [c, sh, sw]
        vals = vals.reshape(c, ph, sampling, pw, sampling)
        return jnp.mean(vals, axis=(2, 4))

    out = jax.vmap(pool_one)(rois)
    return {"Out": out}


@register_op("roi_pool", inputs=["X", "ROIs!", "RoisNum?!"],
             outputs=["Out", "Argmax"])
def roi_pool(ins, attrs, ctx):
    x, rois = ins["X"], ins["ROIs"]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def pool_one(roi):
        x1, y1, x2, y2 = jnp.round(roi * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        # max over bilinear-free integer bins, approximated with a fixed
        # sample grid for static shapes
        s = 4
        sy = y1 + (jnp.arange(ph * s) + 0.5) * rh / (ph * s)
        sx = x1 + (jnp.arange(pw * s) + 0.5) * rw / (pw * s)
        yy = jnp.clip(jnp.floor(sy), 0, h - 1).astype(jnp.int32)
        xx = jnp.clip(jnp.floor(sx), 0, w - 1).astype(jnp.int32)
        vals = x[0][:, yy][:, :, xx].reshape(c, ph, s, pw, s)
        return jnp.max(vals, axis=(2, 4))

    out = jax.vmap(pool_one)(rois)
    return {"Out": out, "Argmax": jnp.zeros_like(out, dtype=jnp.int64)}


def expand_aspect_ratios(ars_in, flip):
    """The reference's ExpandAspectRatios: dedup([1.0] + ratios
    (+ flipped)).  ONE definition shared by the prior_box kernel and
    layers.multi_box_head's prior-count mirror — the two must stay
    identical or loc/conf channels desync from the emitted priors."""
    ars = [1.0]
    for ar in ars_in:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    return ars


@register_op("prior_box", inputs=["Input!", "Image!"],
             outputs=["Boxes", "Variances"], grad=None)
def prior_box(ins, attrs, ctx):
    feat, img = ins["Input"], ins["Image"]
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ars_in = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", False)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    ars = expand_aspect_ratios(ars_in, flip)
    sw = step_w if step_w > 0 else iw / w
    sh = step_h if step_h > 0 else ih / h
    min_max_order = attrs.get("min_max_aspect_ratios_order", False)
    boxes = []
    for si, ms in enumerate(min_sizes):
        # reference prior_box_op.h:116 PAIRS max_sizes[s] with
        # min_sizes[s] — never a cross-product
        mx = max_sizes[si] if si < len(max_sizes) else None
        if min_max_order:
            # reference flag: [min(ar=1), max, remaining ratios] so
            # pretrained loc/conf channel order matches
            boxes.append((ms / 2, ms / 2))
            if mx is not None:
                s = (ms * mx) ** 0.5 / 2
                boxes.append((s, s))
            for ar in ars[1:]:
                bw = ms * (ar ** 0.5) / 2
                bh = ms / (ar ** 0.5) / 2
                boxes.append((bw, bh))
            continue
        for ar in ars:
            bw = ms * (ar ** 0.5) / 2
            bh = ms / (ar ** 0.5) / 2
            boxes.append((bw, bh))
        if mx is not None:
            s = (ms * mx) ** 0.5 / 2
            boxes.append((s, s))
    cx = (jnp.arange(w) + offset) * sw
    cy = (jnp.arange(h) + offset) * sh
    gx, gy = jnp.meshgrid(cx, cy, indexing="xy")
    all_boxes = []
    for bw, bh in boxes:
        b = jnp.stack([(gx - bw) / iw, (gy - bh) / ih,
                       (gx + bw) / iw, (gy + bh) / ih], axis=-1)
        all_boxes.append(b)
    out = jnp.stack(all_boxes, axis=2).reshape(h, w, len(boxes), 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    return {"Boxes": out, "Variances": var}


@register_op("box_coder", inputs=["PriorBox!", "PriorBoxVar?!", "TargetBox!"],
             outputs=["OutputBox"], grad=None)
def box_coder(ins, attrs, ctx):
    prior = ins["PriorBox"]  # [m, 4]
    target = ins["TargetBox"]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    pv = ins.get("PriorBoxVar")
    one = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + one
    ph_ = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph_ / 2
    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + tw / 2
        tcy = target[:, 1] + th / 2
        out = jnp.stack([(tcx[:, None] - pcx[None]) / pw[None],
                         (tcy[:, None] - pcy[None]) / ph_[None],
                         jnp.log(tw[:, None] / pw[None]),
                         jnp.log(th[:, None] / ph_[None])], axis=-1)
        if pv is not None:
            out = out / pv[None]
        return {"OutputBox": out}
    # decode: target [n, m, 4]
    t = target
    if pv is not None:
        t = t * pv[None]
    ocx = t[..., 0] * pw + pcx
    ocy = t[..., 1] * ph_ + pcy
    ow = jnp.exp(t[..., 2]) * pw
    oh = jnp.exp(t[..., 3]) * ph_
    out = jnp.stack([ocx - ow / 2, ocy - oh / 2,
                     ocx + ow / 2 - one, ocy + oh / 2 - one], axis=-1)
    return {"OutputBox": out}


@register_op("box_clip", inputs=["Input", "ImInfo!"], outputs=["Output"],
             grad=None)
def box_clip(ins, attrs, ctx):
    boxes, im = ins["Input"], ins["ImInfo"]
    h, w = im[0, 0], im[0, 1]
    x1 = jnp.clip(boxes[..., 0], 0, w - 1)
    y1 = jnp.clip(boxes[..., 1], 0, h - 1)
    x2 = jnp.clip(boxes[..., 2], 0, w - 1)
    y2 = jnp.clip(boxes[..., 3], 0, h - 1)
    return {"Output": jnp.stack([x1, y1, x2, y2], axis=-1)}


@register_op("iou_similarity", inputs=["X!", "Y!"], outputs=["Out"],
             grad=None)
def iou_similarity(ins, attrs, ctx):
    a, b = ins["X"], ins["Y"]  # [n,4], [m,4]
    ax1, ay1, ax2, ay2 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix1 = jnp.maximum(ax1[:, None], bx1[None])
    iy1 = jnp.maximum(ay1[:, None], by1[None])
    ix2 = jnp.minimum(ax2[:, None], bx2[None])
    iy2 = jnp.minimum(ay2[:, None], by2[None])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = (ax2 - ax1) * (ay2 - ay1)
    area_b = (bx2 - bx1) * (by2 - by1)
    return {"Out": inter / jnp.maximum(area_a[:, None] + area_b[None] - inter,
                                       1e-10)}


@register_op("yolo_box", inputs=["X", "ImgSize!"],
             outputs=["Boxes", "Scores"], grad=None)
def yolo_box(ins, attrs, ctx):
    x, img = ins["X"], ins["ImgSize"]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, c, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    gx, gy = jnp.meshgrid(jnp.arange(w), jnp.arange(h), indexing="xy")
    bx = (jax.nn.sigmoid(x[:, :, 0]) + gx[None, None]) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + gy[None, None]) / h
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
    in_w = downsample * w
    in_h = downsample * h
    bw = jnp.exp(x[:, :, 2]) * aw / in_w
    bh = jnp.exp(x[:, :, 3]) * ah / in_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    img_h = img[:, 0].reshape(n, 1, 1, 1).astype(jnp.float32)
    img_w = img[:, 1].reshape(n, 1, 1, 1).astype(jnp.float32)
    boxes = jnp.stack([(bx - bw / 2) * img_w, (by - bh / 2) * img_h,
                       (bx + bw / 2) * img_w, (by + bh / 2) * img_h], axis=-1)
    boxes = boxes.reshape(n, -1, 4)
    scores = jnp.moveaxis(probs, 2, -1).reshape(n, -1, class_num)
    mask = (conf > conf_thresh).reshape(n, -1, 1)
    return {"Boxes": boxes * mask, "Scores": scores * mask}


# ---------------------------------------------------------------------------
# modulated deformable convolution (deformable_conv_op.cc:108, v2 with
# per-sample modulation mask; deformable_conv_v1 without).  TPU-native
# lowering: bilinear sampling becomes four batched gathers + interpolation
# weights, the conv itself one einsum over the sampled patch tensor — no
# im2col scratch, fully differentiable through auto-vjp (offsets get
# gradients through the bilinear weights).
# ---------------------------------------------------------------------------
def _deform_sample(x, offset, mask, attrs, kh, kw, dg):
    n, c, h, w = x.shape
    stride = attrs.get("strides", [1, 1])
    pad = attrs.get("paddings", [0, 0])
    dil = attrs.get("dilations", [1, 1])
    ho = (h + 2 * pad[0] - (dil[0] * (kh - 1) + 1)) // stride[0] + 1
    wo = (w + 2 * pad[1] - (dil[1] * (kw - 1) + 1)) // stride[1] + 1
    # base sampling grid [kh, kw, ho, wo]
    ys = (jnp.arange(ho) * stride[0] - pad[0])[None, None, :, None] \
        + (jnp.arange(kh) * dil[0])[:, None, None, None]
    xs = (jnp.arange(wo) * stride[1] - pad[1])[None, None, None, :] \
        + (jnp.arange(kw) * dil[1])[None, :, None, None]
    ys = jnp.broadcast_to(ys, (kh, kw, ho, wo)).astype(x.dtype)
    xs = jnp.broadcast_to(xs, (kh, kw, ho, wo)).astype(x.dtype)
    # offsets [n, 2*dg*kh*kw, ho, wo] -> y/x per (dg, kh, kw)
    off = offset.reshape(n, dg, kh * kw, 2, ho, wo)
    off_y = off[:, :, :, 0].reshape(n, dg, kh, kw, ho, wo)
    off_x = off[:, :, :, 1].reshape(n, dg, kh, kw, ho, wo)
    py = ys[None, None] + off_y            # [n, dg, kh, kw, ho, wo]
    px = xs[None, None] + off_x
    # bilinear corners with zero padding outside
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    wy = py - y0
    wx = px - x0

    def gather(yi, xi):
        # x grouped by dg: [n, dg, c/dg, h, w]; index [n, dg, kh,kw,ho,wo]
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        xg = x.reshape(n, dg, c // dg, h, w)
        ni = jnp.arange(n)[:, None, None, None, None, None]
        gi = jnp.arange(dg)[None, :, None, None, None, None]
        # channels last, then one advanced-index gather over (n, dg, y, x)
        xgl = jnp.moveaxis(xg, 2, -1)      # [n, dg, h, w, c/dg]
        vals = xgl[ni, gi, yc, xc]         # [n, dg, kh,kw,ho,wo, c/dg]
        return jnp.where(valid[..., None], vals, 0.0)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wy_ = wy[..., None]
    wx_ = wx[..., None]
    sampled = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_
               + v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)
    if mask is not None:
        m = mask.reshape(n, dg, kh, kw, ho, wo)
        sampled = sampled * m[..., None]
    return sampled, ho, wo  # [n, dg, kh, kw, ho, wo, c/dg]


def _deform_conv(ins, attrs, ctx, with_mask):
    x, w_f = ins["Input"], ins["Filter"]
    offset = ins["Offset"]
    mask = ins.get("Mask") if with_mask else None
    cout, cin_g, kh, kw = w_f.shape
    dg = int(attrs.get("deformable_groups", 1))
    groups = int(attrs.get("groups", 1))
    n, c = x.shape[0], x.shape[1]
    sampled, ho, wo = _deform_sample(x, offset, mask, attrs, kh, kw, dg)
    # [n, dg, kh, kw, ho, wo, c/dg] -> [n, c, kh, kw, ho, wo]
    sampled = jnp.moveaxis(sampled, -1, 2).reshape(
        n, c, kh, kw, ho, wo)
    # grouped conv: split channels
    sampled = sampled.reshape(n, groups, c // groups, kh, kw, ho, wo)
    wg = w_f.reshape(groups, cout // groups, cin_g, kh, kw)
    out = jnp.einsum("ngcijhw,gocij->ngohw", sampled, wg)
    return {"Output": out.reshape(n, cout, ho, wo)}


@register_op("deformable_conv",
             inputs=["Input", "Offset", "Mask", "Filter"],
             outputs=["Output"])
def deformable_conv(ins, attrs, ctx):
    return _deform_conv(ins, attrs, ctx, with_mask=True)


@register_op("deformable_conv_v1",
             inputs=["Input", "Offset", "Filter"],
             outputs=["Output"])
def deformable_conv_v1(ins, attrs, ctx):
    return _deform_conv(ins, attrs, ctx, with_mask=False)

"""RNN ops (reference: gru_op.cc, lstm_op.cc, gru_unit_op.cc, lstm_unit_op.cc,
warpctc, beam search).  Time loops use lax.scan — compiler-friendly, static
shapes, no per-step Python dispatch (the reference runs one C++ kernel per
step inside a while op)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op


@register_op("lstm_unit", inputs=["X", "C_prev"], outputs=["C", "H"])
def lstm_unit(ins, attrs, ctx):
    x, c_prev = ins["X"], ins["C_prev"]
    forget_bias = attrs.get("forget_bias", 0.0)
    i, j, f, o = jnp.split(x, 4, axis=1)
    c = c_prev * jax.nn.sigmoid(f + forget_bias) + \
        jax.nn.sigmoid(i) * jnp.tanh(j)
    h = jnp.tanh(c) * jax.nn.sigmoid(o)
    return {"C": c, "H": h}


@register_op("gru_unit", inputs=["Input", "HiddenPrev", "Weight", "Bias?"],
             outputs=["Gate", "ResetHiddenPrev", "Hidden"])
def gru_unit(ins, attrs, ctx):
    x, h_prev, w = ins["Input"], ins["HiddenPrev"], ins["Weight"]
    d = h_prev.shape[1]
    if ins.get("Bias") is not None:
        x = x + ins["Bias"].reshape(1, -1)
    # w: [d, 3d] -> gates [d, 2d], candidate [d, d]
    w_gates, w_cand = w[:, :2 * d], w[:, 2 * d:]
    xu, xr, xc = x[:, :d], x[:, d:2 * d], x[:, 2 * d:]
    gates = jnp.concatenate([xu, xr], 1) + h_prev @ w_gates
    u = jax.nn.sigmoid(gates[:, :d])
    r = jax.nn.sigmoid(gates[:, d:])
    rh = r * h_prev
    c = jnp.tanh(xc + rh @ w_cand)
    h = u * h_prev + (1.0 - u) * c
    gate = jnp.concatenate([u, r, c], axis=1)
    return {"Gate": gate, "ResetHiddenPrev": rh, "Hidden": h}


def _lstm_scan(x, h0, c0, w, b, reverse=False):
    """x: [b, t, 4d] pre-projected gates input; w: [d, 4d] recurrent weight."""
    d = h0.shape[-1]

    def step(carry, xt):
        h, c = carry
        gates = xt + h @ w + (b if b is not None else 0.0)
        i, f, cand, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(cand)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        return (h_new, c_new), (h_new, c_new)

    xs = jnp.swapaxes(x, 0, 1)  # [t, b, 4d]
    if reverse:
        xs = jnp.flip(xs, 0)
    (_, _), (hs, cs) = jax.lax.scan(step, (h0, c0), xs)
    if reverse:
        hs, cs = jnp.flip(hs, 0), jnp.flip(cs, 0)
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@register_op("lstm", inputs=["Input", "H0?", "C0?", "Weight", "Bias?"],
             outputs=["Hidden", "Cell", "BatchGate", "BatchCellPreAct"])
def lstm(ins, attrs, ctx):
    x = ins["Input"]  # [b, t, 4d] (dense path)
    d = ins["Weight"].shape[0]
    b_sz = x.shape[0]
    h0 = ins.get("H0")
    c0 = ins.get("C0")
    h0 = jnp.zeros((b_sz, d), x.dtype) if h0 is None else h0
    c0 = jnp.zeros((b_sz, d), x.dtype) if c0 is None else c0
    bias = ins.get("Bias")
    hs, cs = _lstm_scan(x, h0, c0, ins["Weight"],
                        bias[:, :4 * d] if bias is not None else None,
                        reverse=attrs.get("is_reverse", False))
    return {"Hidden": hs, "Cell": cs, "BatchGate": x,
            "BatchCellPreAct": cs}


@register_op("gru", inputs=["Input", "H0?", "Weight", "Bias?"],
             outputs=["Hidden", "BatchGate", "BatchResetHiddenPrev",
                      "BatchHidden"])
def gru(ins, attrs, ctx):
    x, w = ins["Input"], ins["Weight"]  # x: [b, t, 3d]
    d = w.shape[0]
    b_sz = x.shape[0]
    h0 = ins.get("H0")
    h0 = jnp.zeros((b_sz, d), x.dtype) if h0 is None else h0
    bias = ins.get("Bias")

    def step(h, xt):
        sub = {"Input": xt, "HiddenPrev": h, "Weight": w}
        if bias is not None:
            sub["Bias"] = bias
        out = gru_unit(sub, attrs, ctx)
        return out["Hidden"], out["Hidden"]

    xs = jnp.swapaxes(x, 0, 1)
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, 0)
    _, hs = jax.lax.scan(step, h0, xs)
    if attrs.get("is_reverse", False):
        hs = jnp.flip(hs, 0)
    hs = jnp.swapaxes(hs, 0, 1)
    return {"Hidden": hs, "BatchGate": x, "BatchResetHiddenPrev": hs,
            "BatchHidden": hs}


@register_op("rnn",
             inputs=["Input", "PreState*", "WeightList*", "SequenceLength?!"],
             outputs=["Out", "State*", "Reserve", "DropoutState"])
def rnn(ins, attrs, ctx):
    """2.0 cudnn-style multi-layer RNN (LSTM/GRU/RNN) over [t, b, d] input."""
    x = ins["Input"]
    mode = attrs.get("mode", "LSTM")
    hidden = attrs.get("hidden_size")
    layers = attrs.get("num_layers", 1)
    bidi = attrs.get("is_bidirec", False)
    ndir = 2 if bidi else 1
    ws = ins["WeightList"]
    pre = ins["PreState"]
    h0_all = pre[0]  # [layers*ndir, b, h]
    c0_all = pre[1] if mode == "LSTM" else None
    t, b, _ = x.shape
    out = x
    h_last, c_last = [], []
    wi = 0
    for layer in range(layers):
        dir_outs = []
        for d_ in range(ndir):
            w_ih, w_hh = ws[wi], ws[wi + 1]
            b_ih = ws[2 * layers * ndir + wi] \
                if len(ws) > 2 * layers * ndir else None
            b_hh = ws[2 * layers * ndir + wi + 1] \
                if len(ws) > 2 * layers * ndir else None
            wi += 2
            idx = layer * ndir + d_
            h0 = h0_all[idx]
            xs = out if d_ == 0 else out
            gates_in = jnp.einsum("tbd,gd->tbg", xs, w_ih)
            if b_ih is not None:
                gates_in = gates_in + b_ih + (b_hh if b_hh is not None else 0)
            if mode == "LSTM":
                c0 = c0_all[idx]

                def step(carry, g):
                    h, c = carry
                    gates = g + h @ w_hh.T
                    i, f, cand, o = jnp.split(gates, 4, axis=-1)
                    c_new = jax.nn.sigmoid(f) * c + \
                        jax.nn.sigmoid(i) * jnp.tanh(cand)
                    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                    return (h_new, c_new), h_new

                seq = gates_in if d_ == 0 else jnp.flip(gates_in, 0)
                (hT, cT), hs = jax.lax.scan(step, (h0, c0), seq)
                if d_ == 1:
                    hs = jnp.flip(hs, 0)
                h_last.append(hT)
                c_last.append(cT)
            elif mode.startswith("RNN"):  # RNN_TANH / RNN_RELU
                act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

                def step_s(h, g):
                    h_new = act(g + h @ w_hh.T)
                    return h_new, h_new

                seq = gates_in if d_ == 0 else jnp.flip(gates_in, 0)
                hT, hs = jax.lax.scan(step_s, h0, seq)
                if d_ == 1:
                    hs = jnp.flip(hs, 0)
                h_last.append(hT)
            else:  # GRU
                def step_g(h, g):
                    zr = g[..., :2 * hidden] + (h @ w_hh.T)[..., :2 * hidden]
                    z = jax.nn.sigmoid(zr[..., :hidden])
                    r = jax.nn.sigmoid(zr[..., hidden:])
                    cand = jnp.tanh(g[..., 2 * hidden:] +
                                    (r * h) @ w_hh[2 * hidden:].T)
                    h_new = z * h + (1 - z) * cand
                    return h_new, h_new

                seq = gates_in if d_ == 0 else jnp.flip(gates_in, 0)
                hT, hs = jax.lax.scan(step_g, h0, seq)
                if d_ == 1:
                    hs = jnp.flip(hs, 0)
                h_last.append(hT)
            dir_outs.append(hs)
        out = jnp.concatenate(dir_outs, axis=-1) if bidi else dir_outs[0]
    states = [jnp.stack(h_last)]
    if mode == "LSTM":
        states.append(jnp.stack(c_last))
    return {"Out": out, "State": states,
            "Reserve": jnp.zeros((1,), x.dtype),
            "DropoutState": jnp.zeros((1,), jnp.uint8)}


@register_op("edit_distance",
             inputs=["Hyps!", "Refs!", "HypsLength?!", "RefsLength?!"],
             outputs=["Out", "SequenceNum"], grad=None)
def edit_distance(ins, attrs, ctx):
    """edit_distance_op.cc — Levenshtein distance per pair; `normalized`
    divides by the reference length (attr default FALSE like the
    reference).  Dense [b, t] tokens; lengths from the optional length
    tensors, else inferred from -1 padding."""
    hyp, ref = ins["Hyps"], ins["Refs"]
    hlen, rlen = ins.get("HypsLength"), ins.get("RefsLength")
    hls = (hlen.reshape(-1).astype(jnp.int32) if hlen is not None
           else jnp.sum(hyp >= 0, axis=1).astype(jnp.int32))
    rls = (rlen.reshape(-1).astype(jnp.int32) if rlen is not None
           else jnp.sum(ref >= 0, axis=1).astype(jnp.int32))

    def dist_one(h, r, hl, rl):
        maxh, maxr = h.shape[0], r.shape[0]
        row = jnp.arange(maxr + 1).astype(jnp.float32)

        def outer(i, row):
            def inner(j, acc):
                prev_row, cur = acc
                cost = jnp.where(h[i - 1] == r[j - 1], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(cur[j - 1] + 1,
                                              prev_row[j] + 1),
                                  prev_row[j - 1] + cost)
                return prev_row, cur.at[j].set(val)

            new = jnp.zeros_like(row).at[0].set(i * 1.0)
            _, new = jax.lax.fori_loop(1, maxr + 1, inner, (row, new))
            # rows past this hypothesis' length leave the DP frozen
            return jnp.where(i <= hl, new, row)

        final = jax.lax.fori_loop(1, maxh + 1, outer, row)
        d = final[rl]
        if attrs.get("normalized", False):
            d = d / jnp.maximum(rl.astype(jnp.float32), 1.0)
        return d

    out = jax.vmap(dist_one)(hyp, ref, hls, rls)
    return {"Out": out.reshape(-1, 1),
            "SequenceNum": jnp.asarray([hyp.shape[0]], jnp.int64)}


@register_op("lstmp",
             inputs=["Input", "H0?", "C0?", "Weight", "ProjWeight",
                     "Bias?"],
             outputs=["Projection", "Cell", "BatchGate",
                      "BatchCellPreAct", "BatchHidden"])
def lstmp(ins, attrs, ctx):
    """LSTM with recurrent projection (lstmp_op.cc / lstmp_op.h): the
    recurrence feeds the PROJECTED hidden r = act(h @ ProjWeight) back
    into the gates — Weight is [proj, 4*hidden], ProjWeight is
    [hidden, proj].  Input is the pre-projected gate sequence
    [b, t, 4*hidden] (caller fc-projects, same contract as `lstm`)."""
    x = ins["Input"]                       # [b, t, 4d]
    w = ins["Weight"]                      # [p, 4d]
    pw = ins["ProjWeight"]                 # [d, p]
    d = pw.shape[0]
    p = pw.shape[1]
    b_sz = x.shape[0]
    h0 = ins.get("H0")                    # [b, p] projected initial
    c0 = ins.get("C0")
    r0 = jnp.zeros((b_sz, p), x.dtype) if h0 is None else h0
    c0 = jnp.zeros((b_sz, d), x.dtype) if c0 is None else c0
    bias = ins.get("Bias")
    _acts = {"tanh": jnp.tanh, "identity": lambda v: v,
             "relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid}
    act = _acts.get(attrs.get("proj_activation", "tanh"), jnp.tanh)
    act_gate = _acts.get(attrs.get("gate_activation", "sigmoid"),
                         jax.nn.sigmoid)
    act_cand = _acts.get(attrs.get("candidate_activation", "tanh"),
                         jnp.tanh)
    act_cell = _acts.get(attrs.get("cell_activation", "tanh"), jnp.tanh)
    # use_peepholes=True (the reference lstmp default, lstmp_op.h): Bias
    # carries [1, 7*hidden] — 4d gate bias then the diagonal peephole
    # weights W_ic, W_if (on c_prev) and W_oc (on c_new)
    use_peep = bool(attrs.get("use_peepholes", False))
    if use_peep and (bias is None or bias.reshape(-1).shape[0] < 7 * d):
        raise ValueError(
            "lstmp: use_peepholes=True needs a [1, 7*hidden] Bias "
            "(4d gate bias + W_ic/W_if/W_oc peephole diagonals)")
    if use_peep:
        flat_b = bias.reshape(-1)
        w_ic, w_if, w_oc = (flat_b[4 * d:5 * d], flat_b[5 * d:6 * d],
                            flat_b[6 * d:7 * d])

    def step(carry, xt):
        r, c = carry
        gates = xt + r @ w + (bias.reshape(-1)[:4 * d].reshape(1, -1)
                              if bias is not None else 0.0)
        i, f, cand, o = jnp.split(gates, 4, axis=-1)
        if use_peep:
            i = i + w_ic * c
            f = f + w_if * c
        c_new = act_gate(f) * c + act_gate(i) * act_cand(cand)
        if use_peep:
            o = o + w_oc * c_new
        h_new = act_gate(o) * act_cell(c_new)
        r_new = act(h_new @ pw)
        return (r_new, c_new), (r_new, c_new)

    xs = jnp.swapaxes(x, 0, 1)
    if attrs.get("is_reverse", False):
        xs = jnp.flip(xs, 0)
    (_, _), (rs, cs) = jax.lax.scan(step, (r0, c0), xs)
    if attrs.get("is_reverse", False):
        rs, cs = jnp.flip(rs, 0), jnp.flip(cs, 0)
    rs = jnp.swapaxes(rs, 0, 1)
    cs = jnp.swapaxes(cs, 0, 1)
    return {"Projection": rs, "Cell": cs, "BatchGate": x,
            "BatchCellPreAct": cs, "BatchHidden": rs}

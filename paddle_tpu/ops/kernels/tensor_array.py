"""TensorArray ops: create / write_to_array / read_from_array / length.

Reference: /root/reference/paddle/fluid/operators/controlflow/
tensor_array_read_write_op.cc (WriteToArray/ReadFromArray over
LoDTensorArray), lod_array_length_op.cc.

TPU redesign: LoDTensorArray is a host-side vector of tensors — impossible
under XLA's static shapes.  Here an array is a fixed-capacity device
buffer [capacity, ...] plus an int32 size, registered as a pytree so it
flows through jit / lax.while_loop carries.  Capacity is fixed at the
first write (max_len attr, FLAGS_tensor_array_max_len fallback); writes
are lax.dynamic_update_slice, reads lax.dynamic_index_in_dim — both
compile to in-place HBM updates under XLA buffer donation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op

DEFAULT_MAX_LEN = 256


@jax.tree_util.register_pytree_node_class
class TensorArrayVal:
    """Fixed-capacity device tensor array: buffer [capacity, ...] + size."""

    def __init__(self, buffer, size):
        self.buffer = buffer
        self.size = size

    def tree_flatten(self):
        return (self.buffer, self.size), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self):
        return self.buffer.shape[0]

    def __repr__(self):
        return (f"TensorArrayVal(capacity={self.buffer.shape[0]}, "
                f"elem={self.buffer.shape[1:]}, dtype={self.buffer.dtype})")


def _empty(dtype):
    return TensorArrayVal(jnp.zeros((0,), dtype), jnp.zeros((), jnp.int32))


@register_op("create_tensor_array", inputs=[], outputs=["Out"], grad=None)
def create_tensor_array(ins, attrs, ctx):
    from ...core.dtype import np_dtype
    return {"Out": _empty(np_dtype(attrs.get("dtype", "float32")))}


@register_op("write_to_array", inputs=["X", "I!", "Array?"],
             outputs=["Out"], grad=None)
def write_to_array(ins, attrs, ctx):
    x, i = ins["X"], ins["I"]
    arr = ins.get("Array")
    i = jnp.reshape(i, ()).astype(jnp.int32)
    if arr is None or (arr.buffer.ndim == 1
                       and arr.buffer.shape[0] == 0):
        # first write fixes capacity and element shape
        max_len = int(attrs.get("max_len") or 0)
        if max_len <= 0:
            from ...core.flags import flag
            max_len = int(flag("tensor_array_max_len", DEFAULT_MAX_LEN))
        buf = jnp.zeros((max_len,) + tuple(x.shape), x.dtype)
        arr = TensorArrayVal(buf, jnp.zeros((), jnp.int32))
    zero = jnp.zeros((), i.dtype)
    buf = jax.lax.dynamic_update_slice(
        arr.buffer, x[None].astype(arr.buffer.dtype),
        (i,) + (zero,) * x.ndim)
    size = jnp.maximum(arr.size, i + 1)
    return {"Out": TensorArrayVal(buf, size)}


@register_op("read_from_array", inputs=["X", "I!"], outputs=["Out"],
             grad=None)
def read_from_array(ins, attrs, ctx):
    arr, i = ins["X"], ins["I"]
    i = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": jax.lax.dynamic_index_in_dim(arr.buffer, i, 0,
                                                keepdims=False)}


@register_op("lod_array_length", inputs=["X!"], outputs=["Out"], grad=None)
def lod_array_length(ins, attrs, ctx):
    return {"Out": jnp.reshape(ins["X"].size, (1,)).astype(jnp.int64)}

"""Kernel library: every module registers ops into the global registry on
import (analog of /root/reference/paddle/fluid/operators/ — but each kernel is
one traceable JAX function instead of per-device C++/CUDA code)."""
from . import (  # noqa: F401
    math,
    attention,
    elementwise,
    activation,
    reduce,
    manip,
    nn,
    loss,
    random,
    optimizers,
    control,
    tensor_array,
    metrics,
    collective,
    sequence,
    amp,
    rnn,
    vision,
    quantize,
    detection,
    ctc_crf,
    decode,
    distributed_ops,
    sampled_loss,
)

"""Elementwise binary ops with the reference's `axis` broadcast semantics
(/root/reference/paddle/fluid/operators/elementwise/elementwise_op_function.h):
Y's shape is aligned to X starting at `axis` (axis=-1 means numpy-style
trailing alignment).  XLA fuses these into neighbouring matmuls, so no Pallas
needed here."""
from __future__ import annotations

import jax.numpy as jnp

from ..registry import register_op


def _broadcast_y(x, y, axis):
    if axis == -1 or axis is None or x.shape == y.shape:
        return y
    # pad y's shape with trailing 1s so y.dims align to x.dims at `axis`
    pad = x.ndim - axis - y.ndim
    if pad > 0:
        y = y.reshape(y.shape + (1,) * pad)
    return y


def _ew(name, fn, grad="auto"):
    @register_op(name, inputs=["X", "Y"], outputs=["Out"], grad=grad)
    def kernel(ins, attrs, ctx, _fn=fn):
        x, y = ins["X"], ins["Y"]
        y = _broadcast_y(x, y, attrs.get("axis", -1))
        return {"Out": _fn(x, y)}
    return kernel


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod, grad=None)
_ew("elementwise_floordiv", jnp.floor_divide, grad=None)


# grad_add: used by append_backward for gradient accumulation (reference uses
# sum op / grad_add)
@register_op("grad_add", inputs=["X", "Y"], outputs=["Out"])
def grad_add(ins, attrs, ctx):
    return {"Out": ins["X"] + ins["Y"]}

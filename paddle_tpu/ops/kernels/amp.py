"""AMP support ops (reference: /root/reference/paddle/fluid/operators/amp/
check_finite_and_unscale_op.cc, update_loss_scaling_op.cc).  On TPU the AMP
dtype is bfloat16 (wide exponent — loss scaling rarely strictly needed), but
the full fp16-style dynamic loss-scaling machinery is kept for parity and for
float16 use."""
from __future__ import annotations

import jax.numpy as jnp

from ..registry import register_op


@register_op("check_finite_and_unscale", inputs=["X*", "Scale!"],
             outputs=["Out*", "FoundInfinite"], grad=None, side_effect=True)
def check_finite_and_unscale(ins, attrs, ctx):
    xs = ins["X"]
    scale = ins["Scale"].reshape(()).astype(jnp.float32)
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    outs = []
    for x in xs:
        finite = jnp.all(jnp.isfinite(x.astype(jnp.float32)))
        found = found | ~finite
        outs.append((x.astype(jnp.float32) * inv).astype(x.dtype))
    return {"Out": outs, "FoundInfinite": found.reshape(1)}


@register_op("update_loss_scaling",
             inputs=["X*", "FoundInfinite!", "PrevLossScaling!",
                     "InGoodSteps!", "InBadSteps!"],
             outputs=["Out*", "LossScaling", "OutGoodSteps", "OutBadSteps"],
             grad=None, side_effect=True)
def update_loss_scaling(ins, attrs, ctx):
    found = ins["FoundInfinite"].reshape(())
    scale = ins["PrevLossScaling"].reshape(()).astype(jnp.float32)
    good = ins["InGoodSteps"].reshape(()).astype(jnp.int32)
    bad = ins["InBadSteps"].reshape(()).astype(jnp.int32)
    incr_every = attrs.get("incr_every_n_steps", 1000)
    decr_every = attrs.get("decr_every_n_nan_or_inf", 2)
    incr_ratio = attrs.get("incr_ratio", 2.0)
    decr_ratio = attrs.get("decr_ratio", 0.5)

    new_good = jnp.where(found, 0, good + 1)
    new_bad = jnp.where(found, bad + 1, 0)
    grow = new_good >= incr_every
    shrink = new_bad >= decr_every
    new_scale = jnp.where(grow, scale * incr_ratio,
                          jnp.where(shrink, jnp.maximum(scale * decr_ratio,
                                                        1.0), scale))
    new_good = jnp.where(grow | shrink, 0, new_good)
    new_bad = jnp.where(grow | shrink, 0, new_bad)

    outs = []
    for x in ins["X"]:
        # zero out grads when non-finite so the optimizer step is a no-op
        outs.append(jnp.where(found, jnp.zeros_like(x), x))
    return {"Out": outs,
            "LossScaling": new_scale.reshape(ins["PrevLossScaling"].shape),
            "OutGoodSteps": new_good.reshape(ins["InGoodSteps"].shape),
            "OutBadSteps": new_bad.reshape(ins["InBadSteps"].shape)}


@register_op("cast_with_ptr", inputs=["X"], outputs=["Out"])
def cast_with_ptr(ins, attrs, ctx):
    from ...core.dtype import np_dtype
    return {"Out": ins["X"].astype(np_dtype(attrs["out_dtype"]))}

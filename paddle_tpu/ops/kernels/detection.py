"""Detection op family — the static-shape TPU redesign of
/root/reference/paddle/fluid/operators/detection/ (multiclass_nms_op.cc,
anchor_generator_op.cc, bipartite_match_op.cc, generate_proposals_op.cc,
yolov3_loss_op.cc).

The reference emits LoD outputs whose row counts depend on the data
(variable #detections per image).  XLA wants static shapes, so every op
here returns FIXED-size outputs padded with sentinel rows (label -1 /
score -1 / zero boxes) plus an explicit per-image count tensor — the same
contract paddle 2.x adopted with *RoisNum outputs.  Selection loops are
`lax.fori_loop`s over fixed trip counts so everything stays on-device.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..registry import register_op


def _box_area(b):
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)


def _iou(box, boxes):
    """IoU of one [4] box vs [M, 4] boxes (xyxy)."""
    lt = jnp.maximum(box[:2], boxes[:, :2])
    rb = jnp.minimum(box[2:], boxes[:, 2:])
    inter = jnp.prod(jnp.maximum(rb - lt, 0), axis=-1)
    union = _box_area(box) + _box_area(boxes) - inter
    return inter / jnp.maximum(union, 1e-10)


def _nms_fixed(boxes, scores, iou_threshold, max_out, score_threshold):
    """Greedy NMS with a fixed output count: returns (idx [max_out],
    keep_scores [max_out]) — idx -1 / score -1 on padded rows."""
    M = boxes.shape[0]
    neg = jnp.asarray(-1e30, scores.dtype)
    live = jnp.where(scores > score_threshold, scores, neg)

    def body(i, carry):
        live, idx, kept = carry
        j = jnp.argmax(live)
        ok = live[j] > neg / 2
        idx = idx.at[i].set(jnp.where(ok, j, -1).astype(jnp.int32))
        kept = kept.at[i].set(jnp.where(ok, live[j], -1.0))
        iou = _iou(boxes[j], boxes)
        live = jnp.where((iou >= iou_threshold) | (jnp.arange(M) == j),
                         neg, live)
        live = jnp.where(ok, live, jnp.full_like(live, neg))
        return live, idx, kept

    _, idx, kept = jax.lax.fori_loop(
        0, max_out, body,
        (live, jnp.full((max_out,), -1, jnp.int32),
         jnp.full((max_out,), -1.0, scores.dtype)))
    return idx, kept


@register_op("multiclass_nms", inputs=["BBoxes", "Scores"],
             outputs=["Out", "Index?", "NmsRoisNum?"], grad=None)
def multiclass_nms(ins, attrs, ctx):
    """multiclass_nms_op.cc — per-class NMS then cross-class top-k.
    BBoxes [N, M, 4], Scores [N, C, M] -> Out [N, keep_top_k, 6]
    (label, score, x1, y1, x2, y2; label=-1 rows are padding),
    NmsRoisNum [N]."""
    boxes, scores = ins["BBoxes"], ins["Scores"]
    score_thr = attrs.get("score_threshold", 0.0)
    nms_thr = attrs.get("nms_threshold", 0.3)
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    if keep_top_k < 0:
        keep_top_k = nms_top_k
    bg = attrs.get("background_label", 0)
    N, C, M = scores.shape
    per_cls = min(nms_top_k, M)

    def one_image(bx, sc):
        # dead-score the background class BEFORE the per-class NMS loop so
        # its fixed-trip-count selection (the expensive part) is not run
        # just to be discarded afterwards
        if bg >= 0:
            sc = sc.at[bg].set(-1e30)

        def one_class(c_scores):
            idx, kept = _nms_fixed(bx, c_scores, nms_thr, per_cls,
                                   score_thr)
            sel = jnp.where(idx[:, None] >= 0,
                            bx[jnp.maximum(idx, 0)], 0.0)
            return kept, sel, idx

        kept, sel, idx = jax.vmap(one_class)(sc)
        labels = jnp.broadcast_to(jnp.arange(C)[:, None], (C, per_cls))
        flat_s = kept.reshape(-1)
        flat_b = sel.reshape(-1, 4)
        flat_l = labels.reshape(-1)
        flat_i = idx.reshape(-1)            # original input-box row
        k = min(keep_top_k, flat_s.shape[0])
        top_s, top_i = jax.lax.top_k(flat_s, k)
        live = top_s >= 0
        out = jnp.concatenate(
            [jnp.where(live[:, None], flat_l[top_i][:, None], -1.0)
             .astype(bx.dtype),
             top_s[:, None], flat_b[top_i]], axis=1)
        index = jnp.where(live, flat_i[top_i], -1).astype(jnp.int32)
        count = jnp.sum(live).astype(jnp.int32)
        return out, index, count

    out, index, num = jax.vmap(one_image)(boxes, scores)
    return {"Out": out, "Index": index[..., None], "NmsRoisNum": num}


@register_op("anchor_generator", inputs=["Input!"],
             outputs=["Anchors", "Variances"], grad=None)
def anchor_generator(ins, attrs, ctx):
    """anchor_generator_op.cc — grid of anchors for one feature map.
    Input [N, C, H, W] -> Anchors [H, W, A, 4], Variances same."""
    x = ins["Input"]
    H, W = x.shape[2], x.shape[3]
    sizes = jnp.asarray(attrs.get("anchor_sizes", [64.0, 128.0, 256.0]),
                        jnp.float32)
    ratios = jnp.asarray(attrs.get("aspect_ratios", [0.5, 1.0, 2.0]),
                         jnp.float32)
    stride = attrs.get("stride", [16.0, 16.0])
    offset = attrs.get("offset", 0.5)
    var = jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                      jnp.float32)
    # all (ratio, size) combos, ratio-major (reference loop order)
    r = jnp.repeat(ratios, sizes.shape[0])
    s = jnp.tile(sizes, ratios.shape[0])
    w = s * jnp.sqrt(1.0 / r)
    h = s * jnp.sqrt(r)
    cx = (jnp.arange(W, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(H, dtype=jnp.float32) + offset) * stride[1]
    CX, CY = jnp.meshgrid(cx, cy)  # [H, W]
    anchors = jnp.stack([
        CX[..., None] - 0.5 * w, CY[..., None] - 0.5 * h,
        CX[..., None] + 0.5 * w, CY[..., None] + 0.5 * h], axis=-1)
    A = w.shape[0]
    variances = jnp.broadcast_to(var, (H, W, A, 4))
    return {"Anchors": anchors, "Variances": variances}


@register_op("bipartite_match", inputs=["DistMat"],
             outputs=["ColToRowMatchIndices", "ColToRowMatchDist"],
             grad=None)
def bipartite_match(ins, attrs, ctx):
    """bipartite_match_op.cc — greedy max bipartite matching on a
    [R, C] distance matrix: repeatedly take the global max, bind its
    row+col.  match_type='per_prediction' also binds unmatched cols whose
    best row exceeds dist_threshold."""
    d = ins["DistMat"]
    if d.ndim == 2:
        d = d[None]
    R, C = d.shape[1], d.shape[2]

    def one(dm):
        neg = jnp.asarray(-1e30, dm.dtype)

        def body(i, carry):
            m, idx, dist = carry
            flat = jnp.argmax(m)
            r, c = flat // C, flat % C
            ok = m[r, c] > 0
            idx = idx.at[c].set(jnp.where(ok, r, idx[c]).astype(jnp.int32))
            dist = dist.at[c].set(jnp.where(ok, m[r, c], dist[c]))
            m = jnp.where(ok, m.at[r, :].set(neg).at[:, c].set(neg), m)
            return m, idx, dist

        m0 = (dm, jnp.full((C,), -1, jnp.int32),
              jnp.zeros((C,), dm.dtype))
        _, idx, dist = jax.lax.fori_loop(0, min(R, C), body, m0)
        if attrs.get("match_type", "bipartite") == "per_prediction":
            thr = attrs.get("dist_threshold", 0.5)
            best_r = jnp.argmax(dm, axis=0)
            best_d = jnp.max(dm, axis=0)
            fill = (idx < 0) & (best_d >= thr)
            idx = jnp.where(fill, best_r.astype(jnp.int32), idx)
            dist = jnp.where(fill, best_d, dist)
        return idx, dist

    idx, dist = jax.vmap(one)(d)
    return {"ColToRowMatchIndices": idx, "ColToRowMatchDist": dist}


@register_op("generate_proposals",
             inputs=["Scores", "BboxDeltas", "ImInfo", "Anchors",
                     "Variances"],
             outputs=["RpnRois", "RpnRoiProbs", "RpnRoisNum?"], grad=None)
def generate_proposals(ins, attrs, ctx):
    """generate_proposals_op.cc — RPN: decode anchor deltas, clip to the
    image, drop small boxes, top-pre_nms_topN by score, NMS to
    post_nms_topN.  Outputs are per-image fixed [N, post_nms_topN, ...]
    with RpnRoisNum giving the live count."""
    scores = ins["Scores"]          # [N, A, H, W]
    deltas = ins["BboxDeltas"]      # [N, A*4, H, W]
    im_info = ins["ImInfo"]         # [N, 3] (h, w, scale)
    anchors = ins["Anchors"].reshape(-1, 4)      # [H*W*A, 4]
    variances = ins["Variances"].reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = attrs.get("nms_thresh", 0.7)
    min_size = attrs.get("min_size", 0.1)
    N, A = scores.shape[0], scores.shape[1]
    HW = scores.shape[2] * scores.shape[3]
    K = A * HW

    def one(sc, dl, info):
        s = sc.transpose(1, 2, 0).reshape(-1)          # [H,W,A] -> flat
        d = dl.reshape(A, 4, *dl.shape[1:]).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        # decode (box_coder decode_center_size semantics)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + 0.5 * aw
        acy = anchors[:, 1] + 0.5 * ah
        cx = variances[:, 0] * d[:, 0] * aw + acx
        cy = variances[:, 1] * d[:, 1] * ah + acy
        w = jnp.exp(jnp.minimum(variances[:, 2] * d[:, 2], 10.0)) * aw
        h = jnp.exp(jnp.minimum(variances[:, 3] * d[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                           cx + 0.5 * w - 1, cy + 0.5 * h - 1], axis=1)
        # clip to image
        boxes = jnp.clip(boxes,
                         jnp.zeros((4,), boxes.dtype),
                         jnp.asarray([info[1] - 1, info[0] - 1,
                                      info[1] - 1, info[0] - 1],
                                     boxes.dtype))
        # filter small
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size * info[2]) &
                (boxes[:, 3] - boxes[:, 1] + 1 >= min_size * info[2]))
        s = jnp.where(keep, s, -1e30)
        k = min(pre_n, K)
        top_s, top_i = jax.lax.top_k(s, k)
        top_b = boxes[top_i]
        idx, kept = _nms_fixed(top_b, top_s, nms_thr, post_n, -1e29)
        rois = jnp.where(idx[:, None] >= 0, top_b[jnp.maximum(idx, 0)],
                         0.0)
        probs = jnp.maximum(kept, 0.0)
        return rois, probs, jnp.sum(idx >= 0).astype(jnp.int32)

    rois, probs, num = jax.vmap(one)(scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs[..., None],
            "RpnRoisNum": num}


@register_op("yolov3_loss",
             inputs=["X", "GTBox!", "GTLabel!", "GTScore?!"],
             outputs=["Loss", "ObjectnessMask?", "GTMatchMask?"])
def yolov3_loss(ins, attrs, ctx):
    """yolov3_loss_op.cc — per-cell anchor loss: coordinate SSE (x,y via
    sigmoid-BCE, w,h via L1), objectness BCE with ignore threshold, and
    per-class BCE.  GT boxes are padded rows of zeros (x2<=x1 -> dead)."""
    x = ins["X"]                    # [N, A*(5+C), H, W]
    gtbox = ins["GTBox"]            # [N, B, 4] (cx, cy, w, h; 0..1)
    gtlabel = ins["GTLabel"]        # [N, B]
    anchors = attrs.get("anchors", [])
    mask = attrs.get("anchor_mask", list(range(len(anchors) // 2)))
    class_num = int(attrs["class_num"])
    ignore_thresh = attrs.get("ignore_thresh", 0.7)
    downsample = attrs.get("downsample_ratio", 32)
    N, _, H, W = x.shape
    A = len(mask)
    anc = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)  # [total, 2]
    anc_m = anc[jnp.asarray(mask)]                           # [A, 2]
    in_h, in_w = H * downsample, W * downsample
    x = x.reshape(N, A, 5 + class_num, H, W)
    px, py = x[:, :, 0], x[:, :, 1]
    pw, ph = x[:, :, 2], x[:, :, 3]
    pobj = x[:, :, 4]
    pcls = x[:, :, 5:]

    gx = gtbox[..., 0] * W                      # [N, B] in grid units
    gy = gtbox[..., 1] * H
    gw = gtbox[..., 2] * in_w
    gh = gtbox[..., 3] * in_h
    valid = (gtbox[..., 2] > 0) & (gtbox[..., 3] > 0)
    gi = jnp.clip(gx.astype(jnp.int32), 0, W - 1)
    gj = jnp.clip(gy.astype(jnp.int32), 0, H - 1)

    # best anchor (over ALL anchors) per gt by shape IoU
    inter = jnp.minimum(gw[..., None], anc[:, 0]) * \
        jnp.minimum(gh[..., None], anc[:, 1])
    union = gw[..., None] * gh[..., None] + anc[:, 0] * anc[:, 1] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=-1)  # [N,B]
    # position of best anchor inside this level's mask (-1 if absent)
    mask_arr = jnp.asarray(mask)
    in_level = (best[..., None] == mask_arr).astype(jnp.int32)
    level_a = jnp.argmax(in_level, axis=-1)
    matched = valid & (in_level.sum(-1) > 0)

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    B = gtbox.shape[1]
    bidx = jnp.arange(N)[:, None].repeat(B, 1)
    scale = 2.0 - gtbox[..., 2] * gtbox[..., 3]  # box size weighting
    # mixup sample weights (yolov3_loss_op.cc GTScore input)
    gtscore = ins.get("GTScore")
    if gtscore is not None:
        scale = scale * gtscore.reshape(scale.shape).astype(x.dtype)

    tx = gx - gi
    ty = gy - gj
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(anc_m[level_a, 0], 1e-10),
                             1e-10))
    th = jnp.log(jnp.maximum(gh / jnp.maximum(anc_m[level_a, 1], 1e-10),
                             1e-10))
    sel = (bidx, level_a, gj, gi)
    m = matched.astype(x.dtype) * scale
    loss_xy = (bce(px[sel], tx) + bce(py[sel], ty)) * m
    loss_wh = (jnp.abs(pw[sel] - tw) + jnp.abs(ph[sel] - th)) * m

    # objectness: positive at matched cells; negatives everywhere the
    # predicted box does not overlap any gt above ignore_thresh
    obj_target = jnp.zeros((N, A, H, W), x.dtype)
    pos_w = matched.astype(x.dtype)
    if gtscore is not None:
        pos_w = pos_w * gtscore.reshape(pos_w.shape).astype(x.dtype)
    obj_target = obj_target.at[sel].max(pos_w)
    # predicted boxes for ignore-region computation
    cgx = (jax.nn.sigmoid(px) +
           jnp.arange(W, dtype=x.dtype)) / W            # [N,A,H,W]
    cgy = (jax.nn.sigmoid(py) +
           jnp.arange(H, dtype=x.dtype)[:, None]) / H
    cw = jnp.exp(jnp.clip(pw, -10, 10)) * anc_m[:, 0][None, :, None, None] \
        / in_w
    chh = jnp.exp(jnp.clip(ph, -10, 10)) * anc_m[:, 1][None, :, None, None]\
        / in_h

    def pred_gt_iou(cgx, cgy, cw, chh, gt, gtv):
        # centers/sizes in 0..1; gt [B,4]
        px1, py1 = cgx - cw / 2, cgy - chh / 2
        px2, py2 = cgx + cw / 2, cgy + chh / 2
        gx1 = gt[:, 0] - gt[:, 2] / 2
        gy1 = gt[:, 1] - gt[:, 3] / 2
        gx2 = gt[:, 0] + gt[:, 2] / 2
        gy2 = gt[:, 1] + gt[:, 3] / 2
        ix = jnp.maximum(
            jnp.minimum(px2[..., None], gx2) -
            jnp.maximum(px1[..., None], gx1), 0)
        iy = jnp.maximum(
            jnp.minimum(py2[..., None], gy2) -
            jnp.maximum(py1[..., None], gy1), 0)
        inter = ix * iy
        union = (px2 - px1) * (py2 - py1)
        union = union[..., None] + gt[:, 2] * gt[:, 3] - inter
        iou = inter / jnp.maximum(union, 1e-10)
        return jnp.max(jnp.where(gtv, iou, 0.0), axis=-1)

    best_iou = jax.vmap(pred_gt_iou)(cgx, cgy, cw, chh, gtbox, valid)
    noobj = (best_iou < ignore_thresh) & (obj_target < 0.5)
    loss_obj = bce(pobj, obj_target) * \
        (obj_target + noobj.astype(x.dtype))

    cls_t = jax.nn.one_hot(jnp.clip(gtlabel, 0, class_num - 1), class_num,
                           dtype=x.dtype)
    if attrs.get("use_label_smooth", False):
        # yolov3_loss_op.h label_pos/label_neg smoothing
        delta = min(1.0 / class_num, 1.0 / 40.0)
        cls_t = cls_t * (1.0 - delta) + (1.0 - cls_t) * delta
    pc = pcls[bidx, level_a, :, gj, gi]     # [N, B, C]
    cls_w = matched.astype(x.dtype)
    if gtscore is not None:
        cls_w = cls_w * gtscore.reshape(cls_w.shape).astype(x.dtype)
    loss_cls = jnp.sum(bce(pc, cls_t), -1) * cls_w

    loss = (loss_xy.sum(-1) + loss_wh.sum(-1) + loss_cls.sum(-1)
            + loss_obj.sum((1, 2, 3)))
    return {"Loss": loss,
            "ObjectnessMask": obj_target,
            "GTMatchMask": matched.astype(jnp.int32)}


@register_op("density_prior_box", inputs=["Input!", "Image!"],
             outputs=["Boxes", "Variances"], grad=None)
def density_prior_box(ins, attrs, ctx):
    """density_prior_box_op.h:23 — dense anchors from fixed sizes/ratios/
    densities per feature-map cell.  Pure function of STATIC shapes +
    attrs, so the grid is computed trace-time in numpy and lands in the
    program as a constant (XLA folds it)."""
    import numpy as np
    feat, img = ins["Input"], ins["Image"]
    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    fixed_sizes = list(attrs.get("fixed_sizes", []))
    fixed_ratios = list(attrs.get("fixed_ratios", []))
    densities = [int(d) for d in attrs.get("densities", [])]
    variances = list(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]))
    clip = bool(attrs.get("clip", False))
    offset = float(attrs.get("offset", 0.5))
    step_w = float(attrs.get("step_w", 0.0))
    step_h = float(attrs.get("step_h", 0.0))
    if len(fixed_sizes) != len(densities):
        raise ValueError(
            f"density_prior_box: fixed_sizes ({len(fixed_sizes)}) and "
            f"densities ({len(densities)}) must pair up one-to-one")
    sw = step_w or iw / fw
    sh = step_h or ih / fh
    step_avg = int((sw + sh) * 0.5)

    # per-cell relative layout is identical across the grid: build it once
    # [P, 4] = (dx, dy, bw, bh), then broadcast-add the center grid
    rel = []
    for size, density in zip(fixed_sizes, densities):
        shift = int(step_avg / density)
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            for di in range(density):
                for dj in range(density):
                    rel.append((-step_avg / 2.0 + shift / 2.0 + dj * shift,
                                -step_avg / 2.0 + shift / 2.0 + di * shift,
                                bw, bh))
    rel = np.asarray(rel, np.float32)            # [P, 4]
    cx = ((np.arange(fw) + offset) * sw).astype(np.float32)   # [W]
    cy = ((np.arange(fh) + offset) * sh).astype(np.float32)   # [H]
    x = cx[None, :, None] + rel[None, None, :, 0]  # [1, W, P]
    y = cy[:, None, None] + rel[None, None, :, 1]  # [H, 1, P]
    x = np.broadcast_to(x, (fh, fw, rel.shape[0]))
    y = np.broadcast_to(y, (fh, fw, rel.shape[0]))
    bw = rel[None, None, :, 2]
    bh = rel[None, None, :, 3]
    boxes = np.stack([
        np.maximum((x - bw / 2.0) / iw, 0.0),
        np.maximum((y - bh / 2.0) / ih, 0.0),
        np.minimum((x + bw / 2.0) / iw, 1.0),
        np.minimum((y + bh / 2.0) / ih, 1.0)], axis=-1).astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    vars_ = np.tile(np.asarray(variances, np.float32),
                    (fh, fw, rel.shape[0], 1))
    return {"Boxes": jnp.asarray(boxes), "Variances": jnp.asarray(vars_)}

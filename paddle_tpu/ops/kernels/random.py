"""Random ops (reference: uniform_random_op.cc, gaussian_random_op.cc,
truncated_gaussian_random_op.cc, bernoulli_op, randint_op, randperm_op).
All keys come from the counter-based OpContext PRNG (Philox under JAX) so
static-graph replays are reproducible per (seed, op_uid)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import register_op
from ...core.dtype import np_dtype


def _shape(ins, attrs):
    return tuple(attrs.get("shape", []))


@register_op("uniform_random", inputs=["ShapeTensor?!"], outputs=["Out"],
             grad=None)
def uniform_random(ins, attrs, ctx):
    dt = np_dtype(attrs.get("dtype", "float32"))
    out = jax.random.uniform(ctx.key(attrs), _shape(ins, attrs),
                             jnp.float32,
                             attrs.get("min", -1.0), attrs.get("max", 1.0))
    return {"Out": out.astype(dt)}


@register_op("uniform_random_batch_size_like", inputs=["Input!"],
             outputs=["Out"], grad=None)
def uniform_random_batch_size_like(ins, attrs, ctx):
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ins["Input"].shape[attrs.get("input_dim_idx", 0)]
    out = jax.random.uniform(ctx.key(attrs), tuple(shape), jnp.float32,
                             attrs.get("min", -1.0), attrs.get("max", 1.0))
    return {"Out": out.astype(np_dtype(attrs.get("dtype", "float32")))}


@register_op("gaussian_random", inputs=["ShapeTensor?!"], outputs=["Out"],
             grad=None)
def gaussian_random(ins, attrs, ctx):
    dt = np_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.normal(ctx.key(attrs), _shape(ins, attrs), jnp.float32)
    return {"Out": out.astype(dt)}


@register_op("truncated_gaussian_random", inputs=[], outputs=["Out"],
             grad=None)
def truncated_gaussian_random(ins, attrs, ctx):
    dt = np_dtype(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * \
        jax.random.truncated_normal(ctx.key(attrs), -2.0, 2.0,
                                    _shape(ins, attrs), jnp.float32)
    return {"Out": out.astype(dt)}


@register_op("bernoulli", inputs=["X"], outputs=["Out"], grad=None)
def bernoulli(ins, attrs, ctx):
    x = ins["X"]
    return {"Out": jax.random.bernoulli(ctx.key(attrs), x).astype(x.dtype)}


@register_op("randint", inputs=[], outputs=["Out"], grad=None)
def randint(ins, attrs, ctx):
    dt = np_dtype(attrs.get("dtype", "int64"))
    out = jax.random.randint(ctx.key(attrs), tuple(attrs["shape"]),
                             attrs.get("low", 0), attrs.get("high", 100))
    return {"Out": out.astype(dt)}


@register_op("randperm", inputs=[], outputs=["Out"], grad=None)
def randperm(ins, attrs, ctx):
    n = attrs["n"]
    dt = np_dtype(attrs.get("dtype", "int64"))
    return {"Out": jax.random.permutation(ctx.key(attrs), n).astype(dt)}


@register_op("sampling_id", inputs=["X!"], outputs=["Out"], grad=None)
def sampling_id(ins, attrs, ctx):
    x = ins["X"]
    ids = jax.random.categorical(ctx.key(attrs), jnp.log(x + 1e-12), axis=-1)
    return {"Out": ids.astype(jnp.int64)}


@register_op("multinomial", inputs=["X!"], outputs=["Out"], grad=None)
def multinomial(ins, attrs, ctx):
    x = ins["X"]
    num = attrs.get("num_samples", 1)
    logits = jnp.log(x + 1e-12)
    keys = jax.random.split(ctx.key(attrs), num)
    samples = jnp.stack([jax.random.categorical(k, logits, axis=-1)
                         for k in keys], axis=-1)
    return {"Out": samples.astype(jnp.int64)}


@register_op("seed", inputs=[], outputs=["Out"], grad=None, side_effect=True)
def seed_op(ins, attrs, ctx):
    return {"Out": jnp.asarray([attrs.get("seed", 0)], jnp.int32)}


@register_op("random_crop", inputs=["X", "Seed!"], outputs=["Out", "SeedOut"],
             grad=None)
def random_crop(ins, attrs, ctx):
    x = ins["X"]
    shape = attrs["shape"]  # crop shape for trailing dims
    key = ctx.key(attrs)
    starts = []
    for i, s in enumerate(shape):
        dim = x.shape[x.ndim - len(shape) + i]
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, dim - s + 1))
    sl = [slice(None)] * (x.ndim - len(shape))
    out = jax.lax.dynamic_slice(
        x, [0] * (x.ndim - len(shape)) + starts,
        list(x.shape[:x.ndim - len(shape)]) + list(shape))
    return {"Out": out, "SeedOut": ins["Seed"]}

"""paddle.compat (reference python/paddle/compat.py): py2/3 text
helpers kept for ported-code parity."""
from __future__ import annotations

import builtins
import math

__all__ = ["long_type", "to_text", "to_bytes", "round",
           "floor_division", "get_exception_message"]

long_type = int


def _map(obj, f, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_map(o, f, inplace) for o in obj]
            return obj
        return [_map(o, f, inplace) for o in obj]
    if isinstance(obj, set):
        new = {_map(o, f, inplace) for o in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    return f(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes -> str recursively over lists/sets (compat.py:36)."""

    def conv(o):
        if isinstance(o, bytes):
            return o.decode(encoding)
        return o

    return _map(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    def conv(o):
        if isinstance(o, str):
            return o.encode(encoding)
        return o

    return _map(obj, conv, inplace)


def round(x, d=0):
    """Python-2-style half-away-from-zero rounding (compat.py:193)."""
    if x == 0.0:
        return 0.0
    p = 10 ** d
    if x >= 0:
        return float(math.floor((x * p) + 0.5)) / p
    return float(math.ceil((x * p) - 0.5)) / p


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)

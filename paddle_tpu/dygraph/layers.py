"""nn.Layer — the dygraph module base class.

Analog of /root/reference/python/paddle/fluid/dygraph/layers.py:1 Layer
(parameters/sublayers/hooks/state_dict) with ParamBase
(/root/reference/python/paddle/fluid/framework.py:5169).

Parameters are eager Tensors materialised by running the SAME initializer
ops the static path would append to a startup program — a throwaway block is
built and interpreted, so init numerics are identical between modes.
"""
from __future__ import annotations

import collections
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.program import Program, program_guard, unique_name
from ..core.dtype import convert_dtype
from ..core.generator import global_seed, next_eager_uid
from ..ops.registry import OpContext
from ..static.initializer import (Initializer, Constant, Uniform,
                                  XavierInitializer)
from ..static.param_attr import ParamAttr
from .base import in_dygraph_mode
from .tensor import Tensor

__all__ = ["Layer", "Sequential", "LayerList", "ParameterList", "ParamBase"]


class ParamBase(Tensor):
    """A trainable parameter tensor (framework.py:5169 ParamBase)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip")

    def __init__(self, value, name=None, trainable=True, regularizer=None,
                 need_clip=True):
        super().__init__(value, stop_gradient=not trainable, name=name,
                         persistable=True, trainable=trainable)
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = regularizer
        self.need_clip = need_clip

    def __repr__(self):
        return "Parameter " + super().__repr__()


def materialize_initializer(init: Initializer, shape, dtype="float32",
                            name=None) -> np.ndarray:
    """Run an initializer's op eagerly and return the value — shares kernels
    with the startup-program path so eager/static init match exactly."""
    from ..static.executor import BlockTracer
    prog = Program()
    prog.random_seed = global_seed()
    with program_guard(prog, prog):
        var = prog.global_block().create_var(
            name=name or unique_name("param_init"), shape=shape, dtype=dtype,
            persistable=True)
        init(var, prog.global_block())
    env = {}
    # fold a fresh uid so two layers built in a row get different samples
    ctx = OpContext(seed=global_seed() + next_eager_uid())
    BlockTracer(prog.global_block()).run(env, ctx)
    return env[var.name]


class HookRemoveHelper:
    def __init__(self, hooks, idx):
        self._hooks, self._idx = hooks, idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    """Base network module."""

    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = convert_dtype(dtype) if dtype else "float32"
        self._full_name = unique_name(
            name_scope or type(self).__name__.lower())
        self._parameters: "collections.OrderedDict[str, ParamBase]" = \
            collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = \
            collections.OrderedDict()
        self._buffers: "collections.OrderedDict[str, Tensor]" = \
            collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # -- naming -------------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- parameter creation -------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> ParamBase:
        dtype = convert_dtype(dtype or self._dtype)
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = (attr.initializer if attr and attr.initializer is not None
                else default_initializer)
        if init is None:
            init = Constant(0.0) if is_bias else XavierInitializer()
        name = (attr.name if attr and attr.name
                else unique_name(self._full_name + ".w"))
        value = materialize_initializer(init, shape, dtype, name)
        p = ParamBase(value, name=name,
                      trainable=(attr.trainable if attr else True),
                      regularizer=(attr.regularizer if attr else None),
                      need_clip=(attr.need_clip if attr else True))
        if attr and attr.learning_rate != 1.0:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        return p

    def create_variable(self, name=None, persistable=False, dtype=None):
        return Tensor(np.zeros([0], dtype=np_like(dtype or self._dtype)),
                      name=name or unique_name(self._full_name + ".var"),
                      persistable=persistable)

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_parameter(self, name, parameter) -> ParamBase:
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer) -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # -- attribute magic ----------------------------------------------------
    def _drop_from_stores(self, name, keep=None):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            if store == keep:
                continue
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]

    def __setattr__(self, name, value):
        if isinstance(value, ParamBase):
            if not hasattr(self, "_parameters"):
                raise RuntimeError("call Layer.__init__ first")
            self._drop_from_stores(name, keep="_parameters")
            self._parameters[name] = value
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            self._drop_from_stores(name, keep="_sub_layers")
            self._sub_layers[name] = value
            object.__setattr__(self, name, value)
        else:
            buffers = self.__dict__.get("_buffers")
            if buffers is not None and name in buffers and \
                    isinstance(value, Tensor):
                # `self.x = self.register_buffer("x", t)` (and later
                # re-assignments of a registered buffer) update the buffer
                # store rather than unregistering it
                buffers[name] = value
            else:
                # reassigning a former parameter/sublayer/buffer slot to
                # None or a plain value drops the stale registry entry
                self._drop_from_stores(name)
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True) -> List[ParamBase]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, lay in self.named_sublayers(prefix=prefix,
                                              include_self=True):
            if not include_sublayers and lay is not self:
                continue
            for pname, p in lay._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        seen = set()
        stack: List[Tuple[str, Layer]] = [(prefix, self)]
        first = True
        while stack:
            name, lay = stack.pop(0)
            if id(lay) in seen:
                continue
            seen.add(id(lay))
            if include_self or not first:
                yield name, lay
            first = False
            for cname, child in lay._sub_layers.items():
                if child is None:
                    continue
                stack.append((name + "." + cname if name else cname, child))

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True,
                      persistable_only=False):
        for name, lay in self.named_sublayers(prefix=prefix,
                                              include_self=True):
            if not include_sublayers and lay is not self:
                continue
            for bname, b in lay._buffers.items():
                if b is None:
                    continue
                if persistable_only and \
                        bname in lay._non_persistable_buffer_names:
                    continue
                yield (name + "." + bname if name else bname), b

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ---------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers,
                persistable_only=True):
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                val = state_dict[name]
                t.set_value(val.numpy() if isinstance(val, Tensor)
                            else np.asarray(val))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = convert_dtype(dtype)
            for p in self.parameters():
                p.set_value(p.numpy().astype(np_like(dtype)))
            self._dtype = dtype
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = [f"{type(self).__name__}({extra}"]
        for name, child in self.named_children():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        return ("\n".join(lines) + ")") if len(lines) > 1 else lines[0] + ")"


def np_like(dtype):
    from ..core.dtype import np_dtype
    return np_dtype(convert_dtype(dtype))


class Sequential(Layer):
    """nn.Sequential — accepts layers or (name, layer) tuples."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], (list, tuple)):
            layers = layers[0]
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                self.add_sublayer(str(l[0]), l[1])
            else:
                self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        n = len(self._sub_layers)
        if not -n <= idx < n:
            raise IndexError(
                f"index {idx} out of range for LayerList of length {n}")
        return self._sub_layers[str(idx % n)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def extend(self, layers):
        for l in layers:
            self.append(l)
        return self

    def insert(self, index, layer):
        items = list(self._sub_layers.values())
        items.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(items):
            self._sub_layers[str(i)] = l


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self)), parameter)
        return self

"""Dygraph mode switching & autograd guards.

Analog of the reference's dygraph mode machinery
(/root/reference/python/paddle/fluid/framework.py:181 in_dygraph_mode,
 fluid/dygraph/base.py guard/enabled/no_grad, imperative/tracer.cc:50).

paddle 2.0 semantics: dynamic mode is ON by default; `enable_static()`
switches to graph building.  Static-API calls (`paddle_tpu.static.*`) always
build programs regardless of this flag — the flag only steers the dual-mode
`paddle_tpu.tensor` / `paddle_tpu.nn.functional` surface.
"""
from __future__ import annotations

import contextlib
import functools
import threading

__all__ = [
    "enabled", "in_dygraph_mode", "in_dynamic_mode", "enable_dygraph",
    "disable_dygraph", "enable_static", "disable_static", "guard",
    "no_grad", "enable_grad", "is_grad_enabled", "set_grad_enabled",
    "grad_scope",
]


class _Mode(threading.local):
    def __init__(self):
        self.dygraph = True       # paddle 2.0 default: imperative
        self.grad_enabled = True


_mode = _Mode()


def in_dygraph_mode() -> bool:
    return _mode.dygraph


in_dynamic_mode = in_dygraph_mode
enabled = in_dygraph_mode


def enable_dygraph(place=None):
    _mode.dygraph = True
    if place is not None:
        from ..core.place import set_device
        set_device(place)


def disable_dygraph():
    _mode.dygraph = False


def enable_static():
    _mode.dygraph = False


def disable_static(place=None):
    enable_dygraph(place)


@contextlib.contextmanager
def guard(place=None):
    """fluid.dygraph.guard — run a `with` body in dygraph mode."""
    prev = _mode.dygraph
    _mode.dygraph = True
    try:
        yield
    finally:
        _mode.dygraph = prev


# ---------------------------------------------------------------------------
# grad guards (imperative has_grad / paddle.no_grad)
# ---------------------------------------------------------------------------
def is_grad_enabled() -> bool:
    return _mode.grad_enabled


def set_grad_enabled(flag: bool):
    class _Guard:
        def __init__(self):
            self.prev = _mode.grad_enabled
            _mode.grad_enabled = bool(flag)

        def __enter__(self):
            return self

        def __exit__(self, *a):
            _mode.grad_enabled = self.prev

    return _Guard()


class no_grad:
    """Context manager AND decorator disabling tape recording
    (fluid/dygraph/base.py no_grad)."""

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper

    def __enter__(self):
        self._prev = _mode.grad_enabled
        _mode.grad_enabled = False
        return self

    def __exit__(self, *a):
        _mode.grad_enabled = self._prev


class enable_grad:
    def __enter__(self):
        self._prev = _mode.grad_enabled
        _mode.grad_enabled = True
        return self

    def __exit__(self, *a):
        _mode.grad_enabled = self._prev


@contextlib.contextmanager
def grad_scope(flag: bool):
    prev = _mode.grad_enabled
    _mode.grad_enabled = flag
    try:
        yield
    finally:
        _mode.grad_enabled = prev

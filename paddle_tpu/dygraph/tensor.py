"""Eager Tensor (VarBase analog) for dygraph mode.

Reference: /root/reference/paddle/fluid/imperative/layer.h:65 VarBase wrapping
VariableWrapper; python-side method patches in
/root/reference/python/paddle/fluid/dygraph/varbase_patch_methods.py and
math_op_patch.py.

TPU-native: the payload is a jax.Array living on the current expected place's
device; every op call runs the same traceable kernels as the static executor,
dispatched eagerly (JAX op-by-op dispatch is the eager runtime — there is no
separate kernel table, cf. prepared_operator.cc:69 kernel lookup in the
reference).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, np_dtype
from ..core.program import unique_name

__all__ = ["Tensor", "to_tensor", "to_variable"]


class Tensor:
    """Eager tensor with tape-based autograd."""

    __slots__ = ("_value", "stop_gradient", "persistable", "name", "grad_",
                 "_grad_node", "trainable", "_hooks", "__weakref__")

    def __init__(self, value, dtype=None, place=None, stop_gradient=True,
                 name=None, persistable=False, trainable=True):
        if isinstance(value, Tensor):
            value = value._value
        arr = jnp.asarray(value)
        if dtype is not None:
            want = np_dtype(convert_dtype(dtype))
            if arr.dtype != want:
                arr = arr.astype(want)
        elif not isinstance(value, jnp.ndarray) and \
                not hasattr(value, "dtype") and \
                arr.dtype == jnp.float32:
            # python floats/lists follow paddle.set_default_dtype; typed
            # inputs (numpy/jax arrays) keep their own dtype
            from ..core.dtype import get_default_dtype
            want = np_dtype(get_default_dtype())
            if arr.dtype != want:
                arr = arr.astype(want)
        if place is not None:
            dev = place.jax_device() if hasattr(place, "jax_device") else place
            arr = jax.device_put(arr, dev)
        self._value = arr
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self.name = name or unique_name("eager_tmp")
        self.grad_: Optional["Tensor"] = None
        self._grad_node = None  # GradNode that produced this tensor
        self._hooks = None      # list of grad hooks (register_hook)

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return convert_dtype(str(self._value.dtype))

    @property
    def place(self):
        from ..core.place import _current_expected_place
        return _current_expected_place()

    @property
    def grad(self):
        return self.grad_

    @grad.setter
    def grad(self, g):
        self.grad_ = g

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    def item(self):
        return self._value.item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._value.item())

    def __int__(self):
        return int(self._value.item())

    def __bool__(self):
        return bool(self._value.item())

    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        from .engine import run_backward
        run_backward(self, grad_tensor, retain_graph)

    def gradient(self):
        return None if self.grad_ is None else self.grad_.numpy()

    def clear_gradient(self):
        self.grad_ = None

    def clear_grad(self):
        self.grad_ = None

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True,
                   name=self.name + ".detach")
        return t

    def clone(self) -> "Tensor":
        from .tracer import trace_op
        return trace_op("assign", {"X": self}, {}, ["Out"])

    @property
    def is_leaf(self):
        return self._grad_node is None

    def register_hook(self, hook):
        from .engine import register_tensor_hook
        return register_tensor_hook(self, hook)

    # -- mutation (optimizers write in place) -------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._value
        arr = jnp.asarray(value)
        if arr.dtype != self._value.dtype:
            arr = arr.astype(self._value.dtype)
        self._value = arr

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def fill_(self, value):
        self._value = jnp.full_like(self._value, value)
        return self

    def zero_(self):
        self._value = jnp.zeros_like(self._value)
        return self

    def scale_(self, s):
        self._value = self._value * s
        return self

    # -- dtype / device sugar ----------------------------------------------
    def astype(self, dtype):
        from .tracer import trace_op
        return trace_op("cast", {"X": self},
                        {"out_dtype": convert_dtype(dtype)}, ["Out"])

    def cast(self, dtype):
        return self.astype(dtype)

    def cpu(self):
        return self

    def cuda(self, device_id=0):
        return self

    def pin_memory(self):
        return self

    # -- op sugar (math_op_patch parity) ------------------------------------
    def _op(self, type_, other=None, reverse=False, **attrs):
        from .tracer import trace_op
        ins = {"X": self}
        if other is not None:
            if not isinstance(other, Tensor):
                # use the device array's dtype directly — .numpy() would be a
                # full D2H transfer just to learn the dtype
                other = Tensor(jnp.asarray(other, dtype=self._value.dtype),
                               stop_gradient=True)
            ins = ({"X": other, "Y": self} if reverse
                   else {"X": self, "Y": other})
        return trace_op(type_, ins, attrs, ["Out"])

    def __add__(self, o):
        return self._op("elementwise_add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._op("elementwise_sub", o)

    def __rsub__(self, o):
        return self._op("elementwise_sub", o, reverse=True)

    def __mul__(self, o):
        return self._op("elementwise_mul", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._op("elementwise_div", o)

    def __rtruediv__(self, o):
        return self._op("elementwise_div", o, reverse=True)

    def __pow__(self, o):
        return self._op("elementwise_pow", o)

    def __mod__(self, o):
        return self._op("elementwise_mod", o)

    def __floordiv__(self, o):
        return self._op("elementwise_floordiv", o)

    def __matmul__(self, o):
        from .tracer import trace_op
        return trace_op("matmul", {"X": self, "Y": o}, {}, ["Out"])

    def __neg__(self):
        return self._op("scale", scale=-1.0, bias=0.0)

    def __abs__(self):
        return self._op("abs")

    def _cmp(self, type_, o):
        from .tracer import trace_op
        if not isinstance(o, Tensor):
            o = Tensor(jnp.asarray(o, dtype=self._value.dtype))
        return trace_op(type_, {"X": self, "Y": o}, {}, ["Out"])

    def __lt__(self, o):
        return self._cmp("less_than", o)

    def __le__(self, o):
        return self._cmp("less_equal", o)

    def __gt__(self, o):
        return self._cmp("greater_than", o)

    def __ge__(self, o):
        return self._cmp("greater_equal", o)

    def __eq__(self, o):
        if isinstance(o, (Tensor, int, float, np.ndarray)):
            return self._cmp("equal", o)
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (Tensor, int, float, np.ndarray)):
            return self._cmp("not_equal", o)
        return NotImplemented

    def __hash__(self):
        return id(self)

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        # slicing detaches nothing: route through jnp directly, recording a
        # generic slice via tracked op when grad is needed
        from .tracer import trace_jax
        return trace_jax(lambda v: v[idx], [self], f"getitem")

    def __setitem__(self, idx, value):
        if isinstance(value, Tensor):
            value = value._value
        self._value = self._value.at[idx].set(value)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # -- reductions/method sugar (subset; full set patched by tensor module)--
    def _reduce(self, type_, axis, keepdim):
        from .tracer import trace_op
        attrs = {"keep_dim": keepdim}
        if axis is None:
            attrs["reduce_all"] = True
            attrs["dim"] = [0]
        else:
            attrs["dim"] = [axis] if np.isscalar(axis) else list(axis)
        return trace_op(type_, {"X": self}, attrs, ["Out"])

    def sum(self, axis=None, keepdim=False):
        return self._reduce("reduce_sum", axis, keepdim)

    def mean(self, axis=None, keepdim=False):
        return self._reduce("reduce_mean", axis, keepdim)

    def max(self, axis=None, keepdim=False):
        return self._reduce("reduce_max", axis, keepdim)

    def min(self, axis=None, keepdim=False):
        return self._reduce("reduce_min", axis, keepdim)

    def prod(self, axis=None, keepdim=False):
        return self._reduce("reduce_prod", axis, keepdim)

    def reshape(self, shape):
        from .tracer import trace_op
        return trace_op("reshape2", {"X": self}, {"shape": list(shape)},
                        ["Out"])

    def transpose(self, perm):
        from .tracer import trace_op
        return trace_op("transpose2", {"X": self}, {"axis": list(perm)},
                        ["Out"])

    def flatten(self, start_axis=0, stop_axis=-1):
        shape = self.shape
        n = len(shape)
        stop = stop_axis % n
        start = start_axis % n
        new = shape[:start] + [-1] + shape[stop + 1:]
        return self.reshape(new)

    def squeeze(self, axis=None):
        from .tracer import trace_op
        axes = [] if axis is None else ([axis] if np.isscalar(axis) else list(axis))
        return trace_op("squeeze2", {"X": self}, {"axes": axes}, ["Out"])

    def unsqueeze(self, axis):
        from .tracer import trace_op
        axes = [axis] if np.isscalar(axis) else list(axis)
        return trace_op("unsqueeze2", {"X": self}, {"axes": axes}, ["Out"])

    def numel(self):
        return self.size

    def dim(self):
        return self.ndim

    def __repr__(self):
        grad_note = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}"
                f"{grad_note},\n       {np.asarray(self._value)!r})")


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """paddle.to_tensor."""
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)


def to_variable(value, name=None, zero_copy=None, dtype=None) -> Tensor:
    """fluid.dygraph.to_variable (legacy alias)."""
    return Tensor(value, dtype=dtype, name=name)

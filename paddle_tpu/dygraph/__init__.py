"""paddle_tpu.dygraph — imperative (eager) mode.

Analog of /root/reference/paddle/fluid/imperative/ (C20) +
python/paddle/fluid/dygraph/: eager Tensor over the shared kernel registry,
tape autograd engine, Layer module system.
"""
from .base import (  # noqa: F401
    enabled, guard, no_grad, enable_grad, in_dygraph_mode, in_dynamic_mode,
    enable_dygraph, disable_dygraph, enable_static, disable_static,
    is_grad_enabled, set_grad_enabled,
)
from .tensor import Tensor, to_tensor, to_variable  # noqa: F401
from .tracer import trace_op, trace_jax  # noqa: F401
from .engine import grad  # noqa: F401
from .layers import (  # noqa: F401
    Layer, Sequential, LayerList, ParameterList, ParamBase,
)
from ..jit import ProgramTranslator  # noqa: F401

"""Dygraph autograd engine.

Analog of /root/reference/paddle/fluid/imperative/basic_engine.cc:161
BasicEngine::Execute (reverse traversal with dep counts :124-155) and
partial_grad_engine.cc (`paddle.grad`).

TPU-native: traversal is a host-side reverse-topological walk; each grad op
dispatches the registered `<op>_grad` kernel eagerly (the same kernels the
static whole-block path traces).  Gradient accumulation is plain addition —
the reference's sorted-sum mode (FLAGS_sort_sum_gradient) is irrelevant
because jnp addition is deterministic.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..ops.registry import get_op_info, OpContext
from .tensor import Tensor
from .tracer import GradNode

__all__ = ["run_backward", "grad", "register_tensor_hook"]


def register_tensor_hook(tensor: Tensor, hook):
    if tensor._hooks is None:
        tensor._hooks = []
    tensor._hooks.append(hook)

    class _Handle:
        def remove(self, t=tensor, h=hook):
            t._hooks.remove(h)

    return _Handle()


def _topo_order(root_node: GradNode) -> List[GradNode]:
    """Reverse-postorder DFS over the consumer->producer graph = an order
    where every node appears before the producers of its inputs."""
    order, seen = [], set()
    stack = [(root_node, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.input_tensors():
            prod = t._grad_node
            if isinstance(prod, GradNode) and id(prod) not in seen:
                stack.append((prod, False))
    order.reverse()  # reverse postorder: consumers before producers
    return order


def _apply_hooks(t: Tensor, g):
    if t._hooks:
        for h in t._hooks:
            out = h(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else jnp.asarray(out)
    return g


class _GradMap:
    """id(tensor) -> accumulated raw grad, with tensor keepalive."""

    def __init__(self):
        self.vals: Dict[int, object] = {}
        self.keep: Dict[int, Tensor] = {}
        self.blocked: set = set()  # no_grad_vars: ids that absorb no grad

    def add(self, t: Tensor, g):
        if g is None or id(t) in self.blocked:
            return
        k = id(t)
        self.keep[k] = t
        cur = self.vals.get(k)
        self.vals[k] = g if cur is None else cur + g

    def get(self, t: Tensor):
        return self.vals.get(id(t))


def _node_grad_ins(node: GradNode, gmap: _GradMap):
    """Assemble the grad kernel's input dict per the registry convention:
    forward ins, forward outs, and <out>@GRAD cotangents."""
    info = get_op_info(node.op_type)
    ins = {}
    for slot in info.inputs:
        if node.amp_raws is not None and slot.name in node.amp_raws:
            # AMP forward consumed casted inputs; replay with the same
            # dtypes so the vjp's cotangent types line up
            ins[slot.name] = node.amp_raws[slot.name]
            continue
        v = node.ins.get(slot.name)
        if slot.duplicable:
            ins[slot.name] = [t._value if isinstance(t, Tensor) else t
                              for t in (v or [])]
        else:
            ins[slot.name] = v._value if isinstance(v, Tensor) else v
    for slot in info.outputs:
        ins[slot.name] = node.outs_raw.get(slot.name)
        ts = node.out_tensors.get(slot.name, [])
        if slot.duplicable:
            gs = [_final_grad(t, gmap) for t in ts]
            ins[slot.name + "@GRAD"] = gs
        else:
            ins[slot.name + "@GRAD"] = (_final_grad(ts[0], gmap)
                                        if ts else None)
    return ins, info


def _final_grad(t: Tensor, gmap: _GradMap):
    g = gmap.get(t)
    if g is not None:
        g = _apply_hooks(t, g)
    return g


def _run_node(node: GradNode, gmap: _GradMap):
    if node.vjp_fn is not None:  # trace_jax / to_static node
        ts = node.out_tensors["Out"]
        gs = [_final_grad(t, gmap) for t in ts]
        if all(g is None for g in gs):
            return
        if getattr(node, "vjp_multi", False):
            gs = [jnp.zeros_like(t._value) if g is None else g
                  for t, g in zip(ts, gs)]
            dins = node.vjp_fn(gs)
        else:
            dins = node.vjp_fn(gs[0])
        for t, d in zip(node.ins["X"], dins):
            if isinstance(t, Tensor) and not t.stop_gradient:
                gmap.add(t, d)
        return

    gtype = node.op_type + "_grad"
    ginfo = get_op_info(gtype)
    if ginfo is None:
        raise RuntimeError(f"no grad kernel for op {node.op_type!r}")
    ins, finfo = _node_grad_ins(node, gmap)
    ctx = OpContext(seed=node.seed)
    gouts = ginfo.kernel(ins, node.attrs, ctx)
    if not gouts:
        return
    for slot in finfo.inputs:
        if slot.no_grad:
            continue
        g = gouts.get(slot.name + "@GRAD")
        if g is None:
            continue
        v = node.ins.get(slot.name)
        if slot.duplicable:
            for t, gi in zip(v or [], g):
                if isinstance(t, Tensor) and not t.stop_gradient:
                    gmap.add(t, gi)
        elif isinstance(v, Tensor) and not v.stop_gradient:
            gmap.add(v, g)


def _seed_grad(root: Tensor, grad_tensor):
    if grad_tensor is None:
        return jnp.ones_like(root._value)
    return (grad_tensor._value if isinstance(grad_tensor, Tensor)
            else jnp.asarray(grad_tensor))


class _FreedGraph:
    """Sentinel replacing a root's GradNode after a non-retained backward."""


_FREED = _FreedGraph()


def run_backward(root: Tensor, grad_tensor=None, retain_graph=False):
    """tensor.backward(): accumulate grads into every reachable LEAF tensor
    with stop_gradient=False (paddle semantics: non-leaf grads are not
    retained)."""
    if root.stop_gradient:
        raise RuntimeError(
            "backward() on a tensor with stop_gradient=True")
    if root._grad_node is _FREED:
        raise RuntimeError(
            "backward() called twice on the same graph; pass "
            "retain_graph=True to the first call to allow this")
    gmap = _GradMap()
    gmap.add(root, _seed_grad(root, grad_tensor))
    if root._grad_node is not None:
        for node in _topo_order(root._grad_node):
            _run_node(node, gmap)
    # write back leaf grads (accumulating across backward calls)
    for k, t in gmap.keep.items():
        if t.stop_gradient or t._grad_node is not None:
            continue
        g = _apply_hooks(t, gmap.vals[k])
        if t.grad_ is None:
            t.grad_ = Tensor(g, stop_gradient=True, name=t.name + "@GRAD")
        else:
            t.grad_ = Tensor(t.grad_._value + g, stop_gradient=True,
                             name=t.name + "@GRAD")
    if not retain_graph and root._grad_node is not None:
        root._grad_node = _FREED


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — PartialGradEngine analog: return grads of `outputs`
    w.r.t. `inputs` without touching .grad."""
    if create_graph:
        raise NotImplementedError(
            "create_graph=True (double backward) is not supported by the "
            "tape engine yet; use jax.grad composition via the static path")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    for out in outputs:
        if out._grad_node is _FREED:
            raise RuntimeError(
                "grad(): the graph reaching this output was freed by a "
                "previous backward(); pass retain_graph=True to backward()")
    gmap = _GradMap()
    if no_grad_vars:
        gmap.blocked = {id(t) for t in no_grad_vars}
    for out, go in zip(outputs, grad_outputs):
        gmap.add(out, _seed_grad(out, go))
    # a virtual root over all outputs gives one globally-valid topo order
    # even when the roots' graphs share interior nodes
    virtual = GradNode("__root__", {"X": list(outputs)}, {}, {}, {}, 0)
    for node in _topo_order(virtual):
        if node is virtual:
            continue
        _run_node(node, gmap)

    results = []
    for t in inputs:
        g = gmap.get(t)
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {t.name} is unreachable from outputs "
                    "(set allow_unused=True to get None)")
            results.append(None)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results

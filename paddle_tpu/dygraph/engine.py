"""Dygraph autograd engine.

Analog of /root/reference/paddle/fluid/imperative/basic_engine.cc:161
BasicEngine::Execute (reverse traversal with dep counts :124-155) and
partial_grad_engine.cc (`paddle.grad`).

TPU-native: traversal is a host-side reverse-topological walk; each grad op
dispatches the registered `<op>_grad` kernel eagerly (the same kernels the
static whole-block path traces).  Gradient accumulation is plain addition —
the reference's sorted-sum mode (FLAGS_sort_sum_gradient) is irrelevant
because jnp addition is deterministic.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from ..core.generator import global_seed
from ..ops.registry import get_op_info, OpContext
from .tensor import Tensor
from .tracer import GradNode

__all__ = ["run_backward", "grad", "register_tensor_hook"]


def register_tensor_hook(tensor: Tensor, hook):
    if tensor._hooks is None:
        tensor._hooks = []
    tensor._hooks.append(hook)

    class _Handle:
        def remove(self, t=tensor, h=hook):
            t._hooks.remove(h)

    return _Handle()


def _topo_order(root_node: GradNode) -> List[GradNode]:
    """Reverse-postorder DFS over the consumer->producer graph = an order
    where every node appears before the producers of its inputs."""
    order, seen = [], set()
    stack = [(root_node, False)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.input_tensors():
            prod = t._grad_node
            if isinstance(prod, GradNode) and id(prod) not in seen:
                stack.append((prod, False))
    order.reverse()  # reverse postorder: consumers before producers
    return order


def _apply_hooks(t: Tensor, g):
    if t._hooks:
        for h in t._hooks:
            out = h(Tensor(g, stop_gradient=True))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else jnp.asarray(out)
    return g


class _GradMap:
    """id(tensor) -> accumulated raw grad, with tensor keepalive."""

    def __init__(self):
        self.vals: Dict[int, object] = {}
        self.keep: Dict[int, Tensor] = {}
        self.blocked: set = set()  # no_grad_vars: ids that absorb no grad

    def add(self, t: Tensor, g):
        if g is None or id(t) in self.blocked:
            return
        k = id(t)
        self.keep[k] = t
        cur = self.vals.get(k)
        self.vals[k] = g if cur is None else cur + g

    def get(self, t: Tensor):
        return self.vals.get(id(t))


def _node_grad_ins(node: GradNode, gmap: _GradMap):
    """Assemble the grad kernel's input dict per the registry convention:
    forward ins, forward outs, and <out>@GRAD cotangents."""
    info = get_op_info(node.op_type)
    ins = {}
    for slot in info.inputs:
        if node.amp_raws is not None and slot.name in node.amp_raws:
            # AMP forward consumed casted inputs; replay with the same
            # dtypes so the vjp's cotangent types line up
            ins[slot.name] = node.amp_raws[slot.name]
            continue
        v = node.ins.get(slot.name)
        if slot.duplicable:
            ins[slot.name] = [t._value if isinstance(t, Tensor) else t
                              for t in (v or [])]
        else:
            ins[slot.name] = v._value if isinstance(v, Tensor) else v
    for slot in info.outputs:
        ins[slot.name] = node.outs_raw.get(slot.name)
        ts = node.out_tensors.get(slot.name, [])
        if slot.duplicable:
            gs = [_final_grad(t, gmap) for t in ts]
            ins[slot.name + "@GRAD"] = gs
        else:
            ins[slot.name + "@GRAD"] = (_final_grad(ts[0], gmap)
                                        if ts else None)
    return ins, info


def _final_grad(t: Tensor, gmap: _GradMap):
    g = gmap.get(t)
    if g is not None:
        g = _apply_hooks(t, g)
    return g


def _run_node(node: GradNode, gmap: _GradMap):
    if node.vjp_fn is not None:  # trace_jax / to_static node
        ts = node.out_tensors["Out"]
        gs = [_final_grad(t, gmap) for t in ts]
        if all(g is None for g in gs):
            return
        if getattr(node, "vjp_multi", False):
            gs = [jnp.zeros_like(t._value) if g is None else g
                  for t, g in zip(ts, gs)]
            dins = node.vjp_fn(gs)
        else:
            dins = node.vjp_fn(gs[0])
        for t, d in zip(node.ins["X"], dins):
            if isinstance(t, Tensor) and not t.stop_gradient:
                gmap.add(t, d)
        return

    gtype = node.op_type + "_grad"
    ginfo = get_op_info(gtype)
    if ginfo is None:
        raise RuntimeError(f"no grad kernel for op {node.op_type!r}")
    ins, finfo = _node_grad_ins(node, gmap)
    ctx = OpContext(seed=node.seed)
    gouts = ginfo.kernel(ins, node.attrs, ctx)
    if not gouts:
        return
    for slot in finfo.inputs:
        if slot.no_grad:
            continue
        g = gouts.get(slot.name + "@GRAD")
        if g is None:
            continue
        v = node.ins.get(slot.name)
        if slot.duplicable:
            for t, gi in zip(v or [], g):
                if isinstance(t, Tensor) and not t.stop_gradient:
                    gmap.add(t, gi)
        elif isinstance(v, Tensor) and not v.stop_gradient:
            gmap.add(v, g)


def _seed_grad(root: Tensor, grad_tensor):
    if grad_tensor is None:
        return jnp.ones_like(root._value)
    return (grad_tensor._value if isinstance(grad_tensor, Tensor)
            else jnp.asarray(grad_tensor))


class _FreedGraph:
    """Sentinel replacing a root's GradNode after a non-retained backward."""


_FREED = _FreedGraph()


def run_backward(root: Tensor, grad_tensor=None, retain_graph=False):
    """tensor.backward(): accumulate grads into every reachable LEAF tensor
    with stop_gradient=False (paddle semantics: non-leaf grads are not
    retained)."""
    if root.stop_gradient:
        raise RuntimeError(
            "backward() on a tensor with stop_gradient=True")
    if root._grad_node is _FREED:
        raise RuntimeError(
            "backward() called twice on the same graph; pass "
            "retain_graph=True to the first call to allow this")
    gmap = _GradMap()
    gmap.add(root, _seed_grad(root, grad_tensor))
    if root._grad_node is not None:
        for node in _topo_order(root._grad_node):
            _run_node(node, gmap)
    # write back leaf grads (accumulating across backward calls)
    for k, t in gmap.keep.items():
        if t.stop_gradient or t._grad_node is not None:
            continue
        g = _apply_hooks(t, gmap.vals[k])
        if t.grad_ is None:
            t.grad_ = Tensor(g, stop_gradient=True, name=t.name + "@GRAD")
        else:
            t.grad_ = Tensor(t.grad_._value + g, stop_gradient=True,
                             name=t.name + "@GRAD")
    if not retain_graph and root._grad_node is not None:
        root._grad_node = _FREED


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — PartialGradEngine analog: return grads of `outputs`
    w.r.t. `inputs` without touching .grad.  create_graph=True returns
    grads that are themselves on the tape (double backward), implemented
    by replaying the recorded forward as a pure function and nesting
    jax.vjp (reference: imperative/partial_grad_engine.cc +
    the per-op DoubleGradMakers, e.g. operators/conv_op.cc)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused, no_grad_vars)

    for out in outputs:
        if out._grad_node is _FREED:
            raise RuntimeError(
                "grad(): the graph reaching this output was freed by a "
                "previous backward(); pass retain_graph=True to backward()")
    gmap = _GradMap()
    if no_grad_vars:
        gmap.blocked = {id(t) for t in no_grad_vars}
    for out, go in zip(outputs, grad_outputs):
        gmap.add(out, _seed_grad(out, go))
    # a virtual root over all outputs gives one globally-valid topo order
    # even when the roots' graphs share interior nodes
    virtual = GradNode("__root__", {"X": list(outputs)}, {}, {}, {}, 0)
    for node in _topo_order(virtual):
        if node is virtual:
            continue
        _run_node(node, gmap)

    results = []
    for t in inputs:
        g = gmap.get(t)
        if g is None:
            if not allow_unused:
                raise RuntimeError(
                    f"input {t.name} is unreachable from outputs "
                    "(set allow_unused=True to get None)")
            results.append(None)
        else:
            results.append(Tensor(g, stop_gradient=True))
    return results


# ---------------------------------------------------------------------------
# create_graph=True: replay the tape as a pure function, nest jax.vjp
# ---------------------------------------------------------------------------
def _replay_node(node: GradNode, env: Dict[int, object], blocked):
    """Re-execute one recorded forward op on (possibly traced) env values."""
    def val_of(t):
        if not isinstance(t, Tensor):
            return t
        if id(t) in blocked:
            return t._value  # no_grad_vars: sever the dependence
        return env.get(id(t), t._value)

    if node.vjp_fn is not None:
        fn = node.replay_fn
        if fn is None:
            raise NotImplementedError(
                f"create_graph over non-replayable node {node.op_type!r}")
        out = fn(*[val_of(t) for t in node.ins["X"]])
        ts = node.out_tensors["Out"]
        if node.vjp_multi:
            # multi-output vjp node (a previous create_graph grad): bind
            # every returned grad, not just the first
            for t, v in zip(ts, out):
                env[id(t)] = v
        else:
            env[id(ts[0])] = out
        return
    info = get_op_info(node.op_type)
    raw_ins = {}
    for slot in info.inputs:
        v = node.ins.get(slot.name)
        if slot.duplicable:
            raw_ins[slot.name] = [val_of(t) for t in (v or [])]
        else:
            raw_ins[slot.name] = val_of(v) if v is not None else None
    if node.amp_raws is not None:
        # forward consumed AMP-casted inputs; replay at the same dtypes
        for k, rv in node.amp_raws.items():
            cur = raw_ins.get(k)
            if cur is not None and hasattr(rv, "dtype") \
                    and hasattr(cur, "dtype") and cur.dtype != rv.dtype:
                raw_ins[k] = cur.astype(rv.dtype)
    outs = info.kernel(raw_ins, node.attrs, OpContext(seed=node.seed))
    for slot, ts in node.out_tensors.items():
        val = outs.get(slot) if outs else None
        if val is None:
            continue
        if isinstance(val, (list, tuple)):
            for t, v in zip(ts, val):
                env[id(t)] = v
        else:
            env[id(ts[0])] = val


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused,
                       no_grad_vars):
    import jax

    for out in outputs:
        if out._grad_node is _FREED:
            raise RuntimeError(
                "grad(): the graph reaching this output was freed by a "
                "previous backward(); pass retain_graph=True to backward()")
    virtual = GradNode("__root__", {"X": list(outputs)}, {}, {}, {}, 0)
    fwd_nodes = list(reversed(_topo_order(virtual)))  # producers first
    fwd_nodes = [n for n in fwd_nodes if n is not virtual]
    blocked = frozenset(id(t) for t in (no_grad_vars or []))

    # unused-input detection: an input is reachable iff some recorded node
    # consumes it (or it IS an output)
    consumed = {id(t) for n in fwd_nodes for t in n.input_tensors()}
    consumed |= {id(o) for o in outputs}
    unused = [t for t in inputs if id(t) not in consumed]
    if unused and not allow_unused:
        raise RuntimeError(
            f"input {unused[0].name} is unreachable from outputs "
            "(set allow_unused=True to get None)")
    used_inputs = [t for t in inputs if id(t) in consumed]
    in_ids = [id(t) for t in used_inputs]

    # cotangents: differentiable grad_outputs become extra diff arguments
    cot_tensors: List[Tensor] = []
    cot_spec = []  # None -> ones_like; ("const", raw); ("arg", idx)
    for go in grad_outputs:
        if go is None:
            cot_spec.append(None)
        elif isinstance(go, Tensor) and not go.stop_gradient:
            cot_spec.append(("arg", len(cot_tensors)))
            cot_tensors.append(go)
        else:
            raw = go._value if isinstance(go, Tensor) else jnp.asarray(go)
            cot_spec.append(("const", raw))

    # every other differentiable leaf of the subgraph (layer weights, ...)
    # must be a traced argument too — backward() on a function of the
    # returned grads has to reach them (gradient-penalty training)
    produced = {id(t) for n in fwd_nodes
                for ts in n.out_tensors.values() for t in ts}
    taken = set(in_ids) | {id(t) for t in cot_tensors} | set(blocked)
    leaf_extras: List[Tensor] = []
    for n in fwd_nodes:
        for t in n.input_tensors():
            if (id(t) in produced or id(t) in taken or t.stop_gradient
                    or not jnp.issubdtype(jnp.asarray(t._value).dtype,
                                          jnp.inexact)):
                continue
            taken.add(id(t))
            leaf_extras.append(t)
    extra_ids = [id(t) for t in leaf_extras]
    n_in, n_cot = len(used_inputs), len(cot_tensors)

    def replay(in_raws, extra_raws):
        env = dict(zip(in_ids, in_raws))
        env.update(zip(extra_ids, extra_raws))
        for node in fwd_nodes:
            _replay_node(node, env, blocked)
        return tuple(env.get(id(o), o._value) for o in outputs)

    def first_grads(*arg_raws):
        xs = arg_raws[:n_in]
        cot_args = arg_raws[n_in:n_in + n_cot]
        extras = arg_raws[n_in + n_cot:]
        outs, vjp = jax.vjp(lambda *a: replay(a, extras), *xs)
        cots = []
        for spec, o in zip(cot_spec, outs):
            if spec is None:
                cots.append(jnp.ones_like(o))
            elif spec[0] == "const":
                cots.append(spec[1].astype(o.dtype))
            else:
                cots.append(cot_args[spec[1]].astype(o.dtype))
        return vjp(tuple(cots))

    arg_tensors = list(used_inputs) + cot_tensors + leaf_extras
    out_raws, vjp_fn = jax.vjp(first_grads,
                               *[t._value for t in arg_tensors])

    # one multi-output tape node makes the first grads differentiable again
    node = GradNode("__vjp__:grad", {"X": arg_tensors}, {},
                    {"Out": out_raws}, {}, global_seed())
    node.vjp_fn = lambda gs: vjp_fn(tuple(gs))
    node.vjp_multi = True
    node.replay_fn = first_grads
    grads = []
    for r in out_raws:
        t = Tensor(r, stop_gradient=False)
        t._grad_node = node
        grads.append(t)
    node.out_tensors = {"Out": grads}

    results, gi = [], iter(grads)
    for t in inputs:
        results.append(None if id(t) not in consumed else next(gi))
    return results

"""Dygraph tracer: eager op dispatch + autograd graph capture.

Analog of /root/reference/paddle/fluid/imperative/tracer.cc:50 TraceOp —
run the kernel eagerly, then CreateGradOpNode (tracer.cc:104) records a node
into the reverse graph.  Kernel dispatch reuses the SAME registry as the
static executor (ops/registry.py), so eager and traced execution can never
diverge numerically (the reference guarantees this by sharing OpKernelType
dispatch, prepared_operator.cc:69).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.generator import global_seed, next_eager_uid
from ..ops.registry import get_op_info, OpContext
from .base import is_grad_enabled
from .tensor import Tensor

__all__ = ["trace_op", "trace_jax", "GradNode"]

# program capture hook (paddle_tpu.jit to_static): when set, every traced op
# is also mirrored into a Program (program_desc_tracer.cc analog)
_PROGRAM_RECORDER = None


class GradNode:
    """One recorded op in the reverse graph (OpBase/GradOpNode analog,
    imperative/layer.h)."""

    __slots__ = ("op_type", "ins", "attrs", "outs_raw", "out_tensors",
                 "seed", "vjp_fn", "n_vjp_inputs", "in_tensors_flat",
                 "amp_raws", "vjp_multi", "replay_fn")

    def __init__(self, op_type, ins, attrs, outs_raw, out_tensors, seed):
        self.op_type = op_type
        self.ins = ins                # slot -> Tensor | [Tensor] | None
        self.attrs = attrs
        self.outs_raw = outs_raw      # slot -> raw value(s) (for grad kernels)
        self.out_tensors = out_tensors  # slot -> [Tensor] (strong refs)
        self.seed = seed
        self.vjp_fn = None            # set for trace_jax nodes
        self.n_vjp_inputs = 0
        self.in_tensors_flat: List[Tensor] = []
        # AMP: the casted raw inputs the kernel actually consumed; backward
        # must replay with these so vjp dtypes match the forward trace
        self.amp_raws = None
        self.vjp_multi = False  # vjp_fn takes/returns multi-output tuples
        # pure fn for re-tracing this node (create_graph double backward)
        self.replay_fn = None

    def input_tensors(self) -> List[Tensor]:
        if self.in_tensors_flat:
            return self.in_tensors_flat
        out = []
        for v in self.ins.values():
            if isinstance(v, Tensor):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                out.extend(t for t in v if isinstance(t, Tensor))
        self.in_tensors_flat = out
        return out


def _raw(v):
    if isinstance(v, Tensor):
        return v._value
    if isinstance(v, (list, tuple)):
        return [_raw(x) for x in v]
    return v


def _requires_grad(ins) -> bool:
    for v in ins.values():
        if isinstance(v, Tensor) and not v.stop_gradient:
            return True
        if isinstance(v, (list, tuple)):
            if any(isinstance(t, Tensor) and not t.stop_gradient for t in v):
                return True
    return False


def trace_op(op_type: str, ins: Dict[str, Any], attrs: Dict[str, Any],
             out_slots: Sequence[str], n_outs: Optional[Dict[str, int]] = None):
    """Run one op eagerly; record a GradNode when grad is required.

    Returns a single Tensor if `out_slots` has one entry, else a tuple in
    slot order.  Duplicable output slots return lists.
    """
    info = get_op_info(op_type)
    if info is None:
        raise NotImplementedError(f"op {op_type!r} has no registered kernel")

    attrs = dict(attrs or {})
    attrs.setdefault("op_uid", next_eager_uid())
    seed = global_seed()
    ctx = OpContext(seed=seed)

    raw_ins = {}
    for slot in info.inputs:
        v = ins.get(slot.name)
        if slot.duplicable:
            raw_ins[slot.name] = [_raw(t) for t in (v or [])]
        else:
            raw_ins[slot.name] = _raw(v) if v is not None else None

    # dygraph AMP interception point (imperative/amp_auto_cast.cc analog)
    from ..amp.auto_cast import amp_state, amp_cast_inputs
    amp_casted = None
    if amp_state().enabled:
        casted = amp_cast_inputs(op_type, raw_ins)
        if casted is not raw_ins:
            amp_casted = casted
            raw_ins = casted

    outs = info.kernel(raw_ins, attrs, ctx)

    needs_grad = (is_grad_enabled() and info.has_grad and _requires_grad(ins))

    node = None
    out_tensors: Dict[str, List[Tensor]] = {}
    if needs_grad:
        node = GradNode(op_type, dict(ins), attrs, outs, out_tensors, seed)
        node.amp_raws = amp_casted

    results = []
    for slot_name in out_slots:
        slot = next((s for s in info.outputs if s.name == slot_name), None)
        val = outs.get(slot_name) if outs else None
        if slot is not None and slot.duplicable:
            ts = []
            for v in (val or []):
                t = Tensor(v, stop_gradient=not needs_grad)
                t._grad_node = node
                ts.append(t)
            out_tensors[slot_name] = ts
            results.append(ts)
        else:
            if val is None:
                results.append(None)
                continue
            sg = not needs_grad or not jnp.issubdtype(
                jnp.asarray(val).dtype, jnp.inexact)
            t = Tensor(val, stop_gradient=sg)
            if not sg:
                t._grad_node = node
            out_tensors[slot_name] = [t]
            results.append(t)

    if _PROGRAM_RECORDER is not None:
        _PROGRAM_RECORDER.record(op_type, ins, attrs, out_tensors)

    return results[0] if len(out_slots) == 1 else tuple(results)


def trace_jax(fn, in_tensors: List[Tensor], label: str = "jax_fn"):
    """Trace an arbitrary jax function of the given tensors (used for
    indexing and other sugar that has no named op)."""
    if _PROGRAM_RECORDER is not None:
        raise NotImplementedError(
            f"to_static cannot capture raw-jax operation {label!r} "
            "(tensor indexing sugar etc.) — use named layer/tensor ops "
            "in a traced forward")
    raws = [t._value for t in in_tensors]
    needs_grad = is_grad_enabled() and any(
        not t.stop_gradient for t in in_tensors)
    if not needs_grad:
        return Tensor(fn(*raws))
    out_raw, vjp_fn = jax.vjp(fn, *raws)
    t = Tensor(out_raw, stop_gradient=False)
    node = GradNode("__vjp__:" + label, {"X": list(in_tensors)}, {},
                    {"Out": out_raw}, {"Out": [t]}, global_seed())
    node.vjp_fn = vjp_fn
    node.n_vjp_inputs = len(in_tensors)
    node.replay_fn = fn
    t._grad_node = node
    return t

"""Pipeline parallelism (SURVEY.md §2.3 — PipelineTrainer/SectionWorker
analog, TPU-native GPipe over per-stage XLA computations)."""
from .pipeline_program import PipelineCompiledProgram, assign_stages  # noqa: F401
from .pipeline_optimizer import PipelineOptimizer  # noqa: F401

"""Pipeline-parallel program splitting + GPipe micro-batch scheduler.

Reference: the reference's pipeline stack —
  * program cut into per-device "sections" on the op_device attr
    (fluid PipelineOptimizer; trainer_desc.proto:66,86 section_param),
  * `PipelineTrainer` with one thread per section +
    microbatch_scopes_[section][microbatch] (framework/trainer.h:230-262),
  * `SectionWorker::TrainFiles` GPipe schedule: all-microbatch forward,
    all-microbatch backward, optimizer once
    (framework/section_worker.cc:82,109-178), condition-variable handoff
    between stages (:135-147).

TPU-native redesign: each (stage, phase) becomes ONE jitted XLA computation
pinned to its chip; the host scheduler replaces SectionWorker threads.
JAX's async dispatch gives the pipelining: the host enqueues stage s of
micro-batch m right after stage s-1's output future, so stage s runs
micro-batch m while stage s+1 still computes m-1 — the 1F1B/GPipe overlap
falls out of dispatch order without condition variables.  Activations stay
resident on their stage's chip; boundary tensors move over ICI via
device_put (the reference moved them through pinned-memory queues).
Gradients accumulate per stage across micro-batches (GPipe), the optimizer
phase runs once per mini-batch — matching SectionWorker's
forward*M / backward*M / optimize-once schedule exactly.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.program import Program, Block, OpDesc, OpRole
from ..ops.registry import OpContext
from ..static.executor import BlockTracer, _persistable_names

__all__ = ["PipelineCompiledProgram", "assign_stages"]

_DEV_RE = re.compile(r"^(?:gpu|xla|tpu|cpu|npu)?:?(\d+)$")


def _stage_of_device(dev: Optional[str]) -> Optional[int]:
    if not dev:
        return None
    m = _DEV_RE.match(str(dev))
    return int(m.group(1)) if m else None


def assign_stages(block: Block) -> List[int]:
    """Stage index per op: explicit op_device wins; otherwise the max stage
    of the producers of its inputs (boundary-crossing ops land downstream,
    like the reference's section cut).  A second, consumer-driven pass fixes
    unanchored source ops (no device, no produced inputs — e.g. the loss
    cotangent fill_constant seed): they move to the stage of their first
    consumer so each phase's dataflow stays self-contained."""
    producer: Dict[str, int] = {}
    stages: List[int] = []
    unanchored: List[int] = []
    for i, op in enumerate(block.ops):
        s = _stage_of_device(op.attrs.get("op_device"))
        if s is None:
            ins = [producer.get(n) for n in op.input_names()]
            ins = [x for x in ins if x is not None]
            if ins:
                s = max(ins)
            else:
                s = 0
                unanchored.append(i)
        stages.append(s)
        for n in op.output_names():
            producer[n] = s

    if unanchored:
        consumer_stage: Dict[str, int] = {}
        for op, s in zip(block.ops, stages):
            for n in op.input_names():
                consumer_stage[n] = max(consumer_stage.get(n, 0), s)
        for i in unanchored:
            outs = block.ops[i].output_names()
            cs = [consumer_stage[n] for n in outs if n in consumer_stage]
            if cs:
                stages[i] = max(cs)
    return stages


class _Phase:
    """One (stage, role) slice of the program = one jitted computation,
    pinned to its stage's chip (device_put moves boundary tensors over ICI;
    no-op for values already resident)."""

    def __init__(self, block: Block, ops: List[OpDesc], device=None):
        self.device = device
        self.ops = ops
        written: set = set()
        reads: List[str] = []
        for op in ops:
            for n in op.input_names():
                if n not in written and n not in reads:
                    reads.append(n)
            written.update(op.output_names())
        self.in_names = reads
        self.out_names = [n for n in dict.fromkeys(
            n for op in ops for n in op.output_names())]
        self._tracer = BlockTracer(block)
        self._jitted = None

    def __bool__(self):
        return bool(self.ops)

    def compile(self):
        if self._jitted is not None or not self.ops:
            return
        tracer, in_names, out_names, ops = \
            self._tracer, self.in_names, self.out_names, self.ops

        def fn(env_in, seed):
            env = dict(env_in)
            ctx = OpContext(seed=seed)
            tracer.run(env, ctx, ops=ops)
            return {n: env[n] for n in out_names}

        self._jitted = jax.jit(fn)

    def run(self, env: Dict[str, Any], seed) -> Dict[str, Any]:
        """Consume inputs from `env`, merge outputs back into it."""
        if not self.ops:
            return env
        self.compile()
        ins = {n: env[n] for n in self.in_names if n in env}
        if self.device is not None:
            ins = {n: jax.device_put(v, self.device)
                   for n, v in ins.items()}
        outs = self._jitted(ins, seed)
        env.update(outs)
        return env


def _role_phase(op) -> str:
    role = op.attrs.get(OpRole.KEY, OpRole.Forward)
    if role & OpRole.Optimize or role == OpRole.LRSched:
        return "opt"
    if role & OpRole.Backward:
        return "bwd"
    return "fwd"


class PipelineCompiledProgram:
    """The runnable pipeline: pass to exe.run like a CompiledProgram.

    Built by PipelineOptimizer.minimize.  `num_microbatches` (M) splits the
    fed mini-batch along dim 0; grads accumulate over M then the optimizer
    phase commits once (reference section_worker.cc:166-178).
    """

    def __init__(self, program: Program, num_microbatches: int,
                 params_grads, devices=None):
        self._program = program
        self._M = max(1, int(num_microbatches))
        self._grad_names = [g.name for _, g in (params_grads or [])]
        self._devices = devices
        self._built = False

    # -- build ---------------------------------------------------------------
    def _build(self):
        if self._built:
            return
        block = self._program.global_block()
        stages = assign_stages(block)
        self._n_stages = max(stages) + 1 if stages else 1
        devs = self._devices or jax.devices()
        if len(devs) < self._n_stages:
            # fewer chips than stages: wrap (valid for CPU-mesh testing)
            devs = [devs[i % len(devs)] for i in range(self._n_stages)]
        self._stage_devices = list(devs[: self._n_stages])

        # (stage, phase) op lists, program order preserved
        self._phases: Dict[str, List[_Phase]] = {"fwd": [], "bwd": [],
                                                 "opt": []}
        for s in range(self._n_stages):
            for ph in ("fwd", "bwd", "opt"):
                ops = [op for op, st in zip(block.ops, stages)
                       if st == s and _role_phase(op) == ph
                       and op.type not in ("feed", "fetch")]
                self._phases[ph].append(
                    _Phase(block, ops, self._stage_devices[s]))
        self._built = True

    # -- run -----------------------------------------------------------------
    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        from ..static.executor import global_scope
        self._build()
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in (fetch_list or [])]
        M = self._M
        block = self._program.global_block()

        # split the fed mini-batch into micro-batches along dim 0
        micro_feeds: List[Dict[str, Any]] = [dict() for _ in range(M)]
        micro_batch_size = None
        for name, val in feed.items():
            arr = jnp.asarray(val)
            try:
                want = block.var(name).dtype
                if want is not None and str(arr.dtype) != want:
                    from ..core.dtype import np_dtype
                    arr = arr.astype(np_dtype(want))
            except KeyError:
                pass
            if arr.shape[0] % M != 0:
                raise ValueError(
                    f"batch dim {arr.shape[0]} of feed {name!r} not "
                    f"divisible by num_microbatches={M}")
            mb = arr.shape[0] // M
            micro_batch_size = mb
            for m in range(M):
                micro_feeds[m][name] = arr[m * mb:(m + 1) * mb]

        state = {n: scope.get(n) for n in _persistable_names(self._program)
                 if scope.get(n) is not None}
        seed = jnp.uint32(executor._seed_for_step(self._program))
        executor._step += 1

        # GPipe: forward for every micro-batch (async dispatch pipelines
        # stage s of micro-batch m with stage s+1 of m-1)
        envs: List[Dict[str, Any]] = []
        for m in range(M):
            env = dict(state)
            env.update(micro_feeds[m])
            for s in range(self._n_stages):
                self._phases["fwd"][s].run(env, seed + jnp.uint32(m))
            envs.append(env)

        # backward, micro-batches in order, stages in reverse
        for m in range(M):
            for s in range(self._n_stages - 1, -1, -1):
                self._phases["bwd"][s].run(envs[m], seed + jnp.uint32(m))

        # optimizer-phase environment: persistable state overlaid with the
        # last micro-batch's values (carries fwd-updated state like BN
        # running stats), then param grads replaced by their micro-batch
        # mean (per-microbatch losses are means, so averaging matches the
        # full-batch gradient)
        opt_env = dict(state)
        opt_env.update(envs[-1])
        for g in self._grad_names:
            pieces = [e[g] for e in envs if g in e]
            if pieces:
                opt_env[g] = sum(pieces[1:], pieces[0]) / float(len(pieces))

        # optimizer phase: once per mini-batch (section_worker.cc:166-178)
        for s in range(self._n_stages):
            self._phases["opt"][s].run(opt_env, seed)

        # commit persistable state
        for n in state:
            if n in opt_env:
                scope.set(n, opt_env[n])

        # fetches: per-example tensors (leading dim == micro-batch size) are
        # concatenated back to the full mini-batch; scalar/metric floats are
        # averaged over micro-batches (loss semantics, matching the reference
        # section_worker's loss aggregation)
        results = []
        for n in fetch_names:
            vals = [e[n] for e in envs if n in e]
            if not vals and n in opt_env:
                vals = [opt_env[n]]
            if not vals:
                raise KeyError(f"fetch {n!r} not produced by the pipeline")
            v = vals[0]
            if len(vals) > 1:
                # mb==1 is ambiguous with [1]-shaped scalar metrics (mean
                # emits [1]); treat it as the metric case and average
                if (v.ndim >= 1 and micro_batch_size is not None
                        and micro_batch_size > 1
                        and v.shape[0] == micro_batch_size):
                    v = jnp.concatenate(vals, axis=0)
                elif jnp.issubdtype(v.dtype, jnp.inexact):
                    v = sum(vals[1:], vals[0]) / float(len(vals))
            results.append(np.asarray(v) if return_numpy else v)
        return results

    # introspection for tests
    def stage_op_counts(self):
        self._build()
        return {ph: [len(p.ops) for p in phs]
                for ph, phs in self._phases.items()}

"""PipelineOptimizer — fluid wrapper + fleet meta-optimizer.

Reference: fluid optimizer.py PipelineOptimizer (~:4400, cuts the program by
device_guard annotations into sections, builds TrainerDesc section_param)
and meta_optimizers/pipeline_optimizer.py:90 (fleet wrapper reading
strategy.pipeline_configs, inserting inter-stage sync via PipelineHelper).

TPU-native: minimize returns (ops, params_grads) and stores a
PipelineCompiledProgram on the program; exe.run(<that program>) executes the
GPipe schedule.  Inter-stage c_broadcast/c_allreduce insertion is not
needed: boundary tensors move by device_put over ICI.
"""
from __future__ import annotations

from ..core.program import default_startup_program
from .pipeline_program import PipelineCompiledProgram

__all__ = ["PipelineOptimizer", "FleetPipelineOptimizer"]


class PipelineOptimizer:
    """fluid-style: PipelineOptimizer(opt, num_microbatches=4)."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set,
                                        callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        program = loss.block.program
        compiled = PipelineCompiledProgram(
            program, self._num_microbatches, params_grads)
        program._pipeline_compiled = compiled
        return ops, params_grads

    def __getattr__(self, item):
        return getattr(self.__dict__["_optimizer"], item)


# fleet meta-optimizer form (inserted by fleet_base when strategy.pipeline)
from ..distributed.fleet.meta_optimizers.meta_optimizer_base import \
    MetaOptimizerBase


class FleetPipelineOptimizer(MetaOptimizerBase):
    # pipeline owns the executor: DP-over-mesh (GraphExecution) and k-step
    # rewrites don't compose with the staged scheduler in this round
    _incompatible = ("GradientMergeOptimizer", "LocalSGDOptimizer",
                     "GraphExecutionOptimizer")

    def _can_apply(self):
        return bool(self.user_defined_strategy.pipeline)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.pipeline = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        c = self.user_defined_strategy.pipeline_configs
        m = c.get("accumulate_steps", c.get("micro_batch", 1))
        wrapped = PipelineOptimizer(self.inner_opt, num_microbatches=m)
        result = wrapped.minimize(loss, startup_program, parameter_list,
                                  no_grad_set)
        # expose the pipeline program as fleet.main_program
        program = loss.block.program
        program._compiled_for_fleet = program._pipeline_compiled
        return result

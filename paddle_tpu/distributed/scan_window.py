"""Commit-tail hoisting for the scanned micro-step window.

Under gradient-merge the rewritten program is straight-line: every
micro-step runs the masked optimizer commit (update + ZeRO publish
allgather) and `where(mask, ...)` throws K-1 of K results away.  That
is the right shape for the LOOPED executor (one XLA computation, no
host round-trip), but `CompiledProgram._run_steps`' scanned window
(`jit(shard_map(lax.scan(step)))`) runs all K micro-steps in one
dispatch — and straight-line XLA cannot skip a collective, so the scan
pays the publish allgather (and the merged-grad allreduce) K times for
one commit's worth of information.

`split_commit_tail` splits the gm window at the `gm_role` stamps the
rewrites leave behind (fleet/meta_optimizers/rewrite_utils.py,
gradient_merge_optimizer.py):

  * scan BODY — forward/backward, the per-bucket reduce-scatter fold
    into the ``dp_shard`` accumulator (ZeRO-2), the full-size
    ``acc += g`` accumulates, and the counter increment: everything
    that must run once per micro-step;
  * commit TAIL — the averaging scales, the (masked) optimizer update,
    the publish allgather chain, the merged-grad allreduce spliced by
    `with_data_parallel`, the where-commits, and the accumulator
    resets: a pure function of persistable state, hoisted OUT of the
    scan and run once per window.

K publishes become 1 per window; `scan_window_wire_bytes` prices the
cut with `verifier.entry_wire_bytes` so bench A/Bs and the planner's
roofline see the same number.  The split refuses (returns None) and
the caller falls back to the unhoisted scan whenever the program's
dataflow crosses the boundary through a non-persistable temp — an lr
computed in forward, AMP's found_inf, a fetch written by the commit —
because then "body ×K + tail ×1" is no longer the original program
run K times.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

__all__ = ["WindowSplit", "split_commit_tail", "mark_scan_hoist",
           "scan_window_wire_bytes"]


class WindowSplit:
    """The two halves of a hoisted gm window.

    ``body``/``tail`` are full Programs (clones sharing var
    declarations with the original): ``body`` is one micro-step with
    the commit removed, ``tail`` recomputes the mask from the final
    counter value and commits once.  ``k`` is the window length (the
    gm K), ``counter`` the persistable step counter whose phase gates
    the hoist (a window may only start on a commit boundary).
    """

    def __init__(self, body, tail, k: int, counter: str,
                 n_tail_ops: int):
        self.body = body
        self.tail = tail
        self.k = int(k)
        self.counter = counter
        self.n_tail_ops = int(n_tail_ops)

    def __repr__(self):
        return (f"WindowSplit(k={self.k}, counter={self.counter!r}, "
                f"n_tail_ops={self.n_tail_ops})")


def _reads(op) -> List[str]:
    return [n for ns in op.inputs.values() for n in ns if n]


def _writes(op) -> List[str]:
    return [n for ns in op.outputs.values() for n in ns if n]


def split_commit_tail(program, fetch_names: Iterable[str] = ()
                      ) -> Optional[WindowSplit]:
    """Split a gradient-merge program into (scan body, commit tail).

    Returns None — the caller runs the plain unhoisted scan — when the
    program has no gm window, is elastic (the elastic schedule IS a
    masked window; V501 keeps the two apart), predates the ``gm_role``
    stamps, or has dataflow that crosses the hoist boundary through a
    non-persistable temp.
    """
    meta = getattr(program, "_gm_meta", None)
    if not meta or int(meta.get("k", 1)) <= 1:
        return None
    if getattr(program, "_elastic_meta", None) is not None:
        return None
    if len(program.blocks) != 1:
        # control-flow sub-blocks hide reads/writes from the classifier
        return None
    block = program.global_block()
    roles = [op.attrs.get("gm_role") for op in block.ops]
    if "tail" not in roles:
        return None  # pre-stamping build: nothing to classify

    persist = {n for n, v in block.vars.items() if v.persistable}

    # classify: stamped tail ops seed the commit set; unstamped ops
    # whose inputs flow from commit-produced temps (the merged-grad
    # c_allreduce_sum `with_data_parallel` splices onto the optimizer's
    # Grad input reads the @GM_AVG scale output) are commit work too
    tail_idx = set()
    tail_defs = set()
    for i, op in enumerate(block.ops):
        role = op.attrs.get("gm_role")
        if role == "tail" or (role is None and
                              any(n in tail_defs for n in _reads(op))):
            tail_idx.add(i)
            tail_defs.update(_writes(op))

    # soundness 1: the body must not consume a non-persistable value
    # only the commit produces (persistables the tail writes — params,
    # reset accumulators — are the carried state; the body reading
    # them is exactly the looped semantics, since the looped commit
    # also happens after the step's forward/backward)
    for i, op in enumerate(block.ops):
        if i in tail_idx or op.attrs.get("gm_role") == "mask":
            continue
        if any(n in tail_defs and n not in persist for n in _reads(op)):
            return None

    # soundness 2: the tail (mask replay + commit) may read only
    # persistable state and its own temps — anything else means the
    # commit depends on per-micro-step activations and cannot be
    # hoisted behind the last step
    avail = set(persist)
    for i, op in enumerate(block.ops):
        if i not in tail_idx and op.attrs.get("gm_role") != "mask":
            continue
        if any(n not in avail for n in _reads(op)):
            return None
        avail.update(_writes(op))

    # soundness 3: a fetch the commit writes would change value
    # mid-window under the hoist (the looped path publishes it every
    # masked step) — refuse rather than return stale reads
    if any(n in tail_defs for n in fetch_names):
        return None

    body = program.clone()
    bb = body.global_block()
    bb.ops = [op for i, op in enumerate(bb.ops) if i not in tail_idx]
    body._fingerprint_cache = None

    tail = program.clone()
    tb = tail.global_block()
    tb.ops = [op for i, op in enumerate(tb.ops)
              if i in tail_idx or op.attrs.get("gm_role") == "mask"]
    tail._fingerprint_cache = None

    return WindowSplit(body=body, tail=tail, k=int(meta["k"]),
                       counter=meta["counter"],
                       n_tail_ops=len(tail_idx))


def mark_scan_hoist(program) -> WindowSplit:
    """Validate that `program`'s window is hoistable and record the
    ``scan_hoist`` pass entry (the V504 drift authority and the V208
    silencer).  `apply_plan` calls this when the chosen plan's
    ``scan_hoist`` knob is on; raises ValueError on an unhoistable
    program so a plan never claims wire it cannot cut."""
    split = split_commit_tail(program)
    if split is None:
        raise ValueError(
            "scan_hoist: program has no hoistable commit tail (needs "
            "an applied gradient_merge window, no elastic rewrite, and "
            "a commit that reads only persistable state — see "
            "distributed/scan_window.split_commit_tail)")
    from ..core.pass_framework import record_applied
    record_applied(program, "scan_hoist", k=split.k,
                   n_tail_ops=split.n_tail_ops)
    return split


def scan_window_wire_bytes(program, world: int,
                           batch: Optional[int] = None) -> Dict[str, float]:
    """Per-step ring-accounted ICI bytes of the looped vs hoisted
    window, on `verifier.entry_wire_bytes` accounting (the same
    formulas `collective_wire_bytes` and the planner roofline use):

      * ``per_step_looped``  — every collective runs every micro-step;
      * ``per_step_hoisted`` — body collectives every micro-step, tail
        collectives (publish allgather, merged-grad allreduce) once
        per K-step window: body + tail/K.

    On an unsplittable program both numbers are the looped cost.
    """
    from ..static.verifier import (collective_sequence, entry_wire_bytes,
                                   _ring_degrees_from_seq)

    def _wire(prog):
        seq = collective_sequence(prog)
        degrees = _ring_degrees_from_seq(seq)
        return sum(entry_wire_bytes(e, world, degrees, batch)
                   for e in seq)

    looped = _wire(program)
    split = split_commit_tail(program)
    if split is None:
        return {"per_step_looped": looped, "per_step_hoisted": looped,
                "body": looped, "tail": 0.0, "k": 1}
    body = _wire(split.body)
    tail = _wire(split.tail)
    return {"per_step_looped": looped,
            "per_step_hoisted": body + tail / split.k,
            "body": body, "tail": tail, "k": split.k}

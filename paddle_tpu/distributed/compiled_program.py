"""CompiledProgram.with_data_parallel — the ParallelExecutor analog.

Reference: /root/reference/python/paddle/fluid/compiler.py:87 CompiledProgram
→ framework/parallel_executor.cc:461 (per-device scopes, NCCL comms, SSA
graph with AllReduceOpHandle per gradient,
ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:464 CreateAllReduceOp).

TPU-native redesign: no SSA graph, no per-op scheduler threads.  The program
is rewritten once — a `c_allreduce_sum` + 1/N scale is inserted on every
parameter gradient feeding an optimizer op (same insertion point as
multi_devices_graph_pass.cc:632) — then the WHOLE block is traced under
`shard_map` over a jax.sharding.Mesh with a "dp" axis: parameters replicated,
feed batch-sharded, gradients allreduced over ICI by XLA collectives.  The
scheduler the reference needed (fast_threaded_ssa_graph_executor.cc:59) is
XLA's problem now; grad bucketing/fusion (fuse_all_reduce_op_pass) is done by
XLA's collective combiner.
"""
from __future__ import annotations

import copy
import os
from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.program import Program, OpRole, unique_name
from ..ops.registry import get_op_info, OpContext

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "insert_grad_allreduce"]


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class GradientScaleStrategy:
    CoeffNumDevice = 0
    One = 1
    Customized = 2


class BuildStrategy:
    """Knob parity with details/build_strategy.h; most toggles are subsumed
    by XLA (fusion, memory optimization) and kept as accepted no-ops."""
    ReduceStrategy = ReduceStrategy
    GradientScaleStrategy = GradientScaleStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.gradient_scale_strategy = GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True      # XLA collective combiner
        self.fuse_all_optimizer_ops = True   # whole-graph jit subsumes
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_inplace = True           # buffer donation
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.cache_runtime_context = True
        self.trainers_endpoints = []
        self.debug_graphviz_path = ""
        # TPU extension (SURVEY.md §5.7): shard the sequence dim (feed
        # dim 1) over an "sp" mesh axis of this size; ring_attention ops
        # with ring_id=1 ride it.  1 = off.
        self.sequence_parallel_degree = 1
        # TPU extension: Megatron-style tensor parallelism over a "tp"
        # mesh axis (distributed/tensor_parallel.py col/row layers;
        # params annotated dist_attr shard over it).  1 = off.
        self.tensor_parallel_degree = 1
        # fetch semantics across dp replicas: "reduce" (pmean floats /
        # pmax ints — what a training loop wants for loss metrics) or
        # "concat" (reference ParallelExecutor semantics: per-device
        # fetches concatenated along dim 0, scalars stacked to [ndev])
        self.fetch_aggregation = "reduce"


class ExecutionStrategy:
    """details/execution_strategy.h:22 — thread counts are meaningless under
    XLA; kept for API parity."""

    class ExecutorType:
        Default = 0
        Experimental = 1

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.allow_op_delay = False
        self.use_thread_barrier = True


# op types a reduced gradient legitimately flows through between the
# reduction collective and the optimizer op's Grad slot (scaling, AMP
# casts, gradient-merge accumulate/mask plumbing, ZeRO bucket plumbing)
_REDUCE_TRANSPARENT_OPS = frozenset((
    "scale_by_world_size", "scale", "cast", "elementwise_add", "where",
    "reshape", "reshape2", "concat", "pad", "slice", "assign",
    "check_finite_and_unscale", "update_loss_scaling",
))
_REDUCE_OPS = frozenset(("c_allreduce_sum", "c_reducescatter",
                         "c_elastic_fold"))


def _grad_already_reduced(producers: Dict[str, List["OpDesc"]], name: str,
                          limit: int = 96) -> bool:
    """True when `name`'s producer chain already contains a gradient
    reduction (c_allreduce_sum / c_reducescatter), walking back only
    through the ops a reduction pass inserts — the first op outside that
    set (a real backward grad op) terminates the walk.  Makes
    insert_grad_allreduce idempotent and ZeRO-aware: applying the pass
    twice, or on a program `shard_optimizer_states` already rewrote,
    inserts nothing.

    `producers` maps each var to ALL its writers, not just the last: a
    ZeRO-2 shard accumulator is written by its `elementwise_add`
    accumulate AND its masked `where` reset — the reduction sits behind
    the accumulate, and a last-writer-only walk through the reset would
    miss it and re-reduce per-rank shards (summing unrelated slices)."""
    seen, frontier = set(), [name]
    while frontier and limit > 0:
        limit -= 1
        n = frontier.pop()
        if n in seen:
            continue
        seen.add(n)
        for op in producers.get(n, ()):
            if op.type in _REDUCE_OPS:
                return True
            if op.type not in _REDUCE_TRANSPARENT_OPS:
                continue
            frontier.extend(op.input_names())
    return False


def insert_grad_allreduce(program: Program, num_replicas_axis="dp",
                          scale=True, fp16_allreduce=None) -> Program:
    """Insert c_allreduce_sum (+ 1/N scale) on every Grad input of optimizer
    ops.  Mirrors CreateAllReduceOp insertion
    (multi_devices_graph_pass.cc:464,:632); returns a rewritten clone.

    Idempotent: a Grad input whose producer chain already contains a
    c_allreduce_sum / c_reducescatter (this pass applied twice via
    CompiledProgram + a fleet meta-optimizer, or a ZeRO-1 program from
    distributed/sharding.py) is left alone instead of double-reduced.

    fp16_allreduce (meta_optimizers/fp16_allreduce_optimizer.py analog):
    wrap the allreduce in bf16 casts, halving ICI bytes."""
    if fp16_allreduce is None:
        fp16_allreduce = getattr(program, "_fp16_allreduce", False)
    p = copy.deepcopy(program)
    block = p.global_block()
    producers: Dict[str, Any] = {}
    for op in block.ops:
        for n in op.output_names():
            producers.setdefault(n, []).append(op)
    new_ops = []
    inserted = 0
    done: Dict[str, str] = {}
    for op in block.ops:
        if op.attrs.get("zero_sharded"):
            # a ZeRO bucket update: its Grad is the reduce-scattered
            # shard (possibly behind a gradient-merge accumulator) —
            # per-rank DIFFERENT slices an allreduce would sum into
            # garbage.  The producer walk below also catches this, but
            # the stamp is the contract.
            new_ops.append(op)
            continue
        if op.attrs.get(OpRole.KEY) == OpRole.Optimize and "Grad" in op.inputs:
            gnames = op.inputs["Grad"]
            new_gnames = []
            for g in gnames:
                if g in done:
                    new_gnames.append(done[g])
                    continue
                if _grad_already_reduced(producers, g):
                    new_gnames.append(g)
                    continue
                from ..core.program import OpDesc
                src = g
                if fp16_allreduce:
                    low = unique_name(g + "@BF16")
                    block.create_var(name=low, stop_gradient=True,
                                     dtype="bfloat16")
                    new_ops.append(OpDesc(
                        "cast", {"X": [g]}, {"Out": [low]},
                        {"in_dtype": "float32", "out_dtype": "bfloat16",
                         OpRole.KEY: OpRole.Dist,
                         "op_uid": p._next_uid()}))
                    src = low
                red = unique_name(g + "@ALLREDUCE")
                block.create_var(name=red, stop_gradient=True)
                ar = OpDesc("c_allreduce_sum", {"X": [src]}, {"Out": [red]},
                            {"ring_id": 0, OpRole.KEY: OpRole.Dist,
                             "op_uid": p._next_uid()})
                new_ops.append(ar)
                inserted += 1
                if fp16_allreduce:
                    back = unique_name(g + "@FP32")
                    block.create_var(name=back, stop_gradient=True,
                                     dtype="float32")
                    new_ops.append(OpDesc(
                        "cast", {"X": [red]}, {"Out": [back]},
                        {"in_dtype": "bfloat16", "out_dtype": "float32",
                         OpRole.KEY: OpRole.Dist,
                         "op_uid": p._next_uid()}))
                    red = back
                if scale:
                    scaled = unique_name(g + "@SCALED")
                    block.create_var(name=scaled, stop_gradient=True)
                    sc = OpDesc("scale_by_world_size", {"X": [red]},
                                {"Out": [scaled]},
                                {"ring_id": 0, OpRole.KEY: OpRole.Dist,
                                 "op_uid": p._next_uid()})
                    new_ops.append(sc)
                    red = scaled
                done[g] = red
                new_gnames.append(red)
            op.inputs["Grad"] = new_gnames
        new_ops.append(op)
    block.ops = new_ops
    if inserted:
        # record only EFFECTIVE applications: the idempotent re-apply
        # path (with_data_parallel over an already-reduced program)
        # inserts nothing and must not misreport history
        from ..core.pass_framework import record_applied
        record_applied(p, "grad_allreduce", scale=bool(scale),
                       fp16=bool(fp16_allreduce), reductions=inserted)
    return p


class CompiledProgram:
    """compiler.py:87 parity.  `places` defaults to all local devices."""

    def __init__(self, program_or_graph, build_strategy: BuildStrategy = None):
        self._program: Program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._places = None
        self._share_vars_from = None
        self._compiled = None  # (key -> jitted)
        self._cache: Dict[Any, Any] = {}
        self._mesh: Optional[Mesh] = None
        self._rewritten: Optional[Program] = None
        # device dispatches issued (one per _run, one per _run_steps
        # scan — the number the elastic run_steps K→1 claim is about)
        self._dispatches = 0

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    # -- execution (called from Executor.run) -------------------------------
    def _devices(self):
        if self._places is not None:
            devs = []
            for pl in self._places:
                if hasattr(pl, "jax_device"):
                    devs.append(pl.jax_device())
                else:
                    devs.append(pl)
            return devs
        return list(jax.devices())

    def _get_mesh(self) -> Mesh:
        if self._mesh is None:
            # axis names come from the shared canonicalizer
            # (core/mesh_axes.py) so the runtime mesh and the layout
            # analyzer can never disagree on the tensor axis's name
            from ..core.mesh_axes import (DP_AXIS, SP_AXIS,
                                          MP_AXIS_CANONICAL, runtime_axis)
            devs = np.array(self._devices())
            sp = max(1, int(getattr(self._build_strategy,
                                    "sequence_parallel_degree", 1)))
            tp = max(1, int(getattr(self._build_strategy,
                                    "tensor_parallel_degree", 1)))
            if sp > 1 and tp > 1:
                raise NotImplementedError(
                    "sequence_parallel_degree and tensor_parallel_degree "
                    "cannot both exceed 1 in one CompiledProgram")
            if sp > 1:
                dp = len(devs) // sp
                self._mesh = Mesh(devs[: dp * sp].reshape(dp, sp),
                                  (DP_AXIS, SP_AXIS))
            elif tp > 1:
                dp = len(devs) // tp
                self._mesh = Mesh(
                    devs[: dp * tp].reshape(dp, tp),
                    (DP_AXIS, runtime_axis(MP_AXIS_CANONICAL)))
            else:
                self._mesh = Mesh(devs, (DP_AXIS,))
        return self._mesh

    def _get_program(self) -> Program:
        if self._rewritten is None:
            n = len(self._devices())
            has_zero = any(
                v.attrs.get("dp_shard")
                for b in self._program.blocks for v in b.vars.values())
            has_elastic = getattr(self._program, "_elastic_meta",
                                  None) is not None
            # dp×tp composes: ring 0 binds to the dp sub-axis only (the
            # dist_info registry in _traced_step), so the ZeRO bucket
            # reduce-scatter, the grad allreduce, and the elastic
            # ordered fold all reduce over dp while the tp leg stays
            # intact — tp-partial activations are already completed by
            # the builders' mp_allreduce_sum, tp-sharded weight grads
            # are per-shard values that must NOT cross the tp axis, and
            # dp_shard slot buckets place P("dp") on the 2-D mesh
            # (replicated over tp).  dp×sp still refuses: there
            # gradients are partial over BOTH axes and a dp-only
            # reduction silently drops the sp contributions.
            if has_elastic and int(getattr(
                    self._build_strategy,
                    "sequence_parallel_degree", 1)) > 1:
                raise NotImplementedError(
                    "elastic programs (distributed/elastic.elasticize) "
                    "compose with dp or dp×tp meshes only; the ordered "
                    "fold reduces ring 0's dp axis, but under dp×sp "
                    "gradients are partial over both axes "
                    "(sequence_parallel_degree must be 1)")
            if has_zero and int(getattr(
                    self._build_strategy,
                    "sequence_parallel_degree", 1)) > 1:
                raise NotImplementedError(
                    "ZeRO-1 sharded programs (shard_optimizer_states) "
                    "compose with dp or dp×tp meshes only; the bucket "
                    "reduce-scatter rides ring 0's dp axis, but under "
                    "dp×sp gradients are partial over both axes "
                    "(sequence_parallel_degree must be 1)")
            if self._is_data_parallel:
                scale = (self._build_strategy.gradient_scale_strategy ==
                         GradientScaleStrategy.CoeffNumDevice and n > 1)
                rewritten = insert_grad_allreduce(self._program, scale=scale)
            else:
                rewritten = self._program
            # BuildStrategy-driven graph passes (build_strategy.cc:58-237
            # pass-pipeline assembly analog; core/pass_framework.py)
            from ..core.pass_framework import apply_passes, PassContext
            names = []
            if self._build_strategy.sync_batch_norm and \
                    self._is_data_parallel and n > 1:
                names.append("sync_batch_norm_pass")
            if getattr(self._build_strategy, "debug_graphviz_path", ""):
                names.append("graph_viz_pass")
            if names:
                ctx = PassContext(graph_viz_path=self._build_strategy
                                  .debug_graphviz_path or "program.dot")
                rewritten = apply_passes(rewritten, names, ctx)
            self._rewritten = rewritten
        return self._rewritten

    def _anchor_elastic(self, executor, scope, elastic, n_dev) -> int:
        """Resolve K for THIS mesh and re-anchor a topology-shifted
        restore's counters against it; returns micro_k.  `n_dev` is the
        mesh's DP degree — under a dp×tp mesh the elastic schedule folds
        over dp sub-ranks only (the tp leg is model parallelism, not
        extra data-parallel capacity)."""
        n_logical = int(elastic["logical_dp"])
        if n_logical % n_dev != 0:
            raise ValueError(
                f"elastic logical_dp={n_logical} is not divisible by "
                f"the mesh dp degree {n_dev}")
        micro_k = n_logical // n_dev
        # topology-shifted resume: restore_from_checkpoint left the
        # schedule position in GLOBAL steps (it cannot know the new
        # mesh); re-anchor the executor's micro-step counter for THIS
        # world before deriving seeds from it
        rebase = getattr(executor, "_elastic_rebase_global", None)
        if rebase is not None:
            from ..observability.journal import emit as _jemit
            _jemit("reanchor", world=int(n_dev), k=int(micro_k),
                   global_step=int(rebase))
            executor._step = int(rebase) * micro_k
            executor._elastic_steps = int(rebase) * micro_k
            # the restore re-derived the persistable micro counter
            # for its best-guess default world; THIS mesh is the
            # authority — re-anchor it too, or the commit mask and
            # per-rank RNG phase run at the wrong K (e.g. restore on
            # an 8-device host, then places=4: counter g vs step
            # g*2 would commit after ONE half-folded micro-step)
            scope.set(elastic["counter"],
                      jnp.array(np.full((1,), int(rebase) * micro_k,
                                        np.int32)))
            executor._elastic_rebase_global = None
        executor._last_elastic_world = n_dev
        executor._last_elastic_k = micro_k
        return micro_k

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        from ..static.executor import (global_scope, BlockTracer,
                                       _persistable_names)
        scope = scope or global_scope()
        feed = feed or {}
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in (fetch_list or [])]
        program = self._get_program()
        mesh = self._get_mesh()
        n_dev = len(mesh.devices.flat)
        block = program.global_block()

        elastic = getattr(program, "_elastic_meta", None)
        micro_k = 1
        if elastic is not None:
            micro_k = self._anchor_elastic(executor, scope, elastic,
                                           int(mesh.shape["dp"]))

        # pre-placed feeds (reader.Prefetcher via place_feed) pass through;
        # host arrays take the synchronous conversion
        feed_vals = {n: v if isinstance(v, jax.Array) else jnp.asarray(v)
                     for n, v in feed.items()}
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]
        feed_sig = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                                for n, v in feed_vals.items()))
        key = (program.fingerprint(), feed_sig, tuple(fetch_names),
               tuple(state_names), n_dev,
               getattr(self._build_strategy, "fetch_aggregation", "reduce"))
        from ..core import compile_cache as _ccache
        fn = self._cache.get(key)
        if fn is None:
            # env-gated IR verification rides the (already slow) first
            # compile of each program (PADDLE_TPU_VERIFY, verifier.py)
            from ..static.verifier import verify_first_compile
            verify_first_compile(program, fetch_list=fetch_names)
            _ccache.record_miss()
            _ccache.record_trace()
            from ..observability.journal import emit as _jemit
            _jemit("compile", mode="compiled", world=int(n_dev),
                   fingerprint=str(key[0])[:16])
            fn = self._compile(program, state_names, sorted(feed_vals),
                               fetch_names, mesh)
            self._cache[key] = fn
        else:
            _ccache.record_hit()

        from ..testing import chaos as _chaos
        if _chaos.enabled():
            # same step numbering as the kill hook: the n-th TRAIN step
            # (startup/eval dispatches neither count nor fault)
            if getattr(program, "_chaos_is_training", None) is None:
                from ..static.executor import _is_training
                program._chaos_is_training = _is_training(program)
            if program._chaos_is_training:
                _chaos.collective_hook(executor._train_runs + 1)
        state = {n: scope.get(n) for n in state_names}
        if elastic is not None:
            # one RNG stream per GLOBAL step: all K micro-steps of a
            # window derive from the same base seed, decorrelated per
            # LOGICAL rank inside the traced step — so dropout masks and
            # shuffles replay identically on any mesh size.  Counted by
            # _elastic_steps, which (unlike _step) startup/eval runs
            # never pollute.
            seed = (int(program.random_seed) * 1000003 +
                    executor._elastic_steps // micro_k) % (2 ** 31)
        else:
            seed = executor._seed_for_step(program)
        fetches, new_state = fn(state, feed_vals, jnp.uint32(seed))
        self._dispatches += 1
        executor._step += 1
        if elastic is not None:
            executor._elastic_steps += 1
        for n, v in new_state.items():
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def place_feed(self, feed: Dict[str, Any]) -> Dict[str, Any]:
        """Async-friendly sharded feed placement: ship a host batch onto
        the mesh with the same batch-dim layout `_run`'s shard_map expects
        (dim 0 split over "dp" when it divides evenly, else replicated).
        Designed as a `reader.Prefetcher` place_fn so the host→ICI
        transfer of batch N+1 overlaps the sharded compute of batch N:

            pf = Prefetcher(batches, place_fn=compiled.place_feed)
            for feed in pf: exe.run(compiled, feed=feed, ...)
        """
        from jax.sharding import NamedSharding
        from ..reader.prefetcher import _canonical_array, _x64_enabled
        mesh = self._get_mesh()
        dp = mesh.shape["dp"]
        x64 = _x64_enabled()
        out = {}
        for n, v in feed.items():
            if isinstance(v, jax.Array):
                out[n] = v
                continue
            a = _canonical_array(v, x64)
            if a.ndim >= 1 and a.shape[0] % dp == 0:
                spec = P("dp")
            else:
                spec = P()
            out[n] = jax.device_put(a, NamedSharding(mesh, spec))
        return out

    def _run_steps(self, executor, feed, fetch_list, scope, return_numpy):
        """K steps in ONE device dispatch (Executor.run_steps contract)
        over the sharded mesh: the traced step `lax.scan`s over the
        stacked feeds' leading axis with the persistable state carried
        on device.

        For an elastic program this is the dispatch-collapse the
        ROADMAP names: a global step is K = logical_dp/world
        micro-steps, and driving them through run() pays K host
        dispatch round-trips per global step; feeding the K re-bucketed
        micro-feeds stacked ([K, M·b, ...]) runs the whole commit
        window as ONE device call, bitwise-equal to the looped form
        (same traced step, same per-window seed derivation — the
        per-micro-step RNG phase comes from the persistable counter
        carried through the scan).

        For a gradient-merge (optionally ×ZeRO) program whose K is a
        whole number of commit windows and whose counter sits on a
        window boundary, the scan runs HOISTED (scan_window.py): the
        commit tail — optimizer update, publish allgather, merged-grad
        allreduce — executes once per gm-K window instead of once per
        micro-step, cutting the publish wire to 1/K.  Numerics are
        unchanged (the looped commit is masked off on the same steps);
        set ``PADDLE_TPU_SCAN_HOIST=0`` to force the unhoisted scan
        (the bench A/B switch).

        Stacked feeds ride the executor's FLAGS_feed_bucketing policy:
        a ragged PER-STEP batch pads up to an already-compiled stacked
        bucket (axis 1) under ``fetch_aggregation="reduce"`` — same
        duplicated-row caveats as run()'s bucketing (docs/perf.md).
        The steps axis is never padded."""
        from ..static.executor import global_scope, _persistable_names
        scope = scope or global_scope()
        feed = feed or {}
        if not feed:
            raise ValueError(
                "run_steps needs at least one stacked feed to define "
                "the number of steps")
        fetch_names = [f.name if hasattr(f, "name") else str(f)
                       for f in (fetch_list or [])]
        program = self._get_program()
        mesh = self._get_mesh()
        if set(mesh.axis_names) - {"dp", "tp", "sp"}:
            raise NotImplementedError(
                "run_steps through CompiledProgram supports dp, dp×tp "
                "and dp×sp meshes only")
        n_dev = len(mesh.devices.flat)
        elastic = getattr(program, "_elastic_meta", None)
        micro_k = 1
        if elastic is not None:
            micro_k = self._anchor_elastic(executor, scope, elastic,
                                           int(mesh.shape["dp"]))
        feed_vals = {n: v if isinstance(v, jax.Array) else jnp.asarray(v)
                     for n, v in feed.items()}
        k = None
        for n, v in feed_vals.items():
            shape = tuple(getattr(v, "shape", ()))
            if len(shape) == 0:
                raise ValueError(
                    f"run_steps feed {n!r} is a scalar; every feed "
                    "needs a leading steps axis")
            k = shape[0] if k is None else k
            if shape[0] != k:
                raise ValueError(
                    f"feed {n!r} leading (steps) dim {shape[0]} != {k}")
        k = int(k)
        state_names = [n for n in _persistable_names(program)
                       if scope.get(n) is not None]

        # commit-tail hoist eligibility: a splittable gm window, K a
        # whole number of windows, and the persistable counter on a
        # window boundary (a mid-window start must replay the masked
        # looped semantics — the plain scan does exactly that)
        split = None
        if elastic is None and \
                os.environ.get("PADDLE_TPU_SCAN_HOIST", "1").lower() \
                not in ("0", "false", "off"):
            split = self._window_split(program, tuple(fetch_names))
        hoist = False
        if split is not None and k % split.k == 0:
            cval = scope.get(split.counter)
            if cval is not None:
                cnt = int(np.asarray(cval).reshape(-1)[0])
                hoist = cnt % split.k == 0
        agg = getattr(self._build_strategy, "fetch_aggregation", "reduce")
        feed_sig = tuple(sorted((n, tuple(v.shape), str(v.dtype))
                                for n, v in feed_vals.items()))
        key = ("steps", bool(hoist), program.fingerprint(), feed_sig,
               tuple(fetch_names), tuple(state_names), n_dev, agg)
        from ..core import compile_cache as _ccache
        fn = self._cache.get(key)
        bucket = None  # (real per-step batch, padded per-step batch)
        if fn is None and agg == "reduce":
            bucketed = self._bucket_lookup_steps(executor, key, feed_vals)
            if bucketed is not None:
                key, feed_vals, bucket = bucketed
                fn = self._cache.get(key)
        if fn is None:
            from ..static.verifier import verify_first_compile
            verify_first_compile(program, fetch_list=fetch_names)
            _ccache.record_miss()
            _ccache.record_trace()
            from ..observability.journal import emit as _jemit
            _jemit("compile",
                   mode=("compiled_steps_hoisted" if hoist
                         else "compiled_steps"), world=int(n_dev),
                   fingerprint=str(key[2])[:16])
            fn = self._compile_steps(program, state_names, feed_vals,
                                     fetch_names, mesh,
                                     split=split if hoist else None)
            self._cache[key] = fn
        else:
            _ccache.record_hit()
        from ..testing import chaos as _chaos
        if _chaos.enabled():
            if getattr(program, "_chaos_is_training", None) is None:
                from ..static.executor import _is_training
                program._chaos_is_training = _is_training(program)
            if program._chaos_is_training:
                _chaos.collective_hook(executor._train_runs + 1)
        state = {n: scope.get(n) for n in state_names}
        if elastic is not None:
            # one RNG stream per GLOBAL step, same derivation as K
            # looped _run calls would walk (scanned micro-step i of
            # this window belongs to global step
            # (elastic_steps + i) // K)
            base = int(program.random_seed) * 1000003
            seeds = jnp.asarray(
                [(base + (executor._elastic_steps + i) // micro_k)
                 % (2 ** 31) for i in range(k)], jnp.uint32)
        else:
            # (x % m + i) % m == (x + i) % m: re-applying the modulus
            # keeps micro-step i's seed EXACTLY what the i-th looped
            # _run call would derive, across the 2**31 wrap included
            seeds = jnp.asarray(
                [(executor._seed_for_step(program) + i) % (2 ** 31)
                 for i in range(k)], jnp.uint32)
        fetches, new_state = fn(state, feed_vals, seeds)
        self._dispatches += 1
        executor._step += k
        if elastic is not None:
            executor._elastic_steps += k
        for n, v in new_state.items():
            scope.set(n, v)
        if bucket is not None:
            fetches = executor._unpad_steps_fetches(
                fetches, bucket[0], bucket[1],
                block=program.global_block(), fetch_names=fetch_names)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _compile_steps(self, program, state_names, feed_vals,
                       fetch_names, mesh, split=None):
        """jit(shard_map(scan(step))): the scanned sibling of _compile
        (dp / dp×tp / dp×sp meshes; feeds carry [K, per-step...] with
        the per-step batch on axis 1).

        With `split` (a scan_window.WindowSplit) the scan runs the
        HOISTED window: an outer scan over K/gm_k windows, each window
        an inner scan of gm_k commit-free body steps followed by ONE
        commit-tail execution — the publish allgather and merged-grad
        allreduce run once per window instead of once per micro-step."""
        from ..utils.shard_map_compat import shard_map_unchecked
        from .partition_spec import state_partition_specs
        dp = mesh.shape["dp"]
        has_sp = "sp" in mesh.axis_names
        sp_deg = mesh.shape["sp"] if has_sp else 1
        block = program.global_block()

        if split is not None:
            step = self._traced_step(split.body, state_names,
                                     fetch_names, mesh)
            # the tail is a pure function of persistable state (the
            # splitter's soundness contract): no feed, no fetches — it
            # recomputes the mask from the carried counter and commits
            tail_step = self._traced_step(split.tail, state_names, [],
                                          mesh)
            gm_k = int(split.k)
        else:
            step = self._traced_step(program, state_names, fetch_names,
                                     mesh)

        def body(state, xs):
            feed, seed = xs
            fetches, new_state = step(state, feed, seed)
            return new_state, fetches

        if split is not None:
            def window(state, xs):
                feeds_w, seeds_w = xs
                state, fetches = jax.lax.scan(body, state,
                                              (feeds_w, seeds_w))
                # tail has no RNG ops (splitter contract: persistable
                # reads only) — the seed argument is inert
                _, state = tail_step(state, {}, seeds_w[-1])
                return state, fetches

            def multi(state, feeds, seeds):
                k = seeds.shape[0]
                m = k // gm_k
                feeds_w = {n: v.reshape((m, gm_k) + v.shape[1:])
                           for n, v in feeds.items()}
                seeds_w = seeds.reshape((m, gm_k))
                new_state, fetches = jax.lax.scan(window, state,
                                                  (feeds_w, seeds_w))
                fetches = tuple(f.reshape((k,) + f.shape[2:])
                                for f in fetches)
                return fetches, new_state
        else:
            def multi(state, feeds, seeds):
                new_state, fetches = jax.lax.scan(body, state,
                                                  (feeds, seeds))
                return fetches, new_state

        state_specs = state_partition_specs(program, mesh, state_names)
        feed_specs = {}
        for n, v in feed_vals.items():
            shape = tuple(getattr(v, "shape", ()))
            # steps axis never shards; the per-step batch (axis 1)
            # shards over dp like the looped path's P("dp").  A
            # non-divisible batch must FAIL here like it does there —
            # silently replicating it would run every rank over the
            # full batch with a different summation order, breaking
            # the bitwise-to-looped contract
            if len(shape) >= 2:
                if shape[1] % dp != 0:
                    raise ValueError(
                        f"run_steps feed {n!r} per-step batch "
                        f"{shape[1]} does not divide the dp world "
                        f"{dp} (stacked feeds shard axis 1 over dp, "
                        "like run() shards axis 0)")
                if has_sp:
                    # mirror _compile's sp heuristic one axis right:
                    # the declared per-step dim 1 (sequence) is the
                    # stacked axis 2
                    try:
                        gshape = tuple(block.var(n).shape or ())
                    except KeyError:
                        gshape = ()
                    if len(gshape) >= 2 and gshape[1] is not None and \
                            gshape[1] > 1 and gshape[1] % sp_deg == 0 \
                            and len(shape) >= 3 and \
                            shape[2] % sp_deg == 0:
                        feed_specs[n] = P(None, "dp", "sp")
                    else:
                        feed_specs[n] = P(None, "dp")
                else:
                    feed_specs[n] = P(None, "dp")
            else:
                feed_specs[n] = P(None)  # [K] per-step scalars
        fetch_specs = tuple(P() for _ in fetch_names)
        sharded = shard_map_unchecked(
            multi, mesh, in_specs=(state_specs, feed_specs, P()),
            out_specs=(fetch_specs, state_specs))
        return jax.jit(sharded, donate_argnums=(0,))

    def _window_split(self, program, fetch_names):
        """Cached scan_window.split_commit_tail — the split walks (and
        clones) the whole program, so _run_steps memoizes it per
        (fingerprint, fetches)."""
        key = (program.fingerprint(), tuple(fetch_names))
        cached = getattr(self, "_scan_split_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from .scan_window import split_commit_tail
        split = split_commit_tail(program, fetch_names)
        self._scan_split_cache = (key, split)
        return split

    def _bucket_lookup_steps(self, executor, miss_key, feed_vals):
        """CompiledProgram analog of Executor._bucket_lookup_steps: on
        a scanned-cache miss under the executor's FLAGS_feed_bucketing
        policy, pad the PER-STEP batch (axis 1 of every stacked feed)
        up to the smallest already-compiled stacked bucket with the
        same step count — the steps axis is never padded.  Only under
        ``fetch_aggregation="reduce"`` (concat fetches interleave
        per-shard rows, which un-padding cannot unpick); padded
        duplicate rows carry the same caveats as run()'s bucketing."""
        policy = getattr(executor, "bucket_policy", "off")
        if policy not in ("existing", "pow2") or not feed_vals:
            return None
        memo = getattr(self, "_steps_bucket_map", None)
        if memo is None:
            memo = self._steps_bucket_map = {}
        hit = memo.get(miss_key)
        if hit is not None:
            bucket_key, target = hit
            return (bucket_key,
                    executor._pad_steps_feeds(feed_vals, target), target)
        tag, hoist, fp, feed_sig, rest = (miss_key[0], miss_key[1],
                                          miss_key[2], miss_key[3],
                                          miss_key[4:])
        dims = set()
        for _, shape, _ in feed_sig:
            if len(shape) < 2:
                return None
            dims.add(int(shape[1]))
        if len(dims) != 1:
            return None
        b = dims.pop()

        def rebucket(sig, new_b):
            return tuple((n, (s[0], new_b) + tuple(s[2:]), dt)
                         for n, s, dt in sig)

        candidates = []
        for k in self._cache:
            if len(k) != len(miss_key) or k[0] != tag or k[1] != hoist \
                    or k[2] != fp or k[4:] != rest:
                continue
            cdims = {int(s[1]) for _, s, _ in k[3] if len(s) >= 2}
            if len(cdims) != 1:
                continue
            cand_b = cdims.pop()
            if cand_b < b:
                continue
            if k[3] == rebucket(feed_sig, cand_b):
                candidates.append(cand_b)
        if not candidates:
            return None
        target_b = min(candidates)
        if target_b == b:
            return None
        bucket_key = (tag, hoist, fp, rebucket(feed_sig, target_b)) + rest
        memo[miss_key] = (bucket_key, (b, target_b))
        return (bucket_key,
                executor._pad_steps_feeds(feed_vals, (b, target_b)),
                (b, target_b))

    def _traced_step(self, program, state_names, fetch_names, mesh):
        """The single traced (state, feed, seed) -> (fetches, state')
        step both the per-dispatch (`_compile`) and scanned
        (`_compile_steps`) paths wrap in shard_map."""
        from ..static.executor import BlockTracer
        block = program.global_block()
        tracer = BlockTracer(block)
        axes = tuple(mesh.axis_names)
        has_sp = "sp" in axes
        has_tp = "tp" in axes
        fetch_aggregation = getattr(self._build_strategy,
                                    "fetch_aggregation", "reduce")
        if fetch_aggregation not in ("reduce", "concat"):
            raise ValueError(
                f"BuildStrategy.fetch_aggregation must be 'reduce' or "
                f"'concat', got {fetch_aggregation!r}")

        elastic = getattr(program, "_elastic_meta", None)
        n_mesh_dp = mesh.shape["dp"]
        micro_k = 1
        if elastic is not None:
            micro_k = int(elastic["logical_dp"]) // n_mesh_dp

        def step(state, feed, seed):
            # decorrelate RNG across replicas (the reference gives each
            # device worker a distinct seed).  NOT across tp: tp shards
            # see the same batch and must draw identical dropout masks.
            if elastic is not None:
                # elastic: decorrelate by LOGICAL rank jM+m (micro-step j
                # from the persistable counter, pre-increment), so every
                # topology draws the same per-rank streams
                cnt = jnp.reshape(state[elastic["counter"]], (-1,))[0]
                micro = jnp.mod(cnt.astype(jnp.uint32),
                                jnp.uint32(micro_k))
                local_seed = seed + micro * jnp.uint32(n_mesh_dp) + \
                    jnp.uint32(jax.lax.axis_index("dp"))
            else:
                local_seed = seed + jnp.uint32(jax.lax.axis_index("dp"))
            if has_sp:
                local_seed = local_seed * jnp.uint32(7919) + \
                    jnp.uint32(jax.lax.axis_index("sp"))
            # ring 0 = dp world (grad allreduce); ring 1 = sequence axis
            # SP_RING_ID is the reserved sequence ring (not bound without
            # an sp axis → ring_attention degrades to plain attention).
            # Under dp×sp, gradients are partial over BOTH axes (batch and
            # sequence shards), so ring 0 reduces over the whole mesh;
            # under dp×tp, grads reduce over dp ONLY (tp shards either
            # hold disjoint weight shards or identical replicated grads)
            # and TP_RING_ID binds the Megatron collectives to "tp".
            from ..ops.attention import SP_RING_ID
            from .tensor_parallel import TP_RING_ID
            # TP_RING_ID binds to None when no tp axis exists: the weights
            # are then unsharded, every shard computes the full product,
            # and the Megatron collectives must degrade to identity (like
            # SP_RING_ID) — falling through to the dp axis would psum
            # complete outputs across batch shards
            if has_sp:
                dist_info = {0: ("dp", "sp"), SP_RING_ID: "sp",
                             TP_RING_ID: None, "default": "dp"}
            elif has_tp:
                dist_info = {0: "dp", SP_RING_ID: None,
                             TP_RING_ID: "tp", "default": "dp"}
            else:
                dist_info = {0: "dp", SP_RING_ID: None, TP_RING_ID: None}
            ctx = OpContext(seed=local_seed, mesh_axes=axes,
                            dist_info=dist_info)
            env = dict(state)
            env.update(feed)
            tracer.run(env, ctx)
            new_state = {n: env[n] for n in state_names}
            fetches = []
            for n in fetch_names:
                v = env[n]
                if elastic is not None and (
                        n == elastic.get("loss_avg")
                        or n in elastic.get("accs", ())):
                    # elastic fold outputs are already replicated AND
                    # globally averaged; pmean-ing n identical replicas
                    # computes nL/n, whose rounding depends on the world
                    # size — exactly the variance elastic mode removes
                    fetches.append(v)
                    continue
                if fetch_aggregation == "concat":
                    # reference ParallelExecutor semantics: per-device rows
                    # concatenated along dim 0 (scalars stack to [ndev]).
                    if has_sp:
                        # mirror the feed-spec heuristic: only dim-1
                        # sequence shards reassemble along dim 1; anything
                        # replicated/reduced over sp is averaged
                        try:
                            gshape = tuple(block.var(n).shape or ())
                        except KeyError:
                            gshape = ()
                        sp_sharded = (len(gshape) >= 2
                                      and gshape[1] is not None
                                      and gshape[1] > 1
                                      and gshape[1] % mesh.shape["sp"] == 0)
                        if v.ndim >= 2 and sp_sharded:
                            v = jax.lax.all_gather(v, "sp", axis=1,
                                                   tiled=True)
                        elif jnp.issubdtype(v.dtype, jnp.inexact):
                            # per-example reductions (loss) are replicated
                            # partial means over sp — average them
                            v = jax.lax.pmean(v, "sp")
                        else:
                            v = jax.lax.pmax(v, "sp")
                    if v.ndim == 0:
                        v = jax.lax.all_gather(v, "dp")
                    else:
                        v = jax.lax.all_gather(v, "dp", tiled=True)
                elif jnp.issubdtype(v.dtype, jnp.inexact):
                    # "reduce": average floats (what a training loop wants
                    # for loss metrics)
                    v = jax.lax.pmean(v, axes)
                else:
                    v = jax.lax.pmax(v, axes)
                fetches.append(v)
            return tuple(fetches), new_state

        return step

    def _compile(self, program, state_names, feed_names, fetch_names, mesh):
        from ..utils.shard_map_compat import shard_map_unchecked
        block = program.global_block()
        axes = tuple(mesh.axis_names)
        has_sp = "sp" in axes
        step = self._traced_step(program, state_names, fetch_names, mesh)

        # ZeRO sharded buckets (distributed/sharding.py stages 1-3:
        # optimizer slots, gradient-merge shard accumulators, stage-3
        # param buckets): persistables declared at the GLOBAL padded
        # shape and marked dp_shard shard over "dp", so each rank holds
        # (and donates, and updates) only its slice.  Any dp degree
        # dividing the padded length runs the same program.  The specs
        # come from the partition-spec engine — the single consumption
        # point, so the engine's plan and the mesh's placement can never
        # drift apart.
        # dist_attr tp param sharding + accumulator inheritance live in
        # the engine too, so the per-dispatch and scanned compile paths
        # place identical 2-D layouts
        from .partition_spec import (state_partition_specs,
                                     feed_partition_specs)
        state_specs = state_partition_specs(program, mesh, state_names)
        if has_sp:
            # batch over dp, sequence (dim 1) over sp; rank-1 feeds
            # (e.g. flat labels) shard batch only
            sp_deg = mesh.shape["sp"]
            feed_specs = {}
            for n in feed_names:
                try:
                    shape = tuple(block.var(n).shape or ())
                except KeyError:
                    shape = ()
                # sequence dim (dim 1) rides sp only when it divides evenly
                # ([-1, 1] label feeds and ragged dims shard batch only)
                if len(shape) >= 2 and shape[1] is not None and \
                        shape[1] > 1 and shape[1] % sp_deg == 0:
                    feed_specs[n] = P("dp", "sp")
                else:
                    feed_specs[n] = P("dp")
        else:
            # the partition-spec engine: P("dp") batch split for
            # training feeds (the historical default), dist_attr
            # head-dim tp shards and replicated_feed P() for the
            # tp-decode serving programs
            feed_specs = feed_partition_specs(program, mesh, feed_names)
        fetch_specs = tuple(P() for _ in fetch_names)

        sharded = shard_map_unchecked(
            step, mesh, in_specs=(state_specs, feed_specs, P()),
            out_specs=(fetch_specs, state_specs))
        return jax.jit(sharded, donate_argnums=(0,))

"""paddle.distributed — collective API, launchers, fleet orchestration.

Reference layer: /root/reference/python/paddle/distributed/ (P10-P14 in
SURVEY.md §2.2).  TPU-native backend: XLA collectives over a
jax.sharding.Mesh (ICI/DCN) instead of NCCL rings; jax.distributed
coordination instead of Gloo/TCP bootstrap.
"""
from .collective import (  # noqa: F401
    ReduceOp, broadcast, all_reduce, reduce, all_gather, scatter, barrier,
    all_to_all, alltoall, send, recv, new_group, get_group, wait,
)
from .parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, prepare_context,
    DataParallel, ParallelEnv,
)
from .spawn import spawn  # noqa: F401
from .compiled_program import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy,
)
from .sharding import (  # noqa: F401
    shard_optimizer_states, ShardingPlan, unshard_state, reshard_state,
)
from .partition_spec import (  # noqa: F401
    match_partition_rules, zero_stage_rules, build_sharding_specs,
    tensor_parallel_rules, PartitionRule, REPLICATED, DP_SHARD,
    MP_COL, MP_ROW,
)
from .scan_window import (  # noqa: F401
    WindowSplit, split_commit_tail, mark_scan_hoist,
    scan_window_wire_bytes,
)
from .elastic import (  # noqa: F401
    elasticize, rebucket_feeds, rederive_schedule, reanchor_topology,
    elastic_meta, micro_steps_per_global,
)
from .fleet_control import (  # noqa: F401
    FleetController, FleetBarrier, FleetCommit, fleet_env, fleet_rank,
    fleet_world_size, newest_mutual_checkpoint_step,
)
from . import fleet_control  # noqa: F401
from .dataset import (  # noqa: F401
    DatasetFactory, InMemoryDataset, QueueDataset, MultiSlotDataFeed,
)
from . import fleet  # noqa: F401
from .heter import HeterSection, split_heter_program  # noqa: F401

"""paddle.distributed.spawn — start distributed workers via multiprocessing.

Reference: /root/reference/python/paddle/distributed/spawn.py (spawn N
processes, one per selected GPU, wiring the PADDLE_* env contract and
collecting results / exceptions).

TPU mapping: one worker process per HOST of a slice (each process drives all
of its local chips through one jax client), so `nprocs` defaults to 1 and is
mostly useful for CPU-mesh simulation tests of the multi-host path.
"""
from __future__ import annotations

import multiprocessing
import os
import socket
import sys
import traceback

__all__ = ["spawn", "get_free_ports"]


def get_free_ports(n):
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


class ParallelEnvArgs:
    def __init__(self):
        self.cluster_node_ips = None
        self.node_ip = None
        self.use_paddlecloud = None
        self.started_port = None
        self.selected_devices = None
        self.print_config = True


def _wrap(func, i, nprocs, endpoints, args, error_queue):
    env = os.environ
    env["PADDLE_TRAINER_ID"] = str(i)
    env["PADDLE_TRAINERS_NUM"] = str(nprocs)
    env["PADDLE_CURRENT_ENDPOINT"] = endpoints[i]
    env["PADDLE_TRAINER_ENDPOINTS"] = ",".join(endpoints)
    env["FLAGS_selected_xlas"] = str(i)
    try:
        func(*args)
    except KeyboardInterrupt:
        pass
    except Exception:
        error_queue.put(traceback.format_exc())
        sys.exit(1)


class MultiprocessContext:
    def __init__(self, processes, error_queues):
        self.processes = processes
        self.error_queues = error_queues

    def join(self, timeout=None):
        for p in self.processes:
            p.join(timeout)
        for i, (p, q) in enumerate(zip(self.processes, self.error_queues)):
            if p.exitcode not in (0, None):
                msg = q.get() if not q.empty() else f"exitcode {p.exitcode}"
                for other in self.processes:
                    if other.is_alive():
                        other.terminate()
                raise RuntimeError(
                    f"worker {i} failed:\n{msg}")
        return all(p.exitcode == 0 for p in self.processes)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """Launch `nprocs` worker processes running `func(*args)` under the
    PADDLE_* env contract (spawn.py parity)."""
    if nprocs < 0:
        nprocs = 1
    ports = get_free_ports(nprocs)
    endpoints = [f"127.0.0.1:{p}" for p in ports]
    ctx = multiprocessing.get_context("spawn")
    processes, error_queues = [], []
    for i in range(nprocs):
        q = ctx.SimpleQueue()
        p = ctx.Process(target=_wrap,
                        args=(func, i, nprocs, endpoints, args, q),
                        daemon=daemon)
        p.start()
        processes.append(p)
        error_queues.append(q)
    mp_ctx = MultiprocessContext(processes, error_queues)
    if join:
        mp_ctx.join()
    return mp_ctx

"""ParallelEnv — the PADDLE_* launcher env contract.

Reference: /root/reference/python/paddle/fluid/dygraph/parallel.py ParallelEnv
(rank/world/endpoints from PADDLE_TRAINER_ID, PADDLE_TRAINERS_NUM,
PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS — the contract set by
distributed/fleet/launch.py:198 launch_collective).

TPU mapping: one launched process per host of a TPU slice; within a process
all local chips are driven by a single jax client, so `device_id` is kept for
API parity but local parallelism comes from the mesh, not from one process
per chip.
"""
from __future__ import annotations

import os

__all__ = ["ParallelEnv"]


class ParallelEnv:
    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.environ.get("FLAGS_selected_xlas",
                              os.environ.get("FLAGS_selected_gpus", "0"))
                              .split(",")[0])
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = [e for e in eps.split(",") if e]
        self._nrings = int(os.environ.get("FLAGS_nccl_nrings", "1"))

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    @property
    def nrings(self):
        return self._nrings

    # legacy aliases (parallel.py exposes local_rank/nranks)
    local_rank = rank
    nranks = world_size
    dev_id = device_id

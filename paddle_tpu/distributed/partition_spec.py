"""Declarative partition-spec engine: regex rules over var names decide
the sharding plan.

Reference pattern: the `match_partition_rules` idiom from the pjit
training stacks (SNIPPETS.md [2]) — an ordered list of
``(regex, PartitionSpec)`` pairs is matched against every leaf name and
the first hit wins, scalars are never partitioned, and a name no rule
covers is an explicit decision, not an accident.  SNIPPETS.md [1] is the
same idea from the measurement side: the pjit sharding schemes being
priced are *data*, not code.

This module is the declarative layer the ZeRO pass family
(`distributed/sharding.py` stages 1-3) selects its surface through:
instead of each stage hard-coding "slots shard, params don't", every
stage IS a rule list over qualified var names, and a new model shape
(or a model that wants its embedding replicated under ZeRO-3) gets a
plan by *prepending a rule*, not by writing a new pass.

Qualified names
---------------
Rules match against ``"<category>:<var name>"`` so one ordered rule list
can speak about every class of trainable state at once:

  * ``param:<name>``     — a trainable parameter (ZeRO-3 shards these);
  * ``slot:<name>``      — an optimizer accumulator (moments, velocity —
                           ZeRO-1 shards these);
  * ``grad_acc:<name>``  — a gradient-merge accumulator (ZeRO-2 keeps
                           these reduce-scattered at 1/N);
  * ``scalar:<name>``    — shape-[1] state (beta pows, counters): never
                           partitioned, mirroring the exemplar's
                           "don't partition scalar values" guard.

Specs are mesh-axis tuples in the `jax.sharding.PartitionSpec` spelling:
``DP_SHARD = ("dp",)`` (shard dim 0 over the data-parallel axis) and
``REPLICATED = ()``.  `CompiledProgram` materializes them as real
`PartitionSpec`s when it feeds `shard_map` (`state_partition_specs`).

Contracts (tests/test_partition_spec.py):

  * **precedence** — first matching rule wins, exactly like the
    exemplar's ``re.search`` loop;
  * **no-match fallback** — a name no rule matches is REPLICATED and
    recorded in ``PartitionAssignment.unmatched`` (pass
    ``require_match=True`` to make it an error instead);
  * **over-match refusal** — a *strict* rule (user-written; the built-in
    stage defaults are non-strict) that assigns a sharded spec to a var
    the pass cannot actually partition (unsupported optimizer, sparse
    gradient, explicit MasterParam, dynamic shape) raises ``ValueError``
    naming the rule and the var, so a plan never silently claims memory
    the rewrite will not deliver.
"""
from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "REPLICATED", "DP_SHARD", "MP_COL", "MP_ROW", "PartitionRule",
    "PartitionAssignment", "match_partition_rules", "zero_stage_rules",
    "tensor_parallel_rules", "build_sharding_specs",
    "state_partition_specs", "feed_partition_specs",
]

# spec spelling: tuple of mesh-axis names per dim (None = replicated dim,
# trailing Nones may be omitted), () = fully replicated
REPLICATED: Tuple = ()
DP_SHARD: Tuple = ("dp",)
# tensor-parallel (Megatron) weight splits over the model axis "mp":
# column-parallel fc shards the OUT features (dim 1), row-parallel fc
# shards the IN features (dim 0).  The layout analyzer
# (static/layout_analysis.py) consumes these as seed specs; the runtime
# "tp" mesh axis (distributed/tensor_parallel.py dist_attr) is the same
# axis under its CompiledProgram name.
MP_COL: Tuple = (None, "mp")
MP_ROW: Tuple = ("mp", None)


class PartitionRule:
    """One ``(pattern, spec)`` rule.  ``strict=True`` (the default for
    user-written rules) arms over-match refusal: matching a var the pass
    cannot shard is an error, not a silent fallback."""

    __slots__ = ("pattern", "spec", "strict", "_rx")

    def __init__(self, pattern: str, spec: Sequence, strict: bool = True):
        self.pattern = str(pattern)
        self.spec = tuple(spec)
        self.strict = bool(strict)
        self._rx = re.compile(self.pattern)

    def matches(self, name: str) -> bool:
        return self._rx.search(name) is not None

    def __repr__(self):
        return (f"PartitionRule({self.pattern!r}, {self.spec!r}"
                f"{', strict' if self.strict else ''})")


def _as_rule(r) -> PartitionRule:
    if isinstance(r, PartitionRule):
        return r
    if isinstance(r, (tuple, list)) and len(r) in (2, 3):
        return PartitionRule(r[0], r[1], *(r[2:] or ()))
    raise TypeError(
        f"partition rule must be PartitionRule or (pattern, spec[, "
        f"strict]), got {r!r}")


class PartitionAssignment:
    """The engine's verdict for one program: qualified name → spec, with
    provenance (which rule decided each name) and the no-match record."""

    def __init__(self, specs: Dict[str, Tuple],
                 rule_of: Dict[str, Optional[PartitionRule]],
                 unmatched: List[str]):
        self.specs = dict(specs)
        self.rule_of = dict(rule_of)
        self.unmatched = list(unmatched)

    def spec(self, qualified: str) -> Tuple:
        return self.specs.get(qualified, REPLICATED)

    def sharded(self, qualified: str) -> bool:
        return bool(self.specs.get(qualified))

    def __repr__(self):
        n_sharded = sum(1 for s in self.specs.values() if s)
        return (f"PartitionAssignment({len(self.specs)} vars, "
                f"{n_sharded} sharded, {len(self.unmatched)} unmatched)")


def match_partition_rules(rules: Iterable, names: Iterable[str],
                          numels: Optional[Dict[str, int]] = None,
                          require_match: bool = False) \
        -> PartitionAssignment:
    """Match ordered `rules` against qualified `names`; first hit wins.

    ``numels`` (qualified name → element count) arms the exemplar's
    scalar guard: a var with <= 1 element is REPLICATED no matter what
    rule matches (beta-pow scalars must never be split).  A name no
    rule matches falls back to REPLICATED and is recorded in
    ``unmatched`` — unless ``require_match=True``, which raises instead
    (the exemplar's ``Partition rule not found`` behaviour).
    """
    rules = [_as_rule(r) for r in rules]
    numels = numels or {}
    specs: Dict[str, Tuple] = {}
    rule_of: Dict[str, Optional[PartitionRule]] = {}
    unmatched: List[str] = []
    for name in names:
        if numels.get(name, 2) <= 1:
            specs[name] = REPLICATED  # scalars are never partitioned
            rule_of[name] = None
            continue
        for rule in rules:
            if rule.matches(name):
                specs[name] = rule.spec
                rule_of[name] = rule
                break
        else:
            if require_match:
                raise ValueError(
                    f"partition rule not found for var: {name!r}")
            specs[name] = REPLICATED
            rule_of[name] = None
            unmatched.append(name)
    return PartitionAssignment(specs, rule_of, unmatched)


def zero_stage_rules(stage: int) -> List[PartitionRule]:
    """The ZeRO ladder as data: the default rule list for each stage.

    stage 0 — pure DP, everything replicated;
    stage 1 — optimizer slots shard over dp;
    stage 2 — slots + gradient(-merge) accumulators shard;
    stage 3 — slots + grad accumulators + the parameters themselves.

    Every stage is the previous stage plus one rule; the rules are
    non-strict (a var the pass can't shard degrades to replicated with
    the pass's own warning) so the DEFAULTS never refuse a model —
    refusal is reserved for user rules that name vars explicitly.
    """
    stage = int(stage)
    if stage not in (0, 1, 2, 3):
        raise ValueError(f"ZeRO stage must be 0-3, got {stage}")
    rules: List[PartitionRule] = [
        PartitionRule(r"^scalar:", REPLICATED, strict=False),
    ]
    if stage >= 3:
        rules.append(PartitionRule(r"^param:", DP_SHARD, strict=False))
    if stage >= 2:
        rules.append(PartitionRule(r"^grad_acc:", DP_SHARD, strict=False))
    if stage >= 1:
        rules.append(PartitionRule(r"^slot:", DP_SHARD, strict=False))
    rules.append(PartitionRule(r".*", REPLICATED, strict=False))
    return rules


def tensor_parallel_rules() -> List[PartitionRule]:
    """The Megatron col/row split discipline as data: seed rules for the
    layout analyzer (`static.propagate_shardings`) matching the default
    parameter names `distributed/tensor_parallel.py`'s builders mint
    (``col_parallel_fc_<n>.w_<k>`` etc.).  Parameters the builders
    annotated with ``dist_attr`` don't need these — the rules exist for
    programs rebuilt from serialized IR that predates the annotation,
    and as the vocabulary user rule lists extend (prepend a rule to
    shard a custom projection).  Non-strict: a name that matches but
    cannot shard degrades to replicated."""
    return [
        PartitionRule(r"^param:col_parallel_fc.*\.w_", MP_COL,
                      strict=False),
        PartitionRule(r"^param:col_parallel_fc.*\.b_", ("mp",),
                      strict=False),
        PartitionRule(r"^param:row_parallel_fc.*\.w_", MP_ROW,
                      strict=False),
        PartitionRule(r"^param:row_parallel_fc.*\.b_", REPLICATED,
                      strict=False),
    ]


def build_sharding_specs(program, stage: int,
                         extra_rules: Iterable = ()) -> PartitionAssignment:
    """Run the (user rules + stage defaults) rule list over `program`'s
    trainable-state surface and return the assignment the ZeRO pass
    executes.

    The shardable surface is exactly what `shard_optimizer_states` can
    partition (shared candidate walk, so the plan never promises what
    the pass can't do): each candidate optimizer op contributes its
    ``param:``, ``slot:`` and ``scalar:`` names; ``grad_acc:`` names are
    the per-bucket gradient accumulators `gradient_merge` would create.
    Params the pass must skip (unsupported optimizer, MasterParam,
    sparse grad, dynamic shape) are still matched — a *strict* rule
    landing a sharded spec on one of them is the over-match refusal.
    """
    from .sharding import _collect_candidates, _SHARDABLE
    rules = [_as_rule(r) for r in extra_rules] + zero_stage_rules(stage)
    block = program.global_block()
    cands = _collect_candidates(block, warn=False)
    cand_params = set()
    names: List[str] = []
    numels: Dict[str, int] = {}

    # NOTE: the scalar never-partition guard applies to the ``scalar:``
    # CATEGORY (beta pows — shape-[1] state that must not be split),
    # not to 1-element params/slots: a [1] bias is concatenated into a
    # bucket, never partitioned alone, so it buckets like anything else.
    for _, op in cands:
        spec = _SHARDABLE[op.type]
        pname = op.inputs["Param"][0]
        cand_params.add(pname)
        names.append(f"param:{pname}")
        names.append(f"grad_acc:{op.inputs['Grad'][0]}")
        for in_slot, _out in spec["slots"]:
            for n in op.inputs.get(in_slot, []):
                if n:
                    names.append(f"slot:{n}")
        for in_slot, _out, _k, _d in spec["scalars"]:
            for n in op.inputs.get(in_slot, []):
                if n:
                    names.append(f"scalar:{n}")
                    numels[f"scalar:{n}"] = 1

    # the UN-shardable surface: matched too, so strict rules can refuse.
    # Params come from the var table; their accumulators come from the
    # accum_of link (an Adamax moment has no _SHARDABLE spec to
    # enumerate, but the optimizer stamped its owner at creation).
    unshardable: set = set()
    for v in block.vars.values():
        if v.is_parameter and v.name not in cand_params:
            q = f"param:{v.name}"
            names.append(q)
            unshardable.add(q)
    for v in block.vars.values():
        owner = v.attrs.get("accum_of")
        if owner and owner not in cand_params:
            q = f"slot:{v.name}"
            names.append(q)
            unshardable.add(q)

    assignment = match_partition_rules(rules, names, numels)
    for q in unshardable:
        rule = assignment.rule_of.get(q)
        if assignment.sharded(q) and rule is not None and rule.strict:
            raise ValueError(
                f"partition rule {rule!r} assigns a sharded spec to "
                f"{q!r}, but the sharding pass cannot partition it "
                f"(unsupported optimizer op, MasterParam slot, sparse "
                f"gradient, or dynamic shape) — over-match refused; "
                f"drop the rule or mark it strict=False")
    return assignment


def state_partition_specs(program, mesh, state_names: Iterable[str]):
    """The `shard_map` in/out specs for a program's persistable state:

    * every ``dp_shard``-marked var (the ZeRO passes' stamped spec)
      materializes as ``PartitionSpec("dp")`` — on a 2-D dp×tp mesh
      that places the bucket over the dp sub-axis only, replicated
      across tp (each tp rank holds, donates and updates the same slot
      shard — the ZeRO×tp composition's placement contract);
    * when the mesh carries a ``tp`` axis, parameters annotated
      ``dist_attr`` (`tensor_parallel.shard_param`) shard their
      declared dim over it, and optimizer accumulators inherit their
      param's spec through the ``accum_of`` link (name-prefix + equal
      shape as the legacy fallback);
    * everything else is replicated.

    The single consumption point `CompiledProgram` routes through (both
    the per-dispatch and scanned compile paths), so the spec the engine
    decided and the spec the mesh executes can never drift apart."""
    from jax.sharding import PartitionSpec as P
    block = program.global_block()
    has_tp = "tp" in getattr(mesh, "axis_names", ())
    specs = {}
    annotated = {}
    for n in state_names:
        try:
            v = block.var(n)
        except KeyError:
            specs[n] = P()
            continue
        marked = int(v.attrs.get("dp_shard") or 0)
        if marked:
            dp = mesh.shape["dp"]
            if not v.shape or int(v.shape[0]) % dp != 0:
                raise ValueError(
                    f"ZeRO-sharded var {n!r} (shape {v.shape}) does not "
                    f"divide the mesh dp degree {dp}; re-run "
                    f"shard_optimizer_states for this mesh")
            specs[n] = P("dp")
            continue
        da = v.attrs.get("dist_attr") if has_tp else None
        if da:
            axis, dim = da
            spec = [None] * len(v.shape or ())
            spec[int(dim)] = axis
            specs[n] = P(*spec)
            annotated[n] = (tuple(v.shape or ()), P(*spec))
            continue
        specs[n] = P()
    if annotated:
        # optimizer accumulators inherit their param's tp sharding
        for n in state_names:
            if n in annotated or specs.get(n) != P():
                continue
            try:
                v = block.var(n)
            except KeyError:
                continue
            shape = tuple(v.shape or ())
            # explicit accumulator→param link (set by
            # Optimizer._add_accumulator) — the old name-prefix+shape
            # heuristic could match an unrelated var whose name
            # happened to extend an annotated param's
            owner = v.attrs.get("accum_of")
            if owner is not None:
                hit = annotated.get(owner)
                if hit is not None and shape == hit[0]:
                    specs[n] = hit[1]
                continue
            for pname, (pshape, pspec) in annotated.items():
                if n.startswith(pname + "_") and shape == pshape:
                    specs[n] = pspec
                    break
    return specs


def feed_partition_specs(program, mesh, feed_names: Iterable[str]):
    """The `shard_map` in-specs for a program's FEEDS — the serving
    sibling of `state_partition_specs`.

    Training feeds are batches: dim 0 splits over the data-parallel
    axis, always, and that is the historical hard-coded
    ``P("dp")``-for-everything behaviour this function preserves as the
    default.  A tensor-parallel decode program breaks the monoculture:
    its per-layer KV-cache feeds shard on the HEAD dim over ``tp``
    (`tensor_parallel.shard_param`'s ``dist_attr`` spelling, stamped on
    the feed var by the decode builder), and its token/position/mask
    feeds are REPLICATED (every chip decodes the same rows; dp is a
    replication axis on the serving mesh) — stamped
    ``replicated_feed`` by the builder.  Vars the program does not
    declare fall back to ``P("dp")``, the training contract."""
    from jax.sharding import PartitionSpec as P
    block = program.global_block()
    has_tp = "tp" in getattr(mesh, "axis_names", ())
    specs = {}
    for n in feed_names:
        try:
            v = block.var(n)
        except KeyError:
            specs[n] = P("dp")
            continue
        da = v.attrs.get("dist_attr") if has_tp else None
        if da:
            axis, dim = da
            spec = [None] * len(v.shape or ())
            spec[int(dim)] = axis
            specs[n] = P(*spec)
        elif v.attrs.get("replicated_feed"):
            specs[n] = P()
        else:
            specs[n] = P("dp")
    return specs

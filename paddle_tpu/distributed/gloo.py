"""Gloo-analog: CPU-side barrier / all_gather / all_reduce for the fleet
control plane.

Reference: /root/reference/paddle/fluid/framework/fleet/gloo_wrapper.h:45,106
(GlooWrapper over a gloo store; HdfsStore/HTTP rendezvous) and the python
wrapper /root/reference/python/paddle/distributed/fleet/base/role_maker.py:31
(class Gloo, RENDEZVOUS.HDFS/FILE/HTTP).

TPU-native scope: the DENSE collective path is XLA over ICI and never
touches this; what Gloo actually does for fleet jobs is host-side
coordination — role-maker barriers, UtilBase all_gather of small python
objects, PS init fences.  So this is a small store-based implementation
with two rendezvous backends:

  * FILE  — a shared directory (single host or NFS): each rank writes
    `<prefix>/<world>/<generation>/<rank>` and polls for its peers —
    byte-for-byte the HdfsStore pattern with local files.
  * HTTP  — the KV server (distributed/ps/kv_server.py) as the store,
    reusing its OP_SET/OP_PULL plane (the reference's http_server.py
    role).

Generation counters make barriers/gathers reusable (no stale-key
aliasing between consecutive collectives).
"""
from __future__ import annotations

import os
import pickle
import time
from typing import Any, List, Optional

__all__ = ["Gloo", "RENDEZVOUS"]


class RENDEZVOUS:
    HDFS = 1   # accepted for parity; maps to FILE semantics on a mount
    FILE = 2
    HTTP = 3


class _FileStore:
    def __init__(self, path: str, prefix: str = ""):
        self.root = os.path.join(path, prefix or "gloo")
        os.makedirs(self.root, exist_ok=True)

    def set(self, key: str, blob: bytes):
        p = os.path.join(self.root, key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, p)  # atomic publish

    def get(self, key: str) -> Optional[bytes]:
        p = os.path.join(self.root, key)
        try:
            with open(p, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str):
        try:
            os.remove(os.path.join(self.root, key))
        except OSError:
            pass


class _KVStore:
    def __init__(self, endpoint: str, prefix: str = ""):
        from .ps.kv_server import KVClient
        self._c = KVClient([endpoint])
        self._prefix = prefix or "gloo"

    def set(self, key: str, blob: bytes):
        import numpy as np
        self._c.set_param(f"{self._prefix}/{key}",
                          np.frombuffer(blob, dtype=np.uint8).copy())

    def get(self, key: str) -> Optional[bytes]:
        try:
            arr = self._c.pull(f"{self._prefix}/{key}")
        except KeyError:
            return None
        import numpy as np
        return np.asarray(arr, dtype=np.uint8).tobytes()

    def delete(self, key: str):
        # KV server has no delete op; overwrite with an empty sentinel —
        # get() treats a zero-length value as present, so shrink instead
        # of delete (bounded at one byte per stale key)
        import numpy as np
        try:
            self._c.set_param(f"{self._prefix}/{key}",
                              np.zeros((0,), np.uint8))
        except (ConnectionError, OSError):
            pass


class Gloo:
    """Barrier + object collectives over a rendezvous store."""

    def __init__(self):
        self._store = None
        self._rank = 0
        self._size = 1
        self._gen = {}
        self._timeout = float(os.environ.get(
            "PADDLE_GLOO_RUN_TIMEOUT_SECONDS", "300"))
        self._is_initialized = False

    # -- reference Gloo.init signature (role_maker.py:65) -------------------
    def init(self, rendezvous, role, role_id, worker_num, server_num=0,
             need_init_all=False, kwargs=None):
        kwargs = kwargs or {}
        prefix = kwargs.get("store.prefix", "")
        if rendezvous in (RENDEZVOUS.FILE, RENDEZVOUS.HDFS):
            path = kwargs.get("dfs.path", "")
            if not path:
                raise ValueError("Gloo FILE rendezvous needs dfs.path")
            self._store = _FileStore(path, prefix)
        elif rendezvous == RENDEZVOUS.HTTP:
            host = kwargs.get("http.host", "")
            port = kwargs.get("http.port", "")
            if not host or not port:
                raise ValueError("Gloo HTTP rendezvous needs http.host/port")
            self._store = _KVStore(f"{host}:{port}", prefix)
        else:
            raise ValueError(f"unknown rendezvous {rendezvous}")
        self._rank = int(role_id)
        # the size of THIS role's world (reference: servers rendezvous in
        # their own comm, role_maker.py _init_fs role="SERVER")
        self._size = int(server_num if (str(role).lower() == "server"
                                        and server_num) else worker_num)
        self._role = role
        self._is_initialized = True

    def rank(self):
        return self._rank

    def size(self):
        return self._size

    def is_initialized(self):
        return self._is_initialized

    # -- collectives --------------------------------------------------------
    def _next_gen(self, world: str) -> int:
        g = self._gen.get(world, 0)
        self._gen[world] = g + 1
        return g

    def _gather_blobs(self, world: str, payload: bytes) -> List[bytes]:
        gen = self._next_gen(world)
        base = f"{world}/{gen}"
        self._store.set(f"{base}/{self._rank}", payload)
        if gen > 1:
            # safe-point GC: we completed gen-1, so every peer WROTE its
            # gen-1 blob, and writing gen-1 proves that peer finished
            # READING all of gen-2 — our gen-2 blob can never be needed
            # again.  (gen-1 is NOT safe: a slow peer may still be
            # polling it.)  Keeps a long-running job at <= 2 blobs per
            # rank per world instead of one per collective.
            self._store.delete(f"{world}/{gen - 2}/{self._rank}")
        out: List[Optional[bytes]] = [None] * self._size
        deadline = time.time() + self._timeout
        while True:
            missing = False
            for r in range(self._size):
                if out[r] is None:
                    out[r] = self._store.get(f"{base}/{r}")
                    if out[r] is None:
                        missing = True
            if not missing:
                return out  # type: ignore[return-value]
            if time.time() > deadline:
                absent = [r for r in range(self._size) if out[r] is None]
                raise TimeoutError(
                    f"gloo {world} collective gen {gen}: ranks {absent} "
                    f"absent after {self._timeout:.0f}s")
            time.sleep(0.02)

    def barrier(self, comm_world: str = "worker"):
        self._gather_blobs(f"barrier/{comm_world}", b"1")

    def all_gather(self, obj: Any, comm_world: str = "worker") -> List[Any]:
        blobs = self._gather_blobs(f"gather/{comm_world}",
                                   pickle.dumps(obj))
        return [pickle.loads(b) for b in blobs]

    def all_reduce(self, x, fn="sum", comm_world: str = "worker"):
        import numpy as np
        vals = self.all_gather(np.asarray(x), comm_world)
        if fn in ("sum", "SUM"):
            return sum(vals[1:], vals[0])
        if fn in ("max", "MAX"):
            return np.maximum.reduce(vals)
        if fn in ("min", "MIN"):
            return np.minimum.reduce(vals)
        raise ValueError(f"unknown reduce fn {fn!r}")


def gloo_from_env(role: str = "worker") -> Optional[Gloo]:
    """Build a Gloo from the launcher env contract
    (PADDLE_GLOO_RENDEZVOUS / PADDLE_GLOO_FS_PATH /
    PADDLE_GLOO_HTTP_ENDPOINT — the reference fleet launch variables);
    returns None when no rendezvous is configured.

    rank/size are ROLE-aware: workers index by PADDLE_TRAINER_ID over
    PADDLE_TRAINERS_NUM, servers by their endpoint's position in
    PADDLE_PSERVERS_IP_PORT_LIST (or PADDLE_PSERVER_ID), so the two
    role worlds never alias each other's store keys."""
    rdv = os.environ.get("PADDLE_GLOO_RENDEZVOUS", "")
    if not rdv:
        return None
    g = Gloo()
    if role == "server":
        servers = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        size = max(1, len(servers))
        rank_env = os.environ.get("PADDLE_PSERVER_ID")
        if rank_env is not None:
            rank = int(rank_env)
        else:
            ep = (f"{os.environ.get('POD_IP', '127.0.0.1')}:"
                  f"{os.environ.get('PADDLE_PORT', '0')}")
            if ep not in servers:
                # a silent rank-0 fallback would let several servers
                # claim the same rank and alias store keys — fail loud
                raise ValueError(
                    f"gloo server rendezvous: endpoint {ep!r} not in "
                    f"PADDLE_PSERVERS_IP_PORT_LIST {servers}; set "
                    "PADDLE_PSERVER_ID explicitly or fix POD_IP/"
                    "PADDLE_PORT")
            rank = servers.index(ep)
    else:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    kwargs = {
        # run-unique namespace: a restarted/elastic incarnation (or a
        # second job sharing the same dfs.path) must not consume a
        # previous run's blobs — the launcher stamps a fresh id per
        # incarnation; collectives can't survive a MID-RUN single-rank
        # restart (peers are mid-generation), which matches the
        # reference gloo's behavior (rendezvous is per-job)
        "store.prefix": "gloo_" + os.environ.get(
            "PADDLE_GLOO_RUN_ID", os.environ.get("PADDLE_JOB_ID", "run0")),
    }
    rdv_i = int(rdv)
    if rdv_i in (RENDEZVOUS.FILE, RENDEZVOUS.HDFS):
        kwargs["dfs.path"] = os.environ.get("PADDLE_GLOO_FS_PATH", "")
    else:
        ep = os.environ.get("PADDLE_GLOO_HTTP_ENDPOINT", "")
        host, _, port = ep.rpartition(":")
        kwargs["http.host"] = host
        kwargs["http.port"] = port
    g.init(rdv_i, role, rank, worker_num=size, server_num=size,
           kwargs=kwargs)
    return g

"""Industrial dataset path: InMemoryDataset / QueueDataset + MultiSlot feed.

Reference: /root/reference/python/paddle/fluid/dataset.py (DatasetFactory,
InMemoryDataset load_into_memory/local_shuffle/global_shuffle,
QueueDataset), framework/data_feed.h:117,302 (MultiSlotDataFeed text
format), framework/data_set.h:101-111 (LoadIntoMemory/LocalShuffle/
GlobalShuffle), and the MultiTrainer/DeviceWorker hot loop
(framework/multi_trainer.cc, device_worker.cc) consumed by
Executor.train_from_dataset (executor.py:1345).

TPU-native redesign:
  * The reference's N hogwild device-workers each pull batches and run the
    per-op interpreter; one TPU chip wants ONE whole-block jitted step fed
    fast.  So "threads" become a host-side parse/prefetch producer feeding
    the native C++ BlockingQueue (native/blocking_queue.cc), and the train
    loop pops ready batches and runs the jitted step — IO overlaps compute
    without NUMA worker plumbing.
  * MultiSlot text format is parsed into numpy batches; variable-length id
    slots are padded per batch (io/bucketing.py replaces LoD as the ragged
    carrier; pad value 0 with an explicit <slot>.lod lengths array fed when
    the program declares it).
  * global_shuffle: records are hash-partitioned by instance so each
    trainer keeps a disjoint 1/N shard (data_set.h GlobalShuffle semantics
    of "each ins lands on exactly one trainer").  With a live PS
    (fleet.util KV endpoints) records for other trainers would ride the KV
    server; single-process worlds reduce to a seeded local shuffle.
"""
from __future__ import annotations

import glob as _glob
import pickle
import threading
import zlib
from typing import Dict, Iterable, List, Optional

import numpy as np

__all__ = ["DatasetFactory", "DatasetBase", "InMemoryDataset",
           "QueueDataset", "MultiSlotDataFeed"]


class MultiSlotDataFeed:
    """Parser for the MultiSlot text format (data_feed.h:302): each line
    holds, per slot in order, `<count> v1 ... v<count>`.  Slot dtype comes
    from the bound use_vars: integer vars are sparse id slots
    (variable-length), float vars are dense slots."""

    def __init__(self, slot_names: List[str], slot_dtypes: List[str]):
        self.slot_names = list(slot_names)
        self.slot_dtypes = list(slot_dtypes)

    def parse_line(self, line: str):
        toks = line.split()
        rec, i = [], 0
        for dt in self.slot_dtypes:
            if i >= len(toks):
                raise ValueError(f"truncated MultiSlot line: {line!r}")
            n = int(toks[i])
            i += 1
            vals = toks[i:i + n]
            if len(vals) != n:
                raise ValueError(f"slot count {n} exceeds line: {line!r}")
            i += n
            if "int" in dt:
                rec.append(np.asarray([int(v) for v in vals], np.int64))
            else:
                rec.append(np.asarray([float(v) for v in vals], np.float32))
        return rec


class DatasetBase:
    """fluid.dataset.DatasetBase parity: configuration + batch assembly."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_vars = []
        self.pipe_command = None
        self.seed = 0
        self._feed: Optional[MultiSlotDataFeed] = None

    # -- reference setters ---------------------------------------------------
    def set_batch_size(self, batch_size: int):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        # reference: N device workers; here: prefetch producer count
        self.thread_num = max(1, int(thread_num))

    def set_filelist(self, filelist: List[str]):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        self.use_vars = list(var_list)
        self._feed = MultiSlotDataFeed(
            [v.name for v in self.use_vars],
            [v.dtype or "float32" for v in self.use_vars])

    def set_pipe_command(self, pipe_command: str):
        # reference pipes each file through a shell command (data_feed
        # pipe reader); zero-egress images rarely allow this — store it and
        # refuse at load time so misuse is loud, not silent
        self.pipe_command = pipe_command

    def set_hdfs_config(self, fs_name, fs_ugi):
        raise NotImplementedError(
            "HDFS-backed filelists are not supported; stage files on "
            "local disk (fleet.utils.fs LocalFS)")

    # -- record iteration ----------------------------------------------------
    def _iter_file(self, path: str) -> Iterable[List[np.ndarray]]:
        if self.pipe_command:
            raise NotImplementedError(
                "set_pipe_command preprocessing is not supported on this "
                "runtime; preprocess files ahead of time")
        assert self._feed is not None, "call set_use_var first"
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield self._feed.parse_line(line)

    def _records_to_batch(self, records: List[List[np.ndarray]]):
        """Pad/stack one batch into a feed dict (LoD -> pad + lengths)."""
        from ..io.bucketing import pad_sequences
        feed: Dict[str, np.ndarray] = {}
        for j, v in enumerate(self.use_vars):
            cols = [r[j] for r in records]
            dt = v.dtype or "float32"
            if "int" in dt:
                ls = [c.shape[0] for c in cols]
                if min(ls) == max(ls):
                    padded, lens = pad_sequences(cols, pad_value=0)
                else:
                    # ragged slot: pad to a multiple of 8 so the jit
                    # executor compiles a handful of bucket shapes per
                    # epoch, not one per distinct batch max-length;
                    # consumers mask padding (id 0) via <slot>.lod
                    padded, lens = pad_sequences(cols, pad_value=0,
                                                 multiple_of=8)
                feed[v.name] = padded.astype(np.int64)
                feed[v.name + ".lod"] = lens
            else:
                feed[v.name] = np.stack(cols).astype(np.float32)
        return feed

    def _batches(self, records) -> Iterable[Dict[str, np.ndarray]]:
        buf = []
        for r in records:
            buf.append(r)
            if len(buf) == self.batch_size:
                yield self._records_to_batch(buf)
                buf = []
        if buf:
            yield self._records_to_batch(buf)


class InMemoryDataset(DatasetBase):
    """data_set.h:101 InMemoryDataset: load -> shuffle -> train."""

    def __init__(self):
        super().__init__()
        self._records: List[List[np.ndarray]] = []
        self._loaded = False
        self._preload_thread = None

    def load_into_memory(self):
        self._records = []
        for pat in self.filelist:
            for path in sorted(_glob.glob(pat)) or [pat]:
                self._records.extend(self._iter_file(path))
        self._loaded = True

    def preload_into_memory(self, thread_num=None):
        self._preload_thread = threading.Thread(target=self.load_into_memory,
                                                daemon=True)
        self._preload_thread.start()

    def wait_preload_done(self):
        if self._preload_thread is not None:
            self._preload_thread.join()
            self._preload_thread = None

    def local_shuffle(self):
        rng = np.random.RandomState(self.seed)
        rng.shuffle(self._records)
        self.seed += 1

    def global_shuffle(self, fleet=None, thread_num=None):
        """Hash-partition instances across trainers, then shuffle the own
        shard (GlobalShuffle: every instance lands on exactly one trainer).

        CONTRACT (documented divergence from the reference): every trainer
        must have loaded the SAME filelist — partitioning keeps the
        crc32%N==rank slice of the trainer's own memory and does not
        redistribute records between trainers the way the reference's
        PS-routed GlobalShuffle (data_set.h:109) does.  With disjoint
        per-trainer filelists this would silently drop (N-1)/N of the
        data; use local_shuffle() there instead."""
        rank, nranks = 0, 1
        if fleet is not None:
            try:
                rank = fleet.worker_index()
                nranks = fleet.worker_num()
            except Exception:
                pass
        if nranks > 1:
            keep = []
            for i, r in enumerate(self._records):
                key = zlib.crc32(b"|".join(x.tobytes() for x in r))
                if key % nranks == rank:
                    keep.append(r)
            self._records = keep
        self.local_shuffle()

    def release_memory(self):
        self._records = []
        self._loaded = False

    def get_memory_data_size(self, fleet=None):
        return len(self._records)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._records)


class QueueDataset(DatasetBase):
    """Streaming dataset: batches parsed lazily per epoch; no in-memory
    shuffle (reference QueueDataset.local_shuffle raises)."""

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams from files; use InMemoryDataset for "
            "local_shuffle (reference dataset.py QueueDataset)")

    def global_shuffle(self, fleet=None, thread_num=None):
        raise NotImplementedError(
            "QueueDataset does not support global_shuffle; use "
            "InMemoryDataset")

    def _stream_records(self):
        for pat in self.filelist:
            for path in sorted(_glob.glob(pat)) or [pat]:
                yield from self._iter_file(path)


class DatasetFactory:
    """fluid.DatasetFactory().create_dataset("InMemoryDataset")"""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")


# ---------------------------------------------------------------------------
# prefetching trainer loop (MultiTrainer/DeviceWorker analog)
# ---------------------------------------------------------------------------
def _batch_queue(batches: Iterable[Dict[str, np.ndarray]], capacity: int):
    """Producer thread -> (native, else stdlib) blocking queue of pickled
    batches; returns (pop, join) callables.  A producer exception is
    captured and re-raised in the consumer — never a hang (stdlib) or a
    silently truncated epoch (native)."""
    err: List[BaseException] = []

    def _raise_if_failed():
        if err:
            raise RuntimeError(
                "dataset producer thread failed") from err[0]

    from ..native import BlockingQueue, available
    if available():
        q = BlockingQueue(capacity)

        def produce():
            try:
                for b in batches:
                    q.push(pickle.dumps(b, protocol=4))
            except BaseException as e:  # noqa: BLE001 - reraised in consumer
                err.append(e)
            finally:
                q.close()

        t = threading.Thread(target=produce, daemon=True)
        t.start()

        def pop():
            try:
                data = q.pop()
            except EOFError:  # closed and drained
                _raise_if_failed()
                return None
            if data is None:
                _raise_if_failed()
                return None
            return pickle.loads(data)

        return pop, t.join
    import queue as _q
    q2: "_q.Queue" = _q.Queue(maxsize=capacity)
    _DONE = object()

    def produce2():
        try:
            for b in batches:
                q2.put(b)
        except BaseException as e:  # noqa: BLE001 - reraised in consumer
            err.append(e)
        finally:
            q2.put(_DONE)

    t2 = threading.Thread(target=produce2, daemon=True)
    t2.start()

    def pop2():
        item = q2.get()
        if item is _DONE:
            _raise_if_failed()
            return None
        return item

    return pop2, t2.join


def run_from_dataset(executor, program, dataset, scope=None,
                     fetch_list=None, fetch_info=None, print_period=100,
                     debug=False, chunk_steps=None):
    """One pass over the dataset through the jitted executor step — the
    train_from_dataset/infer_from_dataset hot loop (executor.py:1345,
    multi_trainer.cc RunFromDataset).

    chunk_steps > 1 (or FLAGS_dataset_chunk_steps) batches consecutive
    same-shape steps into ONE device dispatch via Executor.run_steps
    (lax.scan) — the reference's C++ trainer keeps the batch loop out of
    Python for the same reason; on a high-latency dispatch link this is
    the difference between wall and device throughput.  Ragged batches
    (e.g. the last partial one) fall back to per-step run()."""
    if isinstance(dataset, InMemoryDataset):
        if not dataset._loaded:
            raise RuntimeError(
                "InMemoryDataset: call load_into_memory() before "
                "train_from_dataset")
        records = dataset._records
    elif isinstance(dataset, QueueDataset):
        records = dataset._stream_records()
    else:
        raise TypeError(f"not a dataset: {dataset!r}")

    from ..core.flags import flag
    if chunk_steps is None:
        chunk_steps = int(flag("dataset_chunk_steps", 1))
    if flag("eager_run", False):
        # debug modes want the per-op path (op naming in NaN scans);
        # never route them through the scanned dispatch
        chunk_steps = 1

    # drop feed names the program does not declare (.lod helpers);
    # a CompiledProgram exposes its (rewritten) Program's block
    block = (program._get_program() if hasattr(program, "_get_program")
             else program).global_block()
    pop, join = _batch_queue(dataset._batches(records),
                             capacity=max(2, 2 * dataset.thread_num))

    def _popped():
        while True:
            b = pop()
            if b is None:
                return
            yield {k: v for k, v in b.items() if block.has_var(k)}

    # second pipeline stage: async device placement (reader.Prefetcher)
    # so batch N+1's host->device transfer overlaps batch N's step.  A
    # CompiledProgram brings its own mesh-aware placement
    # (CompiledProgram.place_feed: dp-sharded NamedSharding); plain
    # programs take the default single-device place_feed.  The chunked
    # (run_steps) path stacks batches on the HOST before its one big
    # transfer, so there the prefetcher only read-aheads (place=False)
    # instead of paying a device round-trip per batch.
    prefetch_depth = int(flag("dataset_prefetch_depth", 2))
    if prefetch_depth > 0:
        from ..reader.prefetcher import Prefetcher
        place = chunk_steps <= 1 and not flag("eager_run", False)
        place_fn = getattr(program, "place_feed", None) if place else None
        batch_iter = Prefetcher(_popped(), depth=prefetch_depth,
                                place_fn=place_fn, place=place)
    else:
        batch_iter = _popped()

    fetch_list = fetch_list or []
    fetch_names = [f.name if hasattr(f, "name") else str(f)
                   for f in fetch_list]
    step = 0
    last = []

    def _report(vals):
        if debug or (fetch_names and step % print_period == 0):
            info = fetch_info or fetch_names
            msg = ", ".join(f"{n}={np.asarray(v).ravel()[:4]}"
                            for n, v in zip(info, vals))
            print(f"[dataset step {step}] {msg}")

    def _sig(feed):
        return tuple(sorted((k, np.shape(v)) for k, v in feed.items()))

    pending = []  # same-shape feeds awaiting one scanned dispatch

    def _flush():
        nonlocal step, last
        if not pending:
            return
        if len(pending) == 1:
            last = executor.run(program, feed=pending[0],
                                fetch_list=fetch_list, scope=scope)
            step += 1
            _report(last)
        else:
            stacked = {k: np.stack([f[k] for f in pending])
                       for k in pending[0]}
            outs = executor.run_steps(program, feed=stacked,
                                      fetch_list=fetch_list, scope=scope)
            # per-step reporting parity with the unchunked path: the
            # scan returns every step's fetches, not just the last
            for i in range(len(pending)):
                step += 1
                _report([o[i] for o in outs])
            last = [o[-1] for o in outs]
        pending.clear()

    try:
        for feed in batch_iter:
            if chunk_steps <= 1 or not feed:
                # feed-less programs (no declared dataset slots) cannot be
                # stacked — run them per step like the unchunked path
                _flush()
                last = executor.run(program, feed=feed,
                                    fetch_list=fetch_list, scope=scope)
                step += 1
                _report(last)
                continue
            if pending and _sig(feed) != _sig(pending[0]):
                _flush()
            pending.append(feed)
            if len(pending) >= chunk_steps:
                _flush()
        _flush()
    finally:
        close = getattr(batch_iter, "close", None)
        if close is not None:
            close()
    join()
    return last

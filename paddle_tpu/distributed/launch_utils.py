"""Cluster/Pod topology + worker process management for the launcher.

Reference: /root/reference/python/paddle/distributed/fleet/launch_utils.py —
Cluster/Pod/Trainer abstraction, `get_cluster`, `start_local_trainers`,
`watch_local_trainers` (the launcher watchdog that aborts the job and kills
sibling workers when any worker dies — the fleet failure-detection story,
SURVEY.md §5.3), log redirection to workerlog.N.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["Cluster", "Pod", "Trainer", "get_cluster",
           "start_local_trainers", "watch_local_trainers", "terminate_procs",
           "poll_local_trainers", "find_free_ports"]


class Trainer:
    def __init__(self, endpoint="", rank=-1, devices=None):
        self.endpoint = endpoint
        self.rank = rank
        self.accelerators = devices or []

    def __repr__(self):
        return f"Trainer(rank={self.rank}, ep={self.endpoint})"


class Pod:
    """One physical node (= one TPU host)."""

    def __init__(self, id=0, addr="127.0.0.1"):
        self.id = id
        self.addr = addr
        self.port = None
        self.trainers: List[Trainer] = []
        self.servers: List[Trainer] = []
        self.workers: List[Trainer] = []

    def rank(self):
        return self.id


class Cluster:
    def __init__(self, hdfs=None):
        self.job_server = None
        self.pods: List[Pod] = []
        self.hdfs = hdfs

    def trainers_nranks(self) -> int:
        return len(self.trainers_endpoints())

    def trainers_endpoints(self) -> List[str]:
        return [t.endpoint for p in self.pods for t in p.trainers]

    def pods_endpoints(self):
        return [f"{p.addr}:{p.port}" for p in self.pods]


def find_free_ports(num):
    from .spawn import get_free_ports
    return get_free_ports(num)


def get_cluster(node_ips, node_ip, trainer_endpoints, devices_per_proc):
    """launch_utils.py get_cluster parity: build Cluster/Pod/Trainer from
    resolved endpoints.  devices_per_proc: list of device-sets, one per
    trainer on this node."""
    cluster = Cluster()
    rank = 0
    for pod_id, ip in enumerate(node_ips):
        pod = Pod(pod_id, ip)
        eps = (trainer_endpoints[pod_id]
               if isinstance(trainer_endpoints[0], list)
               else [e for e in trainer_endpoints
                     if e.split(":")[0] == ip])
        for i, ep in enumerate(eps):
            devs = (devices_per_proc[i]
                    if i < len(devices_per_proc) else [i])
            pod.trainers.append(Trainer(ep, rank, devs))
            rank += 1
        cluster.pods.append(pod)
    pod = next(p for p in cluster.pods if p.addr == node_ip)
    return cluster, pod


class TrainerProc:
    def __init__(self):
        self.proc: Optional[subprocess.Popen] = None
        self.log_fn = None
        self.rank = None
        self.local_rank = None
        self.cmd = None


def start_local_trainers(cluster: Cluster, pod: Pod, training_script,
                         training_script_args, log_dir=None, envs=None):
    """Spawn one subprocess per local trainer with the PADDLE_* contract
    (launch_utils.py start_local_trainers)."""
    procs = []
    for local_rank, t in enumerate(pod.trainers):
        env = dict(os.environ, **(envs or {}))
        env.update({
            "PADDLE_TRAINER_ID": str(t.rank),
            "PADDLE_CURRENT_ENDPOINT": t.endpoint,
            "PADDLE_TRAINERS_NUM": str(cluster.trainers_nranks()),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(
                cluster.trainers_endpoints()),
            "FLAGS_selected_xlas": ",".join(str(d) for d in t.accelerators),
        })
        cmd = [sys.executable, "-u", training_script] + \
            list(training_script_args)
        tp = TrainerProc()
        tp.rank = t.rank
        tp.local_rank = local_rank
        tp.cmd = cmd
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            tp.log_fn = open(os.path.join(
                log_dir, f"workerlog.{local_rank}"), "a")
            tp.proc = subprocess.Popen(cmd, env=env, stdout=tp.log_fn,
                                       stderr=tp.log_fn)
        else:
            tp.proc = subprocess.Popen(cmd, env=env)
        procs.append(tp)
    return procs


def terminate_procs(procs: List[TrainerProc], sigterm_grace: float = 10.0):
    """Tear a pod down with SIGTERM → grace → SIGKILL escalation.

    SIGTERM first so every trainer's preemption handler gets to drain and
    write its final checkpoint (CheckpointManager.install_preemption_
    handler); any process still alive `sigterm_grace` seconds later is
    SIGKILLed — a trainer wedged inside a dead collective never responds
    to SIGTERM, and leaving it would hang the launcher forever.  Killed
    children are always reaped (no zombies for a long-lived supervisor
    that relaunches in a loop)."""
    for tp in procs:
        if tp.proc is not None and tp.proc.poll() is None:
            tp.proc.terminate()
    deadline = time.time() + max(0.0, float(sigterm_grace))
    for tp in procs:
        if tp.proc is None:
            continue
        try:
            tp.proc.wait(max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            tp.proc.kill()
            try:
                tp.proc.wait(10)  # reap the SIGKILLed child
            except subprocess.TimeoutExpired:  # pragma: no cover - kernel
                pass
        if tp.log_fn:
            tp.log_fn.close()
            tp.log_fn = None


def poll_local_trainers(procs: List[TrainerProc]):
    """One supervision tick: (alive, done, failed).  Exited trainers get
    their workerlog handle closed here — a long-lived elastic supervisor
    drops cleanly-finished ranks from its poll list every tick, and
    nothing else would ever flush/close those fds."""
    alive, done, failed = [], [], []
    for tp in procs:
        ret = tp.proc.poll()
        if ret is None:
            alive.append(tp)
        elif ret == 0:
            done.append(tp)
        else:
            failed.append(tp)
        if ret is not None and tp.log_fn:
            tp.log_fn.close()
            tp.log_fn = None
    return alive, done, failed


def watch_local_trainers(procs: List[TrainerProc], nranks,
                         heartbeat_dir=None,
                         stall_timeout_s=None) -> List[TrainerProc]:
    """Poll children; on any non-zero exit FAIL FAST — kill the whole pod
    (SIGTERM→grace→SIGKILL) and raise.  A dead rank's peers are blocked
    inside the next collective and will never make progress; silently
    dropping the dead rank and waiting on the survivors hangs the job
    forever (the watchdog, launch_utils.py watch_local_trainers).

    With `heartbeat_dir` + `stall_timeout_s`, a LIVE rank whose last
    heartbeat is older than the deadline gets the same treatment as a
    dead one: a rank wedged inside a dead collective never exits, so
    process liveness alone would watch the job hang forever
    (docs/observability.md "rank heartbeats")."""
    alive, _done, failed = poll_local_trainers(procs)
    stalled: List[int] = []
    if not failed and heartbeat_dir and stall_timeout_s:
        from ..observability.heartbeat import stalled_ranks
        live = [tp.rank for tp in alive]
        stalled = stalled_ranks(heartbeat_dir, float(stall_timeout_s),
                                ranks=live)
    if failed or stalled:
        terminate_procs(procs)
        if failed:
            codes = {tp.rank: tp.proc.poll() for tp in failed}
            raise RuntimeError(
                f"trainer rank(s) {sorted(codes)} exited with code(s) "
                f"{codes}; job aborted ({nranks} ranks)")
        raise RuntimeError(
            f"trainer rank(s) {stalled} stalled (no heartbeat for "
            f"{stall_timeout_s}s — wedged in a dead collective?); pod "
            f"torn down ({nranks} ranks)")
    return alive

"""Distributed metric aggregation over trainers.

Reference: /root/reference/python/paddle/distributed/fleet/metrics/metric.py
— sum/max/min/auc/mae/rmse aggregate a local metric value across workers
via fleet.util.all_reduce (Gloo in the reference, jax multihost here).
"""
from __future__ import annotations

import builtins

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "rmse", "acc"]


def _util():
    from ..base.fleet_base import fleet
    if fleet.util is None:
        from ..base.util_factory import UtilBase
        return UtilBase()  # un-initialised fleet: single-worker world
    return fleet.util


def _to_np(value, scope=None):
    if scope is not None and isinstance(value, str):
        v = scope.get(value)
        return np.asarray(v)
    if hasattr(value, "numpy"):
        return value.numpy()
    return np.asarray(value)


def sum(input, scope=None, util=None):
    util = util or _util()
    return util.all_reduce(_to_np(input, scope), "sum")


def max(input, scope=None, util=None):
    util = util or _util()
    return util.all_reduce(_to_np(input, scope), "max")


def min(input, scope=None, util=None):
    util = util or _util()
    return util.all_reduce(_to_np(input, scope), "min")


def mae(abserr, total_ins_num, scope=None, util=None):
    """metric.py mae: global sum of abs error / global instance count."""
    util = util or _util()
    err = util.all_reduce(_to_np(abserr, scope), "sum")
    cnt = util.all_reduce(_to_np(total_ins_num, scope), "sum")
    return float(np.sum(err)) / float(np.sum(cnt))


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    util = util or _util()
    err = util.all_reduce(_to_np(sqrerr, scope), "sum")
    cnt = util.all_reduce(_to_np(total_ins_num, scope), "sum")
    return float(np.sqrt(np.sum(err) / np.sum(cnt)))


def acc(correct, total, scope=None, util=None):
    util = util or _util()
    c = util.all_reduce(_to_np(correct, scope), "sum")
    t = util.all_reduce(_to_np(total, scope), "sum")
    return float(np.sum(c)) / float(np.sum(t))


def auc(stat_pos, stat_neg, scope=None, util=None):
    """metric.py auc: merge per-worker positive/negative histogram stats
    and integrate the ROC curve globally."""
    util = util or _util()
    pos = np.asarray(util.all_reduce(_to_np(stat_pos, scope), "sum"),
                     dtype=np.float64).ravel()
    neg = np.asarray(util.all_reduce(_to_np(stat_neg, scope), "sum"),
                     dtype=np.float64).ravel()
    # walk buckets from high score to low, trapezoidal area
    tot_pos = builtins.sum(pos)
    tot_neg = builtins.sum(neg)
    if tot_pos == 0 or tot_neg == 0:
        return 0.5
    area = 0.0
    cum_pos = 0.0
    cum_neg = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_pos = cum_pos + pos[i]
        new_neg = cum_neg + neg[i]
        area += (new_neg - cum_neg) * (cum_pos + new_pos) / 2.0
        cum_pos, cum_neg = new_pos, new_neg
    return float(area / (tot_pos * tot_neg))

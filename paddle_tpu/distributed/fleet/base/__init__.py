from .distributed_strategy import DistributedStrategy  # noqa: F401
from .role_maker import (  # noqa: F401
    Role, RoleMakerBase, PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from .fleet_base import Fleet, fleet  # noqa: F401
from .util_factory import UtilBase, UtilFactory  # noqa: F401

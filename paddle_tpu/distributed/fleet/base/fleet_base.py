"""Fleet — the unified distributed-training front-end.

Reference: /root/reference/python/paddle/distributed/fleet/base/fleet_base.py
— `fleet.init(role_maker, is_collective)` (:125), worker/server queries,
`fleet.distributed_optimizer(opt, strategy)` (:924) returning a wrapper
whose `minimize` chains meta-optimizers via StrategyCompiler (:1032).

TPU-native: collective mode wraps the minimized program in a
CompiledProgram over a jax.sharding.Mesh (GraphExecutionOptimizer); PS mode
is served by the gRPC-free parameter-server tier (distributed/ps, see
SURVEY.md C9/P15 capability).
"""
from __future__ import annotations

import copy
from typing import Optional

from .distributed_strategy import DistributedStrategy
from .role_maker import PaddleCloudRoleMaker, RoleMakerBase
from .meta_optimizer_factory import MetaOptimizerFactory
from .strategy_compiler import StrategyCompiler
from .util_factory import UtilFactory

__all__ = ["Fleet", "fleet"]


class Fleet:
    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._is_collective = False
        self._user_defined_strategy: Optional[DistributedStrategy] = None
        self._user_defined_optimizer = None
        self._final_optimizer = None
        self._chosen_metas = []
        self._util = None
        self._origin_main_program = None
        self._origin_startup_program = None
        self._compiled_program = None

    # -- init & topology (fleet_base.py:125) --------------------------------
    def init(self, role_maker=None, is_collective=False, strategy=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker(is_collective=is_collective)
            self._is_collective = is_collective
        elif isinstance(role_maker, RoleMakerBase):
            self._is_collective = getattr(role_maker, "_is_collective",
                                          is_collective)
        else:
            raise TypeError("role_maker must be a RoleMakerBase")
        self._role_maker = role_maker
        self._user_defined_strategy = strategy or DistributedStrategy()
        self._util = UtilFactory()._create_util(
            {"role_maker": role_maker})
        if self._is_collective and self.worker_num() > 1:
            from ...parallel import init_parallel_env
            init_parallel_env()
        return self

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_num(self):
        return self._role_maker.server_num()

    def server_index(self):
        return self._role_maker.server_index()

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return self._role_maker.is_server()

    @property
    def util(self):
        return self._util

    def barrier_worker(self):
        self._util.barrier("worker")

    # -- PS runtime hooks (fleet_base.py init_worker/init_server) -----------
    def init_worker(self):
        from ...ps.the_one_ps import ps_runtime
        ps_runtime().init_worker(self)

    def init_server(self, *args, **kwargs):
        from ...ps.the_one_ps import ps_runtime
        ps_runtime().init_server(self, *args, **kwargs)

    def run_server(self):
        from ...ps.the_one_ps import ps_runtime
        ps_runtime().run_server(self)

    def stop_worker(self):
        from ...ps.the_one_ps import ps_runtime
        ps_runtime().stop_worker(self)

    # -- training (fleet_base.py:924) ---------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._user_defined_optimizer = optimizer
        if strategy is not None:
            self._user_defined_strategy = strategy
        return self

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._user_defined_optimizer is None:
            raise RuntimeError("call fleet.distributed_optimizer first")
        strategy = copy.deepcopy(self._user_defined_strategy)
        if not self._is_collective and not strategy.a_sync:
            # PS sync mode is expressed via a_sync=False but the PS tier is
            # only engaged when a server set exists
            pass
        candidates = MetaOptimizerFactory()._get_valid_meta_optimizers(
            self._user_defined_optimizer)
        if strategy.pipeline:
            from ....pipeline.pipeline_optimizer import \
                FleetPipelineOptimizer
            candidates.insert(-1, FleetPipelineOptimizer(
                self._user_defined_optimizer))
        if not self._is_collective and self._role_maker and \
                self._role_maker.get_pserver_endpoints():
            from ...ps.ps_optimizer import ParameterServerOptimizer
            candidates = [ParameterServerOptimizer(
                self._user_defined_optimizer)]
        compiler = StrategyCompiler()
        final_opt, chosen = compiler.generate_optimizer(
            loss, self._role_maker, self._user_defined_optimizer,
            strategy, candidates)
        self._final_optimizer = final_opt
        self._chosen_metas = chosen
        self._origin_main_program = loss.block.program
        from ....core.program import default_startup_program
        self._origin_startup_program = (startup_program
                                        or default_startup_program())
        result = final_opt.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)
        self._compiled_program = getattr(
            self._origin_main_program, "_compiled_for_fleet", None)
        return result

    @property
    def main_program(self):
        """The program to pass to exe.run — compiled (mesh/data-parallel)
        when collective minimize produced one."""
        return self._compiled_program or self._origin_main_program

    @property
    def startup_program(self):
        return self._origin_startup_program

    def applied_meta_list(self):
        return [type(m).__name__ for m in self._chosen_metas]

    # -- checkpoint I/O passthroughs (fleet_base.py save_* ) ----------------
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ....io.framework_io import save_inference_model
        return save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or self._origin_main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from ....io.framework_io import save_persistables
        return save_persistables(executor, dirname,
                                 main_program or self._origin_main_program)


fleet = Fleet()

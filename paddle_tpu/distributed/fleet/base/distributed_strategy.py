"""DistributedStrategy — the user-facing distributed-training config.

Reference: /root/reference/python/paddle/distributed/fleet/base/
distributed_strategy.py, a wrapper over
paddle/fluid/framework/distributed_strategy.proto:105-123 (fields amp,
recompute, localsgd, dgc, gradient_merge, lars, lamb, pipeline, elastic,
auto, a_sync, nccl_comm_num, hierarchical_allreduce, fp16_allreduce...).

Kept as a plain attribute object (no protobuf runtime needed); field names
and *_configs dict keys match the reference so user code ports unchanged.
The NCCL-era knobs (nccl_comm_num, hierarchical_allreduce) are accepted and
recorded but are no-ops on TPU: XLA owns collective scheduling over ICI.
"""
from __future__ import annotations

import copy

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        # collective execution
        self.nccl_comm_num = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.sync_nccl_allreduce = True
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.execution_strategy = None
        self.build_strategy = None

        # mixed precision (distributed_strategy.proto amp + amp_configs)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0,
            "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2,
            "incr_ratio": 2.0,
            "decr_ratio": 0.8,
            "use_dynamic_loss_scaling": True,
            "custom_white_list": [],
            "custom_black_list": [],
            "custom_black_varnames": [],
            # TPU extension: bf16 is the natural AMP dtype on the MXU
            "dtype": "bfloat16",
        }

        # recompute (activation checkpointing)
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}

        # pipeline parallelism
        self.pipeline = False
        self.pipeline_configs = {"micro_batch": 1, "accumulate_steps": 1,
                                 "schedule": "gpipe"}

        # gradient merge / accumulation
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}

        # localsgd
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1, "begin_step": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = {"init_k_steps": 1, "begin_step": 1}

        # gradient compression
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "rampup_step": 1,
                            "sparsity": [0.999]}
        self.fp16_allreduce = False

        # large-batch optimizers
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                             "epsilon": 0.0, "exclude_from_weight_decay": []}
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01,
                             "exclude_from_weight_decay": []}

        # parameter server
        self.a_sync = False
        self.a_sync_configs = {"k_steps": -1, "max_merge_var_num": 1,
                               "send_queue_size": 16,
                               "independent_recv_thread": False,
                               "thread_pool_size": 1,
                               "send_wait_times": 1,
                               "runtime_split_send_recv": False,
                               "launch_barrier": True,
                               "geo_sgd_need_push_nums": 100}

        # misc
        self.elastic = False
        self.auto = False
        self.cudnn_exhaustive_search = False
        self.conv_workspace_size_limit = 512
        self.cudnn_batchnorm_spatial_persistent = False
        self.sync_batch_norm = False

        # TPU extensions (no reference analog; SURVEY.md §5.7 long-context)
        self.sharding = False          # ZeRO-style param sharding over dp
        self.sharding_configs = {"fuse_broadcast_MB": 32}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.sequence_parallel = False
        self.sequence_parallel_configs = {"degree": 1, "ring_attention": True}

    def save_to_prototxt(self, output):
        import json
        with open(output, "w") as f:
            json.dump({k: v for k, v in self.__dict__.items()
                       if not k.startswith("_") and k not in
                       ("execution_strategy", "build_strategy")},
                      f, indent=2, default=str)

    def load_from_prototxt(self, pb_file):
        import json
        with open(pb_file) as f:
            for k, v in json.load(f).items():
                if hasattr(self, k):
                    setattr(self, k, v)

    def __deepcopy__(self, memo):
        new = DistributedStrategy()
        for k, v in self.__dict__.items():
            if k in ("execution_strategy", "build_strategy"):
                setattr(new, k, v)
            else:
                setattr(new, k, copy.deepcopy(v, memo))
        return new

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on})"

"""StrategyCompiler — pick and chain the applicable meta-optimizers.

Reference: fleet/base/strategy_compiler.py (`StrategyCompiler.generate_optimizer`,
called from fleet_base.py:1032) — filters candidates by `_can_apply`,
resolves incompatibilities (first-enabled wins; losers' strategy flags are
disabled), and chains survivors inner→outer via `_update_inner_optimizer`.
"""
from __future__ import annotations

__all__ = ["StrategyCompiler"]


class StrategyCompiler:
    def __init__(self):
        self._meta_optimizers = []
        self._graph_optimizer = None

    def generate_optimizer(self, loss, role_maker, optimizer,
                           user_defined_strategy, meta_optimizer_list):
        applicable = []
        for meta in meta_optimizer_list:
            meta._set_basic_info(loss, role_maker, optimizer,
                                 user_defined_strategy)
            if meta._can_apply():
                applicable.append(meta)

        # resolve incompatibilities (both directions): earlier (inner)
        # optimizer wins
        chosen = []
        for meta in applicable:
            name = type(meta).__name__
            if any(name in m._incompatible or
                   type(m).__name__ in meta._incompatible
                   for m in chosen):
                meta._disable_strategy(user_defined_strategy)
                continue
            chosen.append(meta)

        # chain inner→outer
        inner = optimizer
        for meta in chosen:
            meta._update_inner_optimizer(inner)
            inner = meta
        self._meta_optimizers = chosen
        self._graph_optimizer = next(
            (m for m in chosen if m._is_graph_out()), None)
        return inner, chosen

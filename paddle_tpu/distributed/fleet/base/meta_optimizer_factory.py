"""Meta-optimizer registry & selection order.

Reference: fleet/base/meta_optimizer_factory.py — the list of candidate
meta-optimizers; StrategyCompiler filters by `_can_apply` and chains them
inner→outer.  Order matters: optimizer-replacing ones (lars/lamb/dgc)
innermost, then program rewrites (recompute→amp), then post-minimize
rewrites (gradient_merge/localsgd), with GraphExecutionOptimizer outermost.
"""
from __future__ import annotations

from ..meta_optimizers import (
    AMPOptimizer, RecomputeOptimizer, GradientMergeOptimizer,
    LocalSGDOptimizer, AdaptiveLocalSGDOptimizer, LarsOptimizer,
    LambOptimizer, DGCOptimizer, FP16AllReduceOptimizer,
    ShardingOptimizer, GraphExecutionOptimizer,
)

__all__ = ["MetaOptimizerFactory", "meta_optimizer_names"]

# inner → outer application order
_META_OPTIMIZERS = [
    LarsOptimizer,
    LambOptimizer,
    DGCOptimizer,
    RecomputeOptimizer,
    AMPOptimizer,
    FP16AllReduceOptimizer,
    # ZeRO-1 sharding BEFORE gradient merge: the merge rewrite masks the
    # sharded update's commit, so reduce-scatter serves K micro-steps
    ShardingOptimizer,
    GradientMergeOptimizer,
    LocalSGDOptimizer,
    AdaptiveLocalSGDOptimizer,
    GraphExecutionOptimizer,
]


def meta_optimizer_names():
    return [cls.__name__ for cls in _META_OPTIMIZERS]


class MetaOptimizerFactory:
    def _get_valid_meta_optimizers(self, user_defined_optimizer):
        return [cls(user_defined_optimizer) for cls in _META_OPTIMIZERS]

"""UtilBase — cross-worker utility collectives + filesystem helpers.

Reference: fleet/base/util_factory.py — `fleet.util` exposes all_reduce /
barrier / all_gather over workers/servers (Gloo in the reference) plus
program print/load helpers.

TPU: worker collectives ride the jax.distributed coordination world when
initialised (multi-host); single-process they are identities — the same
degenerate single-trainer behaviour as the reference.
"""
from __future__ import annotations

import warnings

import numpy as np

__all__ = ["UtilBase", "UtilFactory"]


class UtilBase:
    def __init__(self):
        self.role_maker = None

    def _set_role_maker(self, role_maker):
        self.role_maker = role_maker

    def _worker_num(self):
        return self.role_maker.worker_num() if self.role_maker else 1

    def _gloo(self):
        """The role maker's Gloo store when the launcher configured a
        rendezvous — the CPU/PS-mode control plane where jax multihost
        is never initialised (the reference's UtilBase IS the Gloo
        consumer, fleet/base/util_factory.py)."""
        rm = self.role_maker
        if rm is not None and hasattr(rm, "_get_gloo"):
            try:
                return rm._get_gloo()
            except Exception:
                return None
        return None

    # -- collectives (util_factory.py parity) -------------------------------
    def all_reduce(self, input, mode="sum", comm_world="worker"):
        arr = np.asarray(input)
        n = self._worker_num()
        if n <= 1:
            return arr
        g = self._gloo()
        if g is not None:
            return np.asarray(g.all_reduce(arr, mode, comm_world))
        try:
            import jax
            import jax.numpy as jnp
            # multi-host eager path: psum over all processes via jit over
            # the global device set
            f = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[mode]
            gathered = jax.experimental.multihost_utils \
                .process_allgather(arr)
            return np.asarray(f(gathered, axis=0))
        except Exception as e:
            warnings.warn(
                f"fleet.util.all_reduce fell back to the LOCAL value "
                f"(multihost collective failed: {e}); global metrics "
                f"will be per-worker only")
            return arr

    def barrier(self, comm_world="worker"):
        if self._worker_num() <= 1:
            return
        g = self._gloo()
        if g is not None:
            g.barrier(comm_world)
            return
        try:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fleet_util_barrier")
        except Exception as e:
            warnings.warn(f"fleet.util.barrier skipped "
                          f"(multihost sync failed: {e})")

    def all_gather(self, input, comm_world="worker"):
        n = self._worker_num()
        if n <= 1:
            return [input]
        g = self._gloo()
        if g is not None:
            return g.all_gather(input, comm_world)
        try:
            from jax.experimental import multihost_utils
            out = multihost_utils.process_allgather(np.asarray(input))
            return [out[i] for i in range(out.shape[0])]
        except Exception as e:
            warnings.warn(f"fleet.util.all_gather returned only the "
                          f"local value (multihost gather failed: {e})")
            return [input]

    # -- fs / program helpers ----------------------------------------------
    def get_file_shard(self, files):
        """Split a file list evenly over workers (util_factory.py
        get_file_shard — the dataset sharding contract)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file paths")
        n = self._worker_num()
        idx = self.role_maker.worker_index() if self.role_maker else 0
        per, rem = divmod(len(files), n)
        begin = per * idx + min(idx, rem)
        end = begin + per + (1 if idx < rem else 0)
        return files[begin:end]

    def print_on_rank(self, message, rank_id=0):
        me = self.role_maker.worker_index() if self.role_maker else 0
        if me == rank_id:
            print(message)


class UtilFactory:
    def _create_util(self, context=None):
        util = UtilBase()
        if context and "role_maker" in context:
            util._set_role_maker(context["role_maker"])
        return util

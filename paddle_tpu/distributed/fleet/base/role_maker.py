"""RoleMaker — cluster topology from environment variables.

Reference: /root/reference/python/paddle/distributed/fleet/base/role_maker.py
(PaddleCloudRoleMaker parses PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS for collective mode and TRAINING_ROLE /
PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_PORT for PS mode; Gloo barrier init).

TPU: the Gloo rendezvous is replaced by the jax.distributed coordination
service (parallel.init_parallel_env); the env contract is identical.
"""
from __future__ import annotations

import os
from enum import IntEnum

__all__ = ["Role", "RoleMakerBase", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker"]


class Role(IntEnum):
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role_is_generated = False
        self._role = None
        self._current_id = -1

    def _generate_role(self):
        raise NotImplementedError

    def _ensure(self):
        if not self._role_is_generated:
            self._generate_role()

    # -- query API (role_maker.py parity) -----------------------------------
    def is_worker(self):
        self._ensure()
        return self._role == Role.WORKER

    def is_server(self):
        self._ensure()
        return self._role == Role.SERVER

    def is_first_worker(self):
        self._ensure()
        return self._role == Role.WORKER and self._current_id == 0

    def worker_index(self):
        self._ensure()
        return self._current_id

    def server_index(self):
        self._ensure()
        return self._current_id

    def worker_num(self):
        self._ensure()
        n = len(self._worker_endpoints)
        if n <= 1:
            # PS launch sets PADDLE_TRAINERS_NUM without trainer endpoints
            n = max(n, int(os.environ.get("PADDLE_TRAINERS_NUM", "1")))
        return max(1, n)

    def server_num(self):
        self._ensure()
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        self._ensure()
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        self._ensure()
        return self._server_endpoints

    def role_id(self):
        return (self.worker_index() if self.is_worker()
                else self.server_index())

    # barrier/gather: real Gloo-analog over the rendezvous store when the
    # launcher configured one (PADDLE_GLOO_RENDEZVOUS env contract);
    # degenerate single-process otherwise.  Cached PER INSTANCE (each
    # role maker has its own role; a process-wide cache would freeze the
    # first caller's role — or a pre-env None — for everyone)
    def _get_gloo(self):
        if not getattr(self, "_gloo_checked", False):
            from ...gloo import gloo_from_env
            self._gloo = gloo_from_env(
                "worker" if self.is_worker() else "server")
            self._gloo_checked = True
        return self._gloo

    def _default_world(self):
        # a server role maker must never land on the workers' store keys
        # (different world sizes would alias barriers/gathers)
        return "worker" if self.is_worker() else "server"

    def _barrier(self, comm_world=None):
        g = self._get_gloo()
        if g is not None:
            g.barrier(comm_world or self._default_world())

    def _all_gather(self, input, comm_world=None):
        g = self._get_gloo()
        if g is not None:
            return g.all_gather(input, comm_world or self._default_world())
        return [input]


class PaddleCloudRoleMaker(RoleMakerBase):
    """role_maker.py PaddleCloudRoleMaker: env-var driven."""

    def __init__(self, is_collective=False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        self._kwargs = kwargs

    def _generate_role(self):
        if self._is_collective:
            self._worker_endpoints = [
                e for e in os.environ.get(
                    "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            self._role = Role.WORKER
        else:
            role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
            self._worker_endpoints = [
                e for e in os.environ.get(
                    "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
            self._server_endpoints = [
                e for e in os.environ.get(
                    "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
            if role == "PSERVER":
                self._role = Role.SERVER
                ip = os.environ.get("POD_IP", "127.0.0.1")
                port = os.environ.get("PADDLE_PORT", "0")
                ep = f"{ip}:{port}"
                self._current_id = (self._server_endpoints.index(ep)
                                    if ep in self._server_endpoints else 0)
            else:
                self._role = Role.WORKER
                self._current_id = int(os.environ.get(
                    "PADDLE_TRAINER_ID", "0"))
        if not self._worker_endpoints:
            self._worker_endpoints = ["127.0.0.1:0"]
        self._role_is_generated = True


class UserDefinedRoleMaker(RoleMakerBase):
    """role_maker.py UserDefinedRoleMaker: explicit topology."""

    def __init__(self, is_collective=False, current_id=0, role=Role.WORKER,
                 worker_num=1, worker_endpoints=None, server_endpoints=None,
                 **kwargs):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = worker_endpoints or \
            [f"127.0.0.1:{6170 + i}" for i in range(worker_num)]
        self._server_endpoints = server_endpoints or []

    def _generate_role(self):
        self._role_is_generated = True

"""FleetWrapper / BoxWrapper / HeterWrapper — the industrial-PS client
classes (C24).

Reference:
  /root/reference/paddle/fluid/framework/fleet/fleet_wrapper.h:66 —
    pslib client: PullSparseVarsSync / PushSparseVarsAsync /
    PushDenseVarsAsync against pslib tables;
  /root/reference/paddle/fluid/framework/fleet/box_wrapper.h:333 —
    BoxPS: embeddings resident in device memory, PullSparse/PushSparse
    without a remote hop;
  /root/reference/paddle/fluid/framework/fleet/heter_wrapper.h:54 —
    HeterWrapper: CPU trainer <-> device worker activation shipping.

TPU redesign: all three wrap capabilities this framework already has —
the KV tier (distributed/ps/kv_server.py) is the pslib runtime, a dense
HBM table parameter is the BoxPS "device-resident PS" (shardable across
chips by the TP machinery instead of a bespoke allocator), and the KV
named queues are the heter RPC.  These classes exist so industrial-CTR
code written against the reference wrapper API has a same-shape home.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["FleetWrapper", "BoxWrapper", "HeterWrapper"]


class FleetWrapper:
    """fleet_wrapper.h:66 analog over the KV-server tier.

        fw = FleetWrapper()
        fw.init_worker(endpoints, trainer_id)
        fw.init_table("emb", np.zeros((V, D)), optimizer="adam")
        vals = fw.pull_sparse_vars_sync("emb", keys)          # [n, D]
        fw.push_sparse_vars_async("emb", keys, grads, lr)
        fw.push_dense_vars_async(["w0"], [g0], lr)
    """

    def __init__(self):
        self._client = None
        self.scale_sparse_gradient_with_batch_size = True
        self._request_timeout_ms = 500000
        self._connect_timeout_ms = 10000
        self._max_retry = 3

    def set_client2client_config(self, request_timeout_ms,
                                 connect_timeout_ms, max_retry):
        self._request_timeout_ms = request_timeout_ms
        self._connect_timeout_ms = connect_timeout_ms
        self._max_retry = max_retry

    def init_worker(self, endpoints: Sequence[str], trainer_id: int = 0):
        from ...ps.kv_server import KVClient
        self._client = KVClient(
            list(endpoints),
            rpc_deadline=self._request_timeout_ms / 1000.0,
            max_retries=self._max_retry)
        self._client.wait_server_ready(
            timeout=self._connect_timeout_ms / 1000.0 * 6)
        self._client.start_heartbeat(trainer_id)
        return self._client

    def _require_worker(self):
        if self._client is None:
            raise RuntimeError("FleetWrapper: call init_worker() first")
        return self._client

    def init_table(self, table_name: str, value, optimizer: str = "sgd",
                   **opt_kwargs):
        """Create the row-sharded table + install its server-resident
        optimizer (lookup_sparse_table_fuse_* analog)."""
        c = self._require_worker()
        c.init_sparse_table(table_name, np.asarray(value))
        c.config_sparse_optimizer(table_name, optimizer=optimizer,
                                  **opt_kwargs)

    def pull_sparse_vars_sync(self, table_name: str, fea_keys):
        """PullSparseVarsSync — gather rows for fea_keys."""
        return self._require_worker().pull_sparse(
            table_name, np.asarray(fea_keys).reshape(-1))

    def push_sparse_vars_async(self, table_name: str, fea_keys, grads,
                               lr: float, batch_size: Optional[int] = None,
                               sync: bool = False):
        """PushSparseVarsAsync (+ the WithLabel batch-size scaling knob:
        scale_sparse_gradient_with_batch_size divides by the batch)."""
        grads = np.asarray(grads)
        if self.scale_sparse_gradient_with_batch_size and batch_size:
            # pre-scale the values: the sync fanin path deliberately
            # ignores client grad_scale (server-side averaging), so
            # batch scaling must ride in the grads themselves
            grads = grads / float(batch_size)
        self._require_worker().push_sparse(
            table_name, np.asarray(fea_keys).reshape(-1), grads, lr,
            sync=sync)

    def push_dense_vars_async(self, var_names: Sequence[str], grads,
                              lr: float):
        c = self._require_worker()
        for n, g in zip(var_names, grads, strict=True):
            c.push_grad(n, np.asarray(g), lr, sync=False)

    def pull_dense_vars(self, var_names: Sequence[str]):
        c = self._require_worker()
        return [c.pull(n) for n in var_names]

    def barrier(self):
        self._require_worker().barrier()

    def stop_worker(self):
        if self._client is not None:
            self._client.close()
            self._client = None


class BoxWrapper:
    """box_wrapper.h:333 analog.  BoxPS kept the embedding resident in
    GPU memory with a custom allocator; on TPU the honest equivalent is
    a dense HBM table array — pull is a gather, push a fused scatter-add,
    and multi-chip scale comes from sharding the table along its vocab
    axis with the ordinary TP machinery (dist_attr), not a separate PS
    runtime.  Wraps the pull_box_sparse / push_box_sparse kernels so the
    graph-op path and this imperative path share one implementation."""

    def __init__(self):
        self._tables: Dict[str, object] = {}

    def create_table(self, name: str, value):
        import jax.numpy as jnp
        self._tables[name] = jnp.asarray(value)
        return self._tables[name]

    def pull_sparse(self, name: str, keys) -> "np.ndarray":
        from ....ops.registry import OpContext, run_kernel
        import jax.numpy as jnp
        w = self._tables[name]
        (out,) = run_kernel("pull_box_sparse",
                            {"Ids": [jnp.asarray(keys)], "W": w},
                            {}, OpContext())["Out"]
        return out

    def push_sparse(self, name: str, keys, grads, lr: float = 1.0):
        from ....ops.registry import OpContext, run_kernel
        import jax.numpy as jnp
        self._tables[name] = run_kernel(
            "push_box_sparse",
            {"Ids": [jnp.asarray(keys)], "Grads": [jnp.asarray(grads)],
             "W": self._tables[name]},
            {"lr": lr}, OpContext())["Out"]
        return self._tables[name]


class HeterWrapper:
    """heter_wrapper.h:54 analog: the activation/gradient relay between
    a CPU section worker and the device section worker, over the KV
    named queues (the graph-op form is heter_send/heter_recv; this is
    the imperative client the trainer loops use)."""

    def __init__(self, endpoints: Sequence[str], channel: str = "heter",
                 timeout: float = 60.0):
        from ...ps.kv_server import KVClient
        self._client = KVClient(list(endpoints))
        self._client.wait_server_ready()
        self.channel = channel
        self.timeout = timeout

    def send(self, name: str, value):
        self._client.q_push(f"{self.channel}/{name}", np.asarray(value))

    def recv(self, name: str) -> "np.ndarray":
        return self._client.q_pop(f"{self.channel}/{name}",
                                  timeout=self.timeout)

    def close(self):
        self._client.close()

"""Filesystem abstraction: local + HDFS.

Reference: /root/reference/python/paddle/distributed/fleet/utils/fs.py —
`FS` interface with `LocalFS` and `HDFSClient` (shelling out to
`hadoop fs`), used by auto-checkpoint (P20) and dataset sharding.
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """fs.py LocalFS parity."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path, ignore_errors=True)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def mv(self, src, dst, overwrite=False):
        if not os.path.exists(dst):
            # same-filesystem move is an atomic rename (the checkpoint
            # tier's commit primitive); cross-device falls back to copy
            shutil.move(src, dst)
            return
        if not overwrite:
            raise FSFileExistsError(dst)
        if os.path.isfile(src) and not os.path.isdir(dst):
            try:
                os.replace(src, dst)  # atomic file swap, never a window
                return
            except OSError:
                pass  # cross-device (EXDEV): no atomic swap exists
        self.delete(dst)
        shutil.move(src, dst)

    def touch(self, fs_path, exist_ok=True):
        if os.path.exists(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        open(fs_path, "a").close()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """fs.py HDFSClient parity: shells out to `hadoop fs` (io/fs.cc analog).
    All calls raise FSFileNotExistsError cleanly when the hadoop binary is
    unavailable, so auto-checkpoint degrades to LocalFS."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}
        self._timeout = time_out / 1000.0

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=self._timeout)
        except (FileNotFoundError, subprocess.TimeoutExpired) as e:
            raise FSFileNotExistsError(f"hadoop unavailable: {e}")
        return out

    def is_exist(self, fs_path):
        return self._run("-test", "-e", fs_path).returncode == 0

    def is_dir(self, fs_path):
        return self._run("-test", "-d", fs_path).returncode == 0

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        elif self.is_exist(dst):
            raise FSFileExistsError(dst)
        self._run("-mv", src, dst)

    def touch(self, fs_path, exist_ok=True):
        self._run("-touchz", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

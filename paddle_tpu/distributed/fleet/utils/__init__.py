from .fs import LocalFS, HDFSClient, FS  # noqa: F401

from .fs import LocalFS, HDFSClient, FS  # noqa: F401
from .fleet_wrapper import (  # noqa: F401
    BoxWrapper, FleetWrapper, HeterWrapper)

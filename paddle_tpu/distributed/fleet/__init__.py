"""paddle.distributed.fleet — unified distributed training API.

Reference: /root/reference/python/paddle/distributed/fleet/__init__.py.
Usage parity:

    import paddle_tpu.distributed.fleet as fleet
    fleet.init(is_collective=True)
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    opt = fleet.distributed_optimizer(opt, strategy)
    opt.minimize(loss)
    exe.run(fleet.main_program)       # CompiledProgram over the mesh
"""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.role_maker import (  # noqa: F401
    Role, RoleMakerBase, PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from .base.fleet_base import Fleet, fleet as _fleet_singleton  # noqa: F401
from .base.util_factory import UtilBase  # noqa: F401
from . import meta_optimizers  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)

# module-level passthroughs so `fleet.init(...)` works after
# `import paddle_tpu.distributed.fleet as fleet` (reference __init__.py
# exposes the singleton's methods at module scope)
init = _fleet_singleton.init
distributed_optimizer = _fleet_singleton.distributed_optimizer
minimize = _fleet_singleton.minimize
is_first_worker = _fleet_singleton.is_first_worker
worker_index = _fleet_singleton.worker_index
worker_num = _fleet_singleton.worker_num
is_worker = _fleet_singleton.is_worker
worker_endpoints = _fleet_singleton.worker_endpoints
server_num = _fleet_singleton.server_num
server_index = _fleet_singleton.server_index
server_endpoints = _fleet_singleton.server_endpoints
is_server = _fleet_singleton.is_server
barrier_worker = _fleet_singleton.barrier_worker
init_worker = _fleet_singleton.init_worker
init_server = _fleet_singleton.init_server
run_server = _fleet_singleton.run_server
stop_worker = _fleet_singleton.stop_worker
save_inference_model = _fleet_singleton.save_inference_model
save_persistables = _fleet_singleton.save_persistables
applied_meta_list = _fleet_singleton.applied_meta_list


def __getattr__(name):
    # dynamic properties of the singleton (main_program/startup_program/util)
    if name in ("main_program", "startup_program", "util"):
        return getattr(_fleet_singleton, name)
    raise AttributeError(name)

"""Shared program-rewrite helpers for meta-optimizers.

The reference implements k-step behaviours (gradient merge, LocalSGD) with
`Switch`/conditional_block sub-blocks (meta_optimizers/localsgd_optimizer.py
:23 — `Switch` blocks holding c_allreduce ops).  TPU-native redesign: XLA
wants straight-line dataflow, so conditionals become MASKED UPDATES — every
step computes both branches cheaply and `where(mask, new, old)` selects;
the mask is a scalar derived from a persistable step counter.  This keeps the
whole train step one fused XLA computation with no host round-trip.
"""
from __future__ import annotations

from typing import List, Tuple

from ....core.program import Program, Block, OpDesc, OpRole, unique_name

__all__ = ["append_masked_step_counter", "retarget_op_outputs_masked",
           "new_tmp_var"]


def _op(program, block, type, ins, outs, attrs=None):
    d = OpDesc(type, ins, outs, dict(attrs or {}))
    d.attrs.setdefault("op_uid", program._next_uid())
    d.attrs.setdefault(OpRole.KEY, OpRole.Optimize)
    block.ops.append(d)
    return d


def new_tmp_var(block, like=None, name_hint="tmp", dtype="float32",
                shape=(1,), stop_gradient=True):
    name = unique_name(name_hint)
    if like is not None:
        shape, dtype = like.shape, like.dtype
    block.create_var(name=name, shape=shape, dtype=dtype,
                     stop_gradient=stop_gradient)
    return name


def append_masked_step_counter(program: Program, startup: Program,
                               k_steps: int, begin_step: int = 0,
                               prefix: str = "gm") -> str:
    """Append a persistable step counter and return the name of a bool[1]
    mask var that is True every k-th step (past begin_step).

    Ops appended (all straight-line):
        step = step + 1                (persistable write-back)
        mask = (step % k == 0) [& step >= begin]

    Every op is stamped with a ``gm_role`` attr so the commit-tail
    hoist (distributed/scan_window.py) can split the window: the
    increment is ``"counter_inc"`` (runs once per micro-step, scan
    BODY), the mask derivation is ``"mask"`` (pure function of the
    persistable counter — replayed in BOTH body and hoisted tail).
    """
    block = program.global_block()
    # int32 counter: a float32 counter stops advancing at 2**24 steps
    step = unique_name(f"@{prefix}_step")
    block.create_var(name=step, shape=(1,), dtype="int32",
                     persistable=True, stop_gradient=True)
    sb = startup.global_block()
    sb.create_var(name=step, shape=(1,), dtype="int32", persistable=True,
                  stop_gradient=True)
    d = OpDesc("fill_constant", {}, {"Out": [step]},
               {"shape": [1], "value": 0, "dtype": "int32",
                "op_uid": startup._next_uid()})
    sb.ops.append(d)

    # topology-shifted resume (static/executor.py restore_from_checkpoint)
    # needs to find and re-derive this counter; the return value is the
    # mask, so the counter name rides a program attr
    program._last_masked_counter = step
    _op(program, block, "increment", {"X": [step]}, {"Out": [step]},
        {"step": 1, "gm_role": "counter_inc"})
    kconst = new_tmp_var(block, name_hint=f"@{prefix}_k", dtype="int32")
    _op(program, block, "fill_constant", {}, {"Out": [kconst]},
        {"shape": [1], "value": int(k_steps), "dtype": "int32",
         "gm_role": "mask"})
    rem = new_tmp_var(block, name_hint=f"@{prefix}_rem", dtype="int32")
    _op(program, block, "elementwise_mod", {"X": [step], "Y": [kconst]},
        {"Out": [rem]}, {"gm_role": "mask"})
    zero = new_tmp_var(block, name_hint=f"@{prefix}_zero", dtype="int32")
    _op(program, block, "fill_constant", {}, {"Out": [zero]},
        {"shape": [1], "value": 0, "dtype": "int32", "gm_role": "mask"})
    mask = new_tmp_var(block, name_hint=f"@{prefix}_mask", dtype="bool")
    _op(program, block, "equal", {"X": [rem], "Y": [zero]}, {"Out": [mask]},
        {"gm_role": "mask"})
    if begin_step > 0:
        beg = new_tmp_var(block, name_hint=f"@{prefix}_begin", dtype="int32")
        _op(program, block, "fill_constant", {}, {"Out": [beg]},
            {"shape": [1], "value": int(begin_step), "dtype": "int32",
             "gm_role": "mask"})
        past = new_tmp_var(block, name_hint=f"@{prefix}_past", dtype="bool")
        _op(program, block, "greater_equal", {"X": [step], "Y": [beg]},
            {"Out": [past]}, {"gm_role": "mask"})
        both = new_tmp_var(block, name_hint=f"@{prefix}_both", dtype="bool")
        _op(program, block, "logical_and", {"X": [mask], "Y": [past]},
            {"Out": [both]}, {"gm_role": "mask"})
        mask = both
    return mask


def retarget_op_outputs_masked(program: Program, op: OpDesc, mask: str,
                               insert_after: List[OpDesc],
                               rename: dict = None):
    """Rewrite `op` so its outputs land in temps, then append
    `out = where(mask, temp, out)` write-backs to `insert_after`.

    This is how a conditional_block around an optimizer op (reference
    Switch/cond) becomes straight-line XLA dataflow: compute the update
    every step, commit it only on masked steps.

    `rename` (var -> temp) is updated so LATER ops in the same masked group
    read the freshly computed temps, keeping intra-group dataflow intact
    (e.g. AMP's update_loss_scaling consuming check_finite's FoundInfinite);
    the deferred write-backs commit the whole group atomically on the mask.
    """
    block = program.global_block()
    for slot, names in list(op.outputs.items()):
        new_names = []
        for n in names:
            tmp = new_tmp_var(block, like=block.var(n),
                              name_hint=n + "@MASKED")
            new_names.append(tmp)
            if rename is not None:
                rename[n] = tmp
            # only persistable state needs the masked commit; plain temps
            # have no prior value to preserve (readers go through `rename`)
            if block.var(n).persistable:
                sel = OpDesc("where", {"Condition": [mask], "X": [tmp],
                                       "Y": [n]}, {"Out": [n]},
                             {OpRole.KEY: OpRole.Optimize,
                              "op_uid": program._next_uid()})
                insert_after.append(sel)
        op.outputs[slot] = new_names

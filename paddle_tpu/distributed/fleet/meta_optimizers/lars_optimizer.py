"""LARS meta-optimizer (reference: meta_optimizers/lars_optimizer.py —
swaps a Momentum optimizer for LarsMomentum)."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["LarsOptimizer"]


class LarsOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        if not self.user_defined_strategy.lars:
            return False
        from ....static.optimizer import MomentumOptimizer
        return isinstance(self.user_defined_optimizer, MomentumOptimizer)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.lars = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.optimizer import LarsMomentumOptimizer
        inner = self.user_defined_optimizer
        c = self.user_defined_strategy.lars_configs
        opt = LarsMomentumOptimizer(
            learning_rate=inner._learning_rate,
            momentum=getattr(inner, "_momentum", 0.9),
            lars_coeff=c.get("lars_coeff", 0.001),
            lars_weight_decay=c.get("lars_weight_decay", 0.0005),
            parameter_list=inner._parameter_list,
            regularization=inner._regularization,
            grad_clip=inner._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)

"""AMP meta-optimizer (reference: meta_optimizers/amp_optimizer.py —
delegates to contrib/mixed_precision decorate)."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["AMPOptimizer"]


class AMPOptimizer(MetaOptimizerBase):
    _incompatible = ("DGCOptimizer", "LambOptimizer", "LarsOptimizer")

    def _can_apply(self):
        return bool(self.user_defined_strategy.amp)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.amp = False

    def _wrapped(self):
        from ....amp import decorate, AutoMixedPrecisionLists
        c = self.user_defined_strategy.amp_configs
        lists = AutoMixedPrecisionLists(
            c.get("custom_white_list") or None,
            c.get("custom_black_list") or None,
            c.get("custom_black_varnames") or None)
        return decorate(
            self.inner_opt, amp_lists=lists,
            init_loss_scaling=c.get("init_loss_scaling", 32768.0),
            incr_every_n_steps=c.get("incr_every_n_steps", 1000),
            decr_every_n_nan_or_inf=c.get("decr_every_n_nan_or_inf", 2),
            incr_ratio=c.get("incr_ratio", 2.0),
            decr_ratio=c.get("decr_ratio", 0.8),
            use_dynamic_loss_scaling=c.get("use_dynamic_loss_scaling", True),
            dest_dtype=c.get("dtype", "bfloat16"))

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self._wrapped().minimize(loss, startup_program,
                                        parameter_list, no_grad_set)

"""Graph-execution meta-optimizer — the outermost collective-mode compiler.

Reference: meta_optimizers/graph_execution_optimizer.py — appends the NCCL
bootstrap ops to the startup program (`_setup_nccl_op` :52) and wraps the
main program in a CompiledProgram with multi-trainer build_strategy; it is
the outermost meta-optimizer in collective mode (fleet_base.py:1032).

TPU-native: no NCCL id bootstrap (mesh formation = jax.distributed /
Mesh creation); the program is wrapped in
CompiledProgram.with_data_parallel, whose shard_map tracing lowers the
inserted c_allreduce ops to psum over the mesh's ICI.
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["GraphExecutionOptimizer"]


class GraphExecutionOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        # collective mode only (fleet_base decides); a single worker still
        # compiles fine — allreduce degenerates to identity
        return True

    def _is_graph_out(self):
        return True

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        from ...compiled_program import CompiledProgram, BuildStrategy
        program = loss.block.program
        strategy = self.user_defined_strategy
        bs = (strategy.build_strategy if strategy and strategy.build_strategy
              else BuildStrategy())
        if self.role_maker is not None:
            bs.num_trainers = self.role_maker.worker_num()
            bs.trainer_id = self.role_maker.worker_index()
            bs.trainers_endpoints = self.role_maker.get_trainer_endpoints()
        # DistributedStrategy parallelism degrees flow into the mesh shape;
        # only a degree the user actually set (> 1) overrides — a bare
        # flag with default configs must not clobber a degree already on
        # a user-supplied BuildStrategy
        if getattr(strategy, "sequence_parallel", False):
            deg = int(strategy.sequence_parallel_configs.get("degree", 1))
            if deg > 1:
                bs.sequence_parallel_degree = deg
        if getattr(strategy, "tensor_parallel", False):
            deg = int(strategy.tensor_parallel_configs.get(
                "tensor_parallel_degree", 1))
            if deg > 1:
                bs.tensor_parallel_degree = deg
        compiled = CompiledProgram(program, build_strategy=bs) \
            .with_data_parallel(loss_name=loss.name)
        program._compiled_for_fleet = compiled
        return ops, params_grads

"""DGC (Deep Gradient Compression) meta-optimizer.

Reference: meta_optimizers/dgc_optimizer.py + fluid DGCMomentumOptimizer
(operators/optimizers/dgc_momentum_op.*, details/
sparse_all_reduce_op_handle.{h,cc} — top-k sparsified allreduce with local
residual accumulation and momentum correction, arXiv:1712.01887).

TPU redesign: see the `dgc` kernel (ops/kernels/optimizers.py) — DGC's
numerics (momentum correction, top-k mask, residual) are kept, the encoded
gradient stays dense and rides the normal ICI allreduce.
"""
from __future__ import annotations

from ....core.program import unique_name
from ....static.layer_helper import LayerHelper
from ....static.optimizer import MomentumOptimizer
from ....static.initializer import Constant
from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["DGCOptimizer", "DGCMomentumOptimizer"]


class DGCMomentumOptimizer(MomentumOptimizer):
    """fluid optimizer.py DGCMomentumOptimizer parity."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._rampup_begin_step = rampup_begin_step
        self._rampup_step = rampup_step
        self._sparsity = list(sparsity)

    def _append_optimize_op(self, param, grad, lr):
        helper = LayerHelper("dgc_momentum")
        u = helper.main_program.global_block().create_var(
            name=unique_name(param.name + "@DGC_U"), shape=param.shape,
            dtype="float32", persistable=True, stop_gradient=True)
        Constant(0.0)(u, helper.startup_program.global_block())
        encoded = helper.create_variable_for_type_inference(grad.dtype)
        grad_out = helper.main_program.global_block().create_var(
            name=unique_name(grad.name + "@DGC"), shape=grad.shape,
            dtype=grad.dtype, stop_gradient=True)
        helper.append_op(
            "dgc", inputs={"U": u, "Grad": grad},
            outputs={"UOut": u, "EncodedGrad": encoded,
                     "GradOut": grad_out},
            attrs={"m": self._momentum,
                   "sparsity": float(self._sparsity[-1]),
                   "rampup_begin_step": self._rampup_begin_step,
                   "rampup_step": self._rampup_step})
        # sgd on the sparsified gradient: DGC folds momentum into `u`
        return helper.append_op(
            "sgd",
            inputs={"Param": param, "Grad": grad_out, "LearningRate": lr},
            outputs={"ParamOut": param})


class DGCOptimizer(MetaOptimizerBase):
    _incompatible = ("AMPOptimizer",)

    def _can_apply(self):
        if not self.user_defined_strategy.dgc:
            return False
        return isinstance(self.user_defined_optimizer, MomentumOptimizer)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.dgc = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        inner = self.user_defined_optimizer
        c = self.user_defined_strategy.dgc_configs
        opt = DGCMomentumOptimizer(
            learning_rate=inner._learning_rate,
            momentum=getattr(inner, "_momentum", 0.9),
            rampup_begin_step=c.get("rampup_begin_step", 0),
            rampup_step=c.get("rampup_step", 1),
            sparsity=c.get("sparsity", [0.999]),
            parameter_list=inner._parameter_list,
            regularization=inner._regularization,
            grad_clip=inner._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)

"""LocalSGD meta-optimizer — periodic parameter averaging.

Reference: meta_optimizers/localsgd_optimizer.py:23 (and adaptive variant
:194) — each worker steps locally; every k steps a generated `Switch` block
runs `c_allreduce_sum(param) / nranks` to average parameters across workers.

TPU-native redesign: the Switch block becomes a masked update
(rewrite_utils): every step computes `avg = psum(param)/world` and
`param = where(mask, avg, param)`.  XLA dead-code-eliminates nothing here —
the allreduce does run every step — but it overlaps with compute over ICI;
for the reference cadence semantics run under the multi-process (per-host)
topology where each process owns its local params between syncs.

NOTE (single-process mesh executor): parameters under shard_map are declared
replicated; LocalSGD's between-sync divergence therefore only materialises in
the multi-process topology (one process per host, jax.distributed), which is
exactly the reference's deployment shape (one process per device).
"""
from __future__ import annotations

from ....core.program import OpRole, default_startup_program
from .meta_optimizer_base import MetaOptimizerBase
from .rewrite_utils import append_masked_step_counter, new_tmp_var, _op

__all__ = ["LocalSGDOptimizer", "AdaptiveLocalSGDOptimizer",
           "apply_localsgd"]


def apply_localsgd(program, startup, params, k_steps, begin_step=1):
    """Append masked parameter-averaging ops after the optimizer ops."""
    block = program.global_block()
    mask = append_masked_step_counter(program, startup, k_steps,
                                     begin_step=begin_step, prefix="localsgd")
    for p in params:
        summed = new_tmp_var(block, like=block.var(p.name),
                             name_hint=p.name + "@LSGD_SUM")
        _op(program, block, "c_allreduce_sum", {"X": [p.name]},
            {"Out": [summed]}, {"ring_id": 0, OpRole.KEY: OpRole.Dist})
        avg = new_tmp_var(block, like=block.var(p.name),
                          name_hint=p.name + "@LSGD_AVG")
        _op(program, block, "scale_by_world_size", {"X": [summed]},
            {"Out": [avg]}, {"ring_id": 0})
        _op(program, block, "where", {"Condition": [mask], "X": [avg],
                                      "Y": [p.name]}, {"Out": [p.name]})
    program._fingerprint_cache = None
    return program


class LocalSGDOptimizer(MetaOptimizerBase):
    _incompatible = ("GraphExecutionOptimizer",)

    def _can_apply(self):
        return bool(self.user_defined_strategy.localsgd)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.localsgd = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        c = self.user_defined_strategy.localsgd_configs
        program = loss.block.program
        startup = startup_program or default_startup_program()
        apply_localsgd(program, startup, [p for p, _ in params_grads],
                       c.get("k_steps", 1), c.get("begin_step", 1))
        return ops, params_grads


class AdaptiveLocalSGDOptimizer(LocalSGDOptimizer):
    """adaptive variant (:194) — k adapted from loss decay in the reference;
    here the initial k is used (adaptation hook kept for parity)."""

    def _can_apply(self):
        return bool(self.user_defined_strategy.adaptive_localsgd)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.adaptive_localsgd = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        c = self.user_defined_strategy.adaptive_localsgd_configs
        program = loss.block.program
        startup = startup_program or default_startup_program()
        apply_localsgd(program, startup, [p for p, _ in params_grads],
                       c.get("init_k_steps", 1), c.get("begin_step", 1))
        return ops, params_grads

"""ZeRO-1 sharding meta-optimizer (strategy.sharding).

Reference: meta_optimizers/sharding_optimizer.py — the fleet strategy knob
that partitions optimizer state across the DP world.  The reference emits
per-rank programs with broadcast/allreduce glue; here the rewrite is the
TPU-native `distributed/sharding.py` pass (bucketed reduce-scatter →
sharded update → allgather inside one shard_map-traced program — see that
module's docstring for the whole design).

Ordering: applied after the optimizer-replacing and AMP rewrites, BEFORE
GradientMergeOptimizer — gradient merge's masked-update rewrite then
accumulates the raw grads and commits the sharded update on the k-th
step.  GraphExecutionOptimizer's CompiledProgram wrapping composes via
`insert_grad_allreduce`'s idempotency: the already-reduce-scattered
gradients are skipped, unsharded stragglers still get their allreduce.

sharding_configs:
  * ``dp_degree`` — the DP world the bucket padding targets (default:
    local device count, the mesh CompiledProgram will build);
  * ``bucket_mb`` — flat-bucket coalescing granularity in MB (falls back
    to the reference's ``fuse_broadcast_MB`` key, default 32);
  * ``stage`` — ZeRO stage 1/2/3 (default 1; the reference key
    ``sharding_degree`` semantics stay with ``dp_degree``): 2 keeps the
    reduce-scattered grad buckets sharded through gradient_merge
    accumulation, 3 shards the parameters themselves with just-in-time
    per-bucket allgather (distributed/sharding.py).
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["ShardingOptimizer"]


class ShardingOptimizer(MetaOptimizerBase):
    # LocalSGD averages full per-rank PARAMS every k steps — under ZeRO-1
    # each rank's optimizer state covers only its shard, the two schedules
    # contradict.  DGC rewrites grads into sparse encodings the dense
    # flat bucket would densify.
    _incompatible = ("LocalSGDOptimizer", "AdaptiveLocalSGDOptimizer",
                     "DGCOptimizer")

    def _can_apply(self):
        return bool(getattr(self.user_defined_strategy, "sharding", False))

    def _disable_strategy(self, dist_strategy):
        dist_strategy.sharding = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....core.program import default_startup_program
        from ...sharding import shard_optimizer_states
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        c = dict(getattr(self.user_defined_strategy, "sharding_configs",
                         None) or {})
        bucket_mb = c.get("bucket_mb", c.get("fuse_broadcast_MB", 0))
        program = loss.block.program
        startup = startup_program or default_startup_program()
        shard_optimizer_states(
            program, startup,
            dp_degree=c.get("dp_degree") or None,
            bucket_bytes=int(float(bucket_mb) * 2 ** 20) if bucket_mb
            else None,
            stage=int(c.get("stage", 1)))
        return ops, params_grads

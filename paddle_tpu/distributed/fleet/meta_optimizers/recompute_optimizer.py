"""Recompute meta-optimizer (reference: meta_optimizers/recompute_optimizer.py
— wraps fluid RecomputeOptimizer with strategy-supplied checkpoints)."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["RecomputeOptimizer"]


class RecomputeOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        s = self.user_defined_strategy
        return bool(s.recompute) and \
            len(s.recompute_configs.get("checkpoints", [])) > 0

    def _disable_strategy(self, dist_strategy):
        dist_strategy.recompute = False
        dist_strategy.recompute_configs = {"checkpoints": []}

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.optimizer import RecomputeOptimizer as FluidRecompute
        wrapped = FluidRecompute(self.inner_opt)
        wrapped._set_checkpoints(
            list(self.user_defined_strategy.recompute_configs["checkpoints"]))
        return wrapped.minimize(loss, startup_program, parameter_list,
                                no_grad_set)

"""Gradient merge (accumulation) meta-optimizer.

Reference: meta_optimizers/gradient_merge_optimizer.py + fluid
GradientMergeOptimizer — grads accumulate in persistable buffers for k
steps; the real optimizer ops run inside a conditional block on the k-th.

TPU-native redesign (rewrite_utils): the conditional becomes a masked
update — optimizer ops run every step into temps, `where(mask, ...)`
commits on the k-th step, accumulators reset by the same mask.  The whole
step stays one XLA computation.  Note the merged-grad allreduce (inserted
later by CompiledProgram on the optimizer's Grad input) is then also
executed every step; XLA overlaps it with compute and psum is linear, so
numerics match the reference's communicate-on-apply schedule.
"""
from __future__ import annotations

from ....core.program import OpDesc, OpRole, default_startup_program, \
    unique_name
from .meta_optimizer_base import MetaOptimizerBase
from .rewrite_utils import (append_masked_step_counter,
                            retarget_op_outputs_masked, new_tmp_var, _op)

__all__ = ["GradientMergeOptimizer", "apply_gradient_merge"]


def apply_gradient_merge(program, startup, params_grads, k_steps, avg=True):
    """Rewrite the already-minimized `program` for k-step accumulation."""
    from ....core.pass_framework import finish_pass, has_applied
    if getattr(program, "_gm_meta", None) is not None or \
            has_applied(program, "gradient_merge"):
        # a second application would stack a second counter/mask over the
        # first's @MASKED temps: accumulators of accumulators, committing
        # every k² steps — refuse instead of silently double-masking
        raise ValueError(
            "gradient_merge already applied to this program (see the "
            "applied-passes registry, core/pass_framework.py)")
    block = program.global_block()
    opt_start = next((i for i, op in enumerate(block.ops)
                      if op.op_role == OpRole.Optimize), len(block.ops))
    opt_ops = block.ops[opt_start:]
    block.ops = block.ops[:opt_start]

    mask = append_masked_step_counter(program, startup, k_steps, prefix="gm")

    grad_to_avg = {}   # grad name -> merged (avg) grad fed to optimizer ops
    grad_to_acc = {}   # grad name -> persistable accumulator
    for p, g in params_grads:
        acc = unique_name(g.name + "@GradientMerge")
        block.create_var(name=acc, shape=g.shape, dtype=g.dtype,
                         persistable=True, stop_gradient=True)
        sb = startup.global_block()
        sb.create_var(name=acc, shape=g.shape, dtype=g.dtype,
                      persistable=True, stop_gradient=True)
        sb.ops.append(OpDesc("fill_constant", {}, {"Out": [acc]},
                             {"shape": list(g.shape or [1]), "value": 0.0,
                              "dtype": g.dtype,
                              "op_uid": startup._next_uid()}))
        # acc += g   (every step)
        _op(program, block, "elementwise_add", {"X": [acc], "Y": [g.name]},
            {"Out": [acc]})
        if avg:
            avg_name = new_tmp_var(block, like=block.var(g.name),
                                   name_hint=g.name + "@GM_AVG")
            _op(program, block, "scale", {"X": [acc]}, {"Out": [avg_name]},
                {"scale": 1.0 / k_steps, "bias": 0.0})
        else:
            avg_name = acc
        grad_to_avg[g.name] = avg_name
        grad_to_acc[g.name] = acc

    # optimizer ops: read merged grads, commit only on masked steps.
    # `rename` keeps intra-group dataflow intact: later ops read the fresh
    # @MASKED temps of earlier ops in the group, not the stale vars.
    tail = []
    rename = {}
    for op in opt_ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(grad_to_avg.get(n, n),
                                          grad_to_avg.get(n, n))
                               for n in names]
        retarget_op_outputs_masked(program, op, mask, tail, rename)
        block.ops.append(op)
    block.ops.extend(tail)

    # record what a topology-shifted resume must re-derive: the counter
    # (re-denominated to the new k), and the accumulators (zeroed when a
    # partial window is rounded down) — static/executor.py
    # restore_from_checkpoint reads this meta from both sides
    program._gm_meta = {"counter": program._last_masked_counter,
                        "k": int(k_steps),
                        "accs": sorted(grad_to_acc.values())}

    # reset accumulators on masked steps: acc = where(mask, 0, acc)
    for gname, acc in grad_to_acc.items():
        zeros = new_tmp_var(block, like=block.var(acc),
                            name_hint=acc + "@ZERO")
        gshape = list(block.var(acc).shape or [1])
        _op(program, block, "fill_constant", {}, {"Out": [zeros]},
            {"shape": gshape, "value": 0.0, "dtype": block.var(acc).dtype})
        _op(program, block, "where", {"Condition": [mask], "X": [zeros],
                                      "Y": [acc]}, {"Out": [acc]})
    program._fingerprint_cache = None
    finish_pass(program, "gradient_merge", startup=startup,
                k=int(k_steps))
    return program, mask


class GradientMergeOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        s = self.user_defined_strategy
        return bool(s.gradient_merge) and \
            s.gradient_merge_configs.get("k_steps", 1) > 1

    def _disable_strategy(self, dist_strategy):
        dist_strategy.gradient_merge = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        c = self.user_defined_strategy.gradient_merge_configs
        program = loss.block.program
        startup = startup_program or default_startup_program()
        apply_gradient_merge(program, startup, params_grads,
                             c.get("k_steps", 2), c.get("avg", True))
        return ops, params_grads

"""Gradient merge (accumulation) meta-optimizer.

Reference: meta_optimizers/gradient_merge_optimizer.py + fluid
GradientMergeOptimizer — grads accumulate in persistable buffers for k
steps; the real optimizer ops run inside a conditional block on the k-th.

TPU-native redesign (rewrite_utils): the conditional becomes a masked
update — optimizer ops run every step into temps, `where(mask, ...)`
commits on the k-th step, accumulators reset by the same mask.  The whole
step stays one XLA computation.  Note the merged-grad allreduce (inserted
later by CompiledProgram on the optimizer's Grad input) is then also
executed every step; XLA overlaps it with compute and psum is linear, so
numerics match the reference's communicate-on-apply schedule.

ZeRO-2/3 composition (distributed/sharding.py stage>=2): instead of
full-size per-param accumulators, the bucket gradient is accumulated
AFTER its reduce-scatter — the accumulator is a ``dp_shard`` persistable
at 1/N per chip, so k-step accumulation costs params/N instead of
params.  The sharding pass stamps its ops with ``zero_role`` so this
pass can keep the per-step plumbing (flatten → concat → reduce-scatter
→ scale) raw and unmasked, splice the shard accumulation at the
``grad_shard`` boundary the recorded plan names, and mask only the
update/publish tail.  The merged gradient is never re-gathered — the
V201 "deferred counterpart" story.
"""
from __future__ import annotations

import warnings as _warnings

from ....core.program import OpDesc, OpRole, default_startup_program, \
    unique_name
from .meta_optimizer_base import MetaOptimizerBase
from .rewrite_utils import (append_masked_step_counter,
                            retarget_op_outputs_masked, new_tmp_var, _op)

__all__ = ["GradientMergeOptimizer", "apply_gradient_merge"]


def apply_gradient_merge(program, startup, params_grads, k_steps, avg=True):
    """Rewrite the already-minimized `program` for k-step accumulation."""
    from ....core.pass_framework import finish_pass, has_applied
    if getattr(program, "_gm_meta", None) is not None or \
            has_applied(program, "gradient_merge"):
        # a second application would stack a second counter/mask over the
        # first's @MASKED temps: accumulators of accumulators, committing
        # every k² steps — refuse instead of silently double-masking
        raise ValueError(
            "gradient_merge already applied to this program (see the "
            "applied-passes registry, core/pass_framework.py)")
    block = program.global_block()
    opt_start = next((i for i, op in enumerate(block.ops)
                      if op.op_role == OpRole.Optimize), len(block.ops))
    opt_ops = block.ops[opt_start:]
    block.ops = block.ops[:opt_start]

    # ZeRO-2/3: accumulate the reduce-scattered bucket shard at 1/N
    # instead of full-size per-param grads.  Only sound when the bucket
    # consumes the RAW backward gradients — an interposed rewrite (grad
    # clip, AMP unscale) between backward and the bucket means every
    # micro-step's value is a function of the partial average, and
    # accumulating downstream of it would change the math; fall back to
    # the classic full-size path there (stage 2 degrades to stage 1's
    # accumulation with a warning, numerics first).
    plan = getattr(program, "_zero_shard_plan", None)
    shard_acc = bool(plan is not None and getattr(plan, "stage", 1) >= 2
                     and getattr(plan, "buckets", None))
    bucket_grads = set()
    if shard_acc:
        bucket_grads = {p["grad"] for b in plan.buckets
                        for p in b["params"]}
        raw_grads = {g.name for _p, g in params_grads}
        if not bucket_grads <= raw_grads:
            _warnings.warn(
                "gradient_merge: ZeRO stage>=2 sharded accumulation "
                "needs the gradient buckets to consume raw backward "
                "gradients, but an interposed rewrite (grad clip / AMP "
                "unscale) renamed them — falling back to full-size "
                "per-param accumulators (stage-1 memory behaviour, "
                "identical numerics)", RuntimeWarning, stacklevel=3)
            shard_acc = False
            bucket_grads = set()

    mask = append_masked_step_counter(program, startup, k_steps, prefix="gm")

    grad_to_avg = {}   # grad name -> merged (avg) grad fed to optimizer ops
    grad_to_acc = {}   # grad name -> persistable accumulator
    for p, g in params_grads:
        if g.name in bucket_grads:
            continue  # accumulated post-reduce-scatter at 1/N instead
        acc = unique_name(g.name + "@GradientMerge")
        block.create_var(name=acc, shape=g.shape, dtype=g.dtype,
                         persistable=True, stop_gradient=True)
        sb = startup.global_block()
        sb.create_var(name=acc, shape=g.shape, dtype=g.dtype,
                      persistable=True, stop_gradient=True)
        sb.ops.append(OpDesc("fill_constant", {}, {"Out": [acc]},
                             {"shape": list(g.shape or [1]), "value": 0.0,
                              "dtype": g.dtype,
                              "op_uid": startup._next_uid()}))
        # acc += g   (every step — gm_role "accumulate" keeps it in the
        # scan BODY under the commit-tail hoist; the averaging scale is
        # commit work, only meaningful on the k-th step)
        _op(program, block, "elementwise_add", {"X": [acc], "Y": [g.name]},
            {"Out": [acc]}, {"gm_role": "accumulate"})
        if avg:
            avg_name = new_tmp_var(block, like=block.var(g.name),
                                   name_hint=g.name + "@GM_AVG")
            _op(program, block, "scale", {"X": [acc]}, {"Out": [avg_name]},
                {"scale": 1.0 / k_steps, "bias": 0.0, "gm_role": "tail"})
        else:
            avg_name = acc
        grad_to_avg[g.name] = avg_name
        grad_to_acc[g.name] = acc

    def _append_shard_accumulate(gshard, bucket):
        """sacc += grad_shard every step; the update reads sacc/k.  The
        accumulator is declared at the GLOBAL padded bucket shape and
        marked dp_shard — each rank holds (and donates) 1/N of it."""
        sacc = unique_name(bucket["name"] + "@GSHARD_ACC")
        sb = startup.global_block()
        for blk in (block, sb):
            v = blk.create_var(name=sacc, shape=[bucket["padded_len"]],
                               dtype=bucket["grad_dtype"],
                               persistable=True, stop_gradient=True)
            v.attrs["dp_shard"] = int(plan.dp_degree)
        sb.ops.append(OpDesc(
            "fill_constant", {}, {"Out": [sacc]},
            {"shape": [bucket["padded_len"]], "value": 0.0,
             "dtype": bucket["grad_dtype"],
             "op_uid": startup._next_uid()}))
        _op(program, block, "elementwise_add",
            {"X": [sacc], "Y": [gshard]}, {"Out": [sacc]},
            {"gm_role": "accumulate"})
        if avg:
            avg_name = new_tmp_var(block, like=block.var(sacc),
                                   name_hint=bucket["name"] + "@GM_AVG")
            _op(program, block, "scale", {"X": [sacc]},
                {"Out": [avg_name]}, {"scale": 1.0 / k_steps, "bias": 0.0,
                                      "gm_role": "tail"})
        else:
            avg_name = sacc
        return sacc, avg_name

    # optimizer ops: read merged grads, commit only on masked steps.
    # `rename` keeps intra-group dataflow intact: later ops read the fresh
    # @MASKED temps of earlier ops in the group, not the stale vars.
    tail = []
    rename = {}
    shard_accs = []
    if shard_acc:
        # stage>=2: the bucket reduce-scatters are interleaved in
        # BACKWARD (before the optimizer split), so the per-step shard
        # is already live here — accumulate it at the head of the
        # optimizer region and point the bucket update at the merged
        # shard instead of this step's
        for bucket in plan.buckets:
            gs = bucket.get("grad_shard")
            if not gs:
                continue
            sacc, avg_name = _append_shard_accumulate(gs, bucket)
            shard_accs.append(sacc)
            rename[gs] = avg_name
    for op in opt_ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [rename.get(grad_to_avg.get(n, n),
                                          grad_to_avg.get(n, n))
                               for n in names]
        retarget_op_outputs_masked(program, op, mask, tail, rename)
        # the masked optimizer group only DOES anything on the k-th
        # step — the commit-tail hoist (distributed/scan_window.py)
        # moves it out of the scan body, so the update (and the
        # stage-1/2 publish allgather riding in it) runs once per
        # window instead of K times
        op.attrs.setdefault("gm_role", "tail")
        block.ops.append(op)
    for sel in tail:
        sel.attrs.setdefault("gm_role", "tail")
    block.ops.extend(tail)

    # record what a topology-shifted resume must re-derive: the counter
    # (re-denominated to the new k), and the accumulators (zeroed when a
    # partial window is rounded down) — static/executor.py
    # restore_from_checkpoint reads this meta from both sides
    program._gm_meta = {"counter": program._last_masked_counter,
                        "k": int(k_steps),
                        "accs": sorted(list(grad_to_acc.values()) +
                                       shard_accs)}

    # reset accumulators on masked steps: acc = where(mask, 0, acc).
    # fill_zeros_like, not fill_constant with the declared shape: a
    # dp_shard accumulator is declared at the GLOBAL padded shape but
    # each rank traces its 1/N slice under shard_map — the zeros must
    # follow the runtime shape
    for acc in list(grad_to_acc.values()) + shard_accs:
        zeros = new_tmp_var(block, like=block.var(acc),
                            name_hint=acc + "@ZERO")
        _op(program, block, "fill_zeros_like", {"X": [acc]},
            {"Out": [zeros]}, {"dtype": block.var(acc).dtype,
                               "gm_role": "tail"})
        _op(program, block, "where", {"Condition": [mask], "X": [zeros],
                                      "Y": [acc]}, {"Out": [acc]},
            {"gm_role": "tail"})
    program._fingerprint_cache = None
    finish_pass(program, "gradient_merge", startup=startup,
                k=int(k_steps), zero_stage=(getattr(plan, "stage", 0)
                                            if shard_acc else 0))
    return program, mask


class GradientMergeOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        s = self.user_defined_strategy
        return bool(s.gradient_merge) and \
            s.gradient_merge_configs.get("k_steps", 1) > 1

    def _disable_strategy(self, dist_strategy):
        dist_strategy.gradient_merge = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        c = self.user_defined_strategy.gradient_merge_configs
        program = loss.block.program
        startup = startup_program or default_startup_program()
        apply_gradient_merge(program, startup, params_grads,
                             c.get("k_steps", 2), c.get("avg", True))
        return ops, params_grads

"""FP16 allreduce meta-optimizer.

Reference: meta_optimizers/fp16_allreduce_optimizer.py — gradients are cast
to fp16 before the allreduce and back to fp32 after, halving collective
bytes.  TPU: sets the flag consumed by
distributed/compiled_program.insert_grad_allreduce, which wraps each
inserted c_allreduce_sum with cast ops (bf16 by default — ICI bandwidth
halves just the same, with fp32-range exponents).
"""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["FP16AllReduceOptimizer"]


class FP16AllReduceOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        return bool(self.user_defined_strategy.fp16_allreduce)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.fp16_allreduce = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        # mark the program: CompiledProgram reads this when inserting the
        # grad allreduce and wraps it in bf16 casts
        loss.block.program._fp16_allreduce = True
        return ops, params_grads

"""MetaOptimizerBase — composable distributed-strategy optimizers.

Reference: /root/reference/python/paddle/distributed/fleet/meta_optimizers/
meta_optimizer_base.py: each meta-optimizer wraps the user optimizer (or an
inner meta-optimizer), declares `_can_apply` from the DistributedStrategy,
and rewrites the program in `minimize`.  The StrategyCompiler chains the
applicable ones inner→outer (fleet_base.py:1032).
"""
from __future__ import annotations

__all__ = ["MetaOptimizerBase"]


class MetaOptimizerBase:
    # subclasses list meta-optimizers they cannot compose with
    _incompatible = ()

    def __init__(self, optimizer):
        self.inner_opt = optimizer
        self.role_maker = None
        self.user_defined_optimizer = optimizer
        self.user_defined_strategy = None

    def _set_basic_info(self, loss, role_maker, user_defined_optimizer,
                        user_defined_strategy):
        self.loss = loss
        self.role_maker = role_maker
        self.user_defined_optimizer = user_defined_optimizer
        self.user_defined_strategy = user_defined_strategy

    def _update_inner_optimizer(self, optimizer):
        self.inner_opt = optimizer

    def _can_apply(self) -> bool:
        return False

    def _is_graph_out(self) -> bool:
        """True for the outermost executor-producing optimizer
        (GraphExecutionOptimizer)."""
        return False

    def _disable_strategy(self, dist_strategy):
        pass

    def _enable_strategy(self, dist_strategy, context=None):
        pass

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self.inner_opt.backward(loss, startup_program,
                                       parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        return self.inner_opt.apply_gradients(params_grads)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        return self.inner_opt.minimize(loss, startup_program,
                                       parameter_list, no_grad_set)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self.minimize_impl(loss, startup_program, parameter_list,
                                  no_grad_set)

"""Meta-optimizers (reference: python/paddle/distributed/fleet/
meta_optimizers/) — composable DistributedStrategy-driven program rewrites."""
from .meta_optimizer_base import MetaOptimizerBase  # noqa: F401
from .amp_optimizer import AMPOptimizer  # noqa: F401
from .recompute_optimizer import RecomputeOptimizer  # noqa: F401
from .gradient_merge_optimizer import GradientMergeOptimizer  # noqa: F401
from .localsgd_optimizer import (  # noqa: F401
    LocalSGDOptimizer, AdaptiveLocalSGDOptimizer,
)
from .lars_optimizer import LarsOptimizer  # noqa: F401
from .lamb_optimizer import LambOptimizer  # noqa: F401
from .dgc_optimizer import DGCOptimizer, DGCMomentumOptimizer  # noqa: F401
from .fp16_allreduce_optimizer import FP16AllReduceOptimizer  # noqa: F401
from .sharding_optimizer import ShardingOptimizer  # noqa: F401
from .graph_execution_optimizer import GraphExecutionOptimizer  # noqa: F401

"""LAMB meta-optimizer (reference: meta_optimizers/lamb_optimizer.py —
swaps an Adam optimizer for Lamb)."""
from __future__ import annotations

from .meta_optimizer_base import MetaOptimizerBase

__all__ = ["LambOptimizer"]


class LambOptimizer(MetaOptimizerBase):
    def _can_apply(self):
        if not self.user_defined_strategy.lamb:
            return False
        from ....static.optimizer import AdamOptimizer
        return isinstance(self.user_defined_optimizer, AdamOptimizer)

    def _disable_strategy(self, dist_strategy):
        dist_strategy.lamb = False

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        from ....static.optimizer import LambOptimizer as FluidLamb
        inner = self.user_defined_optimizer
        c = self.user_defined_strategy.lamb_configs
        exclude = c.get("exclude_from_weight_decay", [])

        def exclude_fn(param_name):
            return any(e in param_name for e in exclude)

        opt = FluidLamb(
            learning_rate=inner._learning_rate,
            lamb_weight_decay=c.get("lamb_weight_decay", 0.01),
            beta1=getattr(inner, "_beta1", 0.9),
            beta2=getattr(inner, "_beta2", 0.999),
            epsilon=getattr(inner, "_epsilon", 1e-6),
            exclude_from_weight_decay_fn=exclude_fn if exclude else None,
            parameter_list=inner._parameter_list,
            regularization=inner._regularization,
            grad_clip=inner._grad_clip)
        return opt.minimize(loss, startup_program, parameter_list,
                            no_grad_set)

"""Fleet data generators (reference python/paddle/fluid/incubate/
data_generator/__init__.py): users subclass and override
generate_sample(line) to turn raw input lines into MultiSlot records;
run_from_stdin/run_from_files emit the text format MultiSlotDataFeed
parses (`<len> v1 v2 ... <len> v1 ...`), which is exactly what
InMemoryDataset/QueueDataset load."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user overrides ------------------------------------------------------
    def generate_sample(self, line):
        """Return a zero-arg iterator yielding one or more samples for
        this input line; each sample is [(slot_name, [values...]), ...]."""
        raise NotImplementedError(
            "subclasses must implement generate_sample(line)")

    def generate_batch(self, samples):
        """Optional batch-level hook: receives batch_size_ samples,
        returns a zero-arg iterator of (possibly transformed) samples."""

        def local_iter():
            for s in samples:
                yield s

        return local_iter

    # -- drivers -------------------------------------------------------------
    def _emit(self, sample, out):
        out.write(self._gen_str(sample))

    def _drive(self, lines, out):
        batch = []
        for line in lines:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    for s in self.generate_batch(batch)():
                        self._emit(s, out)
                    batch = []
        if batch:
            for s in self.generate_batch(batch)():
                self._emit(s, out)

    def run_from_stdin(self):
        self._drive(sys.stdin, sys.stdout)

    def run_from_memory(self, lines=None, out=None):
        """Drive from an in-memory line list; returns the emitted text
        when no output stream is given."""
        import io
        buf = out or io.StringIO()
        self._drive(lines or [], buf)
        if out is None:
            return buf.getvalue()

    def run_from_files(self, filelist, out=None):
        def all_lines():
            # ONE stream across the filelist so generate_batch sees full
            # batch_size_ batches spanning file boundaries (reference
            # DataGenerator accumulates across files)
            for fn in filelist:
                with open(fn) as f:
                    yield from f

        self._drive(all_lines(), out or sys.stdout)

    def _gen_str(self, line):
        raise NotImplementedError(
            "pick MultiSlotDataGenerator or MultiSlotStringDataGenerator")


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: emits `<len> v1 v2 ...` per slot, tracking each
    slot's type (uint64 until a float appears)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of generate_sample() must be list/tuple of "
                "(name, [values]) pairs")
        parts = []
        first_pass = self._proto_info is None
        if first_pass:
            self._proto_info = []
        elif len(line) != len(self._proto_info):
            # the MultiSlot text format is positional — a short record
            # would silently misalign every later value in the feed
            raise ValueError(
                f"sample has {len(line)} slots, expected "
                f"{len(self._proto_info)} "
                f"({[n for n, _ in self._proto_info]})")
        for i, (name, elements) in enumerate(line):
            if not isinstance(name, str):
                raise ValueError(f"slot name {name!r} must be str")
            if not isinstance(elements, list) or not elements:
                raise ValueError(
                    f"slot {name!r} needs a non-empty value list (pad "
                    f"in generate_sample if necessary)")
            if first_pass:
                self._proto_info.append((name, "uint64"))
            elif i >= len(self._proto_info) or \
                    self._proto_info[i][0] != name:
                raise ValueError(
                    f"slot order changed: expected "
                    f"{self._proto_info[i][0] if i < len(self._proto_info) else '<none>'!r},"
                    f" got {name!r}")
            parts.append(str(len(elements)))
            for elem in elements:
                if isinstance(elem, float):
                    self._proto_info[i] = (name, "float")
                elif not isinstance(elem, int):
                    raise ValueError(
                        f"slot {name!r} values must be int or float, "
                        f"got {type(elem).__name__}")
                parts.append(str(elem))
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: emits `<len> s1 s2 ...` per slot without type
    tracking (values pass through verbatim)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of generate_sample() must be list/tuple of "
                "(name, [values]) pairs")
        parts = []
        for name, elements in line:
            if not isinstance(elements, (list, tuple)) or not elements:
                raise ValueError(
                    f"slot {name!r} needs a non-empty value list")
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"

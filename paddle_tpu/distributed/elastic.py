"""Elastic data parallelism: one logical schedule, any physical world size.

The spot-fleet problem (ROADMAP "elastic training"): a v5e-32 job loses
hosts mid-run, and the 24 chips that come back must CONTINUE the same
training run — same global batch schedule, same loss trace — not start a
subtly different one.  Three facts make that hard:

  1. the data-parallel world size is baked into the gradient reduction
     (``psum`` over however many devices exist), so the same global batch
     summed on 8 vs 4 devices differs in floating-point reduction order;
  2. gradient-merge counters, RNG streams and sampler positions are all
     denominated in *micro-steps*, whose meaning changes with the world;
  3. ZeRO-sharded optimizer state is laid out for a specific shard count.

This module solves (1) and (2) by fixing the LOGICAL topology and making
the reduction order a property of the program, not of the mesh:

``elasticize(program, startup, logical_dp=N)`` rewrites an
already-minimized program so that

  * a global step is ``K = N / M`` micro-steps on an M-device mesh
    (``K`` is resolved at *trace* time from the mesh — the op list is
    identical for every M, so the program fingerprint, the persistable
    state layout, and the checkpoint format are world-size invariant);
  * gradients are reduced by ``c_elastic_fold`` — an ``all_gather``
    followed by an explicit, unrolled left-fold continued from a
    persistable accumulator.  Micro-step j folds logical ranks
    ``jM .. jM+M-1`` in order, so after K micro-steps the accumulator
    holds exactly ``(((g0+g1)+g2)+...)+g_{N-1}`` — the same adds in the
    same order for EVERY factorization of N, hence bitwise-identical
    updates across topology changes (tests/test_elastic.py proves
    8→4→8 and 8→2→4→8 bitwise-equal to an uninterrupted run);
  * the optimizer commits through a mask derived from a persistable
    micro-step counter (the gradient-merge masking machinery), scaled by
    the exact power-of-two ``1/N``;
  * the per-shard loss is folded the same way, so the committed
    ``<loss>@ELASTIC_AVG`` value reproduces the full-mesh loss trace
    bitwise.

Wire-cost note: the fold gathers every rank's gradient instead of
psum-ing it — (M-1)·|g| bytes vs allreduce's 2(M-1)/M·|g|.  Elastic mode
trades up to ~M/2× gradient wire volume for topology invariance; the
plain (non-elastic) path is untouched.

(3) — ZeRO — composes two ways.  A stage-1 ``shard_optimizer_states``
program elasticizes directly: each bucket's reduce-scattered 1/N
gradient SHARD is folded into a ``dp_shard`` window accumulator
(``c_elastic_fold`` with ``pre_reduced=True`` — no full-size gather,
allreduce-cost wire), the per-micro-step 1/M scale is replaced by one
exact pow2 1/N at commit, and the masked optimizer commit covers the
bucket update + publish.  The reduce-scatter's summation order is
implementation-defined, so THIS composition's cross-topology contract
is allclose (1e-6), not bitwise; same-world kill/resume stays bitwise.
Checkpoint layout conversion across shard counts is still handled at
restore by ``Executor.restore_from_checkpoint`` routing state through
``sharding.unshard_state`` → ``sharding.reshard_state`` (see
docs/elastic.md).  Stages 2/3 refuse (chains interleave into backward).

run_steps: an elastic program driven through
``Executor.run_steps(CompiledProgram(...), feed=stacked_micro_feeds)``
scans the whole K-micro-step commit window in ONE device dispatch,
bitwise-equal to the looped form (compiled_program._run_steps).
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

import numpy as np

from ..core.program import OpDesc, OpRole, Program, unique_name
from .fleet.meta_optimizers.rewrite_utils import (
    _op, new_tmp_var, retarget_op_outputs_masked)

__all__ = ["elasticize", "rebucket_feeds", "rederive_schedule",
           "elastic_meta", "micro_steps_per_global"]


def elastic_meta(program) -> Optional[dict]:
    """The elastic rewrite's metadata dict, or None for plain programs."""
    return getattr(program, "_elastic_meta", None)


def micro_steps_per_global(program, world: int) -> int:
    """K for `program` on a `world`-device mesh (1 for plain programs)."""
    meta = elastic_meta(program)
    if meta is None:
        return 1
    n = int(meta["logical_dp"])
    if world < 1 or n % world != 0:
        raise ValueError(
            f"elastic logical_dp={n} is not divisible by the physical "
            f"world size {world}; an elastic mesh must be a power-of-two "
            f"divisor of the logical world")
    return n // world


def elasticize(program: Program, startup: Program, logical_dp: int,
               loss_name=None, params_grads=None) -> dict:
    """Rewrite an already-minimized `program` for the elastic schedule.

    Must run BEFORE the startup program executes (it appends accumulator
    initializers, like ``static.gradient_merge``).  `logical_dp` is the
    job's logical data-parallel degree — the reduction order and the
    commit cadence are defined against it forever; any mesh whose size
    divides it runs the same program.  `loss_name` (var or name)
    additionally folds the loss so the committed ``<loss>@ELASTIC_AVG``
    fetch is world-size invariant.  Mutates `program`/`startup` in place
    and returns the recorded meta dict (also at
    ``program._elastic_meta``)."""
    n = int(logical_dp)
    if n < 1 or (n & (n - 1)):
        raise ValueError(f"logical_dp must be a power of two, got {n}")
    from ..core.pass_framework import has_applied
    if elastic_meta(program) is not None or has_applied(program, "elastic"):
        raise ValueError("elasticize already applied to this program")
    plan = getattr(program, "_zero_shard_plan", None)
    if plan is not None and not getattr(plan, "buckets", None):
        plan = None
    if plan is None and has_applied(program, "zero1_sharding"):
        raise ValueError(
            "elasticize: program carries a zero1_sharding registry "
            "entry but no recorded ShardingPlan — cannot locate the "
            "bucket chains to fold")
    if plan is not None and int(getattr(plan, "stage", 1)) >= 2:
        raise NotImplementedError(
            "elasticize composes with ZeRO stage 1 only: stages 2/3 "
            "interleave their bucket chains into backward, where the "
            "elastic window accumulation is not defined yet "
            "(docs/elastic.md)")
    if getattr(program, "_gm_meta", None) is not None or \
            has_applied(program, "gradient_merge"):
        raise NotImplementedError(
            "elasticize does not stack on static.gradient_merge: the "
            "elastic schedule IS a masked accumulation window (K = "
            "logical_dp / world); apply only one of the two")
    pgs = params_grads or getattr(program, "_ps_params_grads", None)
    if not pgs:
        raise ValueError(
            "elasticize: run optimizer.minimize(loss) on the program "
            "first (it records the param/grad pairs), or pass "
            "params_grads= explicitly")

    block = program.global_block()
    sblock = startup.global_block()
    opt_start = next((i for i, op in enumerate(block.ops)
                      if op.op_role == OpRole.Optimize), len(block.ops))
    opt_ops = block.ops[opt_start:]
    block.ops = block.ops[:opt_start]

    def _persistable(name, shape, dtype, value):
        for b in (block, sblock):
            b.create_var(name=name, shape=shape, dtype=dtype,
                         persistable=True, stop_gradient=True)
        sblock.ops.append(OpDesc(
            "fill_constant", {}, {"Out": [name]},
            {"shape": list(shape), "value": value, "dtype": dtype,
             "op_uid": startup._next_uid()}))

    # micro-step counter; (counter % K == 0) AFTER the increment marks the
    # commit micro-step.  K = logical_dp / mesh-size is resolved inside
    # the elastic_commit_mask kernel at trace time, so this same op list
    # serves every world size.
    counter = unique_name("@elastic_step")
    _persistable(counter, (1,), "int32", 0)
    _op(program, block, "increment", {"X": [counter]}, {"Out": [counter]},
        {"step": 1})
    mask = new_tmp_var(block, name_hint="@elastic_mask", dtype="bool")
    _op(program, block, "elastic_commit_mask", {"X": [counter]},
        {"Out": [mask]}, {"ring_id": 0, "logical_dp": n})

    acc_names: List[str] = []
    # (acc, folded, sharded) triples to reset on commit
    resets: List[tuple] = []

    def _fold(src_name, like_var, hint, dist_attr=None):
        """acc += ordered cross-rank fold of `src_name` (over the dp
        axis — ring 0 binds to the dp sub-axis on a dp×tp mesh, leaving
        the tp leg intact); returns the folded (pre-reset) temp and
        registers the reset.  `dist_attr` (the owning param's tp
        annotation) makes the accumulator shard over tp like the grad
        it folds — a tp-sharded grad is a LOCAL shard at runtime, so a
        replicated global-shape accumulator would shape-mismatch inside
        the trace."""
        acc = unique_name(hint + "@ELASTIC_ACC")
        shape = list(like_var.shape or [1])
        _persistable(acc, shape, like_var.dtype or "float32", 0.0)
        if dist_attr:
            for blk in (block, sblock):
                blk.var(acc).attrs["dist_attr"] = list(dist_attr)
        folded = new_tmp_var(block, like=block.var(acc),
                             name_hint=hint + "@ELASTIC_FOLD")
        _op(program, block, "c_elastic_fold",
            {"X": [src_name], "Acc": [acc]}, {"Out": [folded]},
            {"ring_id": 0, "logical_dp": n})
        acc_names.append(acc)
        # tp-sharded accumulators reset through fill_zeros_like so the
        # zeros follow the runtime (local-shard) shape, like dp_shard
        resets.append((acc, folded, bool(dist_attr)))
        return folded

    # -- ZeRO-1 composition (stage-1 plans only, gated above) ---------------
    # The bucket chain (flatten → concat → pad → c_reducescatter) runs
    # every micro-step and the window accumulates the 1/N reduce-
    # scattered SHARD into a dp_shard persistable accumulator — 1/world
    # of the gradient window memory per chip, and no full-size gather.
    # The chain's per-micro-step `scale_by_world_size` (1/M, a function
    # of the MESH) is dropped; the commit applies the exact pow2 1/N
    # once.  The reduce-scatter's cross-rank summation order is
    # implementation-defined, so this composition's topology-invariance
    # contract is allclose, not bitwise (docs/elastic.md); same-world
    # kill/resume stays bitwise.
    bucket_grads: set = set()
    drop_scale_ids: set = set()
    fold_at: Dict[int, tuple] = {}  # anchor op id -> (ops, replaced, committed)
    if plan is not None:
        bucket_grads = {p["grad"] for b in plan.buckets
                        for p in b["params"]}
        by_bucket: Dict[str, List[OpDesc]] = {}
        for op in opt_ops:
            bn = op.attrs.get("zero_bucket")
            if bn:
                by_bucket.setdefault(bn, []).append(op)
        for b in plan.buckets:
            chain = by_bucket.get(b["name"], [])
            if not chain:
                raise ValueError(
                    f"elasticize: recorded ZeRO bucket {b['name']!r} "
                    "has no ops in the optimizer tail — plan and "
                    "program drifted apart")
            scale_op = next((o for o in chain
                             if o.type == "scale_by_world_size"), None)
            if scale_op is not None:
                drop_scale_ids.add(id(scale_op))
                fold_src = scale_op.inputs["X"][0]
                replaced = scale_op.outputs["Out"][0]
                anchor = scale_op
            else:
                fold_src = b["grad_shard"]
                replaced = b["grad_shard"]
                anchor = next(o for o in chain
                              if fold_src in o.output_names())
            acc = unique_name(b["name"] + "@ELASTIC_ACC")
            for blk in (block, sblock):
                v = blk.create_var(name=acc, shape=[b["padded_len"]],
                                   dtype=b["grad_dtype"],
                                   persistable=True, stop_gradient=True)
                v.attrs["dp_shard"] = int(plan.dp_degree)
            sblock.ops.append(OpDesc(
                "fill_constant", {}, {"Out": [acc]},
                {"shape": [b["padded_len"]], "value": 0.0,
                 "dtype": b["grad_dtype"],
                 "op_uid": startup._next_uid()}))
            folded = new_tmp_var(block, like=block.var(acc),
                                 name_hint=b["name"] + "@ELASTIC_FOLD")
            committed = new_tmp_var(block, like=block.var(acc),
                                    name_hint=b["name"] + "@ELASTIC_AVG")
            emit = [
                OpDesc("c_elastic_fold",
                       {"X": [fold_src], "Acc": [acc]},
                       {"Out": [folded]},
                       {"ring_id": 0, "logical_dp": n,
                        "pre_reduced": True,
                        "op_uid": program._next_uid(),
                        OpRole.KEY: OpRole.Optimize}),
                OpDesc("scale", {"X": [folded]}, {"Out": [committed]},
                       {"scale": 1.0 / n, "bias": 0.0,
                        "op_uid": program._next_uid(),
                        OpRole.KEY: OpRole.Optimize}),
            ]
            fold_at[id(anchor)] = (emit, replaced, committed)
            acc_names.append(acc)
            resets.append((acc, folded, True))

    grad_to_committed: Dict[str, str] = {}
    for p, g in pgs:
        gname = g.name if hasattr(g, "name") else str(g)
        if gname in grad_to_committed:
            continue
        if gname in bucket_grads:
            continue  # folded at the bucket-shard level instead
        gvar = block.var(gname)
        pvar = block.vars.get(p.name if hasattr(p, "name") else str(p))
        folded = _fold(gname, gvar, gname,
                       dist_attr=(pvar.attrs.get("dist_attr")
                                  if pvar is not None else None))
        committed = new_tmp_var(block, like=gvar,
                                name_hint=gname + "@ELASTIC_AVG")
        _op(program, block, "scale", {"X": [folded]}, {"Out": [committed]},
            {"scale": 1.0 / n, "bias": 0.0})
        grad_to_committed[gname] = committed

    loss_avg = None
    if loss_name is not None:
        lname = loss_name.name if hasattr(loss_name, "name") else \
            str(loss_name)
        lvar = block.var(lname)
        lfold = _fold(lname, lvar, lname)
        loss_avg = lname + "@ELASTIC_AVG"
        block.create_var(name=loss_avg, shape=list(lvar.shape or [1]),
                         dtype=lvar.dtype or "float32", stop_gradient=True)
        _op(program, block, "scale", {"X": [lfold]}, {"Out": [loss_avg]},
            {"scale": 1.0 / n, "bias": 0.0})

    # optimizer ops read the committed fold and commit through the mask;
    # `rename` keeps intra-group dataflow on the fresh @MASKED temps
    tail: List[OpDesc] = []
    rename: Dict[str, str] = {}

    def _emit_fold(entry):
        emit, replaced, committed = entry
        for fop in emit:
            # the fold's X is a chain temp the masking loop may have
            # renamed — read the fresh value, not the stale var
            for slot, names in fop.inputs.items():
                fop.inputs[slot] = [rename.get(nm, nm) for nm in names]
            block.ops.append(fop)
        rename[replaced] = committed

    for op in opt_ops:
        if id(op) in drop_scale_ids:
            # the per-micro-step 1/M scale is replaced by the window
            # fold + exact 1/N commit scale, emitted in its place
            _emit_fold(fold_at.pop(id(op)))
            continue
        entry = fold_at.pop(id(op), None)
        for slot, names in op.inputs.items():
            op.inputs[slot] = [
                rename.get(grad_to_committed.get(nm, nm),
                           grad_to_committed.get(nm, nm))
                for nm in names]
        retarget_op_outputs_masked(program, op, mask, tail, rename)
        block.ops.append(op)
        if entry is not None:  # scale-less chain: fold after the rs
            _emit_fold(entry)
    block.ops.extend(tail)

    # accumulators reset on commit so the next window folds from zero
    for acc, folded, sharded in resets:
        zeros = new_tmp_var(block, like=block.var(acc),
                            name_hint=acc + "@ZERO")
        if sharded:
            # dp_shard accumulators are declared at the GLOBAL padded
            # shape but trace their 1/world slice under shard_map —
            # the zeros must follow the runtime shape
            _op(program, block, "fill_zeros_like", {"X": [acc]},
                {"Out": [zeros]}, {"dtype": block.var(acc).dtype})
        else:
            _op(program, block, "fill_constant", {}, {"Out": [zeros]},
                {"shape": list(block.var(acc).shape or [1]), "value": 0.0,
                 "dtype": block.var(acc).dtype})
        _op(program, block, "where",
            {"Condition": [mask], "X": [zeros], "Y": [folded]},
            {"Out": [acc]})

    program._fingerprint_cache = None
    startup._fingerprint_cache = None
    meta = {"logical_dp": n, "counter": counter, "loss_avg": loss_avg,
            "accs": acc_names, "version": 1,
            # ZeRO-1 composition marker: sharded-bucket reductions trade
            # the bitwise cross-topology contract for allclose (verifier
            # V206 exempts the stamped reduce-scatters off this)
            "zero_stage1": plan is not None}
    program._elastic_meta = meta
    from ..core.pass_framework import finish_pass
    finish_pass(program, "elastic", startup=startup, logical_dp=n)
    return meta


def rebucket_feeds(feed: dict, logical_dp: int, world: int,
                   batch_rows: Optional[int] = None) -> List[dict]:
    """Split one GLOBAL-batch feed dict (N·b rows) into the K = N/M
    micro-step feeds an M-device mesh consumes: micro-step j carries the
    rows of logical ranks jM .. jM+M-1, which is simply the next M·b-row
    slice — the same row order every topology sees.

    Feeds carrying the batch axis are split; everything else (lr
    scalars, lookup tables, replicated vectors) rides every micro-step
    whole.  The batch axis is `batch_rows` when given; otherwise the
    leading dim SHARED BY MOST feeds (a lone big table must not hijack
    row detection), and an ambiguous tie raises — pass batch_rows=
    explicitly.  A non-divisible batch FAILS rather than being silently
    replicated K times (duplicated data, wrong loss scale)."""
    k = int(logical_dp) // int(world)
    if int(logical_dp) % int(world) != 0 or k < 1:
        raise ValueError(
            f"world {world} does not divide logical_dp {logical_dp}")
    if k == 1:
        return [dict(feed)]
    arrays = {name: np.asarray(arr) for name, arr in feed.items()}
    if batch_rows is not None:
        rows = int(batch_rows)
    else:
        counts: Dict[int, int] = {}
        for a in arrays.values():
            if a.ndim >= 1:
                counts[a.shape[0]] = counts.get(a.shape[0], 0) + 1
        if not counts:
            rows = 0
        else:
            best = max(counts.values())
            modes = sorted(d for d, c in counts.items() if c == best)
            if len(modes) > 1:
                raise ValueError(
                    f"ambiguous batch axis: leading dims {modes} are "
                    f"equally common across feeds — pass batch_rows= "
                    "to rebucket_feeds")
            rows = modes[0]
    micro = [dict() for _ in range(k)]
    for name, a in arrays.items():
        if a.ndim >= 1 and rows > 0 and a.shape[0] == rows:
            if rows % k != 0:
                raise ValueError(
                    f"feed {name!r} carries {rows} global-batch rows, "
                    f"not divisible into K={k} micro-steps — elastic "
                    f"global batches must be logical_dp·b rows "
                    f"(logical_dp={logical_dp})")
            for j, part in enumerate(np.split(a, k, axis=0)):
                micro[j][name] = part
        else:
            for j in range(k):
                micro[j][name] = a
    return micro


def reanchor_topology(executor, program, scope, world: int) -> int:
    """In-process topology shift: re-anchor an elastic program's schedule
    for a new mesh world WITHOUT a checkpoint round-trip (the live-shrink
    path tools/elastic_smoke.py exercises; a relaunched process gets the
    same treatment from ``Executor.restore_from_checkpoint``).

    Re-derives the executor micro-step and the persistable micro counter
    for the new K, zeroes partially-folded accumulators when the position
    was mid-window (that window replays), and re-homes every persistable
    through the host so the next CompiledProgram can place it on a
    different device set.  Returns the global step."""
    import jax.numpy as jnp
    meta = elastic_meta(program)
    if meta is None:
        raise ValueError("reanchor_topology needs an elasticized program")
    k_old = max(1, int(getattr(executor, "_last_elastic_k", 1)))
    g, j = divmod(int(getattr(executor, "_elastic_steps",
                              executor._step)), k_old)
    if j:
        warnings.warn(
            f"elastic topology shift mid-window (micro {j}/{k_old}): "
            f"rounding down to global step {g}; the partial window "
            "replays", RuntimeWarning, stacklevel=2)
    k_new = micro_steps_per_global(program, world)
    executor._step = g * k_new
    executor._elastic_steps = g * k_new
    executor._last_elastic_k = k_new
    executor._last_elastic_world = int(world)
    from ..static.executor import _persistable_names
    for name in _persistable_names(program):
        v = scope.get(name)
        if v is not None:
            # host round-trip: drop the old mesh's committed sharding
            scope.set(name, jnp.array(np.asarray(v)))
    scope.set(meta["counter"],
              jnp.array(np.full((1,), g * k_new, np.int32)))
    if j:
        for acc in meta["accs"]:
            v = scope.get(acc)
            if v is not None:
                scope.set(acc, jnp.zeros_like(jnp.asarray(v)))
    if executor._ckpt is not None:
        # periodic-checkpoint cadence is denominated in micro-steps too
        executor._ckpt.last = executor._step
    from ..observability.journal import emit as _jemit
    _jemit("reanchor", world=int(world), k=int(k_new), global_step=int(g),
           replayed_micro=int(j))
    return g


def rederive_schedule(extra: dict, new_world: int) -> Optional[dict]:
    """Map a checkpoint's elastic schedule position onto `new_world`.

    The sidecar's ``extra["elastic"]`` records the logical world N and
    the micro-step denominator K_old the checkpoint was written under.
    Returns the re-derived positions (all denominated for K_new):

      * ``executor_step`` — micro-step count to restore into the
        executor so per-global-step derived RNG seeds replay;
      * ``counter_value`` — value for the persistable micro counter;
      * ``global_batches_consumed`` — how many GLOBAL batches the data
        pipeline should skip (feed re-bucketing happens on top with
        `rebucket_feeds`);
      * ``replayed_micro`` — nonzero when the checkpoint was taken
        mid-accumulation-window: the position is rounded DOWN to the
        window start and the partially-folded accumulators must be
        zeroed (the window replays; the committed trace is unaffected).

    Returns None when the checkpoint has no elastic sidecar."""
    el = (extra or {}).get("elastic")
    if not el:
        return None
    n = int(el["logical_dp"])
    k_old = max(1, int(el.get("k", 1)))
    if int(new_world) < 1 or n % int(new_world) != 0:
        raise ValueError(
            f"cannot resume an elastic logical_dp={n} job on "
            f"{new_world} devices (must divide the logical world)")
    k_new = n // int(new_world)
    # the program's own micro counter is authoritative (the executor step
    # also counts startup/eval runs); fall back for older sidecars
    step_old = int(el.get("counter_value",
                          extra.get("executor_step", 0)))
    g, j = divmod(step_old, k_old)
    if j:
        warnings.warn(
            f"elastic resume from a mid-window checkpoint (micro "
            f"{j}/{k_old}): rounding down to global step {g}; the "
            "partial window replays and its accumulators are reset",
            RuntimeWarning, stacklevel=3)
    return {"logical_dp": n, "k_new": k_new, "global_step": g,
            "executor_step": g * k_new, "counter_value": g * k_new,
            "global_batches_consumed": g, "replayed_micro": j}

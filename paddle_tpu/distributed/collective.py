"""paddle.distributed collective communication API.

Reference: /root/reference/python/paddle/distributed/collective.py —
`broadcast` (:59), `all_reduce` (:116), `reduce` (:191), `all_gather` (:274),
`scatter` (:347), `barrier` (:419), `ReduceOp` (:38).  Each function emits a
`c_*` op in static mode or runs it eagerly in dygraph mode.

TPU-native lowering: the emitted `c_*` ops are traced under shard_map over a
jax.sharding.Mesh by CompiledProgram/fleet and become XLA collectives
(psum / all_gather / psum_scatter / ppermute) over ICI.  Eagerly (dygraph),
outside any mesh, the world is this process's collective group: with
world_size == 1 the ops are identities — the same degenerate behaviour the
reference has with a single trainer.  Multi-host eager collectives ride
jax.distributed (see parallel.init_parallel_env): arrays sharded over the
global mesh reduce over ICI/DCN when the op runs inside a pjit'ed step.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = [
    "ReduceOp", "broadcast", "all_reduce", "reduce", "all_gather", "scatter",
    "barrier", "all_to_all", "alltoall", "send", "recv", "new_group",
    "get_group", "wait", "split",
]


class ReduceOp:
    """collective.py:38 — reduction kinds for all_reduce/reduce."""
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


_RED_SUFFIX = {ReduceOp.SUM: "sum", ReduceOp.MAX: "max",
               ReduceOp.MIN: "min", ReduceOp.PROD: "prod"}


class Group:
    """A communicator group = reference ring_id (collective_helper.h:62
    NCCLCommContext registry keyed by ring_id)."""

    def __init__(self, id: int, ranks: Optional[List[int]] = None):
        self.id = id
        self.ranks = ranks
        self.nranks = len(ranks) if ranks else _world_size()

    @property
    def name(self):
        return f"_default_group_{self.id}"

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks})"


_groups = {0: None}  # lazily built default group


def _world_size() -> int:
    from .parallel_env import ParallelEnv
    return ParallelEnv().world_size


def _default_group() -> Group:
    if _groups[0] is None:
        _groups[0] = Group(0, list(range(_world_size())))
    return _groups[0]


def new_group(ranks=None, backend=None) -> Group:
    """Create a sub-communicator; maps to a new ring_id.  Under the mesh
    executor the ring is bound to mesh axes via OpContext.dist_info."""
    gid = max(k for k in _groups) + 1
    g = Group(gid, list(ranks) if ranks is not None else None)
    _groups[gid] = g
    return g


def get_group(gid: int = 0) -> Group:
    if gid == 0:
        return _default_group()
    return _groups[gid]


def _ring_id(group) -> int:
    if group is None:
        return 0
    if isinstance(group, Group):
        return group.id
    return int(group)


def _in_dygraph(tensor=None):
    # dispatch on the argument when one is given: a build-time VarDesc means
    # static graph capture regardless of the global mode (the reference's
    # layers accept Variables under program_guard even in dygraph sessions)
    if tensor is not None:
        from ..core.program import VarDesc
        if isinstance(tensor, VarDesc):
            return False
        from ..dygraph.tensor import Tensor
        if isinstance(tensor, Tensor):
            return True
    from ..dygraph.base import in_dygraph_mode
    return in_dygraph_mode()


def _eager(op_type, tensor, attrs, out_slots=("Out",)):
    from ..dygraph.tracer import trace_op
    return trace_op(op_type, {"X": tensor}, attrs, list(out_slots))


def _static(op_type, tensor, attrs):
    from ..static.layer_helper import LayerHelper
    from ..core.program import OpRole
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype=tensor.dtype)
    attrs = dict(attrs)
    attrs[OpRole.KEY] = OpRole.Dist
    helper.append_op(op_type, inputs={"X": [tensor]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def _collective(op_type, tensor, group, extra_attrs=None, in_place=True):
    attrs = {"ring_id": _ring_id(group), "use_calc_stream": True}
    if extra_attrs:
        attrs.update(extra_attrs)
    if _in_dygraph(tensor):
        out = _eager(op_type, tensor, attrs)
        if in_place:
            tensor._value = out._value
            return None
        return out
    return _static(op_type, tensor, attrs)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, use_calc_stream=True):
    """collective.py:116 — in-place allreduce across the group."""
    return _collective("c_allreduce_" + _RED_SUFFIX[op], tensor, group)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, use_calc_stream=True):
    """collective.py:191 — reduce to rank `dst` (XLA collectives are
    symmetric; every rank holds the root's value, root semantics kept)."""
    return _collective("c_reduce_" + _RED_SUFFIX[op], tensor, group,
                       {"root_id": dst})


def broadcast(tensor, src=0, group=None, use_calc_stream=True):
    """collective.py:59 — broadcast rank `src`'s tensor to the group."""
    return _collective("c_broadcast", tensor, group, {"root": src})


def all_gather(tensor_list, tensor, group=None, use_calc_stream=True):
    """collective.py:274 — gather each rank's tensor; result (stacked along
    a new leading slice of dim 0) appended to `tensor_list`."""
    attrs = {"ring_id": _ring_id(group), "use_calc_stream": True,
             "nranks": (group.nranks if isinstance(group, Group)
                        else _world_size())}
    if _in_dygraph(tensor):
        out = _eager("c_allgather", tensor, attrs)
        n = attrs["nranks"]
        if n <= 1:
            tensor_list.append(out)
        else:
            for part in _split_rows(out, n):
                tensor_list.append(part)
        return None
    out = _static("c_allgather", tensor, attrs)
    if tensor_list is not None:
        tensor_list.append(out)
    return out


def _split_rows(t, n):
    shard = t.shape[0] // n
    return [t[i * shard:(i + 1) * shard] for i in range(n)]


def scatter(tensor, tensor_list=None, src=0, group=None, use_calc_stream=True):
    """collective.py:347 — rank src scatters tensor_list; others receive."""
    attrs = {"ring_id": _ring_id(group), "root": src,
             "use_calc_stream": True}
    if _in_dygraph(tensor):
        from ..dygraph.tensor import Tensor
        from ..tensor.manipulation import concat
        n = (group.nranks if isinstance(group, Group) else _world_size())
        if n <= 1:
            src_t = tensor_list[0] if tensor_list else tensor
            tensor._value = (src_t._value if isinstance(src_t, Tensor)
                             else src_t)
            return None
        # non-src ranks may pass tensor_list=None (collective.py:347
        # contract); the kernel broadcasts from root, so they contribute a
        # zero full-shaped buffer (n stacked shards)
        if tensor_list:
            stacked = concat(tensor_list, axis=0)
        else:
            import jax.numpy as jnp
            z = jnp.zeros((n * tensor.shape[0],) + tuple(tensor.shape[1:]),
                          tensor._value.dtype)
            stacked = Tensor(z)
        out = _eager("c_scatter", stacked, attrs)
        tensor._value = out._value
        return None
    return _static("c_scatter", tensor, attrs)


def barrier(group=None):
    """collective.py:419 — block until all group members arrive."""
    attrs = {"ring_id": _ring_id(group)}
    if _in_dygraph():
        import jax.numpy as jnp
        from ..dygraph.tensor import Tensor
        t = Tensor(jnp.zeros((1,), jnp.float32))
        _eager("barrier", t, attrs)
        return None
    from ..static.layer_helper import LayerHelper
    helper = LayerHelper("barrier")
    tmp = helper.create_variable_for_type_inference("float32")
    helper.append_op("fill_constant", {}, {"Out": [tmp]},
                     {"shape": [1], "value": 0.0, "dtype": "float32"})
    helper.append_op("barrier", {"X": [tmp]}, {"Out": [tmp]}, attrs)
    return None


def all_to_all(in_tensor_list, out_tensor_list=None, group=None,
               use_calc_stream=True):
    """All-to-all over the group (TPU: lax.all_to_all over the mesh axis).
    The reference gained this op post-1.8; included for the long-context /
    expert-parallel path (SURVEY.md §5.7)."""
    from ..tensor.manipulation import concat
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = concat(list(in_tensor_list), axis=0)
    else:
        stacked = in_tensor_list
    attrs = {"ring_id": _ring_id(group), "use_calc_stream": True}
    if _in_dygraph(stacked):
        out = _eager("alltoall", stacked, attrs)
        n = (group.nranks if isinstance(group, Group) else _world_size())
        if out_tensor_list is not None:
            out_tensor_list.extend(
                _split_rows(out, n) if n > 1 else [out])
            return None
        return out
    return _static("alltoall", stacked, attrs)


alltoall = all_to_all


def send(tensor, dst=0, group=None, use_calc_stream=True):
    """Point-to-point send — TPU lowering is a collective_permute
    (lax.ppermute) in the pipeline path; eagerly world-1 it is a no-op."""
    return _collective("p_send", tensor, group, {"peer": dst},
                       in_place=False)


def recv(tensor, src=0, group=None, use_calc_stream=True):
    out = _collective("p_recv", tensor, group, {"peer": src},
                      in_place=False)
    if _in_dygraph(tensor) and out is not None:
        tensor._value = out._value
        return None
    return out


def wait(tensor, group=None, use_calc_stream=True):
    """c_sync_*_stream analog: XLA owns scheduling; kept for API parity."""
    return None


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Model-parallel layer splitter (paddle.distributed.split).  On TPU the
    natural spelling is mesh sharding; provided for API parity — implemented
    as the c_embedding / c_split + c_concat op pattern in static mode."""
    raise NotImplementedError(
        "paddle_tpu: use paddle_tpu.distributed.fleet tensor-parallel "
        "sharding (mesh axis 'mp') instead of split()")

"""Tensor (model) parallelism: Megatron-style column/row parallel layers.

The reference has NO tensor parallelism (SURVEY.md §2.3 "NOT present");
this is the TPU-native extension the north star calls for: weights are
sharded over a "tp" mesh axis, shard_map splits/reassembles the global
arrays held in the scope (checkpointing sees full tensors), and the two
collectives are the classic conjugate pair:

  * column-parallel fc — weight [in, out/tp]; the input passes through
    `c_identity` (fwd identity, bwd allreduce over tp — the Megatron "f");
    output stays sharded on the feature dim unless `gather_output`.
  * row-parallel fc — weight [in/tp, out] consuming a feature-sharded
    input; the partial products `c_allreduce_sum` over tp (the "g"; its
    backward is the broadcast identity).

Sharding is declared on the VarDesc (`dist_attr = [axis_name, dim]`);
CompiledProgram turns the annotation into shard_map in/out specs for the
parameter state (optimizer moments inherit by name prefix + shape).

Composes as in Megatron MLP/attention blocks: col(fc) → activation →
row(fc) leaves activations replicated again at block boundaries.
"""
from __future__ import annotations

import numpy as np

from ..core.program import VarDesc
from ..static.layer_helper import LayerHelper

__all__ = ["col_parallel_fc", "row_parallel_fc", "TP_RING_ID",
           "shard_param"]

# reserved ring binding the tensor-parallel mesh axis (sp uses 101)
TP_RING_ID = 102


def shard_param(var: VarDesc, dim: int, axis: str = "tp") -> VarDesc:
    """Annotate a parameter as sharded over `axis` at `dim`."""
    var.attrs["dist_attr"] = [axis, int(dim)]
    return var


def col_parallel_fc(input, size, num_flatten_dims=1, param_attr=None,
                    bias_attr=None, act=None, gather_output=False,
                    name=None):
    """fc with the OUTPUT features split over tp.  `size` is the GLOBAL
    output width (must divide by the tp degree); the runtime shard is
    size/tp.  Output is feature-sharded unless gather_output."""
    helper = LayerHelper("col_parallel_fc", name=name)
    in_features = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_features, size],
                                input.dtype)
    shard_param(w, dim=1)
    # Megatron f: identity fwd, allreduce-over-tp bwd (grads of the
    # replicated input must sum the per-shard contributions)
    xid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("c_identity", {"X": [input]}, {"Out": [xid]},
                     {"ring_id": TP_RING_ID})
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("mul", {"X": [xid], "Y": [w]}, {"Out": [out]},
                     {"x_num_col_dims": num_flatten_dims,
                      "y_num_col_dims": 1})
    b = helper.create_parameter(bias_attr, [size], input.dtype,
                                is_bias=True)
    if b is not None:
        shard_param(b, dim=0)
        tmp = helper.create_variable_for_type_inference(out.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [tmp]}, {"axis": len(out.shape) - 1})
        out = tmp
    if gather_output:
        g = helper.create_variable_for_type_inference(out.dtype)
        helper.append_op("c_concat", {"X": [out]}, {"Out": [g]},
                         {"ring_id": TP_RING_ID})
        out = g
    return helper.append_activation(out, act)


def row_parallel_fc(input, size, num_flatten_dims=1, param_attr=None,
                    bias_attr=None, act=None, input_is_parallel=True,
                    name=None):
    """fc with the INPUT features split over tp (consumes a
    col_parallel_fc output); the partial results allreduce over tp, so
    the output is replicated.  Weight global shape is [in, size] with in
    = the GLOBAL feature width."""
    helper = LayerHelper("row_parallel_fc", name=name)
    if not input_is_parallel:
        raise NotImplementedError(
            "row_parallel_fc expects a tp-sharded input "
            "(col_parallel_fc output); scatter-on-entry is not built")
    in_features = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_features, size],
                                input.dtype)
    shard_param(w, dim=0)
    part = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("mul", {"X": [input], "Y": [w]}, {"Out": [part]},
                     {"x_num_col_dims": num_flatten_dims,
                      "y_num_col_dims": 1})
    # Megatron g: sum the partial products; backward is identity
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("mp_allreduce_sum", {"X": [part]}, {"Out": [out]},
                     {"ring_id": TP_RING_ID})
    b = helper.create_parameter(bias_attr, [size], input.dtype,
                                is_bias=True)
    if b is not None:  # replicated bias, added after the reduce
        tmp = helper.create_variable_for_type_inference(out.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [tmp]}, {"axis": len(out.shape) - 1})
        out = tmp
    return helper.append_activation(out, act)

"""Tensor (model) parallelism: Megatron-style column/row parallel layers.

The reference has NO tensor parallelism (SURVEY.md §2.3 "NOT present");
this is the TPU-native extension the north star calls for: weights are
sharded over a "tp" mesh axis, shard_map splits/reassembles the global
arrays held in the scope (checkpointing sees full tensors), and the two
collectives are the classic conjugate pair:

  * column-parallel fc — weight [in, out/tp]; the input passes through
    `c_identity` (fwd identity, bwd allreduce over tp — the Megatron "f");
    output stays sharded on the feature dim unless `gather_output`.
  * row-parallel fc — weight [in/tp, out] consuming a feature-sharded
    input; the partial products `c_allreduce_sum` over tp (the "g"; its
    backward is the broadcast identity).

Sharding is declared on the VarDesc (`dist_attr = [axis_name, dim]`);
CompiledProgram turns the annotation into shard_map in/out specs for the
parameter state (optimizer moments inherit by name prefix + shape).

Static-analysis surface: every op a builder emits is stamped with
``mp_axis`` (+ ``tp_degree`` when the caller declared one) and each
builder call records itself in the applied-passes registry
(`core/pass_framework.record_applied`, pass name "tensor_parallel") —
so the sharding-propagation analyzer (`static/layout_analysis.py`), the
V50x composition checks and the per-ring wire pricer see tensor-parallel
structure instead of anonymous ops.

Composes as in Megatron MLP/attention blocks: col(fc) → activation →
row(fc) leaves activations replicated again at block boundaries.
"""
from __future__ import annotations

import numpy as np

from ..core.program import VarDesc
from ..static.layer_helper import LayerHelper

__all__ = ["col_parallel_fc", "row_parallel_fc", "parallel_attention",
           "tp_identity", "TP_RING_ID", "MP_AXIS", "shard_param"]

# reserved ring binding the tensor-parallel mesh axis (sp uses 101)
TP_RING_ID = 102

# the canonical model-parallel axis name the layout analyzer speaks
# (the runtime mesh axis is spelled "tp" — same axis, CompiledProgram
# binds TP_RING_ID to it); both spellings come from the shared
# canonicalizer so the stamp and the runtime mesh can never drift
from ..core.mesh_axes import MP_AXIS_CANONICAL as MP_AXIS
from ..core.mesh_axes import MP_AXIS_RUNTIME as _TP_AXIS


def shard_param(var: VarDesc, dim: int, axis: str = _TP_AXIS) -> VarDesc:
    """Annotate a parameter as sharded over `axis` at `dim` (runtime
    spelling; the layout analyzer canonicalizes via core/mesh_axes)."""
    var.attrs["dist_attr"] = [axis, int(dim)]
    return var


def _stamp(op, tp_degree=None):
    """Mark a builder-emitted op as tensor-parallel structure: the mesh
    axis it rides and (when declared at build time) the tp degree the
    caller is planning for — the analyzer's axis resolution and the
    per-ring wire pricer both read these."""
    op.attrs["mp_axis"] = MP_AXIS
    if tp_degree:
        op.attrs["tp_degree"] = int(tp_degree)
    return op


def _record_build(helper, builder: str, tp_degree=None, params=()):
    from ..core.pass_framework import record_applied
    record_applied(helper.main_program, "tensor_parallel",
                   builder=builder, layer=helper.name,
                   tp_degree=int(tp_degree or 0),
                   params=[p.name for p in params if p is not None])


def tp_identity(input, name=None, tp_degree=None):
    """The Megatron f-operator standalone: identity forward, allreduce
    over tp backward.  Apply ONCE per replicated block input when several
    column-parallel projections share it (parallel_attention's q/k/v) —
    the autodiff then sums their input grads before a single allreduce."""
    helper = LayerHelper("tp_identity", name=name)
    xid = helper.create_variable_for_type_inference(input.dtype)
    _stamp(helper.append_op("c_identity", {"X": [input]}, {"Out": [xid]},
                            {"ring_id": TP_RING_ID}), tp_degree)
    return xid


def col_parallel_fc(input, size, num_flatten_dims=1, param_attr=None,
                    bias_attr=None, act=None, gather_output=False,
                    input_is_identity=False, tp_degree=None, name=None):
    """fc with the OUTPUT features split over tp.  `size` is the GLOBAL
    output width (must divide by the tp degree); the runtime shard is
    size/tp.  Output is feature-sharded unless gather_output.
    `input_is_identity`: the caller already applied tp_identity (shared
    block input) — skip the per-layer f-op.  `tp_degree` (optional) is a
    build-time declaration only — stamped onto the emitted ops for the
    static analyzers; the runtime degree still comes from the mesh."""
    helper = LayerHelper("col_parallel_fc", name=name)
    in_features = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_features, size],
                                input.dtype)
    shard_param(w, dim=1)
    # Megatron f: identity fwd, allreduce-over-tp bwd (grads of the
    # replicated input must sum the per-shard contributions)
    xid = input if input_is_identity else tp_identity(input,
                                                     tp_degree=tp_degree)
    out = helper.create_variable_for_type_inference(input.dtype)
    _stamp(helper.append_op("mul", {"X": [xid], "Y": [w]}, {"Out": [out]},
                            {"x_num_col_dims": num_flatten_dims,
                             "y_num_col_dims": 1}), tp_degree)
    b = helper.create_parameter(bias_attr, [size], input.dtype,
                                is_bias=True)
    if b is not None:
        shard_param(b, dim=0)
        tmp = helper.create_variable_for_type_inference(out.dtype)
        _stamp(helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                                {"Out": [tmp]},
                                {"axis": len(out.shape) - 1}), tp_degree)
        out = tmp
    if gather_output:
        g = helper.create_variable_for_type_inference(out.dtype)
        _stamp(helper.append_op("c_concat", {"X": [out]}, {"Out": [g]},
                                {"ring_id": TP_RING_ID}), tp_degree)
        out = g
    _record_build(helper, "col_parallel_fc", tp_degree, (w, b))
    return helper.append_activation(out, act)


def row_parallel_fc(input, size, num_flatten_dims=1, param_attr=None,
                    bias_attr=None, act=None, input_is_parallel=True,
                    in_features=None, tp_degree=None, name=None):
    """fc with the INPUT features split over tp (consumes a
    col_parallel_fc output); the partial results allreduce over tp, so
    the output is replicated.  Weight global shape is [in, size] with in
    = the GLOBAL feature width — inferred from the build-time input shape
    (which col_parallel_fc keeps global), or passed via `in_features`
    when the build-time shape is already the local shard (e.g. the
    reshaped per-head context in parallel_attention)."""
    helper = LayerHelper("row_parallel_fc", name=name)
    if not input_is_parallel:
        raise NotImplementedError(
            "row_parallel_fc expects a tp-sharded input "
            "(col_parallel_fc output); scatter-on-entry is not built")
    if in_features is None:
        in_features = int(np.prod(input.shape[num_flatten_dims:]))
    w = helper.create_parameter(param_attr, [in_features, size],
                                input.dtype)
    shard_param(w, dim=0)
    part = helper.create_variable_for_type_inference(input.dtype)
    _stamp(helper.append_op("mul", {"X": [input], "Y": [w]},
                            {"Out": [part]},
                            {"x_num_col_dims": num_flatten_dims,
                             "y_num_col_dims": 1}), tp_degree)
    if part.shape is None:
        # abstract eval can't reconcile a local-shard input width with the
        # global weight (e.g. parallel_attention's reshaped context) —
        # the out shape is known regardless
        part.shape = tuple(input.shape[:num_flatten_dims]) + (size,)
        part.dtype = input.dtype
    # Megatron g: sum the partial products; backward is identity
    out = helper.create_variable_for_type_inference(input.dtype)
    _stamp(helper.append_op("mp_allreduce_sum", {"X": [part]},
                            {"Out": [out]},
                            {"ring_id": TP_RING_ID}), tp_degree)
    if out.shape is None:
        out.shape = part.shape
        out.dtype = part.dtype
    b = helper.create_parameter(bias_attr, [size], input.dtype,
                                is_bias=True)
    if b is not None:  # replicated bias, added after the reduce
        tmp = helper.create_variable_for_type_inference(out.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [tmp]}, {"axis": len(out.shape) - 1})
        out = tmp
    _record_build(helper, "row_parallel_fc", tp_degree, (w, b))
    return helper.append_activation(out, act)


def parallel_attention(x, hidden, num_heads, tp_degree, dropout_rate=0.0,
                       param_attrs=None, name=None):
    """Megatron parallel self-attention block: three column-parallel
    q/k/v projections (each head shard lands whole on one tp rank — a
    fused qkv column shard would slice across q/k/v), local multi-head
    attention over num_heads/tp heads, row-parallel output projection.

    `tp_degree` is needed at BUILD time because the per-shard reshape
    dims (heads/tp) are static attrs; x is [batch, time, hidden]
    replicated, the return is [batch, time, hidden] replicated.
    """
    from ..static import layers
    if num_heads % tp_degree:
        raise ValueError(
            f"num_heads={num_heads} must divide by tp_degree={tp_degree}")
    if hidden % num_heads:
        raise ValueError("hidden must divide by num_heads")
    if x.shape[1] is None or x.shape[1] == -1:
        raise ValueError(
            "parallel_attention needs a static time dim (x.shape[1]) — "
            "the per-head reshape bakes it into the graph")
    if param_attrs is not None and len(param_attrs) != 4:
        raise ValueError(
            "param_attrs must hold exactly 4 entries (q, k, v, out "
            f"projections), got {len(param_attrs)}")
    pa = list(param_attrs) if param_attrs else [None] * 4
    pfx = (name + "_") if name else ""
    # ONE f-op for the shared block input: q/k/v input grads sum before a
    # single tp allreduce instead of three
    xid = tp_identity(x, name=pfx + "f" if pfx else None,
                      tp_degree=tp_degree)
    q = col_parallel_fc(xid, hidden, num_flatten_dims=2, param_attr=pa[0],
                        input_is_identity=True, tp_degree=tp_degree,
                        name=pfx + "q" if pfx else None)
    k = col_parallel_fc(xid, hidden, num_flatten_dims=2, param_attr=pa[1],
                        input_is_identity=True, tp_degree=tp_degree,
                        name=pfx + "k" if pfx else None)
    v = col_parallel_fc(xid, hidden, num_flatten_dims=2, param_attr=pa[2],
                        input_is_identity=True, tp_degree=tp_degree,
                        name=pfx + "v" if pfx else None)

    h_loc = num_heads // tp_degree
    d_key = hidden // num_heads
    t = x.shape[1]

    def _split(z):  # [b, t, h_loc*d] local -> [b, h_loc, t, d]
        z = layers.reshape(z, [-1, t, h_loc, d_key])
        # build-time shapes upstream are GLOBAL while these dims are the
        # local shard — abstract eval bails, but the target is known
        z.shape = (-1, t, h_loc, d_key)
        return layers.transpose(z, [0, 2, 1, 3])

    from ..static import nets
    ctx = nets.attention_core(_split(q), _split(k), _split(v), d_key,
                              dropout_rate,
                              merge_shape=(t, h_loc * d_key))
    out = row_parallel_fc(ctx, hidden, num_flatten_dims=2,
                          in_features=hidden, param_attr=pa[3],
                          tp_degree=tp_degree,
                          name=pfx + "out" if pfx else None)
    from ..core.pass_framework import record_applied
    from ..core.program import default_main_program
    record_applied(default_main_program(), "tensor_parallel",
                   builder="parallel_attention",
                   layer=name or "parallel_attention",
                   tp_degree=int(tp_degree), num_heads=int(num_heads))
    return out

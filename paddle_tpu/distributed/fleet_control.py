"""Fleet control plane — cross-host elastic re-form over a shared filesystem.

The elastic tier (PR 6) made one HOST a supervised, re-formable unit:
``launch.py --elastic`` tears a broken pod down and relaunches it at the
largest power-of-two divisor of the logical world the survivors can
fill.  But the north-star workload — ERNIE pretraining on a v5e-32 —
spans FOUR hosts, and a lost host is a fleet problem: every surviving
launcher must independently reach the SAME conclusions (who is still
here, what world do we re-form to, which checkpoint step do we resume
from) or the re-formed job is a chimera of disagreeing meshes.

This module is that coordination layer.  It deliberately has no network
server: TPU pods always mount a shared filesystem for checkpoints (GCS
fuse, NFS), and the checkpoint tier's atomic-commit primitives
(``checkpoint/atomic.py``) already make that filesystem a correct
rendezvous medium — a reader never sees a torn record, and rename
publishes are ordered.  TorchElastic reaches the same agreement through
an etcd/c10d store; the artifact carried per host here is the same
(epoch-stamped membership + a committed survivor set).

Three sub-protocols:

**Membership** — every launcher maintains ``member.host<h>.json``
(atomic write, refreshed each supervision tick) carrying its host id,
capacity (local devices it can contribute), current fleet epoch, pid
and wall-clock.  Liveness is the record's age — a lost host simply
stops refreshing — PLUS the trainer heartbeat files
(``observability/heartbeat.py``): a host whose launcher still refreshes
but whose every trainer heartbeat went stale past the stall deadline is
wedged-in-a-dead-collective and counts as lost too.

**Two-phase survivor agreement** — on member loss (or initial
formation) each live launcher:

  1. *proposes*: writes ``propose.e<E>.host<h>.json`` with the survivor
     set it observes, the re-formed world (largest pow2 divisor of the
     LOGICAL world the survivors' capacity fills), and the restore
     step; then
  2. *commits*: when every proposed member has filed an IDENTICAL
     proposal for epoch E, the lowest-id member (the coordinator)
     publishes ``commit.e<E>.json``; everyone else adopts the committed
     record (first write wins — a racing coordinator re-reads instead
     of overwriting).  A host dying mid-agreement makes the proposals
     disagree; the survivors re-observe and re-propose the smaller set
     at the same epoch, which converges because liveness loss is
     monotone within an epoch.

**Restore-step agreement** — the committed record carries the newest
MUTUALLY-VISIBLE checkpoint step, computed from the run journals
(``observability/journal.py``): `reconstruct_timeline` — built in PR 8
as a post-hoc forensic tool — is used LIVE here, folding each surviving
rank's journal into its incarnation story and intersecting the steps
every survivor staged (``checkpoint_save``) with the steps some rank
published (``checkpoint_commit``).  A step one survivor never staged
cannot be restored rank-merged; a step staged everywhere but never
committed is a torn artifact.

The committed record is exported to workers as the
``PADDLE_TPU_FLEET_*`` env contract (`fleet_env` parses it back), and
`CheckpointManager.load_merged` (checkpoint/manager.py) closes the
loop: the re-formed world reads ALL of the old world's per-rank shard
manifests and reassembles rank-complete state.

Observability: ``fleet.members`` / ``fleet.epoch`` /
``fleet.reform_count`` gauges through ``core/monitor`` (Prometheus
exposition included) and a ``reform`` event in the run journal per
committed (re-)formation.
"""
from __future__ import annotations

import json
import os
import time
import warnings
from typing import Dict, List, Optional, Sequence

__all__ = [
    "FLEET_DIR_ENV", "FleetAgreementTimeout", "FleetCommit",
    "FleetController", "FleetBarrier", "FleetEnv", "fleet_env",
    "fleet_rank", "fleet_world_size", "write_member", "read_members",
    "live_members", "propose_reform", "read_proposals", "read_commit",
    "newest_mutual_checkpoint_step",
]

FLEET_DIR_ENV = "PADDLE_TPU_FLEET_DIR"

# the full worker-side contract (launch.py exports these; fleet_env reads
# them back in the trainer)
ENV_DIR = FLEET_DIR_ENV
ENV_EPOCH = "PADDLE_TPU_FLEET_EPOCH"
ENV_HOST = "PADDLE_TPU_FLEET_HOST_ID"
ENV_HOSTS = "PADDLE_TPU_FLEET_HOSTS"
ENV_WORLD = "PADDLE_TPU_FLEET_WORLD"
ENV_LOGICAL = "PADDLE_TPU_FLEET_LOGICAL_WORLD"
ENV_RESTORE_STEP = "PADDLE_TPU_FLEET_RESTORE_STEP"
ENV_LAUNCHER_PID = "PADDLE_TPU_FLEET_LAUNCHER_PID"

# controller journal streams must not collide with trainer ranks: rank
# 900+h is the fleet-controller namespace (read_rank_journals still
# parses it; newest_mutual_checkpoint_step only reads the ranks asked)
CONTROLLER_RANK_BASE = 900

DEFAULT_MEMBER_TIMEOUT_S = 20.0


class FleetAgreementTimeout(RuntimeError):
    """The two-phase survivor agreement did not converge in time."""


class FleetCommit(dict):
    """The committed (re-)formation record: plain dict with attribute
    sugar for the fields every consumer reads."""

    @property
    def epoch(self) -> int:
        return int(self["epoch"])

    @property
    def members(self) -> List[int]:
        return [int(h) for h in self["members"]]

    @property
    def world(self) -> int:
        return int(self["world"])

    @property
    def restore_step(self) -> Optional[int]:
        s = self.get("restore_step")
        return None if s is None else int(s)


def fleet_world_size(capacity: int, logical_world: int) -> int:
    """Largest power-of-two divisor of `logical_world` that `capacity`
    surviving chips can fill — the same re-form math launch.py applies
    to a single host's survivors, lifted to the fleet."""
    if capacity < 1:
        return 0
    w = 1
    while w * 2 <= capacity and logical_world % (w * 2) == 0:
        w *= 2
    return w


def fleet_rank(host: int, members: Sequence[int]) -> int:
    """This host's rank in the CURRENT formation — its index in the
    sorted member list.  Host ids are stable across re-forms; ranks are
    dense per formation (the CheckpointManager rank/world contract)."""
    ordered = sorted(int(h) for h in members)
    return ordered.index(int(host))


# ---------------------------------------------------------------------------
# membership files
# ---------------------------------------------------------------------------
def _member_path(directory: str, host: int) -> str:
    return os.path.join(directory, f"member.host{int(host)}.json")


def _write_json(path: str, record: dict) -> None:
    from ..checkpoint.atomic import atomic_write
    with atomic_write(path, mode="w", fsync=False) as f:
        json.dump(record, f, sort_keys=True)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None  # racing an atomic replace / not yet written


def write_member(directory: str, host: int, capacity: int, epoch: int,
                 ranks: Sequence[int] = (), **fields) -> dict:
    """Write (or refresh) this host's epoch-stamped membership record.
    Atomic via checkpoint/atomic.py — a peer reading concurrently sees
    the previous complete record, never a torn one."""
    os.makedirs(directory, exist_ok=True)
    rec = {"host": int(host), "capacity": int(capacity),
           "epoch": int(epoch), "ranks": [int(r) for r in ranks],
           "pid": os.getpid(), "t": time.time()}
    rec.update(fields)
    _write_json(_member_path(directory, host), rec)
    return rec


def read_members(directory: str) -> Dict[int, dict]:
    """host -> last complete membership record."""
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        if not (name.startswith("member.host") and name.endswith(".json")):
            continue
        rec = _read_json(os.path.join(directory, name))
        if rec is not None and "host" in rec:
            out[int(rec["host"])] = rec
    return out


def live_members(directory: str, timeout_s: float = DEFAULT_MEMBER_TIMEOUT_S,
                 heartbeat_dir: Optional[str] = None,
                 stall_timeout_s: Optional[float] = None,
                 now: Optional[float] = None) -> Dict[int, dict]:
    """Members whose record is fresh — a lost host stops refreshing and
    ages out.  With `heartbeat_dir` + `stall_timeout_s`, a host whose
    launcher still refreshes but whose EVERY trainer heartbeat is stale
    past the deadline is dropped too (wedged in a dead collective:
    alive-looking, making no progress)."""
    now = time.time() if now is None else now
    out = {}
    for host, rec in read_members(directory).items():
        if rec.get("status") == "done":
            continue  # cleanly departed: not live, and never "lost"
        if now - float(rec.get("t", 0)) > timeout_s:
            continue
        if heartbeat_dir and stall_timeout_s and rec.get("ranks"):
            from ..observability.heartbeat import stalled_ranks
            ranks = [int(r) for r in rec["ranks"]]
            stalled = stalled_ranks(heartbeat_dir, float(stall_timeout_s),
                                    ranks=ranks, now=now)
            if len(stalled) == len(ranks):
                continue
        out[host] = rec
    return out


# ---------------------------------------------------------------------------
# two-phase survivor agreement
# ---------------------------------------------------------------------------
def _propose_path(directory: str, epoch: int, host: int) -> str:
    return os.path.join(directory, f"propose.e{int(epoch)}.host{int(host)}.json")


def _commit_path(directory: str, epoch: int) -> str:
    return os.path.join(directory, f"commit.e{int(epoch)}.json")


def propose_reform(directory: str, host: int, epoch: int,
                   members: Sequence[int], world: int,
                   restore_step: Optional[int]) -> dict:
    """Phase 1: publish this host's view of the epoch-E formation.
    Re-proposing (after the observed set changed) atomically replaces
    the previous proposal."""
    rec = {"host": int(host), "epoch": int(epoch),
           "members": sorted(int(h) for h in members), "world": int(world),
           "restore_step": (None if restore_step is None
                            else int(restore_step)),
           "t": time.time()}
    _write_json(_propose_path(directory, epoch, host), rec)
    return rec


def read_proposals(directory: str, epoch: int) -> Dict[int, dict]:
    out: Dict[int, dict] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    prefix = f"propose.e{int(epoch)}.host"
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".json")):
            continue
        rec = _read_json(os.path.join(directory, name))
        if rec is not None and "host" in rec:
            out[int(rec["host"])] = rec
    return out


def read_commit(directory: str, epoch: int) -> Optional[FleetCommit]:
    rec = _read_json(_commit_path(directory, epoch))
    return FleetCommit(rec) if rec else None


def _proposal_key(rec: dict):
    return (tuple(rec["members"]), int(rec["world"]), rec.get("restore_step"))


# ---------------------------------------------------------------------------
# restore-step agreement off the run journals
# ---------------------------------------------------------------------------
def newest_mutual_checkpoint_step(journal_dir: str,
                                  ranks: Sequence[int]) -> Optional[int]:
    """The newest checkpoint step every surviving rank can restore from,
    derived LIVE from the run journals: `reconstruct_timeline` folds
    each rank's event stream into its incarnation story, and a step
    qualifies when every rank in `ranks` STAGED it (``checkpoint_save``
    across any incarnation) and at least one rank PUBLISHED it
    (``checkpoint_commit`` — in the multi-host layout only rank 0
    commits).  Returns None when no step qualifies (fresh start)."""
    from ..observability.journal import read_journal, reconstruct_timeline
    staged_per_rank: List[set] = []
    committed: set = set()
    for rank in ranks:
        path = os.path.join(journal_dir, f"journal.rank{int(rank)}.jsonl")
        try:
            events = read_journal(path)
        except OSError:
            return None  # a survivor with no journal has nothing staged
        timeline = reconstruct_timeline(events)
        staged: set = set()
        for inc in timeline["incarnations"]:
            staged.update(int(s) for s in inc.get("saves", ())
                          if s is not None)
            committed.update(int(s) for s in inc.get("commits", ())
                             if s is not None)
        staged_per_rank.append(staged)
    if not staged_per_rank:
        return None
    mutual = set.intersection(*staged_per_rank) & committed
    return max(mutual) if mutual else None


# ---------------------------------------------------------------------------
# cross-host barrier (shared-fs; the CheckpointManager publish barrier)
# ---------------------------------------------------------------------------
class FleetBarrier:
    """Zero-arg callable barrier over the fleet dir, usable as the
    ``barrier=`` argument of ``Executor.enable_checkpointing`` so
    multi-host periodic checkpoints PUBLISH during the run (save → wait
    → barrier → rank-0 commit) instead of staying staged.

    Every member must call it the same number of times in the same
    order (periodic checkpoint cadence is deterministic, so this
    holds); call ``n`` of epoch E synchronizes on
    ``barrier.e<E>.n<n>/host<h>`` marker files."""

    def __init__(self, directory: str, host: int, members: Sequence[int],
                 epoch: int = 0, timeout_s: float = 120.0,
                 poll_s: float = 0.02):
        self.dir = directory
        self.host = int(host)
        self.members = sorted(int(h) for h in members)
        self.epoch = int(epoch)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        self._n = 0

    def __call__(self) -> None:
        self._n += 1
        d = os.path.join(self.dir, f"barrier.e{self.epoch}.n{self._n}")
        os.makedirs(d, exist_ok=True)
        mine = os.path.join(d, f"host{self.host}")
        with open(mine, "w") as f:
            f.write(str(time.time()))
        deadline = time.monotonic() + self.timeout_s
        want = {f"host{h}" for h in self.members}
        while True:
            try:
                have = set(os.listdir(d))
            except OSError:
                have = set()
            if want <= have:
                break
            if time.monotonic() > deadline:
                raise FleetAgreementTimeout(
                    f"fleet barrier {d} timed out: have "
                    f"{sorted(have)}, want {sorted(want)}")
            time.sleep(self.poll_s)
        # best-effort GC of the previous round (everyone has passed it)
        prev = os.path.join(self.dir,
                            f"barrier.e{self.epoch}.n{self._n - 1}")
        if self._n > 1 and os.path.isdir(prev):
            import shutil
            shutil.rmtree(prev, ignore_errors=True)


# ---------------------------------------------------------------------------
# the controller (one per launcher)
# ---------------------------------------------------------------------------
class FleetController:
    """One launcher's handle on the fleet: membership refresh, loss
    detection, and the two-phase (re-)formation agreement.

    Typical launcher loop (launch.py drives this)::

        ctl = FleetController(dir, host=h, capacity=4, logical_world=8)
        commit = ctl.form(expect=(0, 1))          # initial rendezvous
        ...spawn trainers with ctl.env_for_workers(commit)...
        while supervising:
            ctl.tick(ranks=my_trainer_ranks)      # refresh membership
            lost = ctl.lost_members(commit)
            if lost: teardown(); commit = ctl.reform(commit); respawn()
    """

    def __init__(self, directory: str, host: int, capacity: int,
                 logical_world: int,
                 member_timeout_s: float = DEFAULT_MEMBER_TIMEOUT_S,
                 journal_dir: Optional[str] = None,
                 heartbeat_dir: Optional[str] = None,
                 stall_timeout_s: Optional[float] = None,
                 agreement_timeout_s: float = 120.0,
                 poll_s: float = 0.05):
        self.dir = str(directory)
        self.host = int(host)
        self.capacity = int(capacity)
        self.logical_world = int(logical_world)
        self.member_timeout_s = float(member_timeout_s)
        self.journal_dir = journal_dir
        self.heartbeat_dir = heartbeat_dir
        self.stall_timeout_s = stall_timeout_s
        self.agreement_timeout_s = float(agreement_timeout_s)
        self.poll_s = float(poll_s)
        self.epoch = 0
        self.reform_count = 0
        self.ranks: List[int] = []
        self._last_refresh = 0.0
        self._journal = None
        os.makedirs(self.dir, exist_ok=True)

    # -- membership ---------------------------------------------------------
    def reset_rendezvous(self) -> None:
        """Sweep a PREVIOUS run's protocol files before the initial
        formation (the launcher calls this once at startup).  A reused
        ``--fleet_dir`` would otherwise poison the new run: a stale
        ``commit.e<E>`` is adopted verbatim by `form` (stale members,
        stale restore step), stale proposals trip `reform_requested`,
        stale barrier markers let a fresh `FleetBarrier` pass before
        the peers staged, and a previous run's ``status=done``
        membership permanently excludes a returning host.

        Safe against the CURRENT run's rendezvous: the initial `form`
        waits for every expected host's fresh membership before anyone
        proposes or commits, and each host sweeps before writing its
        own record — so any commit visible during a sweep is stale by
        construction, and a swept current-run proposal is simply
        rewritten on the next agreement iteration.  One fleet per
        directory."""
        import shutil
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                if name.startswith(("propose.", "commit.")):
                    os.unlink(path)
                elif name.startswith("barrier."):
                    shutil.rmtree(path, ignore_errors=True)
                elif name.startswith("member.host"):
                    rec = _read_json(path)
                    if rec is None or rec.get("status") == "done" or \
                            time.time() - float(rec.get("t", 0)) \
                            > self.member_timeout_s:
                        os.unlink(path)
            except OSError:
                pass  # racing a peer's sweep of the same stale file

    def tick(self, ranks: Optional[Sequence[int]] = None,
             min_interval_s: float = 0.25) -> None:
        """Refresh this host's membership record (rate-limited; the
        launcher calls this every supervision poll)."""
        if ranks is not None:
            self.ranks = [int(r) for r in ranks]
        now = time.time()
        if now - self._last_refresh < min_interval_s:
            return
        self._last_refresh = now
        write_member(self.dir, self.host, self.capacity, self.epoch,
                     ranks=self.ranks)

    def observe(self) -> Dict[int, dict]:
        """Live member records by this host's current evidence."""
        return live_members(self.dir, self.member_timeout_s,
                            heartbeat_dir=self.heartbeat_dir,
                            stall_timeout_s=self.stall_timeout_s)

    def lost_members(self, commit: FleetCommit) -> List[int]:
        """Members of the committed formation no longer observably live
        (this host excluded — its own liveness is not in question; a
        host that LEFT cleanly, status "done", is departed, not lost)."""
        live = self.observe()
        done = {h for h, rec in read_members(self.dir).items()
                if rec.get("status") == "done"}
        return sorted(h for h in commit.members
                      if h != self.host and h not in live
                      and h not in done)

    def reform_requested(self) -> bool:
        """True when a peer already started (or committed) the NEXT
        epoch's agreement — e.g. its local trainers died while ours are
        healthy.  The supervision loop treats this like member loss:
        tear down and join the agreement."""
        nxt = self.epoch + 1
        return bool(read_commit(self.dir, nxt)
                    or read_proposals(self.dir, nxt))

    def leave(self) -> None:
        """Depart cleanly (all local work finished): peers stop counting
        this host toward formations without treating it as lost."""
        write_member(self.dir, self.host, self.capacity, self.epoch,
                     ranks=self.ranks, status="done")

    def await_members(self, expect: Sequence[int],
                      timeout_s: Optional[float] = None) -> Dict[int, dict]:
        """Initial rendezvous: block until every host in `expect` has a
        fresh membership record (each arriving launcher writes its own
        first, so the wait converges)."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.agreement_timeout_s)
        want = {int(h) for h in expect}
        while True:
            self.tick(min_interval_s=0.0)
            live = self.observe()
            if want <= set(live):
                return live
            if time.monotonic() > deadline:
                raise FleetAgreementTimeout(
                    f"fleet formation timed out waiting for hosts "
                    f"{sorted(want - set(live))} (have {sorted(live)})")
            time.sleep(self.poll_s)

    # -- agreement ----------------------------------------------------------
    def _restore_step(self, members: Dict[int, dict]) -> Optional[int]:
        if not self.journal_dir:
            return None
        ranks = sorted(r for rec in members.values()
                       for r in rec.get("ranks", ()))
        if not ranks:
            return None
        try:
            return newest_mutual_checkpoint_step(self.journal_dir, ranks)
        except Exception:  # forensics must not wedge the re-form
            return None

    def form(self, expect: Optional[Sequence[int]] = None,
             epoch: Optional[int] = None) -> FleetCommit:
        """Run the two-phase agreement for `epoch` (default: the
        controller's current epoch) and return the committed formation.
        With `expect`, first blocks until those hosts rendezvous (the
        initial formation); without it, whoever is observably live forms
        the survivor set (the re-form path)."""
        epoch = self.epoch if epoch is None else int(epoch)
        self.epoch = epoch
        if expect is not None:
            self.await_members(expect)
        deadline = time.monotonic() + self.agreement_timeout_s
        prev_key = None
        while True:
            committed = read_commit(self.dir, epoch)
            if committed is not None:
                return self._adopt(committed)
            self.tick(min_interval_s=0.0)
            live = self.observe()
            if self.host not in live:  # clock skew on a slow mount
                live[self.host] = {"host": self.host,
                                   "capacity": self.capacity,
                                   "ranks": self.ranks}
            members = sorted(live)
            capacity = sum(int(r.get("capacity", 0))
                           for r in live.values())
            world = fleet_world_size(capacity, self.logical_world)
            if world < 1:
                raise FleetAgreementTimeout(
                    "no surviving capacity to form a fleet world")
            mine = propose_reform(self.dir, self.host, epoch, members,
                                  world, self._restore_step(live))
            props = read_proposals(self.dir, epoch)
            agreed = (set(props) >= set(members)
                      and all(_proposal_key(props[h]) == _proposal_key(mine)
                              for h in members))
            # commit only once the agreed view has been STABLE across
            # two consecutive observations: the fastest survivor must
            # not freeze a formation that excludes a peer whose
            # membership refresh is one tick behind
            stable = agreed and prev_key == _proposal_key(mine)
            prev_key = _proposal_key(mine)
            if stable and self.host == min(members):
                # coordinator publishes; first write wins — re-read
                # rather than clobber if a racing epoch already landed
                path = _commit_path(self.dir, epoch)
                if not os.path.exists(path):
                    rec = dict(mine)
                    rec["coordinator"] = self.host
                    _write_json(path, rec)
                committed = read_commit(self.dir, epoch)
                if committed is not None:
                    return self._adopt(committed)
            if time.monotonic() > deadline:
                raise FleetAgreementTimeout(
                    f"fleet epoch {epoch} agreement timed out "
                    f"(proposals: { {h: _proposal_key(p) for h, p in props.items()} })")
            time.sleep(self.poll_s)

    def reform(self, prev: FleetCommit) -> FleetCommit:
        """Member loss → next epoch's agreement among the survivors."""
        self.reform_count += 1
        return self.form(epoch=prev.epoch + 1)

    def _adopt(self, commit: FleetCommit) -> FleetCommit:
        self.epoch = commit.epoch
        self._observe_metrics(commit)
        return commit

    def _observe_metrics(self, commit: FleetCommit) -> None:
        try:
            from ..core.monitor import gauge_set
            gauge_set("fleet.members", len(commit.members))
            gauge_set("fleet.epoch", commit.epoch)
            gauge_set("fleet.reform_count", self.reform_count)
        except Exception:
            pass
        if self.journal_dir:
            try:
                from ..observability.journal import RunJournal
                if self._journal is None:
                    self._journal = RunJournal(
                        self.journal_dir,
                        rank=CONTROLLER_RANK_BASE + self.host)
                self._journal.event(
                    "reform", epoch=commit.epoch, world=commit.world,
                    members=commit.members,
                    restore_step=commit.restore_step,
                    reform_count=self.reform_count)
            except Exception:
                pass  # telemetry must never wedge the re-form

    # -- worker env contract ------------------------------------------------
    def env_for_workers(self, commit: FleetCommit) -> Dict[str, str]:
        env = {
            ENV_DIR: self.dir,
            ENV_EPOCH: str(commit.epoch),
            ENV_HOST: str(self.host),
            ENV_HOSTS: ",".join(str(h) for h in commit.members),
            ENV_WORLD: str(commit.world),
            ENV_LOGICAL: str(self.logical_world),
            ENV_LAUNCHER_PID: str(os.getpid()),
        }
        if commit.restore_step is not None:
            env[ENV_RESTORE_STEP] = str(commit.restore_step)
        return env

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None


class FleetEnv:
    """The parsed worker-side view of the ``PADDLE_TPU_FLEET_*`` env
    contract a fleet launcher exports."""

    __slots__ = ("dir", "epoch", "host", "hosts", "world", "logical_world",
                 "restore_step")

    def __init__(self, dir, epoch, host, hosts, world, logical_world,
                 restore_step):
        self.dir = dir
        self.epoch = epoch
        self.host = host
        self.hosts = hosts
        self.world = world
        self.logical_world = logical_world
        self.restore_step = restore_step

    @property
    def rank(self) -> int:
        """This host's dense rank in the current formation (the
        CheckpointManager rank)."""
        return fleet_rank(self.host, self.hosts)

    @property
    def n_hosts(self) -> int:
        return len(self.hosts)

    def barrier(self, timeout_s: float = 120.0) -> FleetBarrier:
        """A publish barrier for this formation (pass as
        ``enable_checkpointing(barrier=...)``)."""
        return FleetBarrier(self.dir, self.host, self.hosts,
                            epoch=self.epoch, timeout_s=timeout_s)

    def __repr__(self):
        return (f"FleetEnv(epoch={self.epoch}, host={self.host}, "
                f"hosts={self.hosts}, world={self.world})")


def fleet_env(environ: Optional[Dict[str, str]] = None) -> Optional[FleetEnv]:
    """Parse the worker-side fleet contract; None when not under a fleet
    launcher."""
    e = os.environ if environ is None else environ
    directory = e.get(ENV_DIR)
    if not directory:
        return None
    try:
        hosts = [int(h) for h in e.get(ENV_HOSTS, "").split(",") if h != ""]
        restore = e.get(ENV_RESTORE_STEP)
        return FleetEnv(
            dir=directory,
            epoch=int(e.get(ENV_EPOCH, "0")),
            host=int(e.get(ENV_HOST, "0")),
            hosts=hosts or [int(e.get(ENV_HOST, "0"))],
            world=int(e.get(ENV_WORLD, "1")),
            logical_world=int(e.get(ENV_LOGICAL, e.get(ENV_WORLD, "1"))),
            restore_step=None if restore in (None, "") else int(restore),
        )
    except ValueError:
        warnings.warn(
            f"malformed {FLEET_DIR_ENV} env contract; ignoring fleet mode",
            RuntimeWarning, stacklevel=2)
        return None

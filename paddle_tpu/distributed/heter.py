"""Heterogeneous parameter-server training (HeterWrapper analog).

Reference: /root/reference/paddle/fluid/framework/fleet/heter_wrapper.h:54
and framework/heterxpu_trainer.cc — CPU trainer processes own the
data/sparse side (embedding pull/push against the PS) while device
workers run the heavy dense compute, bridged by the HeterService RPC
(CallRemoteXpu / activation + gradient shipping).

TPU redesign (NOT a translation): one Program is built, minimized and
PS-transpiled as usual, then SPLIT at user-named boundary activations
into two section programs:

  * the CPU section — everything upstream of the boundary (the
    distributed_lookup_table pulls and feature plumbing) plus everything
    downstream of the boundary GRADIENTS (the SelectedRows table grad +
    sparse push) — runs in a plain CPU process against the KV tier;
  * the device section — the dense forward, loss, dense backward and
    local optimizer ops — runs jitted on the TPU/mesh process.

The handoff is expressed as GRAPH OPS (`heter_send` / `heter_recv`,
ops/kernels/distributed_ops.py) over named blocking queues hosted by the
same KV service the PS tier uses, reached through ordered io_callback —
so each section stays one compiled step and the relay rides the existing
RPC plane, replacing heter_wrapper.h's bespoke HeterService.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from ..core.program import Block, OpDesc, Program, VarDesc

__all__ = ["split_heter_program", "HeterSection"]


class HeterSection:
    """One side of the split: a runnable Program plus the feed names it
    still consumes from the host."""

    def __init__(self, program: Program, feeds: List[str]):
        self.program = program
        self.feeds = feeds


def _copy_var(block: Block, v: VarDesc):
    if v.name in block.vars:
        return
    nv = block.create_var(
        name=v.name, shape=v.shape, dtype=v.dtype,
        persistable=v.persistable, stop_gradient=v.stop_gradient,
        is_parameter=v.is_parameter, initializer=v.initializer,
        trainable=v.trainable, lod_level=v.lod_level, is_data=v.is_data)
    nv.attrs = dict(v.attrs)


def _copy_ops(src_block: Block, dst: Program, ops: Sequence[OpDesc]):
    blk = dst.global_block()
    for op in ops:
        for n in op.input_names() + op.output_names():
            if src_block.has_var(n):
                _copy_var(blk, src_block.var(n))
        blk.ops.append(OpDesc(op.type, op.inputs, op.outputs,
                              dict(op.attrs)))


def _grad_name(program: Program, block: Block, name: str) -> str:
    """Resolve the gradient var of `name` (append_backward suffixes grad
    names, e.g. x@GRAD_0 — prefer the program's grad map, fall back to a
    unique @GRAD-prefixed var)."""
    gmap = getattr(program, "_grad_map", None)
    if gmap and name in gmap:
        return gmap[name]
    prefix = name + "@GRAD"
    cands = [n for n in block.vars if n == prefix
             or n.startswith(prefix + "_")]
    if len(cands) != 1:
        raise ValueError(
            f"cannot resolve the gradient of boundary var {name!r}: "
            f"candidates {cands} — was backward appended?")
    return cands[0]


def _static_shape(v: VarDesc, batch_size: int) -> Tuple[int, ...]:
    if v.shape is None:
        raise ValueError(
            f"heter boundary var {v.name!r} has no static shape — the "
            "relay needs one (set shapes on the data layers)")
    return tuple(batch_size if s in (-1, None) else int(s)
                 for s in v.shape)


def split_heter_program(program: Program, boundary: Sequence,
                        endpoints: Sequence[str], batch_size: int,
                        channel: str = "heter", timeout: float = 60.0):
    """Partition a minimized (+PS-transpiled) main program at `boundary`
    (vars or names) into (cpu_section, device_section).

    CPU section = ancestor ops of the boundary vars + descendant ops of
    their gradients (the sparse-table backward + push).  Device section =
    the rest.  heter_send/heter_recv pairs are inserted at the cut in
    both directions.  Raises if any non-boundary value would have to
    cross the cut — the boundary the caller named must be the complete
    interface."""
    if len(program.blocks) > 1:
        raise ValueError(
            "split_heter_program supports single-block programs only — "
            "the section copies would drop control-flow sub-blocks "
            f"(program has {len(program.blocks)} blocks)")
    block = program.global_block()
    bnames = [b if isinstance(b, str) else b.name for b in boundary]
    gnames = [_grad_name(program, block, n) for n in bnames]

    # ---- CPU-forward: ops whose outputs transitively reach the boundary
    need = set(bnames)
    cpu_fwd = []
    for op in reversed(block.ops):
        if any(n in need for n in op.output_names()):
            cpu_fwd.append(op)
            need.update(op.input_names())
    cpu_fwd.reverse()
    fwd_set = set(map(id, cpu_fwd))

    # ---- CPU-backward: ops consuming the boundary grads (transitively)
    avail = set(gnames)
    cpu_bwd = []
    for op in block.ops:
        if id(op) in fwd_set:
            continue
        if any(n in avail for n in op.input_names()):
            cpu_bwd.append(op)
            avail.update(op.output_names())
    bwd_set = set(map(id, cpu_bwd))

    device_ops = [op for op in block.ops
                  if id(op) not in fwd_set and id(op) not in bwd_set]

    # ---- the named boundary must be the complete interface
    cpu_out = {n for op in cpu_fwd for n in op.output_names()}
    dev_out = {n for op in device_ops for n in op.output_names()}
    leak = [n for op in device_ops for n in op.input_names()
            if n in cpu_out and n not in bnames]
    if leak:
        raise ValueError(
            f"device section reads CPU-section values {sorted(set(leak))} "
            "that are not in the declared boundary")
    leak = [n for op in cpu_bwd for n in op.input_names()
            if n in dev_out and n not in gnames]
    if leak:
        raise ValueError(
            f"CPU backward section reads device values "
            f"{sorted(set(leak))} outside the boundary gradients")

    b_vars = [block.var(n) for n in bnames]
    shapes = [_static_shape(v, batch_size) for v in b_vars]
    dtypes = [v.dtype for v in b_vars]
    wire = {"endpoints": list(endpoints), "channel": channel,
            "timeout": float(timeout)}

    # ---- CPU section: fwd -> send(acts) -> recv(act grads) -> bwd ------
    cpu_prog = Program()
    cb = cpu_prog.global_block()
    _copy_ops(block, cpu_prog, cpu_fwd)
    dummy = cb.create_var(shape=[1], dtype="float32")
    cb.ops.append(OpDesc("heter_send", {"X": bnames},
                         {"Dummy": [dummy.name]},
                         dict(wire, send_varnames=bnames)))
    for n, s, d in zip(gnames, shapes, dtypes):
        cb.create_var(name=n, shape=s, dtype=d)
    cb.ops.append(OpDesc("heter_recv", {"Dummy": [dummy.name]},
                         {"Out": gnames},
                         dict(wire, recv_varnames=gnames,
                              shapes=[list(s) for s in shapes],
                              dtypes=dtypes)))
    _copy_ops(block, cpu_prog, cpu_bwd)

    # ---- device section: recv(acts) -> dense step -> send(act grads) --
    dev_prog = Program()
    db = dev_prog.global_block()
    for v, s in zip(b_vars, shapes):
        _copy_var(db, v)
        db.var(v.name).shape = s
    ddummy = db.create_var(shape=[1], dtype="float32")
    db.ops.append(OpDesc("heter_recv", {"Dummy": [ddummy.name]},
                         {"Out": bnames},
                         dict(wire, recv_varnames=bnames,
                              shapes=[list(s) for s in shapes],
                              dtypes=dtypes)))
    _copy_ops(block, dev_prog, device_ops)
    db.ops.append(OpDesc("heter_send", {"X": gnames},
                         {"Dummy": [ddummy.name + "_s"]},
                         dict(wire, send_varnames=gnames)))
    db.create_var(name=ddummy.name + "_s", shape=(1,), dtype="float32")

    def _feeds(prog):
        used = {n for op in prog.global_block().ops
                for n in op.input_names()}
        return [n for n, v in prog.global_block().vars.items()
                if v.is_data and n in used]

    return HeterSection(cpu_prog, _feeds(cpu_prog)), \
        HeterSection(dev_prog, _feeds(dev_prog))

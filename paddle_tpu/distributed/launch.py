"""`python -m paddle_tpu.distributed.launch` — multi-host job launcher.

Reference: /root/reference/python/paddle/distributed/fleet/launch.py —
`launch_collective` (:198) spawns per-device worker subprocesses with the
PADDLE_* env contract and watches them; `launch_ps` (:248) starts
pserver+trainer processes for parameter-server mode.

TPU mapping: one worker process per host of the slice (`--nproc_per_node`
defaults to 1 — a single jax client drives all local chips); `--ips` lists
slice hosts; rank-0 endpoint doubles as the jax.distributed coordinator.

Supervision (docs/elastic.md): the launcher is a SUPERVISOR, not a
passive poller.  A rank that dies leaves its peers wedged inside the
next collective, so on any non-zero exit the pod is torn down fail-fast
(SIGTERM → grace → SIGKILL, giving every survivor's preemption handler a
chance to checkpoint).  With ``--elastic``, the launcher then re-forms
the job from the surviving capacity — the new world is the largest
power-of-two divisor of the ORIGINAL (logical) world that the survivors
can fill — and relaunches with the elastic env contract
(``PADDLE_TPU_ELASTIC=1``, ``PADDLE_TPU_ELASTIC_LOGICAL_WORLD=<N>``,
``PADDLE_TPU_ELASTIC_RESTART=<n>``); workers resume from the last
committed checkpoint via ``Executor.restore_from_checkpoint``, whose
topology-shifted restore re-buckets state and schedule for the new
world.

Multi-host elastic (docs/elastic.md "Cross-host fleets"): with several
``--ips`` hosts, ``--elastic --fleet_dir <shared-fs dir>`` runs
`launch_collective_fleet` — each host's launcher joins the fleet
control plane (distributed/fleet_control.py), supervises its local
trainers AND its peers' membership, and on a lost host every surviving
launcher tears down, runs the two-phase survivor agreement (same
re-formed world, same restore step, picked from the run journals), and
relaunches with the ``PADDLE_TPU_FLEET_*`` contract; workers whose
writer world changed restore through the rank-merged
``CheckpointManager.load_merged``.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

from .launch_utils import (Cluster, Pod, get_cluster, start_local_trainers,
                           watch_local_trainers, poll_local_trainers,
                           terminate_procs, find_free_ports)

__all__ = ["launch_collective", "launch_collective_fleet", "launch_ps",
           "main", "elastic_world_size"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips of the slice")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes per host (1 per TPU host)")
    p.add_argument("--started_port", type=int, default=None)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--run_mode", type=str, default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--elastic", action="store_true",
                   help="supervise: on a lost rank, re-form the job from "
                        "survivors and relaunch resuming from the last "
                        "checkpoint (docs/elastic.md); with multiple "
                        "--ips hosts this needs --fleet_dir (the "
                        "cross-host rendezvous)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="elastic relaunch budget before giving up")
    p.add_argument("--fleet_dir", type=str,
                   default=os.environ.get("PADDLE_TPU_FLEET_DIR"),
                   help="shared-filesystem rendezvous dir for multi-host "
                        "elastic (distributed/fleet_control.py): every "
                        "host's launcher joins membership here, agrees "
                        "on the survivor set after a lost host, and "
                        "exports the PADDLE_TPU_FLEET_* contract to its "
                        "workers")
    p.add_argument("--host_rank", type=int, default=None,
                   help="this host's index in --ips (default: the "
                        "position of POD_IP in --ips, else 0); must be "
                        "explicit when simulating several hosts on one "
                        "machine")
    p.add_argument("--host_capacity", type=int, default=None,
                   help="logical chips this host contributes to the "
                        "fleet world (default: --nproc_per_node); the "
                        "elastic logical world is the sum over --ips")
    p.add_argument("--member_timeout", type=float, default=20.0,
                   help="seconds without a membership refresh before a "
                        "fleet host counts as lost")
    p.add_argument("--journal_dir", type=str,
                   default=os.environ.get("PADDLE_TPU_JOURNAL_DIR"),
                   help="run-journal dir (exported to workers); the "
                        "fleet re-form reads the survivors' journals to "
                        "agree on the newest mutually-visible "
                        "checkpoint step")
    p.add_argument("--term_grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL at teardown")
    p.add_argument("--heartbeat_dir", type=str, default=None,
                   help="arm progress-based supervision: workers write "
                        "per-rank heartbeat files here each train step "
                        "(PADDLE_TPU_HEARTBEAT_DIR is exported to them); "
                        "a live rank whose heartbeat goes stale past "
                        "--stall_timeout is torn down like a dead one "
                        "(wedged-in-a-dead-collective detection)")
    p.add_argument("--stall_timeout", type=float, default=300.0,
                   help="seconds without a heartbeat before a rank "
                        "counts as stalled (must out-wait the longest "
                        "legitimate step, first-step compile included)")
    p.add_argument("--server_num", type=int, default=None)
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("--servers", type=str, default="")
    p.add_argument("--workers", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def elastic_world_size(survivors: int, logical_world: int) -> int:
    """Largest power-of-two divisor of `logical_world` that `survivors`
    ranks can fill — the world the re-formed mesh runs at (the elastic
    schedule requires the physical world to divide the logical one)."""
    if survivors < 1:
        return 0
    w = 1
    while w * 2 <= survivors and logical_world % (w * 2) == 0:
        w *= 2
    return w


def _spawn_pod(args, nproc, envs):
    node_ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    this_ip = os.environ.get("POD_IP", node_ips[0])
    if args.started_port is not None:
        ports = list(range(args.started_port, args.started_port + nproc))
    else:
        ports = find_free_ports(nproc)
    endpoints = [[f"{ip}:{port}" for port in ports] for ip in node_ips]
    devices_per_proc = [[i] for i in range(nproc)]
    cluster, pod = get_cluster(node_ips, this_ip, endpoints,
                               devices_per_proc)
    procs = start_local_trainers(cluster, pod, args.training_script,
                                 args.training_script_args,
                                 log_dir=args.log_dir, envs=envs)
    return cluster, procs


def launch_collective(args):
    """launch.py:198 parity, upgraded to a supervision loop.

    Non-elastic: any rank dying tears the pod down (fail-fast) and exits
    non-zero — survivors blocked in a dead collective must not hang the
    job forever.  ``--elastic``: the teardown is followed by re-forming
    the mesh from surviving capacity and relaunching with the elastic
    env contract; workers resume from the last committed checkpoint."""
    nproc = args.nproc_per_node
    n_ips = len([ip for ip in args.ips.split(",") if ip.strip()])
    if args.elastic and (n_ips > 1 or args.fleet_dir):
        # multi-host elastic: every host's launcher joins the shared-fs
        # rendezvous and the fleet controller drives the cross-host
        # survivor agreement (distributed/fleet_control.py)
        if not args.fleet_dir:
            sys.stderr.write(
                "--elastic with multiple --ips hosts needs --fleet_dir "
                "(a shared-filesystem rendezvous dir every host "
                "mounts; docs/elastic.md)\n")
            return 2
        return launch_collective_fleet(args)
    logical_world = nproc * n_ips
    hb_dir = args.heartbeat_dir
    restarts = 0
    while True:
        envs = {}
        if args.elastic:
            envs = {"PADDLE_TPU_ELASTIC": "1",
                    "PADDLE_TPU_ELASTIC_LOGICAL_WORLD": str(logical_world),
                    "PADDLE_TPU_ELASTIC_RESTART": str(restarts)}
        if hb_dir:
            # progress-based supervision (docs/observability.md): the
            # workers beat per train step; stale heartbeats from a
            # previous incarnation must not trip the NEW pod before its
            # first step, so the dir is swept at every (re)spawn
            envs["PADDLE_TPU_HEARTBEAT_DIR"] = hb_dir
            os.makedirs(hb_dir, exist_ok=True)
            for name in os.listdir(hb_dir):
                if name.startswith("heartbeat.rank"):
                    try:
                        os.unlink(os.path.join(hb_dir, name))
                    except OSError:
                        pass
        cluster, procs = _spawn_pod(args, nproc, envs)
        failed, stalled = [], []
        try:
            while True:
                procs, _done, failed = poll_local_trainers(procs)
                if failed or not procs:
                    break
                if hb_dir:
                    from ..observability.heartbeat import stalled_ranks
                    stalled = stalled_ranks(
                        hb_dir, args.stall_timeout,
                        ranks=[tp.rank for tp in procs])
                    if stalled:
                        break
                time.sleep(0.5)
        except KeyboardInterrupt:
            terminate_procs(procs, sigterm_grace=args.term_grace)
            return 1
        if not failed and not stalled:
            return 0
        if failed:
            codes = {tp.rank: tp.proc.poll() for tp in failed}
        else:
            # a wedged rank never exits on its own: a stale heartbeat IS
            # the failure signal, and the teardown below is what turns
            # "hangs forever" into "re-forms and finishes"
            codes = {r: "stalled" for r in stalled}
            sys.stderr.write(
                f"trainer rank(s) {stalled} stalled: no heartbeat for "
                f"{args.stall_timeout}s — treating as lost\n")
        # fail fast: peers of a dead rank are wedged in the next
        # collective — tear the pod down (SIGTERM lets their preemption
        # handlers checkpoint) instead of letting them hang
        terminate_procs(procs + failed, sigterm_grace=args.term_grace)
        survivors = nproc - len(failed) - len(stalled)
        if survivors < 1 and stalled and not failed:
            # stall-only teardown: every process was ALIVE and the host
            # answered — the capacity exists even though progress froze
            # (on a real mesh one wedged collective stalls every peer's
            # heartbeat at once).  Re-form minimally instead of declaring
            # the fleet gone; --max_restarts still bounds the loop.
            survivors = 1
        if not args.elastic or restarts >= args.max_restarts:
            sys.stderr.write(
                f"trainer rank(s) {sorted(codes)} exited non-zero "
                f"{codes}; pod terminated (elastic="
                f"{bool(args.elastic)}, restarts={restarts})\n")
            return 1
        new_world = elastic_world_size(survivors, logical_world)
        if new_world < 1:
            sys.stderr.write("no surviving capacity to re-form the mesh\n")
            return 1
        sys.stderr.write(
            f"elastic: rank(s) {sorted(codes)} lost ({codes}); re-forming "
            f"mesh {nproc} -> {new_world} of logical {logical_world}, "
            f"restart {restarts + 1}/{args.max_restarts}\n")
        nproc = new_world
        restarts += 1


def _spawn_fleet_pod(args, nproc, envs, member_hosts, my_host, node_ips):
    """Spawn THIS host's trainers for the current fleet formation.

    Trainer ranks are dense over the formation: sorted member hosts ×
    nproc (the CheckpointManager/journal/heartbeat rank layout every
    consumer of the formation shares).  Pods are selected by host INDEX,
    not by addr — simulated fleets run several 'hosts' on one ip."""
    from .launch_utils import Cluster, Pod, Trainer
    members = sorted(int(h) for h in member_hosts)
    my_index = members.index(int(my_host))
    if args.started_port is not None:
        ports = list(range(args.started_port, args.started_port + nproc))
    else:
        ports = find_free_ports(nproc)
    cluster = Cluster()
    rank = 0
    for idx, h in enumerate(members):
        ip = node_ips[h] if h < len(node_ips) else "127.0.0.1"
        pod = Pod(idx, ip)
        for i in range(nproc):
            # remote hosts' endpoints are decorative here (no connect in
            # the simulated fleet; a real slice passes --started_port so
            # every host derives the same port map)
            pod.trainers.append(Trainer(f"{ip}:{ports[i]}", rank, [i]))
            rank += 1
        cluster.pods.append(pod)
    pod = cluster.pods[my_index]
    procs = start_local_trainers(cluster, pod, args.training_script,
                                 args.training_script_args,
                                 log_dir=args.log_dir, envs=envs)
    ranks = [t.rank for t in pod.trainers]
    return procs, ranks


def launch_collective_fleet(args):
    """Multi-host elastic supervision: the per-host launcher joined to
    the fleet control plane (distributed/fleet_control.py).

    Each host's launcher (1) rendezvouses at --fleet_dir and agrees the
    epoch-0 formation, (2) spawns its local trainers with the elastic +
    fleet env contract, (3) supervises — local exit codes, heartbeat
    stalls, AND peer membership — and (4) on any loss tears its pod
    down and runs the two-phase survivor agreement so every surviving
    launcher re-forms to the SAME world and restore step, then
    relaunches.  Workers resume via the rank-merged restore
    (CheckpointManager.load_merged) when the writer world changed."""
    from .fleet_control import (FleetAgreementTimeout, FleetController,
                                fleet_rank)
    nproc = args.nproc_per_node
    node_ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    n_ips = max(1, len(node_ips))
    host = args.host_rank
    if host is None:
        pod_ip = os.environ.get("POD_IP", "")
        host = node_ips.index(pod_ip) if pod_ip in node_ips else 0
    capacity = args.host_capacity or nproc
    logical_world = capacity * n_ips
    hb_dir = args.heartbeat_dir
    ctl = FleetController(
        args.fleet_dir, host=host, capacity=capacity,
        logical_world=logical_world,
        member_timeout_s=args.member_timeout,
        journal_dir=args.journal_dir, heartbeat_dir=hb_dir,
        stall_timeout_s=(args.stall_timeout if hb_dir else None))
    # a reused fleet dir must not replay a previous run's agreement
    # (stale commits/proposals/barriers/done-members) into this one
    ctl.reset_rendezvous()
    try:
        commit = ctl.form(expect=range(n_ips))
    except FleetAgreementTimeout as e:
        sys.stderr.write(f"fleet formation failed: {e}\n")
        return 1
    restarts = 0
    while True:
        my_rank0 = fleet_rank(host, commit.members) * nproc
        ranks = list(range(my_rank0, my_rank0 + nproc))
        envs = {"PADDLE_TPU_ELASTIC": "1",
                "PADDLE_TPU_ELASTIC_LOGICAL_WORLD": str(logical_world),
                "PADDLE_TPU_ELASTIC_RESTART": str(restarts)}
        envs.update(ctl.env_for_workers(commit))
        if args.journal_dir:
            envs["PADDLE_TPU_JOURNAL_DIR"] = args.journal_dir
        if hb_dir:
            envs["PADDLE_TPU_HEARTBEAT_DIR"] = hb_dir
            os.makedirs(hb_dir, exist_ok=True)
            for name in os.listdir(hb_dir):  # sweep stale incarnations
                if name.startswith("heartbeat.rank"):
                    try:
                        os.unlink(os.path.join(hb_dir, name))
                    except OSError:
                        pass
        sys.stderr.write(
            f"fleet host {host}: epoch {commit.epoch} members "
            f"{commit.members} world {commit.world} restore_step "
            f"{commit.restore_step} — spawning ranks {ranks}\n")
        procs, ranks = _spawn_fleet_pod(args, nproc, envs,
                                        commit.members, host, node_ips)
        failed, stalled, lost = [], [], []
        try:
            while True:
                ctl.tick(ranks=ranks)
                procs, _done, failed = poll_local_trainers(procs)
                if failed:
                    break
                if not procs:  # every local trainer finished cleanly
                    ctl.leave()
                    ctl.close()
                    return 0
                if hb_dir:
                    from ..observability.heartbeat import stalled_ranks
                    stalled = stalled_ranks(
                        hb_dir, args.stall_timeout,
                        ranks=[tp.rank for tp in procs])
                    if stalled:
                        break
                lost = ctl.lost_members(commit)
                if lost:
                    break
                if ctl.reform_requested():
                    break
                time.sleep(0.3)
        except KeyboardInterrupt:
            terminate_procs(procs, sigterm_grace=args.term_grace)
            ctl.close()
            return 1
        why = (f"rank(s) failed {[tp.rank for tp in failed]}" if failed
               else f"rank(s) stalled {stalled}" if stalled
               else f"host(s) lost {lost}" if lost
               else "peer requested re-form")
        sys.stderr.write(
            f"fleet host {host}: {why} at epoch {commit.epoch} — "
            "tearing down local pod for survivor agreement\n")
        # SIGTERM first: survivors' preemption handlers stage their
        # final checkpoint before the fleet re-forms on top of it
        terminate_procs(procs + failed, sigterm_grace=args.term_grace)
        if restarts >= args.max_restarts:
            sys.stderr.write(
                f"fleet host {host}: restart budget exhausted "
                f"({restarts}/{args.max_restarts})\n")
            ctl.close()
            return 1
        try:
            commit = ctl.reform(commit)
        except FleetAgreementTimeout as e:
            sys.stderr.write(f"fleet re-form failed: {e}\n")
            ctl.close()
            return 1
        if commit.world < 1 or host not in commit.members:
            sys.stderr.write(
                f"fleet host {host}: not part of the re-formed fleet "
                f"{commit.members}\n")
            ctl.close()
            return 1
        restarts += 1


def launch_ps(args):
    """launch.py:248 parity — spawn pserver + trainer processes with the
    PADDLE_PORT / PADDLE_PSERVERS_IP_PORT_LIST / TRAINING_ROLE contract."""
    server_eps = [e for e in args.servers.split(",") if e]
    worker_eps = [e for e in args.workers.split(",") if e]
    if not server_eps:
        n = args.server_num or 1
        server_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]
    if not worker_eps:
        n = args.worker_num or 1
        worker_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]

    import subprocess
    procs = []
    base_env = dict(os.environ)
    base_env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(server_eps)
    base_env["PADDLE_TRAINERS_NUM"] = str(len(worker_eps))
    for i, ep in enumerate(server_eps):
        env = dict(base_env, TRAINING_ROLE="PSERVER",
                   PADDLE_PORT=ep.split(":")[1], POD_IP=ep.split(":")[0],
                   PADDLE_TRAINER_ID=str(i))
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    for i, ep in enumerate(worker_eps):
        env = dict(base_env, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i),
                   PADDLE_CURRENT_ENDPOINT=ep)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def main(argv=None):
    args = _parse_args(argv)
    if args.run_mode == "ps" or args.server_num or args.servers:
        return launch_ps(args)
    return launch_collective(args)


if __name__ == "__main__":
    sys.exit(main())

"""`python -m paddle_tpu.distributed.launch` — multi-host job launcher.

Reference: /root/reference/python/paddle/distributed/fleet/launch.py —
`launch_collective` (:198) spawns per-device worker subprocesses with the
PADDLE_* env contract and watches them; `launch_ps` (:248) starts
pserver+trainer processes for parameter-server mode.

TPU mapping: one worker process per host of the slice (`--nproc_per_node`
defaults to 1 — a single jax client drives all local chips); `--ips` lists
slice hosts; rank-0 endpoint doubles as the jax.distributed coordinator.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

from .launch_utils import (Cluster, Pod, get_cluster, start_local_trainers,
                           watch_local_trainers, terminate_procs,
                           find_free_ports)

__all__ = ["launch_collective", "launch_ps", "main"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips of the slice")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes per host (1 per TPU host)")
    p.add_argument("--started_port", type=int, default=None)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--run_mode", type=str, default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--server_num", type=int, default=None)
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("--servers", type=str, default="")
    p.add_argument("--workers", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch_collective(args):
    """launch.py:198 parity."""
    node_ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    this_ip = os.environ.get("POD_IP", node_ips[0])
    nproc = args.nproc_per_node
    if args.started_port is not None:
        ports = list(range(args.started_port, args.started_port + nproc))
    else:
        ports = find_free_ports(nproc)
    endpoints = [[f"{ip}:{port}" for port in ports] for ip in node_ips]
    devices_per_proc = [[i] for i in range(nproc)]
    cluster, pod = get_cluster(node_ips, this_ip, endpoints,
                               devices_per_proc)
    procs = start_local_trainers(cluster, pod, args.training_script,
                                 args.training_script_args,
                                 log_dir=args.log_dir)
    try:
        while True:
            procs = watch_local_trainers(procs, cluster.trainers_nranks())
            if not procs:
                return 0
            time.sleep(1)
    except KeyboardInterrupt:
        terminate_procs(procs)
        return 1


def launch_ps(args):
    """launch.py:248 parity — spawn pserver + trainer processes with the
    PADDLE_PORT / PADDLE_PSERVERS_IP_PORT_LIST / TRAINING_ROLE contract."""
    server_eps = [e for e in args.servers.split(",") if e]
    worker_eps = [e for e in args.workers.split(",") if e]
    if not server_eps:
        n = args.server_num or 1
        server_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]
    if not worker_eps:
        n = args.worker_num or 1
        worker_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]

    import subprocess
    procs = []
    base_env = dict(os.environ)
    base_env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(server_eps)
    base_env["PADDLE_TRAINERS_NUM"] = str(len(worker_eps))
    for i, ep in enumerate(server_eps):
        env = dict(base_env, TRAINING_ROLE="PSERVER",
                   PADDLE_PORT=ep.split(":")[1], POD_IP=ep.split(":")[0],
                   PADDLE_TRAINER_ID=str(i))
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    for i, ep in enumerate(worker_eps):
        env = dict(base_env, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i),
                   PADDLE_CURRENT_ENDPOINT=ep)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def main(argv=None):
    args = _parse_args(argv)
    if args.run_mode == "ps" or args.server_num or args.servers:
        return launch_ps(args)
    return launch_collective(args)


if __name__ == "__main__":
    sys.exit(main())

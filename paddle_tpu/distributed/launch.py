"""`python -m paddle_tpu.distributed.launch` — multi-host job launcher.

Reference: /root/reference/python/paddle/distributed/fleet/launch.py —
`launch_collective` (:198) spawns per-device worker subprocesses with the
PADDLE_* env contract and watches them; `launch_ps` (:248) starts
pserver+trainer processes for parameter-server mode.

TPU mapping: one worker process per host of the slice (`--nproc_per_node`
defaults to 1 — a single jax client drives all local chips); `--ips` lists
slice hosts; rank-0 endpoint doubles as the jax.distributed coordinator.

Supervision (docs/elastic.md): the launcher is a SUPERVISOR, not a
passive poller.  A rank that dies leaves its peers wedged inside the
next collective, so on any non-zero exit the pod is torn down fail-fast
(SIGTERM → grace → SIGKILL, giving every survivor's preemption handler a
chance to checkpoint).  With ``--elastic``, the launcher then re-forms
the job from the surviving capacity — the new world is the largest
power-of-two divisor of the ORIGINAL (logical) world that the survivors
can fill — and relaunches with the elastic env contract
(``PADDLE_TPU_ELASTIC=1``, ``PADDLE_TPU_ELASTIC_LOGICAL_WORLD=<N>``,
``PADDLE_TPU_ELASTIC_RESTART=<n>``); workers resume from the last
committed checkpoint via ``Executor.restore_from_checkpoint``, whose
topology-shifted restore re-buckets state and schedule for the new
world.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

from .launch_utils import (Cluster, Pod, get_cluster, start_local_trainers,
                           watch_local_trainers, poll_local_trainers,
                           terminate_procs, find_free_ports)

__all__ = ["launch_collective", "launch_ps", "main", "elastic_world_size"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips of the slice")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes per host (1 per TPU host)")
    p.add_argument("--started_port", type=int, default=None)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("--run_mode", type=str, default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--elastic", action="store_true",
                   help="supervise: on a lost rank, re-form the job from "
                        "survivors and relaunch resuming from the last "
                        "checkpoint (docs/elastic.md)")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="elastic relaunch budget before giving up")
    p.add_argument("--term_grace", type=float, default=10.0,
                   help="seconds between SIGTERM and SIGKILL at teardown")
    p.add_argument("--heartbeat_dir", type=str, default=None,
                   help="arm progress-based supervision: workers write "
                        "per-rank heartbeat files here each train step "
                        "(PADDLE_TPU_HEARTBEAT_DIR is exported to them); "
                        "a live rank whose heartbeat goes stale past "
                        "--stall_timeout is torn down like a dead one "
                        "(wedged-in-a-dead-collective detection)")
    p.add_argument("--stall_timeout", type=float, default=300.0,
                   help="seconds without a heartbeat before a rank "
                        "counts as stalled (must out-wait the longest "
                        "legitimate step, first-step compile included)")
    p.add_argument("--server_num", type=int, default=None)
    p.add_argument("--worker_num", type=int, default=None)
    p.add_argument("--servers", type=str, default="")
    p.add_argument("--workers", type=str, default="")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def elastic_world_size(survivors: int, logical_world: int) -> int:
    """Largest power-of-two divisor of `logical_world` that `survivors`
    ranks can fill — the world the re-formed mesh runs at (the elastic
    schedule requires the physical world to divide the logical one)."""
    if survivors < 1:
        return 0
    w = 1
    while w * 2 <= survivors and logical_world % (w * 2) == 0:
        w *= 2
    return w


def _spawn_pod(args, nproc, envs):
    node_ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    this_ip = os.environ.get("POD_IP", node_ips[0])
    if args.started_port is not None:
        ports = list(range(args.started_port, args.started_port + nproc))
    else:
        ports = find_free_ports(nproc)
    endpoints = [[f"{ip}:{port}" for port in ports] for ip in node_ips]
    devices_per_proc = [[i] for i in range(nproc)]
    cluster, pod = get_cluster(node_ips, this_ip, endpoints,
                               devices_per_proc)
    procs = start_local_trainers(cluster, pod, args.training_script,
                                 args.training_script_args,
                                 log_dir=args.log_dir, envs=envs)
    return cluster, procs


def launch_collective(args):
    """launch.py:198 parity, upgraded to a supervision loop.

    Non-elastic: any rank dying tears the pod down (fail-fast) and exits
    non-zero — survivors blocked in a dead collective must not hang the
    job forever.  ``--elastic``: the teardown is followed by re-forming
    the mesh from surviving capacity and relaunching with the elastic
    env contract; workers resume from the last committed checkpoint."""
    nproc = args.nproc_per_node
    n_ips = len([ip for ip in args.ips.split(",") if ip.strip()])
    if args.elastic and n_ips > 1:
        # this launcher supervises LOCAL trainers only; shrinking a
        # multi-node job needs cross-host re-form coordination (every
        # launcher must agree on the survivor set) — refuse rather than
        # re-size the local pod against a global world it cannot see
        sys.stderr.write(
            "--elastic currently supervises a single node "
            "(--ips with one host); multi-node elastic re-form needs "
            "a cross-host coordinator (docs/elastic.md)\n")
        return 2
    logical_world = nproc * n_ips
    hb_dir = args.heartbeat_dir
    restarts = 0
    while True:
        envs = {}
        if args.elastic:
            envs = {"PADDLE_TPU_ELASTIC": "1",
                    "PADDLE_TPU_ELASTIC_LOGICAL_WORLD": str(logical_world),
                    "PADDLE_TPU_ELASTIC_RESTART": str(restarts)}
        if hb_dir:
            # progress-based supervision (docs/observability.md): the
            # workers beat per train step; stale heartbeats from a
            # previous incarnation must not trip the NEW pod before its
            # first step, so the dir is swept at every (re)spawn
            envs["PADDLE_TPU_HEARTBEAT_DIR"] = hb_dir
            os.makedirs(hb_dir, exist_ok=True)
            for name in os.listdir(hb_dir):
                if name.startswith("heartbeat.rank"):
                    try:
                        os.unlink(os.path.join(hb_dir, name))
                    except OSError:
                        pass
        cluster, procs = _spawn_pod(args, nproc, envs)
        failed, stalled = [], []
        try:
            while True:
                procs, _done, failed = poll_local_trainers(procs)
                if failed or not procs:
                    break
                if hb_dir:
                    from ..observability.heartbeat import stalled_ranks
                    stalled = stalled_ranks(
                        hb_dir, args.stall_timeout,
                        ranks=[tp.rank for tp in procs])
                    if stalled:
                        break
                time.sleep(0.5)
        except KeyboardInterrupt:
            terminate_procs(procs, sigterm_grace=args.term_grace)
            return 1
        if not failed and not stalled:
            return 0
        if failed:
            codes = {tp.rank: tp.proc.poll() for tp in failed}
        else:
            # a wedged rank never exits on its own: a stale heartbeat IS
            # the failure signal, and the teardown below is what turns
            # "hangs forever" into "re-forms and finishes"
            codes = {r: "stalled" for r in stalled}
            sys.stderr.write(
                f"trainer rank(s) {stalled} stalled: no heartbeat for "
                f"{args.stall_timeout}s — treating as lost\n")
        # fail fast: peers of a dead rank are wedged in the next
        # collective — tear the pod down (SIGTERM lets their preemption
        # handlers checkpoint) instead of letting them hang
        terminate_procs(procs + failed, sigterm_grace=args.term_grace)
        survivors = nproc - len(failed) - len(stalled)
        if survivors < 1 and stalled and not failed:
            # stall-only teardown: every process was ALIVE and the host
            # answered — the capacity exists even though progress froze
            # (on a real mesh one wedged collective stalls every peer's
            # heartbeat at once).  Re-form minimally instead of declaring
            # the fleet gone; --max_restarts still bounds the loop.
            survivors = 1
        if not args.elastic or restarts >= args.max_restarts:
            sys.stderr.write(
                f"trainer rank(s) {sorted(codes)} exited non-zero "
                f"{codes}; pod terminated (elastic="
                f"{bool(args.elastic)}, restarts={restarts})\n")
            return 1
        new_world = elastic_world_size(survivors, logical_world)
        if new_world < 1:
            sys.stderr.write("no surviving capacity to re-form the mesh\n")
            return 1
        sys.stderr.write(
            f"elastic: rank(s) {sorted(codes)} lost ({codes}); re-forming "
            f"mesh {nproc} -> {new_world} of logical {logical_world}, "
            f"restart {restarts + 1}/{args.max_restarts}\n")
        nproc = new_world
        restarts += 1


def launch_ps(args):
    """launch.py:248 parity — spawn pserver + trainer processes with the
    PADDLE_PORT / PADDLE_PSERVERS_IP_PORT_LIST / TRAINING_ROLE contract."""
    server_eps = [e for e in args.servers.split(",") if e]
    worker_eps = [e for e in args.workers.split(",") if e]
    if not server_eps:
        n = args.server_num or 1
        server_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]
    if not worker_eps:
        n = args.worker_num or 1
        worker_eps = [f"127.0.0.1:{p}" for p in find_free_ports(n)]

    import subprocess
    procs = []
    base_env = dict(os.environ)
    base_env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(server_eps)
    base_env["PADDLE_TRAINERS_NUM"] = str(len(worker_eps))
    for i, ep in enumerate(server_eps):
        env = dict(base_env, TRAINING_ROLE="PSERVER",
                   PADDLE_PORT=ep.split(":")[1], POD_IP=ep.split(":")[0],
                   PADDLE_TRAINER_ID=str(i))
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    for i, ep in enumerate(worker_eps):
        env = dict(base_env, TRAINING_ROLE="TRAINER",
                   PADDLE_TRAINER_ID=str(i),
                   PADDLE_CURRENT_ENDPOINT=ep)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", args.training_script]
            + args.training_script_args, env=env))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def main(argv=None):
    args = _parse_args(argv)
    if args.run_mode == "ps" or args.server_num or args.servers:
        return launch_ps(args)
    return launch_collective(args)


if __name__ == "__main__":
    sys.exit(main())

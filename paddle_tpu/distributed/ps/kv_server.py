"""Parameter-server KV runtime: TCP server + client.

Reference: /root/reference/paddle/fluid/operators/distributed/ — the
gRPC/BRPC `RPCServer`/`RPCClient` (grpc_client.h:211 AsyncSendVar/
AsyncGetVar), `listen_and_serv_op`, and the sync/async/geo communicator
(communicator.h:183-401).

TPU-native design: the PS tier serves the CPU/sparse capability, so it is a
host-side service — a threaded TCP server speaking a length-prefixed binary
protocol (numpy buffers; no pickle-over-the-wire for values).  The dense
collective path never touches this; XLA collectives own it.

Server-side optimization (sync mode): like the reference pserver running
optimizer blocks, the server applies `param -= lr * mean(grads)` once all
trainers' pushes for a step arrive (barrier counting, heart-beat friendly).
Async mode applies each push immediately (Hogwild, communicator.h
AsyncCommunicator semantics).
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

__all__ = ["KVServer", "KVClient"]

_MAGIC = b"PSRPC1\n"
# ops
OP_INIT = 1        # set param if absent
OP_PULL = 2        # get param
OP_PUSH_SYNC = 3   # push grad; applied when all trainers arrive
OP_PUSH_ASYNC = 4  # push grad; applied immediately
OP_BARRIER = 5
OP_SHUTDOWN = 6
OP_PING = 7
OP_SET = 8         # overwrite param (geo-SGD delta merge uses add)
OP_PUSH_DELTA = 9  # geo: add delta to param
OP_ERROR = 10      # server-side failure; name carries the message
OP_HEARTBEAT = 11  # trainer liveness ping; extra carries the trainer id
OP_PULL_ROWS = 12  # sparse pull: arr carries int64 LOCAL row ids
OP_PUSH_ROWS = 13  # sparse push: ids message then values message (2-part)
OP_CONFIG_SPARSE_OPT = 14  # arr=[beta1,beta2,eps], extra: 0=sgd 1=adam
OP_QPUSH = 16      # named-queue push (heter activation relay)
OP_QPOP = 17       # named-queue BLOCKING pop; extra carries the timeout
OP_PUSH_ROWS_SYNC = 15     # 2-part like PUSH_ROWS; server accumulates
#                            until every live trainer's push arrives,
#                            averages merged rows, then applies the
#                            table's optimizer (fixes the client-trusting
#                            grad_scale protocol: a client that omits
#                            scaling can no longer train at N x lr)


def _send_msg(sock, op: int, name: str, arr: Optional[np.ndarray],
              extra: float = 0.0):
    name_b = name.encode()
    if arr is not None:
        arr = np.ascontiguousarray(arr)
        dtype_b = str(arr.dtype).encode()
        shape = arr.shape
        payload = arr.tobytes()
    else:
        dtype_b, shape, payload = b"", (), b""
    shape_b = ",".join(str(s) for s in shape).encode()
    header = struct.pack("!BIIIdI", op, len(name_b), len(dtype_b),
                         len(shape_b), extra, len(payload))
    sock.sendall(_MAGIC + header + name_b + dtype_b + shape_b + payload)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def _recv_msg(sock):
    magic = _recv_exact(sock, len(_MAGIC))
    if magic != _MAGIC:
        raise ConnectionError("bad magic")
    header = _recv_exact(sock, struct.calcsize("!BIIIdI"))
    op, nl, dl, sl, extra, pl = struct.unpack("!BIIIdI", header)
    name = _recv_exact(sock, nl).decode() if nl else ""
    dtype = _recv_exact(sock, dl).decode() if dl else ""
    shape_s = _recv_exact(sock, sl).decode() if sl else ""
    payload = _recv_exact(sock, pl) if pl else b""
    arr = None
    if dtype:
        shape = tuple(int(x) for x in shape_s.split(",")) if shape_s else ()
        arr = np.frombuffer(payload, dtype=dtype).reshape(shape).copy()
    return op, name, arr, extra


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: "KVServer" = self.server.kv  # type: ignore
        sock = self.request
        try:
            while True:
                op, name, arr, extra = _recv_msg(sock)
                if op == OP_PING:
                    _send_msg(sock, OP_PING, "", None)
                elif op == OP_HEARTBEAT:
                    with srv._sync_cv:
                        srv._trainer_seen[int(extra)] = time.time()
                        srv._sync_cv.notify_all()
                    _send_msg(sock, OP_HEARTBEAT, "", None)
                elif op == OP_INIT:
                    with srv._lock:
                        srv._store.setdefault(name, arr.astype(np.float32))
                    _send_msg(sock, OP_INIT, name, None)
                elif op == OP_SET:
                    with srv._lock:
                        srv._store[name] = arr.astype(np.float32)
                    _send_msg(sock, OP_SET, name, None)
                elif op == OP_PULL:
                    with srv._lock:
                        val = srv._store.get(name)
                    _send_msg(sock, OP_PULL, name, val)
                elif op == OP_PUSH_ASYNC:
                    with srv._lock:
                        srv._apply(name, arr, extra)
                    _send_msg(sock, OP_PUSH_ASYNC, name, None)
                elif op == OP_PUSH_DELTA:
                    with srv._lock:
                        if name in srv._store:
                            srv._store[name] = srv._store[name] + \
                                arr.astype(np.float32)
                    _send_msg(sock, OP_PUSH_DELTA, name, None)
                elif op == OP_PULL_ROWS:
                    # sparse table pull: arr = local row ids of this shard
                    try:
                        with srv._lock:
                            tab = srv._store.get(name)
                            rows = (None if tab is None
                                    else tab[arr.astype(np.int64)])
                    except (IndexError, ValueError) as e:
                        # e.g. out-of-range row id: reply a typed error
                        # instead of dying and leaving the client with an
                        # opaque ConnectionError
                        _send_msg(sock, OP_ERROR,
                                  f"pull_rows({name}): {e}", None)
                    else:
                        _send_msg(sock, OP_PULL_ROWS, name, rows)
                elif op == OP_PUSH_ROWS:
                    # two-part message: ids (this one, extra = lr) then
                    # values on the same socket; the table's configured
                    # optimizer applies immediately (Hogwild — reference
                    # async PS sparse-table semantics)
                    vop, _, vals, _ = _recv_msg(sock)
                    ids = arr.astype(np.int64)
                    try:
                        with srv._lock:
                            if vals is not None:
                                srv._apply_sparse_rows(
                                    name, ids, vals.astype(np.float32),
                                    float(extra))
                    except (KeyError, IndexError, ValueError) as e:
                        _send_msg(sock, OP_ERROR, str(e), None)
                    else:
                        _send_msg(sock, OP_PUSH_ROWS, name, None)
                elif op == OP_PUSH_ROWS_SYNC:
                    vop, _, vals, _ = _recv_msg(sock)
                    try:
                        srv._push_rows_sync(
                            name, arr.astype(np.int64),
                            (np.zeros((0, 1), np.float32) if vals is None
                             else vals.astype(np.float32)), float(extra))
                    except (TimeoutError, KeyError, IndexError,
                            ValueError) as e:
                        _send_msg(sock, OP_ERROR, str(e), None)
                    else:
                        _send_msg(sock, OP_PUSH_ROWS_SYNC, name, None)
                elif op == OP_QPUSH:
                    with srv._queue_cv:
                        srv._queues.setdefault(name, []).append(arr)
                        srv._queue_cv.notify_all()
                    _send_msg(sock, OP_QPUSH, name, None)
                elif op == OP_QPOP:
                    # extra is the server-side wait budget; 0 means a
                    # non-blocking try-pop (the client loops short waits
                    # so no single wait approaches its socket timeout)
                    deadline = time.time() + max(0.0, extra)
                    val = None
                    timed_out = False
                    with srv._queue_cv:
                        while True:
                            q = srv._queues.get(name)
                            if q:
                                val = q.pop(0)
                                break
                            if time.time() > deadline:
                                timed_out = True
                                break
                            srv._queue_cv.wait(timeout=0.5)
                    if timed_out:
                        _send_msg(sock, OP_ERROR,
                                  f"queue {name!r}: pop timed out", None)
                    else:
                        _send_msg(sock, OP_QPOP, name, val)
                elif op == OP_CONFIG_SPARSE_OPT:
                    with srv._lock:
                        cfg = arr.astype(np.float64).reshape(-1)
                        # first writer wins, like OP_INIT: a trainer
                        # restarting mid-training must not wipe the
                        # accumulated moments/step counter
                        srv._sparse_opt.setdefault(name, {
                            "type": "adam" if extra >= 0.5 else "sgd",
                            "beta1": float(cfg[0]), "beta2": float(cfg[1]),
                            "epsilon": float(cfg[2]),
                            "m1": None, "m2": None, "step": 0})
                    _send_msg(sock, OP_CONFIG_SPARSE_OPT, name, None)
                elif op == OP_PUSH_SYNC:
                    try:
                        srv._push_sync(name, arr, extra)
                    except TimeoutError as e:
                        _send_msg(sock, OP_ERROR, str(e), None)
                    else:
                        _send_msg(sock, OP_PUSH_SYNC, name, None)
                elif op == OP_BARRIER:
                    try:
                        srv._barrier_wait()
                    except TimeoutError as e:
                        _send_msg(sock, OP_ERROR, str(e), None)
                    else:
                        _send_msg(sock, OP_BARRIER, "", None)
                elif op == OP_SHUTDOWN:
                    _send_msg(sock, OP_SHUTDOWN, "", None)
                    threading.Thread(target=self.server.shutdown,
                                     daemon=True).start()
                    return
        except (ConnectionError, OSError):
            return


class KVServer:
    """listen_and_serv analog: blocking `serve()`, thread-safe store."""

    def __init__(self, endpoint: str, num_trainers: int = 1,
                 sync_timeout: float = 30.0, heartbeat_timeout: float = 10.0):
        host, port = endpoint.rsplit(":", 1)
        self.num_trainers = max(1, num_trainers)
        self.sync_timeout = sync_timeout
        # heart_beat_monitor.h parity: trainers that registered a heartbeat
        # but have gone silent longer than this are treated as dead, so
        # sync pushes / barriers complete over the survivors instead of
        # hanging the whole job
        self.heartbeat_timeout = heartbeat_timeout
        self._trainer_seen: Dict[int, float] = {}
        self._store: Dict[str, np.ndarray] = {}
        self._lock = threading.RLock()
        self._pending: Dict[str, List[np.ndarray]] = {}
        self._push_gen: Dict[str, int] = {}
        # per-table server-resident optimizer state (pslib analog:
        # lookup_sparse_table_fuse_adam keeps Adam moments ON the server)
        self._sparse_opt: Dict[str, dict] = {}
        self._rows_pending: Dict[str, List] = {}
        self._rows_gen: Dict[str, int] = {}
        # named blocking queues: the heter activation relay + the
        # enqueue/dequeue op family (reference
        # operators/collective/c_*queue* + framework BlockingQueue)
        self._queues: Dict[str, List[np.ndarray]] = {}
        self._queue_cv = threading.Condition()
        self._sync_cv = threading.Condition()
        self._barrier_count = 0
        self._barrier_gen = 0
        socketserver.ThreadingTCPServer.allow_reuse_address = True
        self._tcp = socketserver.ThreadingTCPServer(
            (host or "127.0.0.1", int(port)), _Handler)
        # handler threads block in recv on live client connections; never
        # join them on close (clients own the connection lifetime)
        self._tcp.daemon_threads = True
        self._tcp.block_on_close = False
        self._tcp.kv = self  # type: ignore
        self.endpoint = f"{host}:{self._tcp.server_address[1]}"

    # server-side sgd (reference pserver optimizer block)
    def _apply(self, name, grad, lr):
        if name in self._store and grad is not None:
            self._store[name] = self._store[name] - \
                float(lr) * grad.astype(np.float32)

    def _apply_sparse_rows(self, name, ids, vals, lr):
        """Apply row gradients with the table's configured optimizer.

        Caller holds `_lock`.  Duplicate ids are merged (summed) first —
        required for Adam, whose moments must update once per row per
        step.  sgd: `row -= lr * g`.  adam: the reference
        lookup_sparse_table_fuse_adam_op.cc:145 recipe — server-resident
        per-row moments, GLOBAL beta-power schedule
        (lr' = lr * sqrt(1 - b2^t) / (1 - b1^t))."""
        if ids.size == 0:
            return
        tab = self._store.get(name)
        if tab is None:
            raise KeyError(
                f"sparse table {name!r} not on this server — push dropped")
        if ids.max(initial=0) >= tab.shape[0] or ids.min(initial=0) < 0:
            raise IndexError(
                f"push_rows({name}): row id out of range 0..{tab.shape[0]}")
        uids, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((uids.size,) + vals.shape[1:], np.float32)
        np.add.at(merged, inv, vals)
        # copy-on-write: OP_PULL sends store refs outside the lock
        tab = tab.copy()
        cfg = self._sparse_opt.get(name)
        if cfg is None or cfg["type"] == "sgd":
            tab[uids] -= float(lr) * merged
        else:
            if cfg["m1"] is None:
                cfg["m1"] = np.zeros_like(tab)
                cfg["m2"] = np.zeros_like(tab)
            b1, b2, eps = cfg["beta1"], cfg["beta2"], cfg["epsilon"]
            cfg["step"] += 1
            t = cfg["step"]
            m1 = cfg["m1"][uids] * b1 + (1.0 - b1) * merged
            m2 = cfg["m2"][uids] * b2 + (1.0 - b2) * merged * merged
            cfg["m1"][uids] = m1
            cfg["m2"][uids] = m2
            lr_t = float(lr) * np.sqrt(1.0 - b2 ** t) / (1.0 - b1 ** t)
            tab[uids] -= lr_t * m1 / (np.sqrt(m2) + eps)
        self._store[name] = tab

    def _push_rows_sync(self, name, ids, vals, lr):
        """Sync-mode sparse push: accumulate every live trainer's
        (ids, vals), then apply the AVERAGED merged rows once — the
        dense _push_sync discipline moved server-side, so correctness no
        longer depends on clients passing grad_scale=1/N.  Clients must
        push to every shard each step (empty ids allowed) so the fanin
        count completes."""
        def apply(batch):
            # empty contributions count toward the fanin but may carry
            # degenerate value shapes — drop them here
            nonempty = [(i, v) for i, v in batch if i.size]
            if nonempty:
                all_ids = np.concatenate([i for i, _ in nonempty])
                all_vals = np.concatenate([v for _, v in nonempty])
            else:
                all_ids = np.zeros((0,), np.int64)
                all_vals = np.zeros((0, 1), np.float32)
            with self._lock:
                self._apply_sparse_rows(
                    name, all_ids, all_vals / max(1, len(batch)), lr)

        self._sync_fanin(self._rows_pending, self._rows_gen, name,
                         (ids, vals), apply, "sync sparse push")

    def _effective_trainers(self) -> int:
        """Fanin for sync rounds: only trainers that REGISTERED a heartbeat
        and then went silent count as dead — a trainer that hasn't
        connected yet (startup staggering) is presumed alive, otherwise
        the first booter would complete rounds alone and break sync-SGD
        semantics (heart_beat_monitor.h counts the same way)."""
        now = time.time()
        dead = sum(1 for t in self._trainer_seen.values()
                   if now - t >= self.heartbeat_timeout)
        return max(1, self.num_trainers - dead)

    def _sync_fanin(self, pending, gens, name, mine, apply_fn, what):
        """Shared accumulate-until-every-live-trainer discipline: append
        `mine` to pending[name]; the completing waiter pops the batch,
        runs apply_fn(batch) and bumps the generation.  Per-name
        generation counter avoids the wake-after-next-round race; the
        fanin re-evaluates each second so a trainer dying mid-round
        shrinks the barrier instead of hanging everyone; on timeout the
        waiter WITHDRAWS its own contribution (by identity) so the next
        round's mean does not mix in a stale gradient."""
        deadline = time.time() + self.sync_timeout
        with self._sync_cv:
            pending.setdefault(name, []).append(mine)
            my_gen = gens.get(name, 0)
            while True:
                # completion checks FIRST so a round landing right at the
                # deadline is reported as success, not TimeoutError
                if gens.get(name, 0) != my_gen:
                    return  # a round (including this grad) was applied
                pend = pending.get(name, [])
                if len(pend) >= self._effective_trainers():
                    batch = pending.pop(name)
                    apply_fn(batch)
                    gens[name] = my_gen + 1
                    self._sync_cv.notify_all()
                    return
                if time.time() > deadline:
                    pend = pending.get(name)
                    if pend is not None:
                        for i, item in enumerate(pend):
                            if item is mine:
                                del pend[i]
                                break
                        if not pend:
                            pending.pop(name, None)
                    raise TimeoutError(
                        f"{what} of {name!r}: not all "
                        f"{self.num_trainers} trainers arrived")
                self._sync_cv.wait(timeout=1.0)

    def _push_sync(self, name, grad, lr):
        """Apply the mean once every LIVE trainer's push has arrived."""
        def apply(batch):
            with self._lock:
                self._apply(name, np.mean(batch, axis=0), lr)

        self._sync_fanin(self._pending, self._push_gen, name, grad,
                         apply, "sync push")

    def _barrier_wait(self):
        deadline = time.time() + 60
        with self._sync_cv:
            self._barrier_count += 1
            gen = self._barrier_gen
            while True:
                if gen != self._barrier_gen:
                    return  # released (checked before the deadline raise)
                if self._barrier_count >= self._effective_trainers():
                    self._barrier_count = 0
                    self._barrier_gen += 1
                    self._sync_cv.notify_all()
                    return
                if time.time() > deadline:
                    # withdraw this waiter so a later barrier attempt
                    # doesn't release early on the leaked count
                    self._barrier_count -= 1
                    raise TimeoutError("barrier timeout")
                self._sync_cv.wait(timeout=1.0)

    def serve(self):
        self._tcp.serve_forever(poll_interval=0.1)

    def serve_in_thread(self) -> threading.Thread:
        t = threading.Thread(target=self.serve, daemon=True)
        t.start()
        return t

    def stop(self):
        self._tcp.shutdown()
        self._tcp.server_close()

    def get(self, name):
        with self._lock:
            return self._store.get(name)


class KVClient:
    """RPCClient analog: one socket per pserver, vars sharded round-robin
    by name hash (DistributeTranspiler round-robin param placement,
    transpiler/distribute_transpiler.py:80 VarBlock).

    Transport failures retry with bounded exponential backoff inside an
    rpc_deadline budget (FLAGS_rpc_deadline parity,
    /root/reference/paddle/fluid/operators/distributed/grpc/grpc_client.h:211
    — deadline + error callbacks); each retry drops the cached socket and
    reconnects, so a pserver restart is survived transparently.  Push-type
    ops are at-least-once under retry (a push that was applied just before
    the connection died may re-apply), matching the reference's async RPC
    semantics."""

    def __init__(self, endpoints: List[str], sock_timeout: float = 60.0,
                 rpc_deadline: Optional[float] = None,
                 max_retries: int = 8):
        self.endpoints = list(endpoints)
        self.sock_timeout = sock_timeout
        if rpc_deadline is None:
            try:
                from ...core.flags import get_flags
                rpc_deadline = float(
                    get_flags("rpc_deadline")["rpc_deadline"]) / 1000.0
            except Exception:
                rpc_deadline = 180.0
        self.rpc_deadline = rpc_deadline
        self.max_retries = max_retries
        self._socks: Dict[str, socket.socket] = {}
        self._hb_stop: Optional[threading.Event] = None

    def _sock(self, ep) -> socket.socket:
        s = self._socks.get(ep)
        if s is None:
            host, port = ep.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.sock_timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[ep] = s
        return s

    def _ep_for(self, name: str) -> str:
        # stable across processes (builtin hash() is seed-randomized and
        # would shard the same param to different servers per process)
        import zlib
        return self.endpoints[zlib.crc32(name.encode())
                              % len(self.endpoints)]

    def _with_retry(self, ep, fn, idempotent=True, deadline=None,
                    max_retries=None):
        """Run fn(sock) against ep, reconnecting with exponential backoff
        on transport errors until rpc_deadline/max_retries runs out.

        idempotent=False (OP_PUSH_SYNC, OP_BARRIER — ops the server
        COUNTS): once the request hit the wire a retry could double-count
        this trainer in the sync fanin, so only failures raised before
        the send (connection establishment) are retried; a mid-flight
        failure propagates to the caller instead of corrupting the
        round's average."""
        deadline = time.time() + (self.rpc_deadline if deadline is None
                                  else deadline)
        retries = self.max_retries if max_retries is None else max_retries
        delay = 0.05
        last: Exception = ConnectionError("no attempt made")
        for attempt in range(retries):
            sent = False
            try:
                s = self._sock(ep)

                def guard_send(*a, **kw):
                    nonlocal sent
                    sent = True
                    return _send_msg(*a, **kw)

                return fn(s, guard_send)
            except (ConnectionError, OSError, socket.timeout) as e:
                last = e
                # the socket is in an unknown state: drop and reconnect
                s = self._socks.pop(ep, None)
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
                if sent and not idempotent:
                    raise ConnectionError(
                        f"non-idempotent rpc to {ep} failed mid-flight "
                        f"(not retried to avoid double-apply): {e}") from e
                now = time.time()
                if now >= deadline or attempt == retries - 1:
                    break
                time.sleep(min(delay, max(deadline - now, 0.0)))
                delay = min(delay * 2, 5.0)
        raise ConnectionError(
            f"rpc to pserver {ep} failed after {retries} "
            f"attempts / {self.rpc_deadline:.0f}s deadline: {last}")

    # ops where a post-send retry could double-count on the server
    # (queue ops: a retried push double-enqueues, a retried pop after a
    # server-side success drops an element)
    _NON_IDEMPOTENT = (OP_PUSH_SYNC, OP_BARRIER, OP_PUSH_ROWS_SYNC,
                       OP_QPUSH, OP_QPOP)

    def _call(self, ep, op, name="", arr=None, extra=0.0, deadline=None,
              max_retries=None):
        def roundtrip(s, send):
            send(s, op, name, arr, extra)
            return _recv_msg(s)

        rop, rname, rarr, rextra = self._with_retry(
            ep, roundtrip, idempotent=op not in self._NON_IDEMPOTENT,
            deadline=deadline, max_retries=max_retries)
        if rop == OP_ERROR:
            raise TimeoutError(rname)
        return rop, rname, rarr, rextra

    def wait_server_ready(self, timeout=60):
        """rpc wait_server_ready parity: ping until every server answers.
        Each ping gets a SHORT single-attempt budget so the outer
        `timeout` stays authoritative (the general rpc_deadline retry
        loop would otherwise stretch one dead endpoint to ~3x it)."""
        deadline = time.time() + timeout
        for ep in self.endpoints:
            while True:
                try:
                    self._call(ep, OP_PING, deadline=1.0, max_retries=1)
                    break
                except (ConnectionError, OSError):
                    self._socks.pop(ep, None)
                    if time.time() > deadline:
                        raise TimeoutError(f"pserver {ep} not ready")
                    time.sleep(0.2)

    def init_param(self, name, value):
        self._call(self._ep_for(name), OP_INIT, name, np.asarray(value))

    def set_param(self, name, value):
        self._call(self._ep_for(name), OP_SET, name, np.asarray(value))

    def pull(self, name) -> np.ndarray:
        _, _, arr, _ = self._call(self._ep_for(name), OP_PULL, name)
        if arr is None:
            raise KeyError(f"param {name!r} not on server")
        return arr

    def push_grad(self, name, grad, lr, sync=True):
        op = OP_PUSH_SYNC if sync else OP_PUSH_ASYNC
        self._call(self._ep_for(name), op, name, np.asarray(grad),
                   float(lr))

    def push_delta(self, name, delta):
        self._call(self._ep_for(name), OP_PUSH_DELTA, name,
                   np.asarray(delta))

    # -- sparse (row-sharded) tables ---------------------------------------
    # Row r of a distributed table lives on pserver (r % n_eps) at local
    # row (r // n_eps) — the reference's block-partitioned
    # distributed_lookup_table (distributed_lookup_table_op.cc), with
    # modulo placement instead of contiguous blocks so shards stay
    # balanced under skewed id distributions.
    def init_sparse_table(self, name, value):
        """Split [V, D] rows across pservers (first writer wins)."""
        value = np.asarray(value)
        n = len(self.endpoints)
        for e, ep in enumerate(self.endpoints):
            self._call(ep, OP_INIT, name, value[e::n])

    def pull_sparse(self, name, ids) -> np.ndarray:
        """Gather rows `ids` (global) from the sharded table."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        n = len(self.endpoints)
        out = None
        for e, ep in enumerate(self.endpoints):
            mask = (ids % n) == e
            if not mask.any():
                continue
            local = ids[mask] // n
            _, _, rows, _ = self._call(ep, OP_PULL_ROWS, name, local)
            if rows is None:
                raise KeyError(
                    f"sparse table {name!r} shard {e} not on {ep}")
            if out is None:
                out = np.zeros((ids.size,) + rows.shape[1:], rows.dtype)
            out[mask] = rows
        if out is None:  # ids empty
            raise ValueError("pull_sparse with no ids")
        return out

    def config_sparse_optimizer(self, name, optimizer="adam", beta1=0.9,
                                beta2=0.999, epsilon=1e-8):
        """Install a server-resident optimizer on every shard of `name`
        (pslib analog: lookup_sparse_table_fuse_adam keeps per-row Adam
        moments ON the pserver, fleet_wrapper.h:66 pull/push contract)."""
        if optimizer not in ("sgd", "adam"):
            raise ValueError(f"sparse optimizer {optimizer!r}: sgd|adam")
        cfg = np.array([beta1, beta2, epsilon], np.float64)
        for ep in self.endpoints:
            self._call(ep, OP_CONFIG_SPARSE_OPT, name, cfg,
                       extra=1.0 if optimizer == "adam" else 0.0)

    def push_sparse(self, name, ids, grads, lr, grad_scale=1.0,
                    sync=False):
        """Scatter row grads back; the server applies its configured
        optimizer (sgd default, adam via config_sparse_optimizer).

        sync=True: the server accumulates until every live trainer's push
        arrives and applies the AVERAGE once — grad_scale is ignored and
        an empty push still goes to every shard so the fanin completes.
        grad_scale remains for the legacy async protocol only (callers
        that pre-scale their Hogwild pushes)."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        grads = np.asarray(grads)
        n = len(self.endpoints)
        op = OP_PUSH_ROWS_SYNC if sync else OP_PUSH_ROWS
        eff_lr = float(lr) * (1.0 if sync else float(grad_scale))
        for e, ep in enumerate(self.endpoints):
            mask = (ids % n) == e
            if not sync and not mask.any():
                continue
            local = ids[mask] // n
            vals = grads[mask] if grads.size else \
                np.zeros((0,) + grads.shape[1:], np.float32)

            def roundtrip(s, send, local=local, vals=vals):
                send(s, op, name, local, eff_lr)
                send(s, op, name, vals)
                return _recv_msg(s)

            rop, rname, _, _ = self._with_retry(
                ep, roundtrip, idempotent=op not in self._NON_IDEMPOTENT)
            if rop == OP_ERROR:
                raise TimeoutError(rname)

    # -- named blocking queues (heter relay / enqueue-dequeue ops) ---------
    def q_push(self, name, value):
        """Push onto the named server queue (queue lives on the shard
        `name` hashes to, so all parties agree without coordination)."""
        self._call(self._ep_for(name), OP_QPUSH, name, np.asarray(value))

    def q_pop(self, name, timeout=60.0) -> np.ndarray:
        """Blocking pop; raises TimeoutError if nothing arrives within
        `timeout` (0 = non-blocking try-pop).

        The wait is a client-side loop of SHORT server-side waits, each
        far below sock_timeout: a single long server wait would race the
        socket timeout, and an element popped just after the client gave
        up would be written to a discarded socket — lost, leaving the
        relay off by one forever."""
        deadline = time.time() + float(timeout)
        chunk = max(1.0, min(10.0, self.sock_timeout / 4))
        while True:
            wait = min(chunk, max(deadline - time.time(), 0.0))
            try:
                _, _, arr, _ = self._call(self._ep_for(name), OP_QPOP,
                                          name, extra=wait)
                return arr
            except TimeoutError:
                if time.time() >= deadline:
                    raise TimeoutError(f"queue {name!r}: pop timed out")

    def barrier(self):
        for ep in self.endpoints:
            self._call(ep, OP_BARRIER)

    # -- trainer liveness (heart_beat_monitor.h parity) --------------------
    def start_heartbeat(self, trainer_id: int,
                        interval: float = 2.0) -> threading.Event:
        """Background thread pinging every pserver with this trainer's id;
        the server drops silent trainers from sync fanins.  Uses its own
        sockets (the client's aren't thread-safe).  Returns the stop
        Event (also stopped by close())."""
        if self._hb_stop is not None:
            return self._hb_stop
        stop = threading.Event()
        endpoints = list(self.endpoints)

        def loop():
            # short socket timeout: one hung pserver must not stall the
            # heartbeats to the healthy ones past heartbeat_timeout (which
            # would mark THIS live trainer dead on those servers)
            hb = KVClient(endpoints, sock_timeout=min(2.0, interval))
            try:
                while not stop.is_set():
                    for ep in endpoints:
                        try:
                            hb._call(ep, OP_HEARTBEAT,
                                     extra=float(trainer_id))
                        except (ConnectionError, OSError):
                            hb._socks.pop(ep, None)
                    stop.wait(interval)
            finally:
                hb.close()

        threading.Thread(target=loop, daemon=True).start()
        self._hb_stop = stop
        return stop

    def stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_stop = None

    def shutdown_servers(self):
        for ep in list(self._socks) or self.endpoints:
            try:
                self._call(ep, OP_SHUTDOWN)
            except (ConnectionError, OSError):
                pass

    def close(self):
        self.stop_heartbeat()
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()

"""Parameter-server capability tier (reference: C9 operators/distributed RPC
runtime + P15 fleet PS transpilers, SURVEY.md §2).  TPU deployment note:
collective (mesh) training is the primary path; the PS tier serves the
sparse-embedding / CPU-worker capability."""

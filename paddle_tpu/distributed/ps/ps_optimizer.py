"""Parameter-server training: transpiler + fleet meta-optimizer + runtime.

Reference: /root/reference/python/paddle/fluid/transpiler/
distribute_transpiler.py:256 `DistributeTranspiler` (splits a Program into
trainer/pserver/startup programs; grads sent, params pulled),
fluid/incubate/fleet/parameter_server, and the communicator modes
(operators/distributed/communicator.h:183-401 — Sync / HalfAsync(Async) /
Geo).

TPU-native redesign: the trainer's fwd+bwd stays ONE jitted XLA computation
(grads come back as fetches); the RPC plane is the host-side KV service
(kv_server.py).  Modes:
  * sync  — push grads (server applies mean once all trainers arrive), pull
  * async — push grads applied immediately (Hogwild), pull
  * geo   — train locally with the real optimizer; every k steps push the
            param delta since last sync and pull the merged value
            (GeoCommunicator, communicator.h geo-SGD)
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...core.program import Program, OpRole
from ..fleet.meta_optimizers.meta_optimizer_base import MetaOptimizerBase

__all__ = ["ParameterServerOptimizer", "DistributeTranspiler",
           "DistributeTranspilerConfig", "PSCompiledProgram"]


class DistributeTranspilerConfig:
    """transpiler config parity (slice_var_up etc. accepted, unused)."""

    def __init__(self):
        self.slice_var_up = True
        self.split_method = None
        self.min_block_size = 8192
        self.sync_mode = True
        self.geo_sgd_mode = False
        self.geo_sgd_need_push_nums = 100
        # True → get_trainer_program returns a plain Program carrying
        # send/fetch_barrier/recv GRAPH OPS (reference transpiler shape,
        # distribute_transpiler.py:256); False → the runtime-managed
        # PSCompiledProgram push/pull path
        self.use_graph_ops = False
        # heter mode (heter_wrapper.h analog): ONLY the distributed
        # sparse tables go to the PS; dense params keep their LOCAL
        # optimizer ops (the device section trains them) — the program is
        # then split at boundary activations by
        # distributed/heter.split_heter_program
        self.heter_mode = False


def _strip_optimizer_ops(program: Program) -> Program:
    """Trainer side keeps fwd+bwd only (transpiler removes opt ops and
    replaces them with send/recv — here the runtime does push/pull)."""
    block = program.global_block()
    block.ops = [op for op in block.ops
                 if not (op.op_role & OpRole.Optimize
                         or op.op_role == OpRole.LRSched)]
    program._fingerprint_cache = None
    return program


def _strip_table_optimizer_ops(program: Program, tables) -> Program:
    """Heter mode: remove ONLY the optimizer ops updating distributed
    tables (the PS applies those server-side); dense optimizer ops stay
    with the device section."""
    block = program.global_block()
    block.ops = [op for op in block.ops
                 if not ((op.op_role & OpRole.Optimize)
                         and op.inputs.get("Param", [None])[0] in tables)]
    program._fingerprint_cache = None
    return program


class PSCompiledProgram:
    """Runnable PS trainer program (pass to exe.run).

    fwd+bwd runs jitted; each step: push grads → pull params → scope.
    geo mode: full local program runs (with optimizer); every k steps the
    param delta is pushed and the merged value pulled.
    """

    def __init__(self, program: Program, params_grads, mode: str = "sync",
                 lr: float = 0.01, geo_k: int = 100, endpoints=None,
                 trainer_id: int = 0):
        self._program = program
        self._params = [p.name for p, _ in params_grads]
        self._grads = {p.name: g.name for p, g in params_grads}
        self._mode = mode
        self._lr = lr
        self._geo_k = geo_k
        self._endpoints = endpoints
        self._trainer_id = trainer_id
        self._client = None
        self._inited = False
        self._step = 0
        self._last_sync: Dict[str, np.ndarray] = {}

    def _get_client(self):
        if self._client is None:
            from .kv_server import KVClient
            from ..parallel_env import ParallelEnv
            import os
            eps = self._endpoints or [
                e for e in os.environ.get(
                    "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
            if not eps:
                raise RuntimeError("no pserver endpoints for PS training")
            self._client = KVClient(eps)
            self._client.wait_server_ready()
            # liveness registration: if this trainer dies, the servers
            # shrink sync fanins instead of stalling the others
            self._client.start_heartbeat(self._trainer_id)
        return self._client

    def _init_params(self, scope):
        client = self._get_client()
        for p in self._params:
            v = scope.get(p)
            if v is not None:
                client.init_param(p, np.asarray(v))  # first writer wins
        for p in self._params:
            val = client.pull(p)
            scope.set(p, val)
            self._last_sync[p] = val.copy()
        self._inited = True

    def _run(self, executor, feed, fetch_list, scope, return_numpy):
        from ...static.executor import global_scope
        scope = scope or global_scope()
        if not self._inited:
            self._init_params(scope)
        client = self._client
        fetch_list = list(fetch_list or [])

        if self._mode == "geo":
            # local step with the real optimizer; periodic delta sync
            res = executor.run(self._program, feed=feed,
                               fetch_list=fetch_list, scope=scope,
                               return_numpy=return_numpy)
            self._step += 1
            if self._step % self._geo_k == 0:
                for p in self._params:
                    cur = np.asarray(scope.get(p))
                    client.push_delta(p, cur - self._last_sync[p])
                    merged = client.pull(p)
                    scope.set(p, merged)
                    self._last_sync[p] = merged.copy()
            return res

        # sync/async: fetch grads out of the jitted fwd+bwd step
        grad_names = [self._grads[p] for p in self._params]
        all_res = executor.run(self._program, feed=feed,
                               fetch_list=fetch_list + grad_names,
                               scope=scope, return_numpy=True)
        user_res = all_res[: len(fetch_list)]
        if not return_numpy:
            import jax.numpy as jnp
            user_res = [jnp.asarray(r) for r in user_res]
        grads = all_res[len(fetch_list):]
        lr = self._current_lr(scope)
        for p, g in zip(self._params, grads):
            client.push_grad(p, g, lr, sync=(self._mode == "sync"))
        for p in self._params:
            scope.set(p, client.pull(p))
        self._step += 1
        return user_res

    def _current_lr(self, scope):
        for name in scope.keys():
            if name.startswith("learning_rate"):
                try:
                    return float(np.asarray(scope.get(name)).reshape(()))
                except (TypeError, ValueError):
                    pass
        return self._lr


class DistributeTranspiler:
    """fluid.transpiler.DistributeTranspiler API parity."""

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_program = None
        self._pserver_endpoint = None
        self._startup = None

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  startup_program=None, current_endpoint=""):
        from ...core.program import default_main_program, \
            default_startup_program
        self._program = program or default_main_program()
        self._startup = startup_program or default_startup_program()
        self._pservers = [e for e in pservers.split(",") if e]
        self._trainers = trainers
        self._trainer_id = trainer_id
        self._current_endpoint = current_endpoint
        if self.config.use_graph_ops and self.config.geo_sgd_mode:
            raise ValueError(
                "use_graph_ops does not support geo_sgd_mode (geo's "
                "every-k-steps delta push is runtime-managed; use the "
                "PSCompiledProgram path)")
        if self.config.use_graph_ops:
            # rewrite the startup program NOW (reference transpiler shape:
            # startup carries init send → barrier → recv so exe.run(startup)
            # works no matter when get_trainer_program() is called)
            pgs = getattr(self._program, "_ps_params_grads", None)
            if pgs is None:
                raise RuntimeError(
                    "transpile() requires a program minimized by an "
                    "optimizer (params_grads recorded)")
            self._rewrite_startup_with_graph_ops(pgs)

    def get_trainer_program(self, wait_port=True):
        pgs = getattr(self._program, "_ps_params_grads", None)
        if pgs is None:
            raise RuntimeError(
                "transpile() requires a program minimized by an optimizer "
                "(params_grads recorded)")
        if self.config.heter_mode and not self.config.use_graph_ops:
            raise ValueError(
                "heter_mode requires use_graph_ops=True — the runtime "
                "PSCompiledProgram path would silently train a non-heter "
                "dense-PS topology")
        if self.config.use_graph_ops and not self.config.geo_sgd_mode:
            return self._transpile_with_graph_ops(pgs)
        if self._distributed_tables(self._program):
            raise ValueError(
                "is_distributed embedding tables need the graph-op "
                "transpiler (DistributeTranspilerConfig.use_graph_ops = "
                "True); the runtime-managed PSCompiledProgram path would "
                "replicate and dense-sync the whole table")
        if self.config.geo_sgd_mode:
            mode = "geo"
            prog = self._program  # geo keeps local optimizer ops
        else:
            mode = "sync" if self.config.sync_mode else "async"
            prog = _strip_optimizer_ops(self._program.clone())
        return PSCompiledProgram(
            prog, pgs, mode=mode,
            geo_k=self.config.geo_sgd_need_push_nums,
            endpoints=self._pservers, trainer_id=self._trainer_id)

    _LOOKUP_TYPES = ("lookup_table", "lookup_table_v2", "embedding")

    def _sparse_opt_config(self, param_name):
        """Server-resident optimizer for a distributed table, read off the
        optimizer op that consumed its grad before stripping (pslib
        analog: the pserver runs lookup_sparse_table_fuse_{adam,sgd}, so
        Adam moment state lives ON the server,
        lookup_sparse_table_fuse_adam_op.cc:145)."""
        for op in self._program.global_block().ops:
            if not (op.op_role & OpRole.Optimize):
                continue
            if op.inputs.get("Param", [None])[0] != param_name:
                continue
            if op.type == "adam":
                return {"type": "adam",
                        "beta1": float(op.attrs.get("beta1", 0.9)),
                        "beta2": float(op.attrs.get("beta2", 0.999)),
                        "epsilon": float(op.attrs.get("epsilon", 1e-8))}
            if op.type in ("adamw", "lamb"):
                # adamw's decoupled decay / lamb's trust ratio have no
                # server-side implementation — silently training the
                # table under a DIFFERENT optimizer than configured is
                # worse than failing here
                raise ValueError(
                    f"distributed table {param_name!r} is optimized by "
                    f"{op.type!r}, but the server-resident sparse "
                    "optimizer supports sgd|adam — use Adam/SGD for the "
                    "table or drop is_distributed")
            return {"type": "sgd"}
        return {"type": "sgd"}

    def _distributed_tables(self, program) -> set:
        """Tables marked is_distributed on their lookup ops — these shard
        row-wise across pservers instead of replicating."""
        tables = set()
        for op in program.global_block().ops:
            if op.type in self._LOOKUP_TYPES and \
                    op.attrs.get("is_distributed"):
                if not op.attrs.get("is_sparse"):
                    raise ValueError(
                        "distributed embedding tables need "
                        "is_sparse=True (the SelectedRows gradient is "
                        "what gets pushed row-wise)")
                tables.add(op.inputs["W"][0])
        return tables

    def _transpile_with_graph_ops(self, params_grads) -> Program:
        """Reference transpiler shape (distribute_transpiler.py:256): the
        returned trainer Program itself carries `send` (grads out) →
        `fetch_barrier` → `recv` (params in) ops; exe.run of the program IS
        the PS step.  Startup gets a mode="init" send pushing initial
        params to the server (pserver-side startup analog).

        Distributed (row-sharded) embedding tables take the sparse path:
        their forward lookups become `distributed_lookup_table` (pull only
        the touched rows), their SelectedRows grads go out through a
        sparse `send` (server-side row SGD), and they are EXCLUDED from
        the dense send/recv round — the [V, D] table never crosses the
        wire whole (reference distributed_lookup_table_op.cc)."""
        # read the exact lr var off the optimizer ops before stripping them
        lr_var = next(
            (op.inputs["LearningRate"][0]
             for op in self._program.global_block().ops
             if (op.op_role & OpRole.Optimize) and
             op.inputs.get("LearningRate")), None)
        dist_tables = self._distributed_tables(self._program)
        if self.config.heter_mode:
            # dense params train locally in the device section; only the
            # table's optimizer moves server-side
            prog = _strip_table_optimizer_ops(self._program.clone(),
                                              dist_tables)
        else:
            prog = _strip_optimizer_ops(self._program.clone())
        block = prog.global_block()
        for op in block.ops:
            if op.type in self._LOOKUP_TYPES and \
                    op.inputs.get("W", [None])[0] in dist_tables:
                op.type = "distributed_lookup_table"
                op.attrs.update({
                    "table_name": op.inputs["W"][0],
                    "endpoints": list(self._pservers),
                    "trainer_id": self._trainer_id})
        param_names = [p.name for p, _ in params_grads
                       if p.name not in dist_tables]
        grad_names = [g.name for p, g in params_grads
                      if p.name not in dist_tables]
        sparse_pgs = [(p, g) for p, g in params_grads
                      if p.name in dist_tables]
        mode = "grad_sync" if self.config.sync_mode else "grad_async"
        if lr_var is not None and not block.has_var(lr_var):
            lr_var = None
        if lr_var is None:
            lr_var = next((v.name for v in block.vars.values()
                           if v.persistable and
                           v.name.startswith("learning_rate")), None)
        for p, g in sparse_pgs:
            send_ins = {"X": [g.name]}
            if lr_var:
                send_ins["LearningRate"] = [lr_var]
            dummy = block.create_var(shape=[1], dtype="float32")
            block.append_op(
                "send", send_ins, {"Dummy": [dummy.name]},
                {"send_varnames": [p.name],
                 "endpoints": list(self._pservers),
                 "mode": "sparse_grad", "trainer_id": self._trainer_id,
                 # sync mode: the SERVER accumulates every live trainer's
                 # rows and applies the average once (OP_PUSH_ROWS_SYNC)
                 # — averaging no longer trusts client-side grad_scale
                 "sync": bool(self.config.sync_mode),
                 OpRole.KEY: OpRole.RPC})
        if param_names and not self.config.heter_mode:
            self._append_ps_graph_ops(block, block, grad_names,
                                      param_names, mode, lr_var=lr_var)
        return prog

    def _append_ps_graph_ops(self, block, shape_block, x_names, param_names,
                             mode, lr_var=None):
        """Append the send → fetch_barrier → recv triple (one wire-attr
        construction shared by the per-step and startup rewrites)."""
        send_ins = {"X": x_names}
        if lr_var:
            send_ins["LearningRate"] = [lr_var]
        dummy = block.create_var(shape=[1], dtype="float32")
        block.append_op("send", send_ins, {"Dummy": [dummy.name]},
                        {"send_varnames": param_names,
                         "endpoints": list(self._pservers),
                         "mode": mode, "trainer_id": self._trainer_id,
                         OpRole.KEY: OpRole.RPC})
        block.append_op("fetch_barrier", {"X": [dummy.name]}, {},
                        {"endpoints": list(self._pservers),
                         OpRole.KEY: OpRole.RPC})
        block.append_op(
            "recv", {"Dummy": [dummy.name]}, {"Out": param_names},
            {"recv_varnames": param_names,
             "endpoints": list(self._pservers),
             "trainer_id": self._trainer_id,
             "shapes": [list(shape_block.var(n).shape)
                        for n in param_names],
             "dtypes": [shape_block.var(n).dtype for n in param_names],
             OpRole.KEY: OpRole.RPC})

    def _rewrite_startup_with_graph_ops(self, params_grads):
        """Startup push of locally-initialized params (first writer wins)
        followed by a pull of the winning values so every trainer starts
        identical (reference distribute_transpiler startup rewrite);
        guarded so repeated transpile() calls don't stack duplicate ops."""
        if getattr(self._startup, "_ps_startup_transpiled", False):
            return
        mb = self._program.global_block()
        dist_tables = self._distributed_tables(self._program)
        param_names = [p.name for p, _ in params_grads
                       if p.name not in dist_tables]
        sparse_names = [p.name for p, _ in params_grads
                        if p.name in dist_tables]
        sb = self._startup.global_block()
        for n in param_names + sparse_names:
            if not sb.has_var(n):
                sb.create_var(n, mb.var(n).shape, mb.var(n).dtype,
                              persistable=True)
        for n in sparse_names:
            # row-shard the locally initialized table across pservers
            # (first writer wins, like the dense init)
            dummy = sb.create_var(shape=[1], dtype="float32")
            sb.append_op(
                "send", {"X": [n]}, {"Dummy": [dummy.name]},
                {"send_varnames": [n], "endpoints": list(self._pservers),
                 "mode": "init_sparse", "trainer_id": self._trainer_id,
                 "sparse_opt": self._sparse_opt_config(n),
                 OpRole.KEY: OpRole.RPC})
        if param_names and not self.config.heter_mode:
            self._append_ps_graph_ops(sb, mb, param_names, param_names,
                                      "init")
        self._startup._ps_startup_transpiled = True

    def get_pserver_program(self, endpoint) -> Program:
        """A marker program whose execution serves the KV store
        (listen_and_serv semantics)."""
        p = Program()
        p.global_block().append_op(
            "listen_and_serv", {}, {},
            {"endpoint": endpoint, "Fanin": self._trainers,
             OpRole.KEY: OpRole.RPC})
        p._ps_server_config = {"endpoint": endpoint,
                               "num_trainers": self._trainers}
        return p

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), Program()

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        return startup_program or self._startup


class ParameterServerOptimizer(MetaOptimizerBase):
    """fleet PS meta-optimizer (incubate/fleet/parameter_server analog):
    minimize → record params_grads, strip opt ops (sync/async) or keep them
    (geo), produce a PSCompiledProgram as fleet.main_program."""

    def _can_apply(self):
        return not getattr(self.user_defined_strategy, "_is_collective",
                           False)

    def minimize_impl(self, loss, startup_program=None, parameter_list=None,
                      no_grad_set=None):
        ops, params_grads = self.inner_opt.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        program = loss.block.program
        program._ps_params_grads = params_grads
        s = self.user_defined_strategy
        a_sync = bool(s.a_sync)
        k = s.a_sync_configs.get("k_steps", -1)
        if a_sync and k > 0:
            mode = "geo"
            prog = program  # local optimizer kept
        elif a_sync:
            mode = "async"
            prog = _strip_optimizer_ops(program.clone())
        else:
            mode = "sync"
            prog = _strip_optimizer_ops(program.clone())
        geo_k = max(1, k) if k > 0 else 100
        compiled = PSCompiledProgram(
            prog, params_grads, mode=mode, geo_k=geo_k,
            trainer_id=self.role_maker.worker_index()
            if self.role_maker else 0)
        program._compiled_for_fleet = compiled
        return ops, params_grads

"""PS runtime front-door used by fleet.init_worker/init_server/run_server.

Reference: the fleet PS runtime (pslib / the_one_ps in later paddle;
here the 1.x capability surface: RoleMaker-driven server start + worker
communicator init, operators/distributed/communicator.h:183-401).

The actual KV server/client live in kv_server.py (TCP, msgpack-free binary
protocol) — see that module; this adapter binds them to the Fleet object.
"""
from __future__ import annotations

__all__ = ["ps_runtime", "PSRuntime"]


class PSRuntime:
    def __init__(self):
        self._server = None
        self._client = None

    # fleet.init_worker()
    def init_worker(self, fleet):
        from .kv_server import KVClient
        eps = fleet.server_endpoints()
        if not eps:
            raise RuntimeError("no pserver endpoints configured "
                               "(PADDLE_PSERVERS_IP_PORT_LIST)")
        self._client = KVClient(eps)
        self._client.wait_server_ready()
        fleet._ps_client = self._client

    # fleet.init_server() / run_server()
    def init_server(self, fleet, *args, **kwargs):
        from .kv_server import KVServer
        idx = fleet.server_index()
        ep = fleet.server_endpoints()[idx]
        self._server = KVServer(ep, num_trainers=fleet.worker_num())
        fleet._ps_server = self._server

    def run_server(self, fleet):
        if self._server is None:
            self.init_server(fleet)
        self._server.serve()  # blocks (listen_and_serv semantics)

    def stop_worker(self, fleet):
        if self._client is not None:
            self._client.shutdown_servers()
            self._client.close()


_runtime = PSRuntime()


def ps_runtime() -> PSRuntime:
    return _runtime

"""Dygraph data parallelism + parallel environment bootstrap.

Reference: /root/reference/python/paddle/fluid/dygraph/parallel.py —
`prepare_context` (:34), `ParallelEnv`, `DataParallel` (:236) with
`scale_loss` (:337) and `apply_collective_grads` (:449 — coalesce grads into
chunks, allreduce each chunk, split back); NCCL bootstrap in
imperative/nccl_context.cc:22-145 (TCP handshake of ncclUniqueId).

TPU-native redesign: there is no NCCL id to hand-shake — multi-host mesh
formation is `jax.distributed.initialize` (coordination service), driven off
the same PADDLE_* env contract the reference launcher sets.  Grad coalescing
(`coalesce_tensors` + split, parallel.py:449) is NOT re-implemented: XLA's
collective combiner fuses small allreduces; DataParallel simply allreduces
each grad and lets the compiler bucket.
"""
from __future__ import annotations

import os
import warnings

from .parallel_env import ParallelEnv
from .collective import all_reduce, ReduceOp

__all__ = ["init_parallel_env", "get_rank", "get_world_size",
           "prepare_context", "DataParallel", "ParallelEnv"]

_parallel_ctx_initialized = False


def get_rank() -> int:
    return ParallelEnv().rank


def get_world_size() -> int:
    return ParallelEnv().world_size


def init_parallel_env():
    """paddle.distributed.init_parallel_env — bootstrap the collective world.

    On a multi-host TPU slice each launched process (one per host, env
    contract from fleet.launch) joins the jax.distributed coordination
    service; rank 0's endpoint is the coordinator.  Single-process: no-op.
    """
    global _parallel_ctx_initialized
    if _parallel_ctx_initialized:
        return ParallelEnv()
    env = ParallelEnv()
    if env.world_size > 1 and env.trainer_endpoints:
        import jax
        coordinator = env.trainer_endpoints[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=env.world_size,
                process_id=env.rank)
        except (RuntimeError, ValueError) as e:  # already initialised / local
            warnings.warn(f"jax.distributed.initialize skipped: {e}")
    _parallel_ctx_initialized = True
    return env


def prepare_context(strategy=None):
    """fluid/dygraph/parallel.py:34 legacy alias."""
    init_parallel_env()
    return strategy


class DataParallel:
    """Dygraph DP wrapper (parallel.py:236).

    Usage parity:
        model = DataParallel(model)
        loss = model.scale_loss(loss)
        loss.backward()
        model.apply_collective_grads()
        opt.minimize(loss)
    """

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, group=None):
        self._layers = layers
        self._env = ParallelEnv()
        self._group = group
        # comm_buffer_size knobs kept for parity; XLA buckets collectives

    @property
    def nranks(self):
        return self._env.world_size

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    __call__ = forward

    def scale_loss(self, loss):
        """parallel.py:337 — pre-scale loss by 1/nranks so the summed
        allreduce of grads averages."""
        if self.nranks <= 1:
            return loss
        return loss / float(self.nranks)

    def apply_collective_grads(self):
        """parallel.py:449 — allreduce every trainable grad.  No manual
        coalescing: XLA's collective combiner fuses them."""
        if self.nranks <= 1:
            return
        for p in self._layers.parameters():
            if p.trainable and p.grad is not None:
                all_reduce(p.grad, op=ReduceOp.SUM, group=self._group)

    # -- passthrough to the wrapped Layer ----------------------------------
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def sublayers(self, include_self=False):
        return self._layers.sublayers(include_self)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, state_dict, *a, **kw):
        return self._layers.set_state_dict(state_dict, *a, **kw)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)
